"""Vectorized tree traversal on device.

TPU-native counterpart of Tree::Predict / GetLeaf
(/root/reference/include/LightGBM/tree.h:116,491) and GBDT's batch scoring
(src/boosting/gbdt_prediction.cpp). The reference walks one row at a time through
pointer-ish child arrays; here all rows advance one level per step of a
``lax.while_loop`` over node-index vectors — wide gathers instead of per-row chase.

Traversal is in *bin space*: rows are binned with the training BinMappers first, so
the decision at a node needs only integer compares plus the missing-bin rules
(dense_bin.hpp Split semantics). Negative node ids encode leaves as -(leaf+1).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs import retrace as retrace_mod
from .split import MISSING_NAN, MISSING_ZERO


class PredictTree(NamedTuple):
    """Device-side flat tree for traversal (subset of TreeArrays + feature meta)."""

    split_feature: jax.Array  # [M-1] int32
    threshold_bin: jax.Array  # [M-1] int32
    default_left: jax.Array  # [M-1] bool
    left_child: jax.Array  # [M-1] int32
    right_child: jax.Array  # [M-1] int32
    leaf_value: jax.Array  # [M] f32
    missing_type: jax.Array  # [M-1] int32 (per split node, gathered from feature)
    default_bin: jax.Array  # [M-1] int32
    nan_bin: jax.Array  # [M-1] int32
    is_cat: jax.Array  # [M-1] bool
    cat_member: jax.Array  # [M-1, B] bool left-side bin membership bitsets
    # EFB (efb.py): column to gather from the (possibly bundled) bin matrix,
    # plus the per-node decode constants; efb all-False when unbundled
    column: jax.Array  # [M-1] int32 (group id when bundled, else feature)
    bin_offset: jax.Array  # [M-1] int32
    efb: jax.Array  # [M-1] bool
    num_leaves: jax.Array  # scalar int32


def make_predict_tree(tree, feature_meta) -> PredictTree:
    """Bundle TreeArrays with per-node feature metadata for traversal."""
    f = tree.split_feature
    num_bin = feature_meta["num_bin"].astype(jnp.int32)
    is_cat = feature_meta.get("is_categorical")
    if is_cat is None:
        is_cat_nodes = jnp.zeros(f.shape, bool)
    else:
        is_cat_nodes = is_cat.astype(bool)[f]
    gid = feature_meta.get("group_id")
    if gid is None:
        column = f.astype(jnp.int32)
        bin_offset = jnp.zeros(f.shape, jnp.int32)
        efb = jnp.zeros(f.shape, bool)
    else:
        column = gid.astype(jnp.int32)[f]
        bin_offset = feature_meta["bin_offset"].astype(jnp.int32)[f]
        efb = jnp.ones(f.shape, bool)
    return PredictTree(
        split_feature=tree.split_feature.astype(jnp.int32),
        threshold_bin=tree.threshold_bin.astype(jnp.int32),
        default_left=tree.default_left,
        left_child=tree.left_child.astype(jnp.int32),
        right_child=tree.right_child.astype(jnp.int32),
        leaf_value=tree.leaf_value.astype(jnp.float32),
        missing_type=feature_meta["missing_type"].astype(jnp.int32)[f],
        default_bin=feature_meta["default_bin"].astype(jnp.int32)[f],
        nan_bin=num_bin[f] - 1,
        is_cat=is_cat_nodes,
        cat_member=tree.cat_member,
        column=column,
        bin_offset=bin_offset,
        efb=efb,
        num_leaves=tree.num_leaves.astype(jnp.int32),
    )


@jax.jit
def tree_predict_leaf(bins_t: jax.Array, tree: PredictTree) -> jax.Array:
    """Leaf index per row. ``bins_t``: [N, F] row-major binned matrix."""
    N = bins_t.shape[0]

    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, _ = state
        active = node >= 0
        nsafe = jnp.maximum(node, 0)
        col_idx = tree.column[nsafe]
        col = jnp.take_along_axis(bins_t, col_idx[:, None], axis=1)[:, 0].astype(jnp.int32)
        thr = tree.threshold_bin[nsafe]
        dl = tree.default_left[nsafe]
        miss = tree.missing_type[nsafe]
        dbin = tree.default_bin[nsafe]
        nbin = tree.nan_bin[nsafe]
        # EFB decode: group bin -> the node feature's sub-bin (efb.py encoding)
        r = col - tree.bin_offset[nsafe]
        dec = jnp.where(
            (r >= 0) & (r < nbin), r + (r >= dbin).astype(jnp.int32), dbin
        )
        col = jnp.where(tree.efb[nsafe], dec, col)
        go_left = col <= thr
        go_left = jnp.where((miss == MISSING_ZERO) & (col == dbin), dl, go_left)
        go_left = jnp.where((miss == MISSING_NAN) & (col == nbin), dl, go_left)
        # categorical: bitset membership (CategoricalDecisionInner, tree.h:275)
        go_left = jnp.where(tree.is_cat[nsafe], tree.cat_member[nsafe, col], go_left)
        nxt = jnp.where(go_left, tree.left_child[nsafe], tree.right_child[nsafe])
        node = jnp.where(active, nxt, node)
        return node, active

    is_stump = tree.num_leaves <= 1
    init = jnp.where(is_stump, -1, 0) * jnp.ones((N,), jnp.int32)
    node, _ = jax.lax.while_loop(cond, body, (init, jnp.ones((N,), bool)))
    return -(node + 1)  # decode -(leaf+1)


@jax.jit
def tree_predict_value(bins_t: jax.Array, tree: PredictTree) -> jax.Array:
    leaf = tree_predict_leaf(bins_t, tree)
    return tree.leaf_value[leaf]


@jax.jit
def ensemble_predict(bins_t: jax.Array, trees: PredictTree) -> jax.Array:
    """Sum of tree outputs for stacked trees (each field has leading axis T).

    The scan keeps the whole ensemble's traversal on device — the counterpart of
    GBDT::PredictRaw's per-tree loop (gbdt_prediction.cpp:13).
    """

    def body(acc, tree):
        return acc + tree_predict_value(bins_t, tree), None

    init = jnp.zeros((bins_t.shape[0],), jnp.float32)
    out, _ = jax.lax.scan(body, init, trees)
    return out


@jax.jit
def ensemble_predict_leaves(bins_t: jax.Array, trees: PredictTree) -> jax.Array:
    """[N, T] leaf indices (predict_leaf_index path, gbdt_prediction.cpp:77)."""

    def body(_, tree):
        return None, tree_predict_leaf(bins_t, tree)

    _, leaves = jax.lax.scan(body, None, trees)
    return leaves.T


# ---------------------------------------------------------------------------
# Packed-ensemble inference (lightgbm_tpu.serve)
#
# The training-side PredictTree above traverses in *training-bin* space and
# needs the dataset's BinMappers — unavailable for a model loaded from text.
# The serving path instead packs the whole ensemble into dense [T, max_nodes]
# tensors in *rank* space: every numerical feature gets a sorted lattice of
# the thresholds the model actually uses (serve/packed.py), rows are converted
# raw -> rank once, and each node decision is an integer compare. Because the
# lattice is built from the model's own float64 thresholds,
# ``rank(x) <= rank(thr)  <=>  x <= thr`` holds exactly, so leaf indices are
# bit-identical to the host Tree.predict_fast walk while the traversal itself
# is one vmapped device dispatch over all T trees (the FIL-style dense layout,
# PAPERS.md).
# ---------------------------------------------------------------------------


class PackedTrees(NamedTuple):
    """Dense rank-space ensemble: node fields [T, M], leaves [T, L].

    ``M = max(num_leaves) - 1`` split slots (min 1); padded slots are inert
    (left = right = -1). ``cat_words`` is one flat uint32 bitset pool shared by
    every categorical node; a node addresses it with (cat_off, cat_n).
    ``cat_n == 0`` on a categorical node marks the legacy single-category
    equality decision (pre-bitset model files) with the raw category value in
    ``thr_rank``. Per-feature rank metadata (rank0/zero_lo/zero_hi) encodes
    NaN->0.0 replacement and the kZeroThreshold window in rank space.
    """

    feature: jax.Array  # [T, M] int32 split feature (original column)
    thr_rank: jax.Array  # [T, M] int32 threshold rank (or legacy cat value)
    default_left: jax.Array  # [T, M] bool
    missing_type: jax.Array  # [T, M] int32
    left_child: jax.Array  # [T, M] int32 (negative = -(leaf+1))
    right_child: jax.Array  # [T, M] int32
    is_cat: jax.Array  # [T, M] bool
    cat_off: jax.Array  # [T, M] int32 word offset into cat_words
    cat_n: jax.Array  # [T, M] int32 word count (0 = legacy equality)
    leaf_value: jax.Array  # [T, L] f32
    num_leaves: jax.Array  # [T] int32
    cat_words: jax.Array  # [W] uint32 flat bitset pool (W >= 1)
    rank0: jax.Array  # [F] int32 rank of 0.0 per feature
    zero_lo: jax.Array  # [F] int32 rank of -kZeroThreshold
    zero_hi: jax.Array  # [F] int32 rank of +kZeroThreshold


def _packed_tree_leaf(codes, isnan, packed: PackedTrees, t) -> jax.Array:
    """Leaf index per row for tree ``t`` (vmapped over ``t`` by the callers).

    ``codes``: [N, F] int32 — threshold rank for numerical features, truncated
    integer category for categorical ones. ``isnan``: [N, F] bool.
    Decision semantics mirror Tree.predict_fast (models/tree.py) node by node.
    """
    N = codes.shape[0]
    feature = packed.feature[t]
    thr = packed.thr_rank[t]
    dl = packed.default_left[t]
    miss = packed.missing_type[t]
    left = packed.left_child[t]
    right = packed.right_child[t]
    is_cat = packed.is_cat[t]
    cat_off = packed.cat_off[t]
    cat_n = packed.cat_n[t]
    n_words = packed.cat_words.shape[0]

    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, _ = state
        active = node >= 0
        nsafe = jnp.maximum(node, 0)
        f = feature[nsafe]
        c = jnp.take_along_axis(codes, f[:, None], axis=1)[:, 0]
        nan = jnp.take_along_axis(isnan, f[:, None], axis=1)[:, 0]
        m = miss[nsafe]
        # numerical: NaN -> 0.0 (rank0) unless missing==NaN, then the
        # kZeroThreshold window / NaN default routing, else rank compare
        eff = jnp.where(nan & (m != MISSING_NAN), packed.rank0[f], c)
        in_band = (eff > packed.zero_lo[f]) & (eff <= packed.zero_hi[f])
        use_default = ((m == MISSING_ZERO) & in_band) | ((m == MISSING_NAN) & nan)
        num_left = jnp.where(use_default, dl[nsafe], eff <= thr[nsafe])
        # categorical bitset membership (FindInBitset, common.h:943)
        iv = jnp.where(nan, 0, c)
        w = iv >> 5
        nw = cat_n[nsafe]
        in_range = (iv >= 0) & (w < nw)
        widx = cat_off[nsafe] + jnp.clip(w, 0, jnp.maximum(nw - 1, 0))
        word = packed.cat_words[jnp.clip(widx, 0, n_words - 1)]
        bit = jnp.right_shift(word, (iv & 31).astype(jnp.uint32)) & jnp.uint32(1)
        cat_left = in_range & (bit > 0) & ~(nan & (m == MISSING_NAN))
        # legacy single-category equality (cat_n == 0): int(fval) == value
        cat_left = jnp.where(
            is_cat[nsafe] & (nw == 0), (~nan) & (c == thr[nsafe]), cat_left
        )
        go_left = jnp.where(is_cat[nsafe], cat_left, num_left)
        nxt = jnp.where(go_left, left[nsafe], right[nsafe])
        node = jnp.where(active, nxt, node)
        return node, active

    is_stump = packed.num_leaves[t] <= 1
    init = jnp.where(is_stump, -1, 0) * jnp.ones((N,), jnp.int32)
    node, _ = jax.lax.while_loop(cond, body, (init, jnp.ones((N,), bool)))
    return -(node + 1)


@jax.jit
def packed_predict_leaves(codes, isnan, packed: PackedTrees) -> jax.Array:
    """[T, N] leaf indices for the whole ensemble — ONE device dispatch."""
    retrace_mod.note_trace("ops.packed_predict_leaves")  # once per XLA trace
    T = packed.num_leaves.shape[0]
    return jax.vmap(
        lambda t: _packed_tree_leaf(codes, isnan, packed, t)
    )(jnp.arange(T, dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("num_class", "average_output"))
def packed_predict_values(
    codes, isnan, packed: PackedTrees, num_class: int = 1,
    average_output: bool = False,
) -> jax.Array:
    """[K, N] f32 raw scores, fused leaf gather + class-wise sum on device.

    Tree i contributes to class i % K (gbdt_prediction.cpp:13 ordering). The
    f32 tree-sum reduction is the serving fast path; the bit-exact-vs-host
    contract belongs to the leaf indices + float64 host finalize
    (serve/packed.py PackedEnsemble.predict).
    """
    retrace_mod.note_trace("ops.packed_predict_values")  # once per XLA trace
    leaves = packed_predict_leaves(codes, isnan, packed)  # [T, N]
    vals = jnp.take_along_axis(packed.leaf_value, leaves, axis=1)  # [T, N]
    T = vals.shape[0]
    iters = max(T // max(num_class, 1), 1)
    out = vals.reshape(iters, num_class, -1).sum(axis=0)
    if average_output:
        out = out / iters
    return out


@jax.jit
def packed_bin_rows(X, bounds, is_cat_feat) -> tuple:
    """On-device raw -> code conversion for the fused serving path.

    ``X``: [N, F] f32 raw rows. ``bounds``: [F, Bmax] f32 per-feature
    threshold lattice padded with +inf. Numerical features get their rank via
    searchsorted; categorical features get the truncated integer category.
    f32 precision: rows within one float32 ulp of a threshold may rank
    differently from the float64 host path — the exact path does this
    conversion on the host instead (serve/packed.py).
    """
    retrace_mod.note_trace("ops.packed_bin_rows")  # once per XLA trace
    isnan = jnp.isnan(X)
    ranks = jax.vmap(
        lambda b, x: jnp.searchsorted(b, x, side="left"), in_axes=(0, 1),
        out_axes=1,
    )(bounds, jnp.where(isnan, jnp.float32(0.0), X)).astype(jnp.int32)
    cat = jnp.trunc(jnp.clip(jnp.where(isnan, 0.0, X), -2.0e9, 2.0e9)).astype(
        jnp.int32
    )
    codes = jnp.where(is_cat_feat[None, :], cat, ranks)
    return codes, isnan
