"""Jitted leaf-wise (best-first) tree growth.

TPU-native counterpart of SerialTreeLearner::Train
(/root/reference/src/treelearner/serial_tree_learner.cpp:173-237) and its split loop.
Differences from the reference are architectural, not semantic:

 * Leaf membership lives in one of two static modes. The default ``bucketed``
   mode keeps a DataPartition-style row permutation (data_partition.hpp:20):
   each split stably partitions the leaf's contiguous segment inside a
   gathered bucket from a {2^k} + {3*2^k} size lattice (``lax.switch`` over
   sizes), so per-split histogram cost tracks leaf size like the
   reference's ordered-index kernels.
   The ``masked`` mode is the simple oracle — a per-row ``leaf_id`` vector
   updated with ``where`` and full-N masked histogram passes — kept for
   differential testing (tests/test_hist_modes.py) and for lazy-CEGB, which
   needs full-row masks.
 * The whole num_leaves-1 split loop runs inside one ``lax.while_loop`` so a tree
   trains without host round-trips.
 * The smaller/larger-leaf histogram subtraction trick (serial_tree_learner.cpp:510,
   feature_histogram.hpp:75 Subtract) is kept: per split, one masked histogram pass
   over the smaller child; the larger child's histogram is parent minus smaller.
 * Monotone-constraint windows per leaf mirror serial_tree_learner.cpp:841-850.
 * Forced splits (ForceSplits, serial_tree_learner.cpp:597-757) are a statically
   unrolled preamble: the JSON's BFS order fixes each forced split's leaf index at
   trace time; each applies under ``lax.cond`` with the reference's
   abort-on-worsening-gain semantics.
 * CEGB (cost-effective gradient boosting) penalties re-rank candidate splits; with
   coupled/lazy feature penalties the grower re-scans every leaf per iteration
   (the reference instead patches its cached splits_per_leaf_,
   serial_tree_learner.cpp:757-775 — same fixpoint, different mechanics).
   Under a histogram pool only slot-RESIDENT leaves rescan; evicted leaves
   keep their cached candidate with the reference's coupled-gain patch.
   Custom split searches (voting) supply a batched ``cegb_rescan`` hook.
 * With ``axis_name`` set (under shard_map), rows are sharded across the mesh and
   the histogram/root sums are combined with psum — the data-parallel learner's
   dataflow (data_parallel_tree_learner.cpp:149-257) collapsed onto XLA collectives.

Output is a flat-array tree in *bin space*; the host Tree object (models/tree.py)
converts thresholds to real values with the BinMappers for prediction on raw data.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import retrace as retrace_mod
from ..utils.platform import env_choice, env_int
from .histogram import (
    _default_backend,
    histogram_source,
    leaf_histogram,
    leaf_values,
    route_effective_impls,
)
from .split import (
    MISSING_NAN,
    MISSING_ZERO,
    CegbParams,
    SplitParams,
    SplitResult,
    calculate_leaf_output,
    find_best_split,
    gather_info_for_threshold,
)


# Bucket-lattice override, resolved ONCE at import (like histogram._ENV_IMPL:
# a trace-time env read would silently keep stale routing for already-compiled
# shapes). "pow2" drops the 3·2^k family; "coarse" keeps every other power of
# two — both cap the lax.switch branch count for compile-time-sensitive runs
# (first TPU contact). Unknown values fall back to the full lattice, loudly.
_ENV_LATTICE = env_choice("LIGHTGBM_TPU_LATTICE", ("pow2", "coarse"))

# Opt-in single-launch Pallas kernel for the two-child split scan
# (ops/split_pallas.py) — experimental until its Mosaic lowering and timing
# are measured on silicon (bringup smoke_psplit stage). Default: XLA scan.
_ENV_SPLIT_IMPL = env_choice("LIGHTGBM_TPU_SPLIT_IMPL", ("pallas",))

# Speculative top-k batched growth ("spec" mode): each while_loop step
# batches the partition/histogram/scan work of the top-k candidate leaves
# and applies the longest prefix the sequential gain order would have
# chosen — measured 3.7x fewer sequential loop steps at k=8 on real split
# sequences (r5 study), attacking the dominant per-split fixed cost of the
# r4 on-silicon breakdown (BENCH_NOTES.md). "spec"/"seq" force the mode on
# any backend (tests use monkeypatch + clear_caches like _ENV_SPLIT_IMPL);
# the default is spec on TPU, sequential elsewhere.
_ENV_GROW = env_choice("LIGHTGBM_TPU_GROW", ("spec", "seq"))
_ENV_SPEC_K = env_int("LIGHTGBM_TPU_SPEC_K", 8, lo=2, hi=64)

# Spec-mode batched-histogram form: "flat" (one concatenated chunk-aligned
# pass — arithmetic ∝ total segment rows) vs "lanes" (vmapped common-max
# lanes — arithmetic ∝ KB x max segment, ~3.4x the sequential row work in
# the r5 batch study). Default: flat whenever the effective histogram impl
# is the XLA one-hot (the r5 TPU default), because flat's fixed chunk
# boundaries then make it BITWISE equal to the per-slot path; under the
# scatter/pallas impls the groupings differ, so lanes (which reuse the
# impl verbatim per lane) keep exactness.
_ENV_SPEC_HIST = env_choice("LIGHTGBM_TPU_SPEC_HIST", ("flat", "lanes"))

def spec_batch_slots(
    num_leaves: int,
    hist_mode: str = "bucketed",
    has_lazy_cegb: bool = False,
    pooled: bool = False,
    cegb_on: bool = False,
    use_subtract: bool = True,
    custom_split: bool = False,
    route_rows_variant: bool = False,
) -> int:
    """Speculative-batch width grow_tree will trace with (0 = sequential).

    The SINGLE source of truth for the spec-mode gate: grow_tree derives its
    KB from this, and callers that allocate the donated ``spec_buf`` carry
    (models/gbdt.py) or attribute its HBM footprint (obs/memwatch.py) call
    it with the same arguments so they can never disagree with the trace.

    ``route_rows_variant`` (histogram.route_rows_variant of the run's frozen
    tune route) declines spec mode: the spec batch histograms candidates at
    the batch-max bucket size, so a route whose impl choice varies with the
    row bucket would let the same logical segment take different kernels in
    the fused (spec) vs segmented (W=1) programs — breaking the profiler's
    bitwise-identity proof (docs/HistogramRouting.md §Exactness).
    """
    bucketed = hist_mode == "bucketed" and not has_lazy_cegb and num_leaves > 1
    spec_ok = (
        bucketed and not pooled and not cegb_on and use_subtract
        and not custom_split and not route_rows_variant
        and _ENV_SPLIT_IMPL != "pallas"
    )
    if _ENV_GROW == "seq":
        kb = 0
    elif _ENV_GROW == "spec":
        kb = _ENV_SPEC_K
    else:
        kb = _ENV_SPEC_K if _default_backend() == "tpu" else 0
    kb = min(kb, num_leaves - 1) if spec_ok else 0
    return kb if kb >= 2 else 0


# which mode the most recent grow_tree TRACE resolved to ("spec"/"seq"),
# and which batched-histogram form ("flat"/"lanes") — set at trace time, so
# only meaningful right after a cache-cleared call; tests use these to
# prove the intended path actually engaged
_LAST_GROW_MODE = None
_LAST_SPEC_HIST = None


class TreeArrays(NamedTuple):
    """Flat-array decision tree (bin-space thresholds), mirroring tree.h:58-522."""

    num_leaves: jax.Array  # scalar int32: leaves actually grown
    split_feature: jax.Array  # [M-1] int32 (used-feature index)
    threshold_bin: jax.Array  # [M-1] int32
    default_left: jax.Array  # [M-1] bool
    left_child: jax.Array  # [M-1] int32 (node idx, or -(leaf+1) for leaves)
    right_child: jax.Array  # [M-1] int32
    split_gain: jax.Array  # [M-1] f32
    internal_value: jax.Array  # [M-1] f32
    internal_count: jax.Array  # [M-1] f32
    leaf_value: jax.Array  # [M] f32
    leaf_count: jax.Array  # [M] f32
    leaf_weight: jax.Array  # [M] f32 (sum of hessians)
    leaf_parent: jax.Array  # [M] int32
    leaf_depth: jax.Array  # [M] int32
    cat_member: jax.Array  # [M-1, B] bool: left-side bin membership bitsets


class PackedBest(NamedTuple):
    """Per-leaf best-split candidates, packed so each split's refresh is 3
    scatters instead of 28 chained single-field updates (the dominant fixed
    cost per split on CPU once the histogram work is bucketed; on TPU each
    scatter is a separate fused kernel launch). Column order is
    _BEST_F / _BEST_I below; ``b`` is [default_left | cat_bitset]."""

    f: jax.Array  # [M, 9] f32
    i: jax.Array  # [M, 3] int32
    b: jax.Array  # [M, 1 + B] bool


_BEST_F = (
    "gain", "left_sum_grad", "left_sum_hess", "left_count",
    "right_sum_grad", "right_sum_hess", "right_count",
    "left_output", "right_output",
)
_BEST_I = ("feature", "threshold", "num_cat")


def _pack_best(res: SplitResult) -> PackedBest:
    """SplitResult with any (shared) leading shape -> PackedBest."""
    f = jnp.stack(
        [jnp.asarray(getattr(res, n), jnp.float32) for n in _BEST_F], axis=-1
    )
    i = jnp.stack(
        [jnp.asarray(getattr(res, n), jnp.int32) for n in _BEST_I], axis=-1
    )
    b = jnp.concatenate(
        [jnp.asarray(res.default_left, bool)[..., None],
         jnp.asarray(res.cat_bitset, bool)],
        axis=-1,
    )
    return PackedBest(f, i, b)


def _unpack_best_row(pb: PackedBest, idx) -> SplitResult:
    """One packed row -> a scalar-field SplitResult."""
    f, i, b = pb.f[idx], pb.i[idx], pb.b[idx]
    kw = {n: f[k] for k, n in enumerate(_BEST_F)}
    kw.update({n: i[k] for k, n in enumerate(_BEST_I)})
    return SplitResult(default_left=b[0], cat_bitset=b[1:], **kw)


# leaf-auxiliary column order: sums + monotone windows, [M, 5] f32
_LAUX_SG, _LAUX_SH, _LAUX_ND, _LAUX_MIN, _LAUX_MAX = range(5)


class PackedTree(NamedTuple):
    """Internal packed tree carry: the ~21 single-element wiring scatters per
    split collapse into 5 (one per array). Node arrays carry M rows; real
    nodes occupy [0, M-1) and row M-1 is the write-off target for the
    parent child-pointer update when the split leaf is the root
    (parent == -1). Unpacked into TreeArrays once, after the grow loop."""

    num_leaves: jax.Array  # scalar int32
    node_f: jax.Array  # [M, 3] f32: split_gain, internal_value, internal_count
    node_i: jax.Array  # [M, 4] i32: split_feature, threshold, left/right child
    node_b: jax.Array  # [M, 1 + B] bool: default_left | cat_member
    leaf_f: jax.Array  # [M, 3] f32: leaf_value, leaf_count, leaf_weight
    leaf_i: jax.Array  # [M, 2] i32: leaf_parent, leaf_depth


def _unpack_tree(pt: PackedTree, M: int) -> TreeArrays:
    return TreeArrays(
        num_leaves=pt.num_leaves,
        split_feature=pt.node_i[: M - 1, 0],
        threshold_bin=pt.node_i[: M - 1, 1],
        default_left=pt.node_b[: M - 1, 0],
        left_child=pt.node_i[: M - 1, 2],
        right_child=pt.node_i[: M - 1, 3],
        split_gain=pt.node_f[: M - 1, 0],
        internal_value=pt.node_f[: M - 1, 1],
        internal_count=pt.node_f[: M - 1, 2],
        leaf_value=pt.leaf_f[:, 0],
        leaf_count=pt.leaf_f[:, 1],
        leaf_weight=pt.leaf_f[:, 2],
        leaf_parent=pt.leaf_i[:, 0],
        leaf_depth=pt.leaf_i[:, 1],
        cat_member=pt.node_b[: M - 1, 1:],
    )


class GrowState(NamedTuple):
    it: jax.Array
    leaf_id: jax.Array  # [N] int32 (masked mode; [1] dummy when bucketed)
    tree: PackedTree
    best: PackedBest  # per-leaf best splits, packed
    laux: jax.Array  # [M, 5] f32: sum_grad, sum_hess, num_data, min/max_con
    hist: jax.Array  # [M, F, B, 3] ([P, F, B, 3] when the pool is capped)
    feature_used: jax.Array  # [F] bool (CEGB coupled bookkeeping)
    unused_cnt: jax.Array  # [M, F] rows-not-yet-charged counts (CEGB lazy)
    used_in_data: jax.Array  # [F, N] bool when lazy CEGB else [1, 1] dummy
    # bucketed mode: DataPartition-style segment layout (data_partition.hpp:20)
    order: jax.Array  # [N] int32 row permutation grouped by leaf ([1] dummy)
    leaf_begin: jax.Array  # [M] int32 segment starts ([1] dummy)
    leaf_phys: jax.Array  # [M] int32 physical rows per leaf ([1] dummy)
    # HistogramPool LRU state (feature_histogram.hpp:654); [1] dummies unpooled
    slot_of: jax.Array  # [M] int32: leaf -> pool slot, -1 = evicted
    slot_leaf: jax.Array  # [P] int32: slot -> leaf, -1 = free
    slot_age: jax.Array  # [P] int32 LRU stamps (0 = never used)
    # spec-mode speculation cache (dummies otherwise): a speculated-but-
    # unapplied split's children results are kept so its heavy work happens
    # exactly once. The LEFT child's histogram is committed straight into
    # the hist carry at cache time (the parent histogram's only use —
    # subtraction — is over by then); the right child has no leaf slot yet,
    # so its histogram parks here keyed by the parent leaf.
    spec_flag: jax.Array  # [M] bool: leaf's pending split is cached
    spec_lphys: jax.Array  # [M] int32: cached left physical count
    spec_rhist: jax.Array  # [M, F, B, 3] cached right-child histograms


def _decision_go_left(col, threshold, default_left, missing_type, default_bin, nan_bin, is_cat, member_val):
    """Bin-space split decision (dense_bin.hpp Split / CategoricalDecisionInner).

    ``member_val`` is the split's left-side membership ALREADY LOOKED UP at
    ``col`` (the caller gathers from its [B]-bool bitset — per-segment, per
    vmapped lane, or per flat row); categorical decisions are that pure
    bitset lookup — no default-direction logic (tree.h:275).
    """
    go_left = col <= threshold
    is_zero_missing = missing_type == MISSING_ZERO
    is_nan_missing = missing_type == MISSING_NAN
    go_left = jnp.where(is_zero_missing & (col == default_bin), default_left, go_left)
    go_left = jnp.where(is_nan_missing & (col == nan_bin), default_left, go_left)
    go_left = jnp.where(is_cat, member_val, go_left)
    return go_left


def _ceil_log2(n: int) -> int:
    return max(int(n - 1).bit_length(), 0)


MIN_BUCKET_LOG2 = 8  # smallest gathered-segment bucket (256 rows)


def bucket_sizes(N: int) -> Tuple[int, ...]:
    """The gathered-segment bucket lattice for an ``N``-row dataset: the
    {2^k} ∪ {3·2^(k-1)} family (x1.33/x1.5 steps, capping round-up waste at
    33% where pure powers of two waste up to 2x), honoring the import-time
    LIGHTGBM_TPU_LATTICE compile-cost knob.

    THE shape distribution the bucketed grower emits histogram calls at —
    shared by ``make_bucket_kernels`` (the lax.switch branch set) and the
    histogram autotuner's sweep (obs/tune.py), which must measure exactly
    these shapes for its routing table to describe real work."""
    step = 2 if _ENV_LATTICE == "coarse" else 1
    sizes = {
        min(1 << b, N)
        for b in range(MIN_BUCKET_LOG2, _ceil_log2(N) + 1, step)
    }
    if _ENV_LATTICE == "":
        sizes |= {
            min(3 << b, N)
            for b in range(MIN_BUCKET_LOG2 - 1, _ceil_log2(N) + 1)
        }
    return tuple(sorted(sizes | {N}))


def _branch_steps(cap: int):
    """Branch-size family up to ``cap``, honoring the same
    LIGHTGBM_TPU_LATTICE compile-cost knob as the bucket lattice:
    branches execute ALL their lanes, so the default {2^k, 3*2^(k-1)}
    family caps round-up waste at 33% (pure powers of two allow 2x),
    while pow2/coarse trade waste for fewer compiled branches."""
    fam = set()
    k = 0
    while (1 << k) < cap * 2:
        if _ENV_LATTICE != "coarse" or k % 2 == 0:
            fam.add(1 << k)
        if _ENV_LATTICE == "":
            fam.add(3 << k)
        k += 1
    return sorted({min(v, cap) for v in fam} | {cap})


class BucketKernels(NamedTuple):
    """The bucketed grower's SEGMENT SEAMS: the per-split partition and
    segment-histogram kernels, extracted from grow_tree so the fused
    while_loop grower and the segmented profiler (obs/prof.py) trace the
    exact same ops — the bitwise-identity guarantee between the two comes
    from sharing THIS code, not from a tolerance."""

    #: (order, begin[W], pcnt[W], feat[W], thr[W], dleft[W], member[W, B])
    #: -> (new order, left physical counts [W])
    partition_batch: Callable
    #: (vals_all [N, 3], order, begin[W], cnt[W]) -> [W, F, B_hist, 3]
    segment_histogram_batch: Callable
    sizes: Tuple[int, ...]  # gathered-segment bucket lattice
    part_sizes: Tuple[int, ...]  # flat-partition branch lattice


def make_bucket_kernels(
    bins: jax.Array,
    feature_meta: Dict[str, jax.Array],
    num_bins: int,
    num_group_bins: Optional[int] = None,
    bins_nf: Optional[jax.Array] = None,
    chunk: int = 4096,
    hist_dtype: str = "float32",
    feature_sharded: bool = False,
    kb: int = 0,
    hist_route=None,
) -> BucketKernels:
    """Build the bucketed partition / segment-histogram kernels for one
    dataset layout. ``kb`` is the speculative-batch width the caller will
    trace with (it only widens the flat-partition branch lattice's cap);
    the profilers pass 0. Bodies are the ones grow_tree always traced —
    moved, not rewritten. Consumers: the fused while_loop grower here,
    the sequential segment profiler (obs/prof.py), and the SHARDED
    segment profiler (obs/dist.py), which traces these same kernels
    per-shard inside shard_map bodies so its local-compute segments are
    op-identical to the fused data-parallel program's.

    ``hist_route`` is the run's frozen histogram tune route
    (ops/histogram.HistRoute) — THIS is the one seam that hands the
    measured per-shape routing to every consumer at once: each bucket
    branch's leaf_histogram call resolves its impl from the route at trace
    time, keyed on that branch's static segment size, so the fused grower,
    both profilers and the sharded path can never disagree on which kernel
    a shape class runs (docs/HistogramRouting.md)."""
    N = bins.shape[1]
    B = num_bins
    F = feature_meta["num_bin"].shape[0]
    f32 = jnp.float32
    num_bin_arr = feature_meta["num_bin"].astype(jnp.int32)
    missing_arr = feature_meta["missing_type"].astype(jnp.int32)
    default_bin_arr = feature_meta["default_bin"].astype(jnp.int32)
    is_cat_arr = feature_meta.get("is_categorical")
    if is_cat_arr is None:
        is_cat_arr = jnp.zeros((F,), bool)
    else:
        is_cat_arr = is_cat_arr.astype(bool)
    bundled = "group_id" in feature_meta
    if bundled:
        gid_arr = feature_meta["group_id"].astype(jnp.int32)  # [F]
        off_arr = feature_meta["bin_offset"].astype(jnp.int32)  # [F]
        B_hist = num_group_bins if num_group_bins is not None else B

        def decode_col(group_col, f):
            """Group-encoded column -> feature f's sub-bins (efb.decode_subbin)."""
            r = group_col - off_arr[f]
            in_range = (r >= 0) & (r < num_bin_arr[f] - 1)
            s = r + (r >= default_bin_arr[f]).astype(jnp.int32)
            return jnp.where(in_range, s, default_bin_arr[f])
    else:
        B_hist = B

    # gathered-segment bucket sizes for the bucketed partition/histogram:
    # the {2^k} ∪ {3·2^k} lattice (x1.33/x1.5 steps) caps round-up waste at
    # 33% where pure powers of two waste up to 2x — worth ~15% of total
    # histogram work at large shapes for ~1.6x the switch branches.
    # _ENV_LATTICE (import-time, like histogram._ENV_IMPL) trades bounded
    # histogram over-work for lax.switch branch count and therefore
    # first-contact compile time (20-40s+ per branch class on TPU).
    # bucket_sizes is also the autotuner's sweep distribution (obs/tune.py).
    SIZES = list(bucket_sizes(N))
    sizes_arr = jnp.asarray(SIZES, jnp.int32)

    # flat-partition branch lattice over 256-row units, up to the worst
    # case (every row plus per-slot 256-alignment)
    _part_cap = -(-N // 256) * 256 + max(kb, 1) * 256
    _part_sizes = [
        u * 256 for u in _branch_steps(-(-_part_cap // 256))
    ]
    _part_sizes_arr = jnp.asarray(_part_sizes, jnp.int32)

    def partition_batch(order, begin, pcnt, feat, thr, dleft, member):
        """Stably partition W disjoint leaf segments in ONE flat segmented
        pass; returns (new order, left physical counts [W]). The W axis is
        the leading axis of every operand; W=1 is the sequential grower's
        per-split partition, W=KB a speculative batch — one implementation,
        so the two modes cannot drift, and arithmetic is proportional to the
        segments' TOTAL rows (a vmapped common-max form would pay
        W x max(segment)).

        Layout after a partition (DataPartition::Split, data_partition.hpp:111):
        [pre-segment | left | right | post-segment], stably, via a segmented
        prefix-sum rank — O(L) scatter instead of an O(L log L) stable sort.
        Integer-exact and idempotent: re-partitioning an already-partitioned
        segment yields the same layout, so work done for a speculated-but-
        unapplied split stays valid when that leaf wins later."""
        W = begin.shape[0]
        miss = missing_arr[feat]
        dbin = default_bin_arr[feat]
        nanb = num_bin_arr[feat] - 1
        iscat = is_cat_arr[feat]
        rows_of = (gid_arr[feat] if bundled else feat).astype(jnp.int32)
        Frows = bins.shape[0]

        padded = ((pcnt + 255) // 256) * 256  # [W]
        ends = jnp.cumsum(padded)
        offs = ends - padded
        L = ends[-1]

        def make_branch(Lb):
            def branch(order, begin, pcnt, offs, ends, rows_of, feat, thr,
                       dleft, miss, dbin, nanb, iscat, member):
                t = jnp.arange(Lb, dtype=jnp.int32)
                j = jnp.minimum(
                    jnp.searchsorted(ends, t, side="right").astype(jnp.int32),
                    W - 1,
                )
                q = t - offs[j]
                valid = q < pcnt[j]
                src = jnp.clip(
                    begin[j] + jnp.minimum(q, jnp.maximum(pcnt[j] - 1, 0)),
                    0, N - 1,
                )
                rows = order[src]
                # per-row feature column through ONE flat gather (each row's
                # slot picks its own split feature)
                flat_idx = rows_of[j] * N + rows
                colraw = (
                    jnp.take(bins_nf.reshape(-1), rows * Frows + rows_of[j])
                    if bins_nf is not None
                    else jnp.take(bins.reshape(-1), flat_idx)
                ).astype(jnp.int32)
                colv = decode_col(colraw, feat[j]) if bundled else colraw
                gl = _decision_go_left(
                    colv, thr[j], dleft[j], miss[j], dbin[j], nanb[j],
                    iscat[j], member[j, jnp.clip(colv, 0, B - 1)],
                )
                is_left = valid & gl
                is_right = valid & ~gl
                # segmented inclusive count of lefts (resets at slot starts);
                # int adds are reassociation-exact
                seg_start = t == offs[j]

                def comb(a, b):
                    av, af = a
                    bv, bf = b
                    return jnp.where(bf, bv, av + bv), af | bf

                lc_inc, _ = jax.lax.associative_scan(
                    comb, (is_left.astype(jnp.int32), seg_start)
                )
                # lefts per slot = inclusive count at the slot's last lane
                # (pad lanes contribute 0); zero-width slots read a stale
                # lane and are masked to 0
                left_cnt = jnp.where(
                    padded > 0, lc_inc[jnp.maximum(ends - 1, 0)], 0
                )
                tgt_local = jnp.where(
                    is_left,
                    lc_inc - 1,
                    left_cnt[j] + q - lc_inc,
                )
                write = is_left | is_right
                gt = jnp.where(write, begin[j] + tgt_local, N + t)
                order2 = order.at[gt].set(rows, unique_indices=True)
                return order2, left_cnt

            return branch

        idx = jnp.clip(
            jnp.searchsorted(_part_sizes_arr, L, side="left"),
            0, len(_part_sizes) - 1,
        )
        return jax.lax.switch(
            idx, [make_branch(Lb) for Lb in _part_sizes],
            order, begin, pcnt, offs, ends, rows_of, feat, thr, dleft, miss,
            dbin, nanb, iscat, member,
        )

    def segment_histogram_batch(vals_all, order, begin, cnt):
        """[W, F, B, 3] histograms of W disjoint segments via ONE lattice-
        switch launch: one fused gather for all segments, then a vmapped
        chunked pass. W=1 is the sequential per-split histogram, W=KB a
        speculative batch — the launch amortization that attacks the
        per-split fixed cost dominating the r4 on-silicon breakdown.

        Cost tracks leaf size like the reference's ordered-index histograms
        (dense_bin.hpp:71); one gather from the precomputed [N, 3]
        (grad*bag, hess*bag, bag) instead of three masked takes — bag/valid
        are exact {0,1} multipliers so the product order cannot change f32
        results."""
        W = begin.shape[0]
        Frows = bins.shape[0]

        def make_branch(S):
            def branch(vals_all, order, begin, cnt):
                def geo(begin_j, cnt_j):
                    # zero-based (NOT the clamped _segment_slice window):
                    # real rows sit at positions [0, cnt) so chunk
                    # boundaries are segment-relative — the invariant that
                    # makes the flat batched form bitwise-identical
                    pos = jnp.arange(S, dtype=jnp.int32)
                    seg = order[jnp.clip(begin_j + pos, 0, N - 1)]
                    return seg, pos < cnt_j

                seg, valid = jax.vmap(geo)(begin, cnt)  # [W, S]
                flat = seg.reshape(-1)
                vals = jnp.take(vals_all, flat, axis=0).reshape(W, S, 3)
                vals = vals * valid[..., None].astype(f32)
                if bins_nf is not None:
                    b_seg = jnp.take(bins_nf, flat, axis=0).reshape(
                        W, S, Frows
                    ).transpose(0, 2, 1)
                else:
                    b_seg = jnp.take(bins, flat, axis=1).reshape(
                        Frows, W, S
                    ).transpose(1, 0, 2)
                return jax.vmap(
                    lambda b, v: leaf_histogram(
                        b, v, B_hist, chunk=chunk, hist_dtype=hist_dtype,
                        feature_sharded=feature_sharded, route=hist_route,
                    )
                )(b_seg, vals)

            return branch

        idx = jnp.clip(
            jnp.searchsorted(sizes_arr, jnp.max(cnt), side="left"),
            0, len(SIZES) - 1,
        )
        return jax.lax.switch(
            idx, [make_branch(S) for S in SIZES], vals_all, order, begin, cnt
        )

    return BucketKernels(
        partition_batch=partition_batch,
        segment_histogram_batch=segment_histogram_batch,
        sizes=tuple(SIZES),
        part_sizes=tuple(_part_sizes),
    )


# node_i column indices for apply_split's fused 6-element scatter (numpy so
# the module builds it once without touching the jax backend at import)
_NODE_I_COLS = np.array([0, 1, 2, 3, 2, 3], np.int32)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "max_depth", "num_bins", "params", "num_group_bins",
        "chunk", "axis_name", "split_fn", "psum_hist", "forced_splits", "cegb",
        "cegb_rescan", "hist_mode", "hist_dtype", "two_way", "feature_sharded",
        "hist_pool_slots", "use_subtract", "hist_route",
    ),
    donate_argnames=("hist_buf", "spec_buf"),
)
def grow_tree(
    bins: jax.Array,  # [F, N] uint8/int32
    grad: jax.Array,  # [N] f32 (already zeroed outside the bag)
    hess: jax.Array,  # [N] f32
    bag_mask: jax.Array,  # [N] f32 (1.0 = in bag)
    feature_mask: jax.Array,  # [F] bool (feature_fraction sample)
    feature_meta: Dict[str, jax.Array],
    num_leaves: int,
    max_depth: int,
    num_bins: int,
    params: SplitParams,
    num_group_bins: Optional[int] = None,
    chunk: int = 4096,
    axis_name: Optional[str] = None,
    split_fn=None,
    psum_hist: bool = True,
    forced_splits: Tuple = (),
    cegb: CegbParams = CegbParams(),
    cegb_state: Optional[Tuple[jax.Array, jax.Array]] = None,
    cegb_rescan=None,
    hist_mode: str = "bucketed",
    hist_dtype: str = "float32",
    two_way: bool = True,
    feature_sharded: bool = False,
    hist_buf: Optional[jax.Array] = None,
    bins_nf: Optional[jax.Array] = None,
    hist_pool_slots: Optional[int] = None,
    use_subtract: bool = True,
    spec_buf: Optional[jax.Array] = None,
    hist_route=None,
):
    """Grow one tree; returns (TreeArrays, leaf_id [N]).

    ``split_fn(hist, sum_g, sum_h, num_data, min_c, max_c, feature_meta,
    feature_mask, params) -> SplitResult`` overrides the best-split search —
    the hook where the voting-parallel learner's top-k vote + reduced psum
    plugs in (voting_parallel_tree_learner.cpp:262-375). With ``axis_name``
    set and ``psum_hist=False``, per-leaf histograms stay shard-local (only
    root totals are psum'd); the split_fn is then responsible for combining
    shard histograms.

    ``forced_splits``: BFS-ordered static tuple of (leaf_idx, used_feature_idx,
    threshold_bin) applied before best-gain growth (ForceSplits).
    ``hist_mode``: "bucketed" (default — segment-permutation histograms whose
    cost tracks leaf size) or "masked" (full-N masked passes; the differential
    oracle, also used automatically for lazy CEGB).
    ``feature_sharded``: set True when ``bins`` is GSPMD-sharded along the
    feature axis (the feature-parallel learner) — selects the row-chunked
    histogram scatter; the default per-feature scan formulation would force
    an all-gather of the bin matrix.
    ``hist_pool_slots``: cap the histogram carry to this many LRU slots
    (HistogramPool, feature_histogram.hpp:654). A split whose parent has been
    evicted runs the reference's use_subtract=false branch: both children are
    summed directly from data (serial_tree_learner.cpp:455-473). None or
    >= num_leaves keeps the full [M, F, B, 3] carry.
    ``use_subtract=False`` disables the smaller-child subtraction trick
    everywhere — the differential oracle for the pool's miss path.
    ``bins_nf``: optional transposed copy of ``bins`` ([N, F]); when given,
    the bucketed segment gathers read it instead of ``bins`` — row gathers
    are contiguous there, ~3x faster on CPU caches. TPU callers leave it
    None ([F, N] is the lane-friendly layout the Pallas kernel wants).
    ``cegb``: static CegbParams; per-feature penalty vectors ride in
    ``feature_meta["cegb_coupled"/"cegb_lazy"]``. ``cegb_state`` is the
    (feature_used [F] bool, used_in_data [F, N] bool) pair carried across trees
    — the reference initializes these once per *training*, not per tree
    (serial_tree_learner.cpp:107-115), so acquisition penalties amortize. When
    ``cegb.enabled`` the return is (tree, leaf_id, new_cegb_state).
    ``spec_buf``: optional donated [M, F, B, 3] scratch for the spec-mode
    right-child cache (``spec_rhist``) — like ``hist_buf`` it skips the
    full-buffer zeros write per tree (stale contents are safe: every read
    is gated on the ``spec_flag`` carry, which starts all-False). Returned
    aliased as the LAST output element so the caller can re-donate;
    allocate it only when :func:`spec_batch_slots` says spec mode engages.
    ``hist_route``: the run's frozen histogram tune route
    (ops/histogram.HistRoute, frozen at GBDT._setup_train) — static, so
    the compiled program's identity includes the table it routed under;
    every leaf_histogram this tree traces resolves its impl from it
    (docs/HistogramRouting.md).
    """
    retrace_mod.note_trace("ops.grow_tree")  # runs once per real XLA trace
    N = bins.shape[1]
    F = feature_meta["num_bin"].shape[0]
    M = num_leaves
    B = num_bins
    f32 = jnp.float32

    # EFB bundling (efb.py): bins is [num_groups, N] with the offset encoding;
    # histograms run over groups at group width, then remap to feature space.
    bundled = "group_id" in feature_meta
    if bundled:
        gid_arr = feature_meta["group_id"].astype(jnp.int32)  # [F]
        off_arr = feature_meta["bin_offset"].astype(jnp.int32)  # [F]
        B_hist = num_group_bins if num_group_bins is not None else B
    else:
        B_hist = B

    if split_fn is None:
        split_fn = find_best_split
    hist_axis = axis_name if psum_hist else None
    cegb_on = cegb.enabled
    if cegb_on and split_fn is not find_best_split and cegb_rescan is None:
        # API contract, not a feature gap: every learner that customizes the
        # split search ships its batched rescan (the voting learner's
        # vote+elect, parallel/voting_parallel.py) — CEGB re-ranks cached
        # candidates per split, so the two hooks must agree on semantics.
        raise ValueError(
            "CEGB with a custom split_fn requires a matching batched "
            "cegb_rescan(hist, lsg, lsh, lnd, mn, mx, pen, feature_meta, "
            "feature_mask, params) -> SplitResult[M]"
        )
    if hist_mode not in ("bucketed", "masked"):
        raise ValueError(
            "hist_mode must be 'bucketed' or 'masked', got %r" % (hist_mode,)
        )
    # lazy CEGB charges per (row, feature) and needs full-row leaf masks
    bucketed = hist_mode == "bucketed" and not cegb.has_lazy and M > 1

    # HistogramPool cap (feature_histogram.hpp:654): with fewer slots than
    # leaves, the [*, F, B, 3] carry holds P LRU slots; an evicted parent
    # disables the subtraction trick for that split and both children are
    # constructed directly (use_subtract = parent_leaf_histogram_array_ !=
    # nullptr, serial_tree_learner.cpp:455).
    pooled = hist_pool_slots is not None and hist_pool_slots < M
    P = int(hist_pool_slots) if pooled else M
    if pooled and P < 2:
        raise ValueError("histogram pool needs at least 2 slots, got %d" % P)
    if pooled and forced_splits and P < len(forced_splits) + 2:
        raise ValueError(
            "histogram pool too small for the forced-splits preamble: "
            "need >= %d slots" % (len(forced_splits) + 2)
        )

    # ---- speculative top-k batching (spec mode) -------------------------
    # Exactness argument: a leaf's cached best split and its children's
    # histograms depend only on that leaf's own segment and histogram, so
    # the work for the top-k candidates is computable in parallel; the
    # applied prefix reproduces argmax's (higher gain, lower slot) order, so
    # the applied split sequence — node numbering included — equals the
    # sequential one. Gated off for CEGB (penalties are order-dependent),
    # histogram pools (slot state is per-split), custom split searches
    # (may contain collectives that don't vmap), masked mode, and the
    # use_subtract=False oracle.
    # the impl set THIS run's reachable bucket classes resolve to under the
    # frozen route ({default} when no route / env pinned): >1 impl gates
    # spec mode off, and a uniform single impl decides the flat-vs-lanes
    # spec histogram below (flat hardcodes the xla one-hot arithmetic)
    _route_impls = route_effective_impls(hist_route, B_hist, hist_dtype, N)
    KB = spec_batch_slots(
        M,
        hist_mode=hist_mode,
        has_lazy_cegb=cegb.has_lazy,
        pooled=pooled,
        cegb_on=cegb_on,
        use_subtract=use_subtract,
        custom_split=split_fn is not find_best_split,
        route_rows_variant=len(_route_impls) > 1,
    )
    if _ENV_SPEC_HIST:
        use_flat = _ENV_SPEC_HIST == "flat"
    else:
        from .histogram import _ENV_IMPL as _hist_env

        # flat spec histograms share onehot_chunk_partial (xla arithmetic),
        # so they are only bitwise-consistent when the effective impl IS
        # xla: env override first, else the route's uniform impl (which is
        # the backend default when no route is active)
        eff_impl = _hist_env or (
            next(iter(_route_impls)) if len(_route_impls) == 1 else ""
        )
        use_flat = eff_impl == "xla"
    global _LAST_GROW_MODE, _LAST_SPEC_HIST  # trace-time test introspection
    _LAST_GROW_MODE = "spec" if KB else "seq"
    _LAST_SPEC_HIST = ("flat" if use_flat else "lanes") if KB else None

    num_bin_arr = feature_meta["num_bin"].astype(jnp.int32)
    missing_arr = feature_meta["missing_type"].astype(jnp.int32)
    default_bin_arr = feature_meta["default_bin"].astype(jnp.int32)
    mono_arr = feature_meta["monotone"].astype(jnp.int32)

    if bundled:
        # feature-space gather plan for the [G, B_hist] -> [F, B] remap:
        # sub-bin s != default maps to group bin off + (s - (s > default));
        # the default row is recovered from leaf totals (efb.py encoding)
        s_iota = jnp.arange(B, dtype=jnp.int32)[None, :]  # [1, B]
        s0_col = default_bin_arr[:, None]
        efb_valid = (s_iota < num_bin_arr[:, None]) & (s_iota != s0_col)  # [F, B]
        efb_gidx = jnp.where(
            efb_valid, off_arr[:, None] + s_iota - (s_iota > s0_col), 0
        )
        f_iota = jnp.arange(F, dtype=jnp.int32)

        def remap_hist(group_hist, sum_g, sum_h, sum_n):
            """[G, B_hist, 3] group histogram -> [F, B, 3] feature histogram.

            The default-bin row is leaf totals minus the feature's
            non-default rows. The remap is affine-linear in (hist, totals),
            so it commutes with cross-shard psum: remapping each shard with
            its SHARD-LOCAL totals and summing equals remapping the global
            histogram with global totals — the voting-parallel learner's
            shard-local mode relies on this (its elected-feature psum then
            runs in feature space)."""
            fh = group_hist[gid_arr[:, None], efb_gidx]  # [F, B, 3]
            fh = fh * efb_valid[:, :, None].astype(fh.dtype)
            totals = jnp.stack(
                [sum_g.astype(fh.dtype), sum_h.astype(fh.dtype), sum_n.astype(fh.dtype)]
            )
            rest = totals[None, :] - jnp.sum(fh, axis=1)  # [F, 3]
            return fh.at[f_iota, default_bin_arr].set(rest)

        def remap_hist_local(group_hist):
            """Shard-local remap: totals recovered from the group histogram
            itself — every row lands in exactly one bin of every group, so
            any group's bins sum to the (local) leaf totals."""
            t = jnp.sum(group_hist[0], axis=0)  # [3]
            return remap_hist(group_hist, t[0], t[1], t[2])

        def decode_col(group_col, f):
            """Group-encoded column -> feature f's sub-bins (efb.decode_subbin)."""
            r = group_col - off_arr[f]
            in_range = (r >= 0) & (r < num_bin_arr[f] - 1)
            s = r + (r >= default_bin_arr[f]).astype(jnp.int32)
            return jnp.where(in_range, s, default_bin_arr[f])

    is_cat_arr = feature_meta.get("is_categorical")
    if is_cat_arr is None:
        is_cat_arr = jnp.zeros((F,), bool)
    else:
        is_cat_arr = is_cat_arr.astype(bool)

    # Bucketed partition / segment-histogram kernels come from the shared
    # seam factory (make_bucket_kernels above): one implementation serves
    # the fused while_loop grower here AND the segmented profiler
    # (obs/prof.py), so the two can never drift numerically.
    if bucketed:
        _kern = make_bucket_kernels(
            bins, feature_meta, B, num_group_bins=num_group_bins,
            bins_nf=bins_nf, chunk=chunk, hist_dtype=hist_dtype,
            feature_sharded=feature_sharded, kb=KB, hist_route=hist_route,
        )
        partition_batch = _kern.partition_batch

        def segment_histogram_batch(order, begin, cnt):
            # vals_all (the per-tree [N, 3] accumulands) binds below, before
            # the first call
            return _kern.segment_histogram_batch(vals_all, order, begin, cnt)

        def partition_segment(order, begin, pcnt, f, threshold, default_left, member):
            """One split's partition — the W=1 case of partition_batch."""
            order2, left_cnt = partition_batch(
                order, begin[None], pcnt[None], f[None], threshold[None],
                default_left[None], member[None],
            )
            return order2, left_cnt[0]

        def segment_histogram(order, begin, cnt):
            """One segment's histogram — the W=1 case of the batch launch."""
            return segment_histogram_batch(order, begin[None], cnt[None])[0]

    if KB:
        from .histogram import _pick_chunk, onehot_chunk_partial

        # flat-chunk batching constants: every slot is padded to a multiple
        # of the SAME chunk the per-slot path would use (the F/B budget cap,
        # un-shrunk by segment size), so chunk boundaries — and therefore
        # f32 accumulation grouping — coincide with the sequential path's,
        # and zero-valued pad lanes are fp-exact no-ops (x + 0 == x): the
        # batched histogram is BITWISE equal to per-slot histograms.
        _Frows = bins.shape[0]
        C_FLAT = _pick_chunk(_Frows, B_hist, chunk, 1 << 60)
        # branch lattice over the flat buffer's CHUNK COUNT (so every branch
        # length is an exact C_FLAT multiple) up to the cap (L = N rows +
        # per-slot alignment), honoring LIGHTGBM_TPU_LATTICE like the rest
        _flat_sizes = [
            n * C_FLAT for n in _branch_steps(-(-N // C_FLAT) + KB)
        ]
        _flat_sizes_arr = jnp.asarray(_flat_sizes, jnp.int32)

        def segment_histogram_flat(order, begin, cnt):
            """[KB, F, B, 3] histograms of KB disjoint segments via ONE flat
            concatenated pass — unlike the vmapped-lane form, arithmetic is
            proportional to the segments' TOTAL padded rows, not
            KB x max(segment): the r5 batch-structure study measured the
            lane form at ~3.4x the sequential row work and this at ~1.06x.

            Layout: slot j owns flat rows [off_j, off_j + ceil_C(cnt_j));
            each C_FLAT-chunk lies inside exactly one slot, so a chunked
            one-hot scan attributes each partial to its slot row with one
            dynamic-index add."""
            padded = ((cnt + C_FLAT - 1) // C_FLAT) * C_FLAT  # [KB]
            ends = jnp.cumsum(padded)  # [KB]
            offs = ends - padded
            L = ends[-1]

            def make_branch(Lb):
                nsteps = Lb // C_FLAT

                def branch(order, begin, cnt, offs, ends):
                    t = jnp.arange(Lb, dtype=jnp.int32)
                    j = jnp.searchsorted(ends, t, side="right").astype(jnp.int32)
                    j = jnp.minimum(j, KB - 1)
                    q = t - offs[j]
                    valid = q < cnt[j]
                    src = jnp.clip(begin[j] + jnp.minimum(q, jnp.maximum(cnt[j] - 1, 0)), 0, N - 1)
                    rows = order[src]
                    vals = jnp.take(vals_all, rows, axis=0) * valid[:, None].astype(f32)
                    b_seg = (
                        jnp.take(bins_nf, rows, axis=0).T
                        if bins_nf is not None
                        else jnp.take(bins, rows, axis=1)
                    )  # [Frows, Lb]
                    slot_of_chunk = jnp.searchsorted(
                        ends, jnp.arange(nsteps, dtype=jnp.int32) * C_FLAT,
                        side="right",
                    ).astype(jnp.int32)
                    slot_of_chunk = jnp.minimum(slot_of_chunk, KB - 1)
                    bins_c = b_seg.reshape(_Frows, nsteps, C_FLAT).transpose(1, 0, 2)
                    vals_c = vals.reshape(nsteps, C_FLAT, 3)
                    op_dtype = (
                        jnp.bfloat16 if hist_dtype == "bfloat16" else jnp.float32
                    )

                    def step(acc, xs):
                        bc, vc, sl = xs  # [Frows, C], [C, 3], scalar
                        part = onehot_chunk_partial(bc, vc, B_hist, op_dtype)
                        return acc.at[sl].add(part), None

                    acc0 = jnp.zeros((KB, _Frows, B_hist, 3), f32)
                    acc, _ = jax.lax.scan(
                        step, acc0, (bins_c, vals_c, slot_of_chunk)
                    )
                    return acc

                return branch

            idx = jnp.clip(
                jnp.searchsorted(_flat_sizes_arr, L, side="left"),
                0, len(_flat_sizes) - 1,
            )
            return jax.lax.switch(
                idx, [make_branch(Lb) for Lb in _flat_sizes],
                order, begin, cnt, offs, ends,
            )

    coupled_arr = feature_meta.get("cegb_coupled")
    lazy_arr = feature_meta.get("cegb_lazy")

    def split2(hist2, sg2, sh2, nd2, mn2, mx2):
        """Best splits for the two children. vmapped over the child axis for
        the plain scan; custom split_fns stay unrolled (they may contain
        collectives, which don't vmap under shard_map)."""
        if split_fn is find_best_split:
            if _ENV_SPLIT_IMPL == "pallas":
                from .histogram import _default_backend
                from .split_pallas import find_best_split_pair_pallas, supported

                backend = _default_backend()
                if supported(feature_meta, backend):
                    return find_best_split_pair_pallas(
                        hist2, sg2, sh2, nd2, mn2, mx2, feature_meta,
                        feature_mask, params, two_way=two_way,
                        interpret=backend != "tpu",
                    )
            return jax.vmap(
                lambda h, sg, sh, nd, mn, mx: find_best_split(
                    h, sg, sh, nd, mn, mx, feature_meta, feature_mask, params,
                    two_way=two_way,
                )
            )(hist2, sg2, sh2, nd2, mn2, mx2)
        results = [
            split_fn(
                hist2[k], sg2[k], sh2[k], nd2[k], mn2[k], mx2[k],
                feature_meta, feature_mask, params,
            )
            for k in range(2)
        ]
        return SplitResult(
            *[jnp.stack([getattr(r, n) for r in results]) for n in SplitResult._fields]
        )

    def masked_values(mask_f32):
        return leaf_values(grad, hess, mask_f32 * bag_mask)

    # [N, 3] (grad*bag, hess*bag, bag) computed once per tree — the bucketed
    # branches gather rows of this instead of three separate takes
    if bucketed:
        vals_all = leaf_values(grad, hess, bag_mask)

    neg_inf = jnp.float32(-jnp.inf)

    def depth_gate(gain, depth):
        if max_depth > 0:
            return jnp.where(depth >= max_depth, neg_inf, gain)
        return gain

    # ---- CEGB penalty machinery -----------------------------------------
    def leaf_penalties(lnd_all, feature_used, unused_cnt):
        """[M, F] gain penalties (serial_tree_learner.cpp:537-543,568-573)."""
        pen = cegb.tradeoff * cegb.penalty_split * lnd_all[:, None]
        pen = jnp.broadcast_to(pen, (M, F)).astype(f32)
        if cegb.has_coupled:
            pen = pen + cegb.tradeoff * coupled_arr[None, :] * (
                ~feature_used
            )[None, :].astype(f32)
        if cegb.has_lazy:
            pen = pen + cegb.tradeoff * lazy_arr[None, :] * unused_cnt
        return pen

    def rescan_all(tree, hist, lsg, lsh, lnd, mn, mx, feature_used, unused_cnt):
        """Re-rank every leaf's best split under current CEGB penalties.

        The reference keeps splits_per_leaf_ cached and patches gains when a
        coupled feature first gets used (Split, serial_tree_learner.cpp:757-775);
        re-scanning from the (resident) histograms reaches the same fixpoint.
        A custom ``cegb_rescan`` (the voting learner's batched vote+elect) takes
        over when the split search itself is custom.
        """
        pen = leaf_penalties(lnd, feature_used, unused_cnt)
        if cegb_rescan is not None:
            res = cegb_rescan(
                hist, lsg, lsh, lnd, mn, mx, pen, feature_meta, feature_mask,
                params,
            )
        else:
            res = jax.vmap(
                lambda h, sg, sh, nd, mn1, mx1, pr: find_best_split(
                    h, sg, sh, nd, mn1, mx1, feature_meta, feature_mask, params,
                    pr, two_way=two_way,
                )
            )(hist, lsg, lsh, lnd, mn, mx, pen)
        exists = jnp.arange(M, dtype=jnp.int32) < tree.num_leaves
        gain = jnp.where(exists, res.gain, neg_inf)
        gain = depth_gate(gain, tree.leaf_i[:, 1])
        return res._replace(gain=gain)

    def rescan_resident(
        tree, hist, slot_leaf, slot_age, laux, feature_used, unused_cnt,
        old_best, prev_feature_used, split_f,
    ):
        """Pooled CEGB: re-rank only slot-RESIDENT leaves from their resident
        histograms; evicted leaves keep their cached candidate, gain-patched
        when this split newly paid a coupled feature — exactly the staleness
        the reference's cached splits_per_leaf_ has (Split,
        serial_tree_learner.cpp:757-775: only the gain of cached splits on the
        newly-used feature is adjusted, no re-argmax)."""
        pen = leaf_penalties(laux[:, _LAUX_ND], feature_used, unused_cnt)
        lv = jnp.maximum(slot_leaf, 0)  # [P] leaf of each slot (0 for free)
        if cegb_rescan is not None:
            # custom split search (the voting learner's batched vote+elect)
            # over the RESIDENT slot rows — it is leading-axis polymorphic
            # and its collectives run uniformly across shards because slot
            # state is a pure function of the replicated split sequence;
            # free-slot rows compute garbage that the `occupied` mask drops
            res = cegb_rescan(
                hist, laux[lv, _LAUX_SG], laux[lv, _LAUX_SH],
                laux[lv, _LAUX_ND], laux[lv, _LAUX_MIN],
                laux[lv, _LAUX_MAX], pen[lv], feature_meta, feature_mask,
                params,
            )
        else:
            res = jax.vmap(
                lambda h, sg, sh, nd, mn1, mx1, pr: find_best_split(
                    h, sg, sh, nd, mn1, mx1, feature_meta, feature_mask,
                    params, pr, two_way=two_way,
                )
            )(
                hist, laux[lv, _LAUX_SG], laux[lv, _LAUX_SH],
                laux[lv, _LAUX_ND],
                laux[lv, _LAUX_MIN], laux[lv, _LAUX_MAX], pen[lv],
            )
        occupied = (slot_leaf >= 0) & (slot_age > 0) & (lv < tree.num_leaves)
        gain = jnp.where(occupied, res.gain, neg_inf)
        gain = depth_gate(gain, tree.leaf_i[lv, 1])
        pk = _pack_best(res._replace(gain=gain))  # [P, ...]
        base = old_best
        if cegb.has_coupled and split_f is not None:
            # the split just paid for split_f: cached candidates on that
            # feature are no longer charged its acquisition penalty
            newly = ~prev_feature_used[split_f]
            patch = jnp.where(
                newly
                & (old_best.i[:, 0] == split_f)
                & (old_best.f[:, 0] > neg_inf),
                cegb.tradeoff * coupled_arr[split_f],
                jnp.float32(0.0),
            )
            base = old_best._replace(f=old_best.f.at[:, 0].add(patch))
        # scatter resident results into their leaf rows; row M (out of range)
        # drops the write for free slots (JAX scatter OOB-drop semantics)
        rows = jnp.where(occupied, slot_leaf, M)
        return PackedBest(
            base.f.at[rows].set(pk.f),
            base.i.at[rows].set(pk.i),
            base.b.at[rows].set(pk.b),
        )

    # ---- root ----------------------------------------------------------
    # with a bucketed partition the [N, 3] values tensor already exists
    # (vals_all); masked_values(ones) would rebuild the identical array
    # (ones * bag_mask == bag_mask) — ~6ms/tree on TPU at 1M
    root_vals = vals_all if bucketed else masked_values(jnp.ones((N,), f32))
    root_hist = leaf_histogram(
        bins, root_vals, B_hist, chunk=chunk, axis_name=hist_axis,
        hist_dtype=hist_dtype, feature_sharded=feature_sharded,
        route=hist_route,
    )
    # Root totals from the histogram of feature 0 would miss rows in padded bins;
    # sum the mask directly instead (psum'd under shard_map like GBDT's root sync,
    # serial_tree_learner.cpp:271 BeforeTrain).
    root_g = jnp.sum(grad * bag_mask)
    root_h = jnp.sum(hess * bag_mask)
    root_n = jnp.sum(bag_mask)
    if axis_name is not None:
        # shard-linear root reductions ride the same partial-accumulation
        # seam as the histograms (HistogramSource, ops/histogram.py)
        _root_src = histogram_source(axis_name)
        root_g = _root_src.combine(root_g)
        root_h = _root_src.combine(root_h)
        root_n = _root_src.combine(root_n)
    if bundled:
        if axis_name is not None and not psum_hist:
            # voting-parallel shard-local mode: remap with LOCAL totals (the
            # linearity argument on remap_hist); the split_fn's elected psum
            # then combines feature-space histograms exactly
            root_hist = remap_hist_local(root_hist)
        else:
            root_hist = remap_hist(root_hist, root_g, root_h, root_n)

    no_con_min = jnp.full((M,), -jnp.inf, f32)
    no_con_max = jnp.full((M,), jnp.inf, f32)

    if cegb_state is not None:
        feature_used0, used_in_data0 = cegb_state
    else:
        feature_used0 = jnp.zeros((F,), bool)
        used_in_data0 = jnp.zeros((F, N) if cegb.has_lazy else (1, 1), bool)
    if cegb.has_lazy:
        root_unused = (~used_in_data0).astype(f32) @ bag_mask  # [F]
        if axis_name is not None:
            root_unused = jax.lax.psum(root_unused, axis_name)
        unused0 = jnp.zeros((M, F), f32).at[0].set(root_unused)
    else:
        unused0 = jnp.zeros((M, F), f32)

    def expand_packed(res: SplitResult, idx: int) -> PackedBest:
        """Scatter one leaf's SplitResult into [M]-leading packed arrays
        (gain initialized to -inf everywhere else)."""
        row = _pack_best(res)
        f0 = jnp.zeros((M, row.f.shape[-1]), f32).at[:, 0].set(-jnp.inf)
        return PackedBest(
            f0.at[idx].set(row.f),
            jnp.zeros((M, row.i.shape[-1]), jnp.int32).at[idx].set(row.i),
            jnp.zeros((M, row.b.shape[-1]), bool).at[idx].set(row.b),
        )

    tree0 = PackedTree(
        num_leaves=jnp.int32(1),
        node_f=jnp.zeros((M, 3), f32),
        node_i=jnp.zeros((M, 4), jnp.int32),
        node_b=jnp.zeros((M, 1 + B), bool),
        leaf_f=jnp.zeros((M, 3), f32).at[0].set(
            jnp.stack(
                [calculate_leaf_output(root_g, root_h, params), root_n, root_h]
            )
        ),
        # leaf_parent -1, leaf_depth 0 (root depth 0, tree.cpp ctor)
        leaf_i=jnp.concatenate(
            [jnp.full((M, 1), -1, jnp.int32), jnp.zeros((M, 1), jnp.int32)],
            axis=1,
        ),
    )

    # The [M, F, B, 3] carry only needs slice 0 initialized: every other
    # leaf's slice is written (smaller-pass + subtraction) when that leaf is
    # created, before any read. A caller-donated scratch buffer therefore
    # skips the 22MB-at-bench-shape zeros write every tree; its stale contents
    # are finite floats whose garbage candidate gains are masked by the
    # leaf-exists checks. Returned (aliased, zero-copy) when donated so the
    # caller can re-donate it for the next tree.
    if hist_buf is not None:
        hist0 = hist_buf.at[0].set(root_hist)
    else:
        hist0 = jnp.zeros((P, F, B, 3), f32).at[0].set(root_hist)
    if pooled:
        slot_of0 = jnp.full((M,), -1, jnp.int32).at[0].set(0)
        slot_leaf0 = jnp.full((P,), -1, jnp.int32).at[0].set(0)
        slot_age0 = jnp.zeros((P,), jnp.int32).at[0].set(1)
    else:
        slot_of0 = jnp.zeros((1,), jnp.int32)
        slot_leaf0 = jnp.zeros((1,), jnp.int32)
        slot_age0 = jnp.zeros((1,), jnp.int32)

    # [M, 5] leaf aux: sums at col 0-2, monotone windows at col 3-4 — one
    # scatter per split updates all five (vs five chained pairs)
    laux0 = jnp.stack(
        [
            jnp.zeros((M,), f32).at[0].set(root_g),
            jnp.zeros((M,), f32).at[0].set(root_h),
            jnp.zeros((M,), f32).at[0].set(root_n),
            no_con_min,
            no_con_max,
        ],
        axis=-1,
    )

    if cegb_on and pooled:
        empty = PackedBest(
            jnp.zeros((M, len(_BEST_F)), f32).at[:, 0].set(-jnp.inf),
            jnp.zeros((M, len(_BEST_I)), jnp.int32),
            jnp.zeros((M, 1 + B), bool),
        )
        best0 = rescan_resident(
            tree0, hist0, slot_leaf0, slot_age0, laux0, feature_used0, unused0,
            empty, feature_used0, None,
        )
    elif cegb_on:
        root_best = rescan_all(
            tree0, hist0,
            laux0[:, _LAUX_SG], laux0[:, _LAUX_SH], laux0[:, _LAUX_ND],
            no_con_min, no_con_max, feature_used0, unused0,
        )
        best0 = _pack_best(root_best)
    else:
        root_kw = {"two_way": two_way} if split_fn is find_best_split else {}
        root_split = split_fn(
            root_hist, root_g, root_h, root_n,
            no_con_min[0], no_con_max[0],
            feature_meta, feature_mask, params, **root_kw,
        )
        best0 = expand_packed(root_split, 0)

    state0 = GrowState(
        it=jnp.int32(0),
        leaf_id=jnp.zeros((1,) if bucketed else (N,), jnp.int32),
        tree=tree0,
        best=best0,
        laux=laux0,
        hist=hist0,
        feature_used=feature_used0,
        unused_cnt=unused0,
        used_in_data=used_in_data0,
        order=jnp.arange(N, dtype=jnp.int32) if bucketed else jnp.zeros((1,), jnp.int32),
        leaf_begin=jnp.zeros((M,) if bucketed else (1,), jnp.int32),
        leaf_phys=(
            jnp.zeros((M,), jnp.int32).at[0].set(N)
            if bucketed
            else jnp.zeros((1,), jnp.int32)
        ),
        slot_of=slot_of0,
        slot_leaf=slot_leaf0,
        slot_age=slot_age0,
        spec_flag=jnp.zeros((M,) if KB else (1,), bool),
        spec_lphys=jnp.zeros((M,) if KB else (1,), jnp.int32),
        # donated scratch (like hist_buf): stale contents are read only
        # through spec_flag-gated selects and spec_flag starts all-False,
        # so skipping the [M, F, B, 3] zeros write per tree is safe
        spec_rhist=(
            (spec_buf if spec_buf is not None else jnp.zeros((M, F, B, 3), f32))
            if KB
            else jnp.zeros((1, 1, 1, 1), f32)
        ),
    )

    def apply_split(s: GrowState, best_leaf, rec: SplitResult) -> GrowState:
        """Apply one split of ``best_leaf`` by ``rec`` (Split,
        serial_tree_learner.cpp:757-851 + the next iteration's FindBestSplits)."""
        node = s.it
        new_leaf = s.tree.num_leaves

        f = rec.feature
        if bucketed:
            leaf_id = s.leaf_id  # dummy; reconstructed from order at the end
            pbegin = s.leaf_begin[best_leaf]
            pphys = s.leaf_phys[best_leaf]
            order, left_phys = partition_segment(
                s.order, pbegin, pphys, f, rec.threshold, rec.default_left,
                rec.cat_bitset,
            )
            right_phys = pphys - left_phys
            leaf_begin = s.leaf_begin.at[new_leaf].set(pbegin + left_phys)
            leaf_phys = (
                s.leaf_phys.at[best_leaf].set(left_phys).at[new_leaf].set(right_phys)
            )
        else:
            row = gid_arr[f] if bundled else f
            col = jax.lax.dynamic_slice(bins, (row, 0), (1, N))[0].astype(jnp.int32)
            if bundled:
                col = decode_col(col, f)
            go_left = _decision_go_left(
                col,
                rec.threshold,
                rec.default_left,
                missing_arr[f],
                default_bin_arr[f],
                num_bin_arr[f] - 1,
                is_cat_arr[f],
                rec.cat_bitset[jnp.clip(col, 0, B - 1)],
            )
            in_leaf = s.leaf_id == best_leaf
            leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, s.leaf_id)
            order, leaf_begin, leaf_phys = s.order, s.leaf_begin, s.leaf_phys

        # ---- wire the tree (5 scatters, PackedTree) ----------------------
        t = s.tree
        child_idx = jnp.stack([best_leaf, new_leaf])
        parent = t.leaf_i[best_leaf, 0]
        # row M-1 is the write-off target when the split leaf is the root
        prow = jnp.where(parent >= 0, parent, M - 1)
        enc_old = -(best_leaf + 1)
        old_plc = t.node_i[prow, 2]
        old_prc = t.node_i[prow, 3]
        new_plc = jnp.where((parent >= 0) & (old_plc == enc_old), node, old_plc)
        new_prc = jnp.where((parent >= 0) & (old_prc == enc_old), node, old_prc)

        depth_child = t.leaf_i[best_leaf, 1] + 1
        parent_aux = s.laux[best_leaf]  # [5]
        parent_value = calculate_leaf_output(
            parent_aux[_LAUX_SG], parent_aux[_LAUX_SH], params
        )
        # (row, col) pairs are distinct: prow < node always (parents are
        # older nodes), and the write-off row M-1 exceeds every node index
        node_i = t.node_i.at[
            jnp.stack([node, node, node, node, prow, prow]),
            _NODE_I_COLS,
        ].set(
            jnp.stack([
                f, rec.threshold, -(best_leaf + 1), -(new_leaf + 1),
                new_plc, new_prc,
            ])
        )
        tree = PackedTree(
            num_leaves=t.num_leaves + 1,
            node_f=t.node_f.at[node].set(
                jnp.stack([rec.gain, parent_value, parent_aux[_LAUX_ND]])
            ),
            node_i=node_i,
            node_b=t.node_b.at[node].set(
                jnp.concatenate([rec.default_left[None], rec.cat_bitset])
            ),
            leaf_f=t.leaf_f.at[child_idx].set(
                jnp.stack([
                    jnp.stack([rec.left_output, rec.left_count,
                               rec.left_sum_hess]),
                    jnp.stack([rec.right_output, rec.right_count,
                               rec.right_sum_hess]),
                ])
            ),
            leaf_i=t.leaf_i.at[child_idx].set(
                jnp.stack([
                    jnp.stack([node, depth_child]),
                    jnp.stack([node, depth_child]),
                ])
            ),
        )

        # ---- leaf aggregates + monotone windows (one [2,5] scatter) ------
        # (serial_tree_learner.cpp:841-850)
        mono_f = mono_arr[f]
        mid = (rec.left_output + rec.right_output) / 2.0
        pmin = parent_aux[_LAUX_MIN]
        pmax = parent_aux[_LAUX_MAX]
        # increasing (+1): left <= right  -> left.max = mid, right.min = mid
        # decreasing (-1): left >= right  -> left.min = mid, right.max = mid
        l_min = jnp.where(mono_f < 0, mid, pmin)
        l_max = jnp.where(mono_f > 0, mid, pmax)
        r_min = jnp.where(mono_f > 0, mid, pmin)
        r_max = jnp.where(mono_f < 0, mid, pmax)
        laux = s.laux.at[child_idx].set(
            jnp.stack(
                [
                    jnp.stack([rec.left_sum_grad, rec.left_sum_hess,
                               rec.left_count, l_min, l_max]),
                    jnp.stack([rec.right_sum_grad, rec.right_sum_hess,
                               rec.right_count, r_min, r_max]),
                ]
            )
        )

        # ---- CEGB bookkeeping --------------------------------------------
        feature_used = s.feature_used
        used_in_data = s.used_in_data
        unused_cnt = s.unused_cnt
        if cegb.has_coupled:
            feature_used = feature_used.at[f].set(True)
        if cegb.has_lazy:
            # rows of the split leaf have now paid for feature f — only rows in
            # the bag: the reference inserts rows from the data partition, i.e.
            # the bagged subset (serial_tree_learner.cpp:772)
            used_in_data = used_in_data.at[f].set(
                used_in_data[f] | (in_leaf & (bag_mask > 0))
            )
            not_used = (~used_in_data).astype(f32)  # [F, N]
            lmask = (bag_mask * (leaf_id == best_leaf)).astype(f32)
            rmask = (bag_mask * (leaf_id == new_leaf)).astype(f32)
            left_unused = not_used @ lmask
            right_unused = not_used @ rmask
            if axis_name is not None:
                left_unused = jax.lax.psum(left_unused, axis_name)
                right_unused = jax.lax.psum(right_unused, axis_name)
            unused_cnt = unused_cnt.at[best_leaf].set(left_unused).at[new_leaf].set(
                right_unused
            )

        # ---- histograms: smaller child pass + subtraction ----------------
        # smaller-child choice uses the global (bagged) counts from the split
        # record: under shard_map the physical counts are shard-local and
        # shards must all histogram the SAME child before the psum
        left_smaller = rec.left_count <= rec.right_count
        small_idx = jnp.where(left_smaller, best_leaf, new_leaf)
        large_idx = jnp.where(left_smaller, new_leaf, best_leaf)
        if bucketed:
            small_begin = jnp.where(left_smaller, pbegin, pbegin + left_phys)
            small_cnt = jnp.where(left_smaller, left_phys, right_phys)
            small_hist = segment_histogram(order, small_begin, small_cnt)
            if hist_axis is not None:
                # collective AFTER the bucket switch: shards may pick different
                # bucket branches, so no psum may live inside them
                small_hist = histogram_source(hist_axis).combine(small_hist)
        else:
            small_mask = (leaf_id == small_idx).astype(f32)
            small_hist = leaf_histogram(
                bins, masked_values(small_mask), B_hist, chunk=chunk,
                axis_name=hist_axis, hist_dtype=hist_dtype,
                feature_sharded=feature_sharded, route=hist_route,
            )
        if bundled:
            if hist_axis is None and axis_name is not None:
                # shard-local histograms: local remap (rec sums are global)
                small_hist = remap_hist_local(small_hist)
            else:
                small_hist = remap_hist(
                    small_hist,
                    jnp.where(left_smaller, rec.left_sum_grad, rec.right_sum_grad),
                    jnp.where(left_smaller, rec.left_sum_hess, rec.right_sum_hess),
                    jnp.where(left_smaller, rec.left_count, rec.right_count),
                )
        def large_direct():
            """Both-children path: the larger child summed from data — the
            reference's use_subtract=false branch (ConstructHistograms,
            serial_tree_learner.cpp:473)."""
            if bucketed:
                lg_begin = jnp.where(left_smaller, pbegin + left_phys, pbegin)
                lg_cnt = jnp.where(left_smaller, right_phys, left_phys)
                h = segment_histogram(order, lg_begin, lg_cnt)
                if hist_axis is not None:
                    h = histogram_source(hist_axis).combine(h)
            else:
                lmask = (leaf_id == large_idx).astype(f32)
                h = leaf_histogram(
                    bins, masked_values(lmask), B_hist, chunk=chunk,
                    axis_name=hist_axis, hist_dtype=hist_dtype,
                    feature_sharded=feature_sharded, route=hist_route,
                )
            if bundled:
                if hist_axis is None and axis_name is not None:
                    h = remap_hist_local(h)
                else:
                    h = remap_hist(
                        h,
                        jnp.where(left_smaller, rec.right_sum_grad, rec.left_sum_grad),
                        jnp.where(left_smaller, rec.right_sum_hess, rec.left_sum_hess),
                        jnp.where(left_smaller, rec.right_count, rec.left_count),
                    )
            return h

        if pooled:
            # HistogramPool::Get: the predicate is identical on every shard
            # (slot state is a pure function of the replicated split sequence),
            # so the collective inside the miss branch executes uniformly.
            pslot = s.slot_of[best_leaf]
            cached = (pslot >= 0) if use_subtract else jnp.asarray(False)
            parent_hist = s.hist[jnp.maximum(pslot, 0)]
            large_hist = jax.lax.cond(
                cached, lambda: parent_hist - small_hist, large_direct
            )
            # slots: the larger child inherits the parent's slot on a hit
            # (the reference's in-place Subtract); otherwise evict the LRU.
            ages = s.slot_age
            slots_iota = jnp.arange(P, dtype=jnp.int32)
            lru0 = jnp.argmin(ages).astype(jnp.int32)
            large_slot = jnp.where(cached, pslot, lru0)
            big = jnp.int32(2**30)
            small_slot = jnp.argmin(
                ages + (slots_iota == large_slot) * big
            ).astype(jnp.int32)
            # invalidate evicted occupants, then map the children
            occ = jnp.stack([s.slot_leaf[large_slot], s.slot_leaf[small_slot]])
            leaves_iota = jnp.arange(M, dtype=jnp.int32)
            slot_of = jnp.where(
                (leaves_iota == occ[0]) | (leaves_iota == occ[1]), -1, s.slot_of
            )
            slot_of = (
                slot_of.at[small_idx].set(small_slot).at[large_idx].set(large_slot)
            )
            slot_pair = jnp.stack([small_slot, large_slot])
            # clear any OTHER slot still mapping to a child (the parent's old
            # slot when a resident parent took the miss path, e.g. the
            # use_subtract=False oracle): a stale entry would later evict as
            # `occ` and wrongly clear the live child's slot_of
            slot_leaf = jnp.where(
                (s.slot_leaf == small_idx) | (s.slot_leaf == large_idx),
                -1,
                s.slot_leaf,
            )
            slot_leaf = slot_leaf.at[slot_pair].set(
                jnp.stack([small_idx, large_idx])
            )
            stamp = s.it + 2  # > the root's stamp of 1; free slots stay 0
            slot_age = ages.at[slot_pair].set(jnp.stack([stamp, stamp]))
            hist = s.hist.at[slot_pair].set(jnp.stack([small_hist, large_hist]))
            child_rows = jnp.stack(
                [
                    jnp.where(left_smaller, small_slot, large_slot),
                    jnp.where(left_smaller, large_slot, small_slot),
                ]
            )
        else:
            parent_hist = s.hist[best_leaf]
            if use_subtract:
                large_hist = parent_hist - small_hist
            else:
                large_hist = large_direct()
            slot_of, slot_leaf, slot_age = s.slot_of, s.slot_leaf, s.slot_age
            # ONE stacked scatter, not two chained .at[].set: XLA updates the
            # [M, F, B, 3] carry in place for a single scatter but inserts a
            # full-buffer copy per chained update (~2 x 22MB per split at
            # M=255/F=28/B=256 — measured 40x slower on CPU, and HBM traffic
            # that would cost ~14ms/iter on TPU)
            hist = s.hist.at[jnp.stack([small_idx, large_idx])].set(
                jnp.stack([small_hist, large_hist])
            )
            child_rows = None  # hist rows ARE leaf rows; set below

        # ---- next-round candidate refresh --------------------------------
        if cegb_on and pooled:
            best = rescan_resident(
                tree, hist, slot_leaf, slot_age, laux, feature_used,
                unused_cnt, s.best, s.feature_used, f,
            )
        elif cegb_on:
            best = _pack_best(
                rescan_all(
                    tree, hist,
                    laux[:, _LAUX_SG], laux[:, _LAUX_SH], laux[:, _LAUX_ND],
                    laux[:, _LAUX_MIN], laux[:, _LAUX_MAX],
                    feature_used, unused_cnt,
                )
            )
        else:
            if child_rows is None:
                child_rows = child_idx  # unpooled: hist rows are leaf rows
            ch_hist = hist[child_rows]  # leaf rows unpooled, slot rows pooled
            ch_aux = laux[child_idx]  # [2, 5]
            ch_split = split2(
                ch_hist, ch_aux[:, _LAUX_SG], ch_aux[:, _LAUX_SH],
                ch_aux[:, _LAUX_ND], ch_aux[:, _LAUX_MIN], ch_aux[:, _LAUX_MAX],
            )
            ch_gain = depth_gate(ch_split.gain, depth_child)
            pb2 = _pack_best(ch_split._replace(gain=ch_gain))
            best = PackedBest(
                s.best.f.at[child_idx].set(pb2.f),
                s.best.i.at[child_idx].set(pb2.i),
                s.best.b.at[child_idx].set(pb2.b),
            )

        return GrowState(
            it=s.it + 1,
            leaf_id=leaf_id,
            tree=tree,
            best=best,
            laux=laux,
            hist=hist,
            feature_used=feature_used,
            unused_cnt=unused_cnt,
            used_in_data=used_in_data,
            order=order,
            leaf_begin=leaf_begin,
            leaf_phys=leaf_phys,
            slot_of=slot_of,
            slot_leaf=slot_leaf,
            slot_age=slot_age,
            spec_flag=s.spec_flag,
            spec_lphys=s.spec_lphys,
            spec_rhist=s.spec_rhist,
        )

    # ---- forced splits preamble (ForceSplits) ---------------------------
    state = state0
    if forced_splits:
        aborted = jnp.asarray(False)
        for (leaf_i, feat_i, thr_i) in forced_splits[: M - 1]:
            if pooled:
                # P >= len(forced_splits)+2 is enforced above, so preamble
                # leaves are never evicted before their forced split applies
                hist_slice = state.hist[
                    jnp.maximum(state.slot_of[leaf_i], 0), feat_i
                ]
            else:
                hist_slice = state.hist[leaf_i, feat_i]
            if axis_name is not None and not psum_hist:
                # voting-parallel keeps shard-local histograms; a forced split
                # needs the global column (the elected-slice psum's little sibling)
                hist_slice = jax.lax.psum(hist_slice, axis_name)
            rec = gather_info_for_threshold(
                hist_slice,
                state.laux[leaf_i, _LAUX_SG],
                state.laux[leaf_i, _LAUX_SH],
                state.laux[leaf_i, _LAUX_ND],
                jnp.int32(thr_i),
                num_bin_arr[feat_i],
                missing_arr[feat_i],
                default_bin_arr[feat_i],
                is_cat_arr[feat_i],
                params,
            )._replace(feature=jnp.int32(feat_i))
            valid = rec.gain > neg_inf
            if max_depth > 0:
                valid &= state.tree.leaf_i[leaf_i, 1] < max_depth
            can = (~aborted) & valid
            applied = apply_split(state, jnp.int32(leaf_i), rec)
            state = jax.tree_util.tree_map(
                lambda a, b: jnp.where(can, a, b), applied, state
            )
            aborted = aborted | ~valid

    # ---- best-gain loop --------------------------------------------------
    def cond(s: GrowState):
        return (s.it < M - 1) & (jnp.max(s.best.f[:, 0]) > 0.0)

    def body(s: GrowState) -> GrowState:
        best_leaf = jnp.argmax(s.best.f[:, 0]).astype(jnp.int32)
        rec = _unpack_best_row(s.best, best_leaf)
        return apply_split(s, best_leaf, rec)

    def body_spec(s: GrowState) -> GrowState:
        """One speculative batch: compute the top-KB candidates' split work
        (skipping slots whose results are cached from an earlier batch),
        apply the longest sequential-order prefix, and CACHE the rest — so
        each split's partition/histogram work happens exactly once no matter
        how often it is speculated."""
        it0 = s.it
        nl0 = s.tree.num_leaves
        kb_iota = jnp.arange(KB, dtype=jnp.int32)

        # top-k by cached gain; lax.top_k breaks ties toward lower indices,
        # matching the sequential argmax's first-max choice
        g_top, b_idx = jax.lax.top_k(s.best.f[:, 0], KB)
        b_top = b_idx.astype(jnp.int32)
        rf = s.best.f[b_top]  # [KB, 9]
        ri = s.best.i[b_top]  # [KB, 3]
        rb = s.best.b[b_top]  # [KB, 1 + B]
        feat, thr = ri[:, 0], ri[:, 1]
        dleft, member = rb[:, 0].astype(bool), rb[:, 1:].astype(bool)
        pbegin = s.leaf_begin[b_top]
        pphys = s.leaf_phys[b_top]
        cached = s.spec_flag[b_top]  # [KB]
        # slots already cached, or with no live split (gain <= 0, incl. the
        # -inf filler the tail of every tree's top-k carries), contribute
        # zero-size segments: the lattice switch keys on the largest slot
        # actually COMPUTING, their lanes carry no histogram mass, and a
        # dead slot's garbage record (feat may be -1) never drives work
        compute = (~cached) & (g_top > 0.0)

        pphys_c = jnp.where(compute, pphys, 0)
        order2, left_phys_c = partition_batch(
            s.order, pbegin, pphys_c, feat, thr, dleft, member
        )
        left_phys = jnp.where(cached, s.spec_lphys[b_top], left_phys_c)
        right_phys = pphys - left_phys

        # smaller-child choice from the GLOBAL counts in the cached record
        # (shard-uniform under shard_map, like the sequential path)
        l_cnt, r_cnt = rf[:, 3], rf[:, 6]
        left_smaller = l_cnt <= r_cnt
        small_begin = jnp.where(left_smaller, pbegin, pbegin + left_phys)
        small_cnt = jnp.where(
            compute, jnp.where(left_smaller, left_phys, right_phys), 0
        )
        small_hist = (
            segment_histogram_flat if use_flat else segment_histogram_batch
        )(order2, small_begin, small_cnt)
        if hist_axis is not None:
            # ONE collective for the whole batch (vs one per split)
            small_hist = histogram_source(hist_axis).combine(small_hist)
        if bundled:
            small_hist = jax.vmap(remap_hist)(
                small_hist,
                jnp.where(left_smaller, rf[:, 1], rf[:, 4]),
                jnp.where(left_smaller, rf[:, 2], rf[:, 5]),
                jnp.where(left_smaller, l_cnt, r_cnt),
            )
        # for a cached slot, hist row b_j already holds the LEFT child's
        # histogram (committed at cache time) and the right child's parks in
        # spec_rhist; for computing slots it still holds the parent's
        parent_hist = s.hist[b_top]
        large_hist = parent_hist - small_hist
        ls4 = left_smaller[:, None, None, None]
        c4 = cached[:, None, None, None]
        lhist = jnp.where(
            c4, parent_hist, jnp.where(ls4, small_hist, large_hist)
        )
        rhist = jnp.where(
            c4, s.spec_rhist[b_top], jnp.where(ls4, large_hist, small_hist)
        )

        # ---- children: aux, monotone windows, one batched scan ----------
        mono_f = mono_arr[feat]
        mid = (rf[:, 7] + rf[:, 8]) * 0.5
        pmin = s.laux[b_top, _LAUX_MIN]
        pmax = s.laux[b_top, _LAUX_MAX]
        l_min = jnp.where(mono_f < 0, mid, pmin)
        l_max = jnp.where(mono_f > 0, mid, pmax)
        r_min = jnp.where(mono_f > 0, mid, pmin)
        r_max = jnp.where(mono_f < 0, mid, pmax)

        ch_hist = jnp.concatenate([lhist, rhist], axis=0)  # [2KB, F, B, 3]
        ch_res = jax.vmap(
            lambda h, sg, sh, nd, mn, mx: find_best_split(
                h, sg, sh, nd, mn, mx, feature_meta, feature_mask, params,
                two_way=two_way,
            )
        )(
            ch_hist,
            jnp.concatenate([rf[:, 1], rf[:, 4]]),
            jnp.concatenate([rf[:, 2], rf[:, 5]]),
            jnp.concatenate([l_cnt, r_cnt]),
            jnp.concatenate([l_min, r_min]),
            jnp.concatenate([l_max, r_max]),
        )
        depth_child = s.tree.leaf_i[b_top, 1] + 1  # [KB]
        ch_gain = depth_gate(
            ch_res.gain, jnp.concatenate([depth_child, depth_child])
        )

        # ---- sequential-prefix validation -------------------------------
        # slot j applies iff (gain, slot) lex-beats every child produced by
        # the batch so far — exactly the argmax order the sequential loop
        # would follow (higher gain wins; equal gain -> lower slot wins).
        gl, gr = ch_gain[:KB], ch_gain[KB:]
        new_slot = nl0 + kb_iota  # child slot ids along the applied prefix
        pair_g = jnp.maximum(gl, gr)
        pair_s = jnp.where(gl >= gr, b_top, new_slot)  # tie -> lower (left)
        big = jnp.int32(2 ** 30)
        run_g, run_s = neg_inf, big
        cm_g, cm_s = [], []
        for j in range(KB):  # exclusive lexicographic running max (tiny)
            cm_g.append(run_g)
            cm_s.append(run_s)
            beats = (pair_g[j] > run_g) | (
                (pair_g[j] == run_g) & (pair_s[j] < run_s)
            )
            run_g = jnp.where(beats, pair_g[j], run_g)
            run_s = jnp.where(beats, pair_s[j], run_s)
        cm_g, cm_s = jnp.stack(cm_g), jnp.stack(cm_s)
        ok = (g_top > cm_g) | ((g_top == cm_g) & (b_top < cm_s))
        valid = (g_top > 0.0) & ok & (it0 + kb_iota < M - 1)
        applied = jnp.cumprod(valid.astype(jnp.int32)).astype(bool)
        p = jnp.sum(applied.astype(jnp.int32))

        # ---- apply the prefix (batched scatters; row M drops) -----------
        drop = jnp.int32(M)
        node_idx = it0 + kb_iota
        nrow = jnp.where(applied, node_idx, drop)
        lrow = jnp.where(applied, b_top, drop)
        rrow = jnp.where(applied, new_slot, drop)
        ch_rows = jnp.concatenate([lrow, rrow])
        # computed-but-unapplied slots with a live split become cache entries
        cache_set = compute & (~applied)
        crow = jnp.where(cache_set, b_top, drop)

        t = s.tree
        # parent pointers: each applied leaf's encoding appears in exactly
        # one existing node row; remap it BEFORE writing the new node rows
        # (whose own left-child encoding is that same value). No write-off
        # row needed: a root split's encoding matches nothing.
        node_ch = t.node_i[:, 2:4]
        for j in range(KB):
            node_ch = jnp.where(
                applied[j] & (node_ch == -(b_top[j] + 1)),
                node_idx[j], node_ch,
            )
        node_i = jnp.concatenate([t.node_i[:, :2], node_ch], axis=1)
        node_i = node_i.at[nrow].set(
            jnp.stack([feat, thr, -(b_top + 1), -(new_slot + 1)], axis=1)
        )
        parent_aux = s.laux[b_top]  # [KB, 5]
        parent_value = calculate_leaf_output(
            parent_aux[:, _LAUX_SG], parent_aux[:, _LAUX_SH], params
        )
        tree = PackedTree(
            num_leaves=nl0 + p,
            node_f=t.node_f.at[nrow].set(
                jnp.stack(
                    [rf[:, 0], parent_value, parent_aux[:, _LAUX_ND]], axis=1
                )
            ),
            node_i=node_i,
            node_b=t.node_b.at[nrow].set(rb.astype(bool)),
            leaf_f=t.leaf_f.at[ch_rows].set(
                jnp.concatenate([
                    jnp.stack([rf[:, 7], rf[:, 3], rf[:, 2]], axis=1),
                    jnp.stack([rf[:, 8], rf[:, 6], rf[:, 5]], axis=1),
                ])
            ),
            leaf_i=t.leaf_i.at[ch_rows].set(
                jnp.concatenate(
                    [jnp.stack([node_idx, depth_child], axis=1)] * 2
                )
            ),
        )
        laux = s.laux.at[ch_rows].set(
            jnp.concatenate([
                jnp.stack([rf[:, 1], rf[:, 2], rf[:, 3], l_min, l_max], axis=1),
                jnp.stack([rf[:, 4], rf[:, 5], rf[:, 6], r_min, r_max], axis=1),
            ])
        )
        leaf_begin = s.leaf_begin.at[rrow].set(pbegin + left_phys)
        leaf_phys = s.leaf_phys.at[ch_rows].set(
            jnp.concatenate([left_phys, right_phys])
        )
        # the LEFT child's histogram lands in row b_j both on apply and on
        # cache (the parent histogram there is dead once its children are
        # built); the right child's goes to its new slot on apply, or parks
        # in spec_rhist keyed by the parent on cache
        lrow_hist = jnp.where(applied | cache_set, b_top, drop)
        hist = s.hist.at[jnp.concatenate([lrow_hist, rrow])].set(
            jnp.concatenate([lhist, rhist])
        )
        spec_rhist = s.spec_rhist.at[crow].set(rhist)
        spec_lphys = s.spec_lphys.at[crow].set(left_phys)
        spec_flag = (
            s.spec_flag.at[crow].set(True)
            .at[lrow].set(False)  # applied: children start uncached
            .at[rrow].set(False)
        )
        pb2 = _pack_best(ch_res._replace(gain=ch_gain))  # [2KB, ...]
        best = PackedBest(
            s.best.f.at[ch_rows].set(pb2.f),
            s.best.i.at[ch_rows].set(pb2.i),
            s.best.b.at[ch_rows].set(pb2.b),
        )
        return GrowState(
            it=it0 + p,
            leaf_id=s.leaf_id,
            tree=tree,
            best=best,
            laux=laux,
            hist=hist,
            feature_used=s.feature_used,
            unused_cnt=s.unused_cnt,
            used_in_data=s.used_in_data,
            order=order2,
            leaf_begin=leaf_begin,
            leaf_phys=leaf_phys,
            slot_of=s.slot_of,
            slot_leaf=s.slot_leaf,
            slot_age=s.slot_age,
            spec_flag=spec_flag,
            spec_lphys=spec_lphys,
            spec_rhist=spec_rhist,
        )

    if M > 1:
        final = jax.lax.while_loop(cond, body_spec if KB else body, state)
    else:
        final = state

    if bucketed:
        # reconstruct per-row leaf ids from the segment layout: position ->
        # owning segment (empty leaves keyed past N so they claim nothing),
        # then scatter through the permutation.
        key = jnp.where(
            final.leaf_phys > 0,
            final.leaf_begin,
            N + jnp.arange(M, dtype=jnp.int32),
        )
        ordl = jnp.argsort(key)
        slot = jnp.searchsorted(key[ordl], jnp.arange(N, dtype=jnp.int32), side="right") - 1
        pos_leaf = ordl[jnp.clip(slot, 0, M - 1)].astype(jnp.int32)
        out_leaf_id = jnp.zeros((N,), jnp.int32).at[final.order].set(pos_leaf)
    else:
        out_leaf_id = final.leaf_id

    out = (_unpack_tree(final.tree, M), out_leaf_id)
    if cegb_on:
        out = out + ((final.feature_used, final.used_in_data),)
    if hist_buf is not None:
        out = out + (final.hist,)  # aliases the donated buffer (zero-copy)
    if spec_buf is not None:
        # aliased like hist: the caller re-adopts it for the next tree. A
        # seq-mode trace (KB == 0) hands the untouched donation back so the
        # donated input still has an aliasable output.
        out = out + (final.spec_rhist if KB else spec_buf,)
    return out


# Scan-invocable entry: the UNDECORATED grow body, for embedding inside an
# outer jit — the device-resident boosting loop (models/gbdt.py train_chunk)
# calls it from a lax.scan body, where the grow must trace into the caller's
# program instead of standing alone behind its own jit/donation boundary.
# jax.jit preserves the wrapped function via functools.wraps; every "static"
# argument is then an ordinary Python value closed over at trace time, and
# ``hist_buf`` donation does not apply (pass None — XLA reuses the per-
# iteration scratch across scan steps on its own).
grow_tree_scan = grow_tree.__wrapped__
