"""Vectorized best-split search over leaf histograms.

TPU-native counterpart of FeatureHistogram's per-feature threshold scans
(/root/reference/src/treelearner/feature_histogram.hpp:91-650). The reference walks
each feature's bins twice (right-to-left then left-to-right) with early-exit
branches; here both directions become cumulative sums over the bin axis for ALL
features at once, with every constraint (min_data_in_leaf, min_sum_hessian_in_leaf,
min_gain_to_split, L1/L2, max_delta_step, monotone clamps, missing-value bin
exclusions) expressed as masks — no data-dependent control flow, so the whole scan
jits into one fused XLA program.

Semantics preserved exactly (including kEpsilon placements, feature_histogram.hpp:87
and the scan accumulator seeds, and scan-order tie-breaking):

 * missing_type None (or num_bin<=2): single right-to-left scan, default_left=True
   (flipped to False when missing_type is NaN and num_bin<=2).
 * missing_type Zero: both scans skip the default(zero) bin — its mass lands on the
   complement side, i.e. zeros follow the default direction.
 * missing_type NaN: the last bin is the NaN bin; it is excluded from explicit
   accumulation so NaNs follow the default direction.
 * dir=-1 prefers the largest threshold among equal gains, dir=+1 the smallest, and
   dir=+1 must strictly beat dir=-1 (strict '>' updates in the reference loops).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15  # meta.h:42
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static split hyperparameters (subset of Config used by the scan)."""

    lambda_l1: float
    lambda_l2: float
    max_delta_step: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float
    # categorical split knobs (config.h:510-540); trailing defaults keep older
    # positional constructions working
    max_cat_to_onehot: int = 4
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    min_data_per_group: int = 100


class CegbParams(NamedTuple):
    """Static CEGB (cost-effective gradient boosting) switches (config.h:389-405).

    The per-feature penalty vectors travel in ``feature_meta`` as
    ``cegb_coupled``/``cegb_lazy`` [F]; these flags gate the (costly) per-leaf
    rescan path in the grower.
    """

    tradeoff: float = 1.0
    penalty_split: float = 0.0
    has_coupled: bool = False
    has_lazy: bool = False

    @property
    def enabled(self) -> bool:
        return self.penalty_split != 0.0 or self.has_coupled or self.has_lazy


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """ThresholdL1 (feature_histogram.hpp:446)."""
    if l1 == 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, p: SplitParams):
    """CalculateSplittedLeafOutput without monotone clamp (feature_histogram.hpp:451)."""
    ret = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + p.lambda_l2)
    if p.max_delta_step > 0.0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    return ret


def _leaf_output_constrained(sum_grad, sum_hess, p: SplitParams, min_c, max_c):
    return jnp.clip(calculate_leaf_output(sum_grad, sum_hess, p), min_c, max_c)


def _gain_given_output(sum_grad, sum_hess, output, p: SplitParams):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:505)."""
    sg_l1 = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg_l1 * output + (sum_hess + p.lambda_l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, p: SplitParams):
    """GetLeafSplitGain (feature_histogram.hpp:498): parent gain, unconstrained."""
    out = calculate_leaf_output(sum_grad, sum_hess, p)
    return _gain_given_output(sum_grad, sum_hess, out, p)


class SplitResult(NamedTuple):
    gain: jax.Array  # scalar f32, already minus gain_shift; <=0 means no split
    feature: jax.Array  # int32 index into used features; -1 if none
    threshold: jax.Array  # int32 bin threshold (left: bin <= threshold)
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    # categorical bitset split (SplitInfo::cat_threshold, split_info.hpp):
    # num_cat = 0 for numerical; >= 1 means "row goes left iff its bin is a
    # member of cat_bitset" (CategoricalDecisionInner, tree.h:275)
    num_cat: Any = 0  # scalar int32
    cat_bitset: Any = False  # [B] bool bin membership


def _bin_prefix(contrib: jax.Array) -> jax.Array:
    """Inclusive prefix over the bin axis (axis=1 of [..., B, 3]).

    On CPU this is a lax.scan left fold — the same sequential accumulation
    order as the reference's per-bin loops, and ~2x faster than XLA:CPU's
    O(B^2) reduce-window lowering of cumsum. Elsewhere (TPU) a 256-step
    sequential scan would serialize, so jnp.cumsum's reduce-window stays.
    The two differ by ~1ulp of f32 reassociation; each backend is
    self-consistent, which is what the dense-vs-EFB tree-equality tests
    require (any mixed-order scheme flips argmax tie-breaks — a reassociated
    associative_scan measurably broke tests/test_sparse_efb.py).

    The choice keys off the PROCESS-DEFAULT backend at trace time, not the
    computation's actual placement: a CPU-placed grow in a TPU-default
    process traces the reduce-window path (correct, just without the CPU
    speedup). Per-process platform pinning — what tests/conftest.py and the
    bench worker do — is the supported way to select the CPU fold.
    """
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if backend != "cpu":
        return jnp.cumsum(contrib, axis=1)
    xs = jnp.moveaxis(contrib, 1, 0)

    def step(carry, row):
        carry = carry + row
        return carry, carry

    _, ys = jax.lax.scan(step, jnp.zeros_like(xs[0]), xs)
    return jnp.moveaxis(ys, 0, 1)


def missing_flags(num_bin, missing):
    """(multi_bin, use_na, skip_def, single_scan) per feature — the
    missing-direction scan selectors shared by the XLA scan and the Pallas
    split kernel (split_pallas.py)."""
    multi_bin = num_bin > 2
    use_na = (missing == MISSING_NAN) & multi_bin
    skip_def = (missing == MISSING_ZERO) & multi_bin
    return multi_bin, use_na, skip_def, ~(use_na | skip_def)


def excluded_bins(bins, num_bin, default_bin, use_na, skip_def):
    """[F, B] mask of bins excluded from explicit accumulation (padding,
    the zero bin under missing=Zero, the NaN bin under missing=NaN)."""
    nan_bin = (num_bin - 1)[:, None]
    excl = bins >= num_bin[:, None]
    excl |= skip_def[:, None] & (bins == default_bin[:, None])
    excl |= use_na[:, None] & (bins == nan_bin)
    return excl


def candidate_gains(
    lg, lh, rg, rh, lc, rc, valid, mono_b, min_c, max_c, min_gain_shift, p
):
    """Masked split gains for one scan direction. Broadcast-polymorphic:
    the XLA scan calls it at [F, B] with scalar constraints, the Pallas
    kernel at [2, F, B] with [2, 1, 1] constraints — all reference gates
    (min_data/min_hess/monotone/min_gain, feature_histogram.hpp:91-650)
    live HERE exactly once."""
    ok = (
        valid
        & (lc >= p.min_data_in_leaf)
        & (rc >= p.min_data_in_leaf)
        & (lh >= p.min_sum_hessian_in_leaf)
        & (rh >= p.min_sum_hessian_in_leaf)
    )
    lo = _leaf_output_constrained(lg, lh, p, min_c, max_c)
    ro = _leaf_output_constrained(rg, rh, p, min_c, max_c)
    g = _gain_given_output(lg, lh, lo, p) + _gain_given_output(rg, rh, ro, p)
    mono_bad = ((mono_b > 0) & (lo > ro)) | ((mono_b < 0) & (lo < ro))
    g = jnp.where(mono_bad, 0.0, g)
    ok &= g > min_gain_shift
    return jnp.where(ok, g, K_MIN_SCORE)


def valid_pos_mask(thresholds, num_bin_b, default_bin_b, skip_def_b, not_single_b):
    """dir=+1 candidate validity (runs only for missing-handling scans)."""
    v = thresholds <= (num_bin_b - 2)
    v &= ~(skip_def_b & (thresholds == default_bin_b))
    return v & not_single_b


def valid_neg_mask(thresholds, num_bin_b, default_bin_b, skip_def_b, use_na_b):
    """dir=-1 candidate validity (excludes the NaN bin's threshold)."""
    v = thresholds <= (num_bin_b - 2 - use_na_b.astype(jnp.int32))
    return v & ~(skip_def_b & (thresholds == default_bin_b - 1))


class _ScanOut(NamedTuple):
    """Per-feature best candidates + side-sum arrays for recovery."""

    g_best: jax.Array  # [F]
    t_best: jax.Array  # [F]
    dl_best: jax.Array  # [F]
    use_pos: jax.Array  # [F]
    is_cat: jax.Array  # [F]
    lg_pos: jax.Array  # [F, B]
    lh_pos: jax.Array
    lc_pos: jax.Array
    lg_neg: jax.Array
    lh_neg: jax.Array
    lc_neg: jax.Array
    # categorical best per feature (already reduced over candidates)
    cat_lg: jax.Array  # [F] left sums of the best categorical candidate
    cat_lh: jax.Array  # [F] (includes +kEpsilon)
    cat_lc: jax.Array  # [F]
    cat_member: jax.Array  # [F, B] bool: left-side bin membership
    cat_ncat: jax.Array  # [F] int32 number of categories on the left
    cat_use_ctr: jax.Array  # [F] bool: True when the CTR path (cat_l2) won
    min_gain_shift: jax.Array


def _scan_candidates(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count)
    sum_grad: jax.Array,  # leaf totals (scalars)
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,  # monotone constraint window for this leaf
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],  # num_bin/missing_type/default_bin/monotone [F]
    params: SplitParams,
    two_way: bool = True,
) -> _ScanOut:
    """Per-feature threshold scan; the shared core of find_best_split and the
    voting-parallel local stage (voting_parallel_tree_learner.cpp:337).

    ``two_way=False`` is a trace-time guarantee that every feature is
    single-scan (missing_type None or num_bin<=2), so the dir=+1 pass — whose
    candidates would all be masked invalid anyway — is skipped entirely.
    Results are identical to ``two_way=True`` whenever the guarantee holds
    (differentially tested in tests/test_micro_exact.py)."""
    F, B, _ = hist.shape
    p = params
    num_bin = feature_meta["num_bin"].astype(jnp.int32)  # [F]
    missing = feature_meta["missing_type"].astype(jnp.int32)
    default_bin = feature_meta["default_bin"].astype(jnp.int32)
    mono = feature_meta["monotone"].astype(jnp.int32)

    sum_hess_eff = sum_hess + 2 * K_EPSILON  # feature_histogram.hpp:87

    gain_shift = leaf_split_gain(sum_grad, sum_hess_eff, p)
    min_gain_shift = gain_shift + p.min_gain_to_split

    multi_bin, use_na, skip_def, single_scan = missing_flags(num_bin, missing)

    bins = jnp.arange(B, dtype=jnp.int32)[None, :]  # [1, B]
    excl = excluded_bins(bins, num_bin, default_bin, use_na, skip_def)
    contrib = hist * (~excl)[:, :, None].astype(hist.dtype)  # [F, B, 3]

    prefix = _bin_prefix(contrib)
    total = prefix[:, -1, :]  # [F, 3] sums over included bins

    thresholds = jnp.arange(B, dtype=jnp.int32)[None, :]  # threshold t -> left bins <= t

    def side_stats(left_g, left_h_raw, left_c):
        left_h = left_h_raw + K_EPSILON
        right_g = sum_grad - left_g
        right_h = sum_hess_eff - left_h
        right_c = num_data - left_c
        return left_h, right_g, right_h, right_c

    def gains_for(left_g, left_h, right_g, right_h, left_c, right_c, valid):
        return candidate_gains(
            left_g, left_h, right_g, right_h, left_c, right_c, valid,
            mono[:, None], min_constraint, max_constraint, min_gain_shift, p,
        )

    # ---- dir = +1 (left-to-right; default_left = False) ------------------
    lg_pos = prefix[:, :, 0]
    lh_pos_raw = prefix[:, :, 1]
    lc_pos = prefix[:, :, 2]
    lh_pos, rg_pos, rh_pos, rc_pos = side_stats(lg_pos, lh_pos_raw, lc_pos)
    if two_way:
        valid_pos = valid_pos_mask(
            thresholds, num_bin[:, None], default_bin[:, None],
            skip_def[:, None], (~single_scan)[:, None],
        )
        gains_pos = gains_for(lg_pos, lh_pos, rg_pos, rh_pos, lc_pos, rc_pos, valid_pos)
    else:
        gains_pos = None  # every candidate would be masked invalid

    # ---- dir = -1 (right-to-left; default_left = True) -------------------
    rg_neg_raw = total[:, None, 0] - prefix[:, :, 0]
    rh_neg_raw = total[:, None, 1] - prefix[:, :, 1]
    rc_neg = total[:, None, 2] - prefix[:, :, 2]
    rh_neg = rh_neg_raw + K_EPSILON
    lg_neg = sum_grad - rg_neg_raw
    lh_neg = sum_hess_eff - rh_neg
    lc_neg = num_data - rc_neg
    valid_neg = valid_neg_mask(
        thresholds, num_bin[:, None], default_bin[:, None],
        skip_def[:, None], use_na[:, None],
    )
    gains_neg = gains_for(lg_neg, lh_neg, rg_neg_raw, rh_neg, lc_neg, rc_neg, valid_neg)

    # ---- categorical candidates -----------------------------------------
    # FindBestThresholdCategorical (feature_histogram.hpp:118-279). Features
    # with num_bin <= max_cat_to_onehot use the one-hot branch (left = one
    # bin); the rest use the CTR-sorted many-vs-many branch: bins with count
    # >= cat_smooth, sorted by sum_grad/(sum_hess+cat_smooth), scanned from
    # both ends with cat_l2 regularization and min_data_per_group grouping.
    is_cat = feature_meta.get("is_categorical")
    has_cat = is_cat is not None  # key presence = static trace-time switch
    if not has_cat:
        is_cat = jnp.zeros((F,), bool)
        zf = jnp.zeros((F,), hist.dtype)
        cat_lg = cat_lh = cat_lc = zf
        cat_member = jnp.zeros((F, B), bool)
        cat_ncat = jnp.zeros((F,), jnp.int32)
        cat_use_ctr = jnp.zeros((F,), bool)
        g_cat = jnp.full((F,), K_MIN_SCORE, hist.dtype)
        t_cat = jnp.zeros((F,), jnp.int32)
    else:
        is_cat = is_cat.astype(bool)
        used_bin = num_bin + jnp.where(missing == MISSING_NONE, 0, -1)  # [F]

        # one-hot branch: left = the single bin t, right = rest; default_left=False
        oh_lg = hist[:, :, 0]
        oh_lh_raw = hist[:, :, 1]
        oh_lc = hist[:, :, 2]
        oh_lh = oh_lh_raw + K_EPSILON
        oh_rg = sum_grad - oh_lg
        oh_rh = sum_hess_eff - oh_lh
        oh_rc = num_data - oh_lc
        oh_valid = thresholds < used_bin[:, None]
        oh_valid &= (oh_lc >= p.min_data_in_leaf) & (oh_rc >= p.min_data_in_leaf)
        oh_valid &= (oh_lh_raw >= p.min_sum_hessian_in_leaf) & (
            oh_rh >= p.min_sum_hessian_in_leaf
        )
        oh_lo = _leaf_output_constrained(oh_lg, oh_lh, p, min_constraint, max_constraint)
        oh_ro = _leaf_output_constrained(oh_rg, oh_rh, p, min_constraint, max_constraint)
        oh_g = _gain_given_output(oh_lg, oh_lh, oh_lo, p) + _gain_given_output(
            oh_rg, oh_rh, oh_ro, p
        )
        oh_valid &= oh_g > min_gain_shift
        gains_oh = jnp.where(oh_valid, oh_g, K_MIN_SCORE)
        t_oh = jnp.argmax(gains_oh, axis=1).astype(jnp.int32)  # smallest t wins ties
        g_oh = jnp.take_along_axis(gains_oh, t_oh[:, None], axis=1)[:, 0]
        oh_sel = t_oh[:, None]
        oh_best_lg = jnp.take_along_axis(oh_lg, oh_sel, axis=1)[:, 0]
        oh_best_lh = jnp.take_along_axis(oh_lh, oh_sel, axis=1)[:, 0]
        oh_best_lc = jnp.take_along_axis(oh_lc, oh_sel, axis=1)[:, 0]

        # CTR-sorted branch (cat_l2 folded into l2 for gains AND leaf outputs)
        p_cat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)
        cnt_b = hist[:, :, 2]
        bin_valid = (bins < used_bin[:, None]) & (cnt_b >= p.cat_smooth)  # [F, B]
        ctr = hist[:, :, 0] / (hist[:, :, 1] + p.cat_smooth)
        sort_idx = jnp.argsort(jnp.where(bin_valid, ctr, jnp.inf), axis=1)  # [F, B]
        rank = jnp.argsort(sort_idx, axis=1)  # inverse permutation: bin -> position
        used_ctr = jnp.sum(bin_valid, axis=1).astype(jnp.int32)  # [F]
        max_num_cat = jnp.minimum(p.max_cat_threshold, (used_ctr + 1) // 2)  # [F]
        i_pos = jnp.arange(B, dtype=jnp.int32)[None, :]

        hist_sorted = jnp.take_along_axis(hist, sort_idx[:, :, None], axis=1)

        def _ctr_dir(h_dir):
            """Candidate gains for one traversal direction over the sorted bins.

            ``h_dir`` is [F, B, 3] in traversal order; candidate i takes the first
            i+1 bins as the left side. min_data_per_group grouping is sequential
            (the group counter resets only on an emitted candidate) -> lax.scan.
            """
            pref = _bin_prefix(h_dir)  # one scan for all 3 channels
            lg = pref[:, :, 0]
            lh = pref[:, :, 1] + K_EPSILON
            lc = pref[:, :, 2]
            rg = sum_grad - lg
            rh = sum_hess - lh
            rc = num_data - lc
            left_ok = (lc >= p.min_data_in_leaf) & (lh >= p.min_sum_hessian_in_leaf)
            right_ok = (
                (rc >= p.min_data_in_leaf)
                & (rc >= p.min_data_per_group)
                & (rh >= p.min_sum_hessian_in_leaf)
            )

            def step(gcnt, x):
                c_i, ok_i = x
                gcnt = gcnt + c_i
                emit = ok_i & (gcnt >= p.min_data_per_group)
                return jnp.where(emit, 0.0, gcnt), emit

            _, emit = jax.lax.scan(
                step,
                jnp.zeros((F,), hist.dtype),
                (h_dir[:, :, 2].T, (left_ok & right_ok).T),
            )
            emit = emit.T  # [F, B]
            lo = _leaf_output_constrained(lg, lh, p_cat, min_constraint, max_constraint)
            ro = _leaf_output_constrained(rg, rh, p_cat, min_constraint, max_constraint)
            g = _gain_given_output(lg, lh, lo, p_cat) + _gain_given_output(
                rg, rh, ro, p_cat
            )
            ok = emit & (i_pos < used_ctr[:, None]) & (i_pos < max_num_cat[:, None])
            ok &= g > min_gain_shift
            return jnp.where(ok, g, K_MIN_SCORE), lg, lh, lc

        g_fwd, lg_fwd, lh_fwd, lc_fwd = _ctr_dir(hist_sorted)
        # reverse traversal starts at sorted position used_ctr-1 and walks down
        rev_pos = jnp.clip(used_ctr[:, None] - 1 - i_pos, 0, B - 1)
        g_rev, lg_rev, lh_rev, lc_rev = _ctr_dir(
            jnp.take_along_axis(hist_sorted, rev_pos[:, :, None], axis=1)
        )
        # candidate order = (dir=+1, i asc) then (dir=-1, i asc), strict-> updates:
        # first max of the concatenation reproduces the reference's tie-breaking
        g_all = jnp.concatenate([g_fwd, g_rev], axis=1)  # [F, 2B]
        j_best = jnp.argmax(g_all, axis=1).astype(jnp.int32)
        g_ctr = jnp.take_along_axis(g_all, j_best[:, None], axis=1)[:, 0]
        fwd_won = j_best < B
        i_best = jnp.where(fwd_won, j_best, j_best - B)
        i_sel = i_best[:, None]

        def _pick_dir(a_fwd, a_rev):
            return jnp.where(
                fwd_won,
                jnp.take_along_axis(a_fwd, i_sel, axis=1)[:, 0],
                jnp.take_along_axis(a_rev, i_sel, axis=1)[:, 0],
            )

        ctr_lg = _pick_dir(lg_fwd, lg_rev)
        ctr_lh = _pick_dir(lh_fwd, lh_rev)
        ctr_lc = _pick_dir(lc_fwd, lc_rev)
        member_ctr = jnp.where(
            fwd_won[:, None],
            rank <= i_sel,
            rank >= (used_ctr[:, None] - 1 - i_sel),
        ) & bin_valid

        # per-feature winner: one-hot vs CTR is decided by num_bin, not by gain
        use_onehot = num_bin <= p.max_cat_to_onehot  # [F]
        g_cat = jnp.where(use_onehot, g_oh, g_ctr)
        t_cat = jnp.where(use_onehot, t_oh, i_best)
        cat_member = jnp.where(use_onehot[:, None], bins == t_oh[:, None], member_ctr)
        cat_ncat = jnp.where(use_onehot, 1, i_best + 1).astype(jnp.int32)
        cat_lg = jnp.where(use_onehot, oh_best_lg, ctr_lg)
        cat_lh = jnp.where(use_onehot, oh_best_lh, ctr_lh)
        cat_lc = jnp.where(use_onehot, oh_best_lc, ctr_lc)
        cat_use_ctr = ~use_onehot

    # ---- per-feature best with scan-order tie-breaking -------------------
    # dir=-1 prefers the LARGEST threshold among equal gains.
    t_neg_rev = jnp.argmax(gains_neg[:, ::-1], axis=1)
    t_neg = B - 1 - t_neg_rev
    g_neg = jnp.take_along_axis(gains_neg, t_neg[:, None], axis=1)[:, 0]
    if two_way:
        # dir=+1 prefers the smallest threshold; must strictly beat dir=-1.
        t_pos = jnp.argmax(gains_pos, axis=1)
        g_pos = jnp.take_along_axis(gains_pos, t_pos[:, None], axis=1)[:, 0]
        use_pos = g_pos > g_neg
        g_best = jnp.where(use_pos, g_pos, g_neg)
        t_best = jnp.where(use_pos, t_pos, t_neg)
    else:
        use_pos = jnp.zeros((F,), bool)
        g_best = g_neg
        t_best = t_neg
    dl_best = ~use_pos  # default_left = (dir == -1)
    # 2-bin NaN features keep default_left=False (feature_histogram.hpp:108-111)
    two_bin_nan = (missing == MISSING_NAN) & ~multi_bin
    dl_best = jnp.where(two_bin_nan, False, dl_best)

    # categorical features use the categorical candidates exclusively
    g_best = jnp.where(is_cat, g_cat, g_best)
    t_best = jnp.where(is_cat, t_cat, t_best)
    dl_best = jnp.where(is_cat, False, dl_best)
    use_pos = jnp.where(is_cat, True, use_pos)  # pick() reads the prefix arrays

    return _ScanOut(
        g_best=g_best,
        t_best=t_best,
        dl_best=dl_best,
        use_pos=use_pos,
        is_cat=is_cat,
        lg_pos=lg_pos,
        lh_pos=lh_pos,
        lc_pos=lc_pos,
        lg_neg=lg_neg,
        lh_neg=lh_neg,
        lc_neg=lc_neg,
        cat_lg=cat_lg,
        cat_lh=cat_lh,
        cat_lc=cat_lc,
        cat_member=cat_member,
        cat_ncat=cat_ncat,
        cat_use_ctr=cat_use_ctr,
        min_gain_shift=min_gain_shift,
    )


def gather_info_for_threshold(
    hist_f: jax.Array,  # [B, 3] one feature's histogram
    sum_grad: jax.Array,
    sum_hess: jax.Array,
    num_data: jax.Array,
    threshold: jax.Array,  # bin threshold (int32 scalar)
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    is_cat: jax.Array,
    params: SplitParams,
) -> SplitResult:
    """SplitInfo for a FORCED (feature, threshold) split
    (FeatureHistogram::GatherInfoForThreshold, feature_histogram.hpp:281-420).

    Numerical: right side = bins in [max(threshold,1), last real bin], skipping
    the default bin when missing=Zero and the NaN bin when missing=NaN;
    default_left=True. Categorical one-hot: left side = the single bin;
    default_left=False. Gain <= min_gain_shift yields -inf (the caller skips the
    forced split and aborts the rest of its BFS, serial_tree_learner.cpp:666).
    """
    p = params
    B = hist_f.shape[0]
    bins = jnp.arange(B, dtype=jnp.int32)
    use_na = missing_type == MISSING_NAN
    skip_def = missing_type == MISSING_ZERO

    gain_shift = leaf_split_gain(sum_grad, sum_hess, p)
    min_gain_shift = gain_shift + p.min_gain_to_split

    # ---- numerical ------------------------------------------------------
    right_mask = (bins >= jnp.maximum(threshold, 1)) & (bins <= num_bin - 1 - use_na)
    right_mask &= ~(skip_def & (bins == default_bin))
    rm = right_mask.astype(hist_f.dtype)[:, None]
    right = jnp.sum(hist_f * rm, axis=0)  # [3]
    num_rg, num_rh, num_rc = right[0], right[1] + K_EPSILON, right[2]
    num_lg = sum_grad - num_rg
    num_lh = sum_hess - num_rh
    num_lc = num_data - num_rc

    # ---- categorical one-hot -------------------------------------------
    left_mask = (bins == threshold).astype(hist_f.dtype)[:, None]
    cleft = jnp.sum(hist_f * left_mask, axis=0)
    cat_lg, cat_lh, cat_lc = cleft[0], cleft[1] + K_EPSILON, cleft[2]
    used_bin = num_bin + jnp.where(missing_type == MISSING_NONE, 0, -1)
    cat_ok = threshold < used_bin

    lg = jnp.where(is_cat, cat_lg, num_lg)
    lh = jnp.where(is_cat, cat_lh, num_lh)
    lc = jnp.where(is_cat, cat_lc, num_lc)
    rg = sum_grad - lg
    rh = sum_hess - lh
    rc = num_data - lc

    current_gain = leaf_split_gain(lg, lh, p) + leaf_split_gain(rg, rh, p)
    ok = (current_gain > min_gain_shift) & jnp.where(is_cat, cat_ok, True)
    ok &= ~jnp.isnan(current_gain)

    left_out = calculate_leaf_output(lg, lh, p)
    right_out = calculate_leaf_output(rg, rh, p)
    gain = jnp.where(ok, current_gain - min_gain_shift, K_MIN_SCORE)
    return SplitResult(
        gain=gain.astype(jnp.float32),
        feature=jnp.int32(-1),  # caller fills the (static) feature index
        threshold=threshold.astype(jnp.int32),
        default_left=jnp.where(is_cat, False, True),
        left_sum_grad=lg,
        left_sum_hess=lh - K_EPSILON,
        left_count=lc,
        right_sum_grad=rg,
        right_sum_hess=rh - K_EPSILON,
        right_count=rc,
        left_output=left_out,
        right_output=right_out,
        num_cat=jnp.where(is_cat, 1, 0).astype(jnp.int32),
        cat_bitset=bins == threshold,
    )


def per_feature_best_gain(
    hist: jax.Array,
    sum_grad: jax.Array,
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],
    feature_mask: jax.Array,
    params: SplitParams,
    two_way: bool = True,
) -> jax.Array:
    """[F] best gain per feature (-inf where none) — the voting-parallel
    local-voting stage (LightSplitInfo gains, voting_parallel_tree_learner.cpp:337)."""
    sc = _scan_candidates(
        hist, sum_grad, sum_hess, num_data, min_constraint, max_constraint,
        feature_meta, params, two_way=two_way,
    )
    return jnp.where(feature_mask, sc.g_best, K_MIN_SCORE)


@functools.partial(jax.jit, static_argnames=("params", "two_way"))
def find_best_split(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count)
    sum_grad: jax.Array,  # leaf totals (scalars)
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,  # monotone constraint window for this leaf
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],  # num_bin/missing_type/default_bin/monotone [F]
    feature_mask: jax.Array,  # [F] bool: feature_fraction sample & usable
    params: SplitParams,
    penalty: Any = None,  # optional [F] CEGB gain penalty per feature
    two_way: bool = True,
) -> SplitResult:
    """Best split for one leaf across all features (FindBestThresholdNumerical)."""
    p = params
    sum_hess_eff = sum_hess + 2 * K_EPSILON  # feature_histogram.hpp:87
    sc = _scan_candidates(
        hist, sum_grad, sum_hess, num_data, min_constraint, max_constraint,
        feature_meta, params, two_way=two_way,
    )
    (g_best, t_best, dl_best, use_pos, is_cat) = (
        sc.g_best, sc.t_best, sc.dl_best, sc.use_pos, sc.is_cat,
    )
    (lg_pos, lh_pos, lc_pos, lg_neg, lh_neg, lc_neg) = (
        sc.lg_pos, sc.lh_pos, sc.lc_pos, sc.lg_neg, sc.lh_neg, sc.lc_neg,
    )
    min_gain_shift = sc.min_gain_shift

    g_best = jnp.where(feature_mask, g_best, K_MIN_SCORE)
    if penalty is not None:
        # CEGB: penalties land on the shifted gain (serial_tree_learner.cpp:537-543),
        # i.e. after min_gain_shift subtraction; shift them into the raw scale here
        # so the argmax and the final reported gain both see penalized values.
        g_best = g_best - penalty

    best_f = jnp.argmax(g_best)  # first max wins ties (feature index order)
    best_gain_raw = g_best[best_f]
    best_t = t_best[best_f]
    best_dl = dl_best[best_f]
    has_split = best_gain_raw > K_MIN_SCORE

    # Recover the chosen candidate's side sums.
    has_cat = "is_categorical" in feature_meta  # static: no cat -> no cat code
    best_is_cat = is_cat[best_f] if has_cat else jnp.asarray(False)

    def pick(arr_pos, arr_neg, cat_v):
        pos_v = arr_pos[best_f, best_t]
        neg_v = arr_neg[best_f, best_t]
        num_v = jnp.where(use_pos[best_f], pos_v, neg_v)
        return jnp.where(best_is_cat, cat_v, num_v) if has_cat else num_v

    left_g = pick(lg_pos, lg_neg, sc.cat_lg[best_f])
    left_h = pick(lh_pos, lh_neg, sc.cat_lh[best_f])  # includes +eps
    left_c = pick(lc_pos, lc_neg, sc.cat_lc[best_f])
    right_g = sum_grad - left_g
    right_h = sum_hess_eff - left_h
    right_c = num_data - left_c

    left_out = _leaf_output_constrained(left_g, left_h, p, min_constraint, max_constraint)
    right_out = _leaf_output_constrained(right_g, right_h, p, min_constraint, max_constraint)
    if has_cat and p.cat_l2 != 0.0:
        # the CTR branch regularizes leaf outputs with lambda_l2 + cat_l2
        # (feature_histogram.hpp:246-255 passes the augmented l2)
        p_cat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)
        use_ctr = best_is_cat & sc.cat_use_ctr[best_f]
        left_out = jnp.where(
            use_ctr,
            _leaf_output_constrained(left_g, left_h, p_cat, min_constraint, max_constraint),
            left_out,
        )
        right_out = jnp.where(
            use_ctr,
            _leaf_output_constrained(right_g, right_h, p_cat, min_constraint, max_constraint),
            right_out,
        )

    gain = jnp.where(has_split, best_gain_raw - min_gain_shift, K_MIN_SCORE)
    B = hist.shape[1]
    bins_r = jnp.arange(B, dtype=jnp.int32)
    if has_cat:
        num_cat = jnp.where(best_is_cat, sc.cat_ncat[best_f], 0)
        cat_bitset = jnp.where(best_is_cat, sc.cat_member[best_f], bins_r == best_t)
    else:
        num_cat = jnp.int32(0)
        cat_bitset = bins_r == best_t
    return SplitResult(
        gain=gain.astype(jnp.float32),
        feature=jnp.where(has_split, best_f.astype(jnp.int32), -1),
        threshold=best_t.astype(jnp.int32),
        default_left=best_dl,
        left_sum_grad=left_g,
        left_sum_hess=left_h - K_EPSILON,
        left_count=left_c,
        right_sum_grad=right_g,
        right_sum_hess=right_h - K_EPSILON,
        right_count=right_c,
        left_output=left_out,
        right_output=right_out,
        num_cat=num_cat.astype(jnp.int32),
        cat_bitset=cat_bitset,
    )
