"""Vectorized best-split search over leaf histograms.

TPU-native counterpart of FeatureHistogram's per-feature threshold scans
(/root/reference/src/treelearner/feature_histogram.hpp:91-650). The reference walks
each feature's bins twice (right-to-left then left-to-right) with early-exit
branches; here both directions become cumulative sums over the bin axis for ALL
features at once, with every constraint (min_data_in_leaf, min_sum_hessian_in_leaf,
min_gain_to_split, L1/L2, max_delta_step, monotone clamps, missing-value bin
exclusions) expressed as masks — no data-dependent control flow, so the whole scan
jits into one fused XLA program.

Semantics preserved exactly (including kEpsilon placements, feature_histogram.hpp:87
and the scan accumulator seeds, and scan-order tie-breaking):

 * missing_type None (or num_bin<=2): single right-to-left scan, default_left=True
   (flipped to False when missing_type is NaN and num_bin<=2).
 * missing_type Zero: both scans skip the default(zero) bin — its mass lands on the
   complement side, i.e. zeros follow the default direction.
 * missing_type NaN: the last bin is the NaN bin; it is excluded from explicit
   accumulation so NaNs follow the default direction.
 * dir=-1 prefers the largest threshold among equal gains, dir=+1 the smallest, and
   dir=+1 must strictly beat dir=-1 (strict '>' updates in the reference loops).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15  # meta.h:42
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static split hyperparameters (subset of Config used by the scan)."""

    lambda_l1: float
    lambda_l2: float
    max_delta_step: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float


class CegbParams(NamedTuple):
    """Static CEGB (cost-effective gradient boosting) switches (config.h:389-405).

    The per-feature penalty vectors travel in ``feature_meta`` as
    ``cegb_coupled``/``cegb_lazy`` [F]; these flags gate the (costly) per-leaf
    rescan path in the grower.
    """

    tradeoff: float = 1.0
    penalty_split: float = 0.0
    has_coupled: bool = False
    has_lazy: bool = False

    @property
    def enabled(self) -> bool:
        return self.penalty_split != 0.0 or self.has_coupled or self.has_lazy


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """ThresholdL1 (feature_histogram.hpp:446)."""
    if l1 == 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, p: SplitParams):
    """CalculateSplittedLeafOutput without monotone clamp (feature_histogram.hpp:451)."""
    ret = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + p.lambda_l2)
    if p.max_delta_step > 0.0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    return ret


def _leaf_output_constrained(sum_grad, sum_hess, p: SplitParams, min_c, max_c):
    return jnp.clip(calculate_leaf_output(sum_grad, sum_hess, p), min_c, max_c)


def _gain_given_output(sum_grad, sum_hess, output, p: SplitParams):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:505)."""
    sg_l1 = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg_l1 * output + (sum_hess + p.lambda_l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, p: SplitParams):
    """GetLeafSplitGain (feature_histogram.hpp:498): parent gain, unconstrained."""
    out = calculate_leaf_output(sum_grad, sum_hess, p)
    return _gain_given_output(sum_grad, sum_hess, out, p)


class SplitResult(NamedTuple):
    gain: jax.Array  # scalar f32, already minus gain_shift; <=0 means no split
    feature: jax.Array  # int32 index into used features; -1 if none
    threshold: jax.Array  # int32 bin threshold (left: bin <= threshold)
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


class _ScanOut(NamedTuple):
    """Per-feature best candidates + side-sum arrays for recovery."""

    g_best: jax.Array  # [F]
    t_best: jax.Array  # [F]
    dl_best: jax.Array  # [F]
    use_pos: jax.Array  # [F]
    is_cat: jax.Array  # [F]
    lg_pos: jax.Array  # [F, B]
    lh_pos: jax.Array
    lc_pos: jax.Array
    lg_neg: jax.Array
    lh_neg: jax.Array
    lc_neg: jax.Array
    cat_lg: jax.Array
    cat_lh: jax.Array
    cat_lc: jax.Array
    min_gain_shift: jax.Array


def _scan_candidates(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count)
    sum_grad: jax.Array,  # leaf totals (scalars)
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,  # monotone constraint window for this leaf
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],  # num_bin/missing_type/default_bin/monotone [F]
    params: SplitParams,
) -> _ScanOut:
    """Per-feature threshold scan; the shared core of find_best_split and the
    voting-parallel local stage (voting_parallel_tree_learner.cpp:337)."""
    F, B, _ = hist.shape
    p = params
    num_bin = feature_meta["num_bin"].astype(jnp.int32)  # [F]
    missing = feature_meta["missing_type"].astype(jnp.int32)
    default_bin = feature_meta["default_bin"].astype(jnp.int32)
    mono = feature_meta["monotone"].astype(jnp.int32)

    sum_hess_eff = sum_hess + 2 * K_EPSILON  # feature_histogram.hpp:87

    gain_shift = leaf_split_gain(sum_grad, sum_hess_eff, p)
    min_gain_shift = gain_shift + p.min_gain_to_split

    multi_bin = num_bin > 2
    use_na = (missing == MISSING_NAN) & multi_bin  # [F]
    skip_def = (missing == MISSING_ZERO) & multi_bin
    single_scan = ~(use_na | skip_def)

    bins = jnp.arange(B, dtype=jnp.int32)[None, :]  # [1, B]
    nan_bin = (num_bin - 1)[:, None]
    excl = (bins >= num_bin[:, None])
    excl |= skip_def[:, None] & (bins == default_bin[:, None])
    excl |= use_na[:, None] & (bins == nan_bin)
    contrib = hist * (~excl)[:, :, None].astype(hist.dtype)  # [F, B, 3]

    prefix = jnp.cumsum(contrib, axis=1)  # inclusive prefix over bins
    total = prefix[:, -1, :]  # [F, 3] sums over included bins

    thresholds = jnp.arange(B, dtype=jnp.int32)[None, :]  # threshold t -> left bins <= t

    def side_stats(left_g, left_h_raw, left_c):
        left_h = left_h_raw + K_EPSILON
        right_g = sum_grad - left_g
        right_h = sum_hess_eff - left_h
        right_c = num_data - left_c
        return left_h, right_g, right_h, right_c

    def gains_for(left_g, left_h, right_g, right_h, left_c, right_c, valid):
        ok = (
            valid
            & (left_c >= p.min_data_in_leaf)
            & (right_c >= p.min_data_in_leaf)
            & (left_h >= p.min_sum_hessian_in_leaf)
            & (right_h >= p.min_sum_hessian_in_leaf)
        )
        lo = _leaf_output_constrained(left_g, left_h, p, min_constraint, max_constraint)
        ro = _leaf_output_constrained(right_g, right_h, p, min_constraint, max_constraint)
        g = _gain_given_output(left_g, left_h, lo, p) + _gain_given_output(
            right_g, right_h, ro, p
        )
        mono_bad = ((mono[:, None] > 0) & (lo > ro)) | ((mono[:, None] < 0) & (lo < ro))
        g = jnp.where(mono_bad, 0.0, g)
        ok &= g > min_gain_shift
        return jnp.where(ok, g, K_MIN_SCORE)

    # ---- dir = +1 (left-to-right; default_left = False) ------------------
    lg_pos = prefix[:, :, 0]
    lh_pos_raw = prefix[:, :, 1]
    lc_pos = prefix[:, :, 2]
    lh_pos, rg_pos, rh_pos, rc_pos = side_stats(lg_pos, lh_pos_raw, lc_pos)
    valid_pos = thresholds <= (num_bin[:, None] - 2)
    valid_pos &= ~(skip_def[:, None] & (thresholds == default_bin[:, None]))
    # dir=+1 runs only for the missing-handling scans
    valid_pos &= (~single_scan)[:, None]
    gains_pos = gains_for(lg_pos, lh_pos, rg_pos, rh_pos, lc_pos, rc_pos, valid_pos)

    # ---- dir = -1 (right-to-left; default_left = True) -------------------
    rg_neg_raw = total[:, None, 0] - prefix[:, :, 0]
    rh_neg_raw = total[:, None, 1] - prefix[:, :, 1]
    rc_neg = total[:, None, 2] - prefix[:, :, 2]
    rh_neg = rh_neg_raw + K_EPSILON
    lg_neg = sum_grad - rg_neg_raw
    lh_neg = sum_hess_eff - rh_neg
    lc_neg = num_data - rc_neg
    valid_neg = thresholds <= (num_bin[:, None] - 2 - use_na[:, None].astype(jnp.int32))
    valid_neg &= ~(skip_def[:, None] & (thresholds == default_bin[:, None] - 1))
    gains_neg = gains_for(lg_neg, lh_neg, rg_neg_raw, rh_neg, lc_neg, rc_neg, valid_neg)

    # ---- categorical one-hot candidates ---------------------------------
    # FindBestThresholdCategorical one-hot branch (feature_histogram.hpp:139-172):
    # left = the single bin t, right = rest; no monotone; default_left=False.
    is_cat = feature_meta.get("is_categorical")
    if is_cat is None:
        is_cat = jnp.zeros((F,), bool)
    else:
        is_cat = is_cat.astype(bool)
    cat_lg = hist[:, :, 0]
    cat_lh_raw = hist[:, :, 1]
    cat_lc = hist[:, :, 2]
    cat_lh = cat_lh_raw + K_EPSILON
    cat_rg = sum_grad - cat_lg
    cat_rh = sum_hess_eff - cat_lh
    cat_rc = num_data - cat_lc
    used_bin = num_bin + jnp.where(missing == MISSING_NONE, 0, -1)  # [F]
    cat_valid = thresholds < used_bin[:, None]
    cat_valid &= (cat_lc >= p.min_data_in_leaf) & (cat_rc >= p.min_data_in_leaf)
    cat_valid &= (cat_lh_raw >= p.min_sum_hessian_in_leaf) & (
        cat_rh >= p.min_sum_hessian_in_leaf
    )
    cat_lo = _leaf_output_constrained(cat_lg, cat_lh, p, min_constraint, max_constraint)
    cat_ro = _leaf_output_constrained(cat_rg, cat_rh, p, min_constraint, max_constraint)
    cat_g = _gain_given_output(cat_lg, cat_lh, cat_lo, p) + _gain_given_output(
        cat_rg, cat_rh, cat_ro, p
    )
    cat_valid &= cat_g > min_gain_shift
    gains_cat = jnp.where(cat_valid, cat_g, K_MIN_SCORE)
    t_cat = jnp.argmax(gains_cat, axis=1)  # smallest t wins ties
    g_cat = jnp.take_along_axis(gains_cat, t_cat[:, None], axis=1)[:, 0]

    # ---- per-feature best with scan-order tie-breaking -------------------
    # dir=-1 prefers the LARGEST threshold among equal gains.
    t_neg_rev = jnp.argmax(gains_neg[:, ::-1], axis=1)
    t_neg = B - 1 - t_neg_rev
    g_neg = jnp.take_along_axis(gains_neg, t_neg[:, None], axis=1)[:, 0]
    # dir=+1 prefers the smallest threshold; must strictly beat dir=-1.
    t_pos = jnp.argmax(gains_pos, axis=1)
    g_pos = jnp.take_along_axis(gains_pos, t_pos[:, None], axis=1)[:, 0]

    use_pos = g_pos > g_neg
    g_best = jnp.where(use_pos, g_pos, g_neg)
    t_best = jnp.where(use_pos, t_pos, t_neg)
    dl_best = ~use_pos  # default_left = (dir == -1)
    # 2-bin NaN features keep default_left=False (feature_histogram.hpp:108-111)
    two_bin_nan = (missing == MISSING_NAN) & ~multi_bin
    dl_best = jnp.where(two_bin_nan, False, dl_best)

    # categorical features use the one-hot candidates exclusively
    g_best = jnp.where(is_cat, g_cat, g_best)
    t_best = jnp.where(is_cat, t_cat, t_best)
    dl_best = jnp.where(is_cat, False, dl_best)
    use_pos = jnp.where(is_cat, True, use_pos)  # pick() reads the prefix arrays

    return _ScanOut(
        g_best=g_best,
        t_best=t_best,
        dl_best=dl_best,
        use_pos=use_pos,
        is_cat=is_cat,
        lg_pos=lg_pos,
        lh_pos=lh_pos,
        lc_pos=lc_pos,
        lg_neg=lg_neg,
        lh_neg=lh_neg,
        lc_neg=lc_neg,
        cat_lg=cat_lg,
        cat_lh=cat_lh,
        cat_lc=cat_lc,
        min_gain_shift=min_gain_shift,
    )


def gather_info_for_threshold(
    hist_f: jax.Array,  # [B, 3] one feature's histogram
    sum_grad: jax.Array,
    sum_hess: jax.Array,
    num_data: jax.Array,
    threshold: jax.Array,  # bin threshold (int32 scalar)
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    is_cat: jax.Array,
    params: SplitParams,
) -> SplitResult:
    """SplitInfo for a FORCED (feature, threshold) split
    (FeatureHistogram::GatherInfoForThreshold, feature_histogram.hpp:281-420).

    Numerical: right side = bins in [max(threshold,1), last real bin], skipping
    the default bin when missing=Zero and the NaN bin when missing=NaN;
    default_left=True. Categorical one-hot: left side = the single bin;
    default_left=False. Gain <= min_gain_shift yields -inf (the caller skips the
    forced split and aborts the rest of its BFS, serial_tree_learner.cpp:666).
    """
    p = params
    B = hist_f.shape[0]
    bins = jnp.arange(B, dtype=jnp.int32)
    use_na = missing_type == MISSING_NAN
    skip_def = missing_type == MISSING_ZERO

    gain_shift = leaf_split_gain(sum_grad, sum_hess, p)
    min_gain_shift = gain_shift + p.min_gain_to_split

    # ---- numerical ------------------------------------------------------
    right_mask = (bins >= jnp.maximum(threshold, 1)) & (bins <= num_bin - 1 - use_na)
    right_mask &= ~(skip_def & (bins == default_bin))
    rm = right_mask.astype(hist_f.dtype)[:, None]
    right = jnp.sum(hist_f * rm, axis=0)  # [3]
    num_rg, num_rh, num_rc = right[0], right[1] + K_EPSILON, right[2]
    num_lg = sum_grad - num_rg
    num_lh = sum_hess - num_rh
    num_lc = num_data - num_rc

    # ---- categorical one-hot -------------------------------------------
    left_mask = (bins == threshold).astype(hist_f.dtype)[:, None]
    cleft = jnp.sum(hist_f * left_mask, axis=0)
    cat_lg, cat_lh, cat_lc = cleft[0], cleft[1] + K_EPSILON, cleft[2]
    used_bin = num_bin + jnp.where(missing_type == MISSING_NONE, 0, -1)
    cat_ok = threshold < used_bin

    lg = jnp.where(is_cat, cat_lg, num_lg)
    lh = jnp.where(is_cat, cat_lh, num_lh)
    lc = jnp.where(is_cat, cat_lc, num_lc)
    rg = sum_grad - lg
    rh = sum_hess - lh
    rc = num_data - lc

    current_gain = leaf_split_gain(lg, lh, p) + leaf_split_gain(rg, rh, p)
    ok = (current_gain > min_gain_shift) & jnp.where(is_cat, cat_ok, True)
    ok &= ~jnp.isnan(current_gain)

    left_out = calculate_leaf_output(lg, lh, p)
    right_out = calculate_leaf_output(rg, rh, p)
    gain = jnp.where(ok, current_gain - min_gain_shift, K_MIN_SCORE)
    return SplitResult(
        gain=gain.astype(jnp.float32),
        feature=jnp.int32(-1),  # caller fills the (static) feature index
        threshold=threshold.astype(jnp.int32),
        default_left=jnp.where(is_cat, False, True),
        left_sum_grad=lg,
        left_sum_hess=lh - K_EPSILON,
        left_count=lc,
        right_sum_grad=rg,
        right_sum_hess=rh - K_EPSILON,
        right_count=rc,
        left_output=left_out,
        right_output=right_out,
    )


def per_feature_best_gain(
    hist: jax.Array,
    sum_grad: jax.Array,
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],
    feature_mask: jax.Array,
    params: SplitParams,
) -> jax.Array:
    """[F] best gain per feature (-inf where none) — the voting-parallel
    local-voting stage (LightSplitInfo gains, voting_parallel_tree_learner.cpp:337)."""
    sc = _scan_candidates(
        hist, sum_grad, sum_hess, num_data, min_constraint, max_constraint,
        feature_meta, params,
    )
    return jnp.where(feature_mask, sc.g_best, K_MIN_SCORE)


@functools.partial(jax.jit, static_argnames=("params",))
def find_best_split(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count)
    sum_grad: jax.Array,  # leaf totals (scalars)
    sum_hess: jax.Array,
    num_data: jax.Array,
    min_constraint: jax.Array,  # monotone constraint window for this leaf
    max_constraint: jax.Array,
    feature_meta: Dict[str, jax.Array],  # num_bin/missing_type/default_bin/monotone [F]
    feature_mask: jax.Array,  # [F] bool: feature_fraction sample & usable
    params: SplitParams,
    penalty: Any = None,  # optional [F] CEGB gain penalty per feature
) -> SplitResult:
    """Best split for one leaf across all features (FindBestThresholdNumerical)."""
    p = params
    sum_hess_eff = sum_hess + 2 * K_EPSILON  # feature_histogram.hpp:87
    sc = _scan_candidates(
        hist, sum_grad, sum_hess, num_data, min_constraint, max_constraint,
        feature_meta, params,
    )
    (g_best, t_best, dl_best, use_pos, is_cat) = (
        sc.g_best, sc.t_best, sc.dl_best, sc.use_pos, sc.is_cat,
    )
    (lg_pos, lh_pos, lc_pos, lg_neg, lh_neg, lc_neg, cat_lg, cat_lh, cat_lc) = (
        sc.lg_pos, sc.lh_pos, sc.lc_pos, sc.lg_neg, sc.lh_neg, sc.lc_neg,
        sc.cat_lg, sc.cat_lh, sc.cat_lc,
    )
    min_gain_shift = sc.min_gain_shift

    g_best = jnp.where(feature_mask, g_best, K_MIN_SCORE)
    if penalty is not None:
        # CEGB: penalties land on the shifted gain (serial_tree_learner.cpp:537-543),
        # i.e. after min_gain_shift subtraction; shift them into the raw scale here
        # so the argmax and the final reported gain both see penalized values.
        g_best = g_best - penalty

    best_f = jnp.argmax(g_best)  # first max wins ties (feature index order)
    best_gain_raw = g_best[best_f]
    best_t = t_best[best_f]
    best_dl = dl_best[best_f]
    has_split = best_gain_raw > K_MIN_SCORE

    # Recover the chosen candidate's side sums.
    best_is_cat = is_cat[best_f]

    def pick(arr_pos, arr_neg, arr_cat):
        pos_v = arr_pos[best_f, best_t]
        neg_v = arr_neg[best_f, best_t]
        cat_v = arr_cat[best_f, best_t]
        return jnp.where(best_is_cat, cat_v, jnp.where(use_pos[best_f], pos_v, neg_v))

    left_g = pick(lg_pos, lg_neg, cat_lg)
    left_h = pick(lh_pos, lh_neg, cat_lh)  # includes +eps
    left_c = pick(lc_pos, lc_neg, cat_lc)
    right_g = sum_grad - left_g
    right_h = sum_hess_eff - left_h
    right_c = num_data - left_c

    left_out = _leaf_output_constrained(left_g, left_h, p, min_constraint, max_constraint)
    right_out = _leaf_output_constrained(right_g, right_h, p, min_constraint, max_constraint)

    gain = jnp.where(has_split, best_gain_raw - min_gain_shift, K_MIN_SCORE)
    return SplitResult(
        gain=gain.astype(jnp.float32),
        feature=jnp.where(has_split, best_f.astype(jnp.int32), -1),
        threshold=best_t.astype(jnp.int32),
        default_left=best_dl,
        left_sum_grad=left_g,
        left_sum_hess=left_h - K_EPSILON,
        left_count=left_c,
        right_sum_grad=right_g,
        right_sum_hess=right_h - K_EPSILON,
        right_count=right_c,
        left_output=left_out,
        right_output=right_out,
    )
