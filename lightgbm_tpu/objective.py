"""Objective functions (gradient/hessian providers).

TPU-native counterpart of the reference objective family
(/root/reference/src/objective/*.hpp, factory objective_function.cpp:15-52,
interface include/LightGBM/objective_function.h). Formulas are reproduced exactly;
the implementation shape differs: per-row gradient loops become jitted jnp
element-wise programs over device arrays, and LambdaRank's per-query pairwise loop
(rank_objective.hpp:82-160) becomes a padded [queries, docs, docs] masked tensor
program chunked over queries.

Scores for multiclass are class-major ``[num_class, num_data]``, matching the
reference's ``num_data * k + i`` indexing (multiclass_objective.hpp:80).
"""
from __future__ import annotations

import functools
import math
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata
from .utils import log

K_EPSILON = 1e-15


def _stable_expish(fn):
    """Run an exp-based host-side output converter with numpy's overflow
    warning suppressed: the reference's C++ converters compute the same
    expressions where overflow silently saturates to +inf (e.g. sigmoid
    1/(1+exp(-kx)) -> 0, exp(x) -> inf) — values are bit-identical either
    way, errstate only drops the warning noise."""
    with np.errstate(over="ignore"):
        return fn()


# ---------------------------------------------------------------------------
# percentile helpers (regression_objective.hpp:18-75, replicated exactly)
# ---------------------------------------------------------------------------

def percentile(data: np.ndarray, alpha: float) -> float:
    """PercentileFun: alpha-quantile via descending order stats."""
    cnt = len(data)
    if cnt == 0:
        return 0.0
    if cnt <= 1:
        return float(data[0])
    desc = np.sort(data)[::-1]
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    if pos < 1:
        return float(desc[0])
    if pos >= cnt:
        return float(desc[-1])
    bias = float_pos - pos
    v1 = float(desc[pos - 1])
    v2 = float(desc[pos])
    return v1 - (v1 - v2) * bias


def weighted_percentile(data: np.ndarray, weights: np.ndarray, alpha: float) -> float:
    """WeightedPercentileFun (regression_objective.hpp:46-75), replicated exactly."""
    cnt = len(data)
    if cnt == 0:
        return 0.0
    if cnt <= 1:
        return float(data[0])
    order = np.argsort(data, kind="stable")
    cdf = np.cumsum(weights[order]).astype(np.float64)
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, cnt - 1)
    if pos == 0 or pos == cnt - 1:
        return float(data[order[pos]])
    v1 = float(data[order[pos - 1]])
    v2 = float(data[order[pos]])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return float((threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1)
    return v2


def _percentile_maybe_weighted(data, weights, alpha):
    if weights is None:
        return percentile(data, alpha)
    return weighted_percentile(data, weights, alpha)


@functools.partial(jax.jit, static_argnames=("num_leaves", "alpha", "weighted"))
def segment_percentile(
    values: jax.Array,  # [N] f64/f32 per-row data (residuals)
    leaf_id: jax.Array,  # [N] int32
    sel: jax.Array,  # [N] bool rows to include (bag)
    weights: Optional[jax.Array],  # [N] or None
    old_outputs: jax.Array,  # [num_leaves] fallback for empty leaves
    num_leaves: int,
    alpha: float,
    weighted: bool,
) -> jax.Array:
    """Per-leaf alpha-percentile, PercentileFun/WeightedPercentileFun semantics
    (regression_objective.hpp:18-75) vectorized over leaves on device.

    Replaces the reference's per-leaf host loops (RenewTreeOutput,
    regression_objective.hpp:189-548): one lex sort by (leaf, value) + masked
    segment order statistics — no per-tree host round-trip of N-sized arrays.
    """
    N = values.shape[0]
    M = num_leaves
    lid = jnp.where(sel, leaf_id.astype(jnp.int32), M)  # deselected -> sentinel
    # lex sort: by value (stable), then by leaf (stable) = (leaf asc, value asc)
    ordv = jnp.argsort(values, stable=True)
    order = ordv[jnp.argsort(lid[ordv], stable=True)]
    l_sorted = lid[order]
    v_sorted = values[order]

    leaves = jnp.arange(M, dtype=jnp.int32)
    begin = jnp.searchsorted(l_sorted, leaves, side="left").astype(jnp.int32)
    end = jnp.searchsorted(l_sorted, leaves, side="right").astype(jnp.int32)
    cnt = end - begin

    def asc(pos):  # [M] gather of the pos-th ascending value per leaf
        return v_sorted[jnp.clip(begin + pos, 0, N - 1)]

    if not weighted:
        # PercentileFun works on DESCENDING stats: desc[i] = asc[cnt-1-i]
        float_pos = (1.0 - alpha) * cnt.astype(values.dtype)
        pos = float_pos.astype(jnp.int32)
        bias = float_pos - pos.astype(values.dtype)
        v1 = asc(cnt - pos)  # desc[pos-1]
        v2 = asc(cnt - 1 - pos)  # desc[pos]
        out = v1 - (v1 - v2) * bias
        out = jnp.where(pos < 1, asc(cnt - 1), out)  # desc[0] = max
        out = jnp.where(pos >= cnt, asc(0), out)  # desc[-1] = min
        out = jnp.where(cnt <= 1, asc(0), out)
    else:
        w_sorted = weights[order] * (l_sorted < M)  # zero out deselected tail
        # f32 cumsum (f64 needs jax_enable_x64); order statistics tolerate it
        cumw = jnp.cumsum(w_sorted)
        base = jnp.where(begin > 0, cumw[jnp.maximum(begin - 1, 0)], 0.0)
        total = jnp.where(end > 0, cumw[jnp.maximum(end - 1, 0)], 0.0) - base
        threshold = total * alpha
        pos = (
            jnp.searchsorted(cumw, base + threshold, side="right").astype(jnp.int32)
            - begin
        )
        pos = jnp.minimum(pos, cnt - 1)

        def cdf(p):  # segment cdf at local index p
            return cumw[jnp.clip(begin + p, 0, N - 1)] - base

        v1 = asc(pos - 1)
        v2 = asc(pos)
        interp = (threshold - cdf(pos)) / (cdf(pos + 1) - cdf(pos)) * (v2 - v1) + v1
        out = jnp.where(cdf(pos + 1) - cdf(pos) >= 1.0, interp, v2)
        edge = (pos <= 0) | (pos >= cnt - 1)
        out = jnp.where(edge, asc(jnp.clip(pos, 0, cnt - 1)), out)
        out = jnp.where(cnt <= 1, asc(0), out)
    return jnp.where(cnt == 0, old_outputs, out.astype(old_outputs.dtype))


# ---------------------------------------------------------------------------
# base class
# ---------------------------------------------------------------------------

class ObjectiveFunction:
    """Interface mirror of objective_function.h."""

    name = "none"
    # True when get_gradients / renew_leaf_outputs_device are pure device
    # (jnp) programs of the score and init-time state, safe to trace inside
    # the chunked boosting scan (models/gbdt.py train_chunk). An objective
    # that reads or mutates HOST state per iteration must set this False to
    # force the per-iteration loop.
    supports_device_chunk = True
    # True when get_gradients is ELEMENTWISE over rows (possibly per class):
    # row i's gradient depends only on row i's score/label/weight, so the
    # data-parallel chunked trainer may evaluate it per row shard with the
    # per-row state swapped for shard-local blocks (row_state below).
    # Cross-row objectives (LambdaRank's query-grouped pairwise lambdas)
    # set this False and fall back to the per-iteration sharded loop.
    supports_row_sharding = True

    def __init__(self, config: Config) -> None:
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label if metadata.label is not None else np.zeros(num_data, np.float32)
        self.weight = metadata.weight
        self._label_dev = jnp.asarray(self.label, jnp.float32)
        self._weight_dev = None if self.weight is None else jnp.asarray(self.weight, jnp.float32)

    # grad/hess on device; score [N] f32 (or [K, N] multiclass)
    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, scores: np.ndarray) -> np.ndarray:
        return scores

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def num_predict_one_row(self) -> int:
        return 1

    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def renew_leaf_outputs(
        self,
        score: np.ndarray,
        leaf_id: np.ndarray,
        bag_mask: Optional[np.ndarray],
        num_leaves: int,
        leaf_outputs: np.ndarray,
    ) -> np.ndarray:
        return leaf_outputs

    def renew_leaf_outputs_device(
        self, score, leaf_id, bag_mask, num_leaves: int, leaf_outputs
    ):
        return leaf_outputs

    def _renew_weights(self):
        """Renew weight vector for RenewTreeOutput-style objectives (None =
        unweighted); overridden where renewal applies."""
        return self.weight

    def _renew_weights_dev(self):
        """Device copy of the renew weights, uploaded ONCE per training.
        A per-call jnp.asarray would re-upload an N-sized array every tree —
        and inside the chunked boosting scan (models/gbdt.py train_chunk)
        re-embed it as a trace constant per chunk shape. Lives on the base
        class because renew_leaf_outputs_device is borrowed across sibling
        classes (RegressionQuantileLoss reuses RegressionL1Loss's)."""
        w = self._renew_weights()
        if w is None:
            return None
        cached = getattr(self, "_renew_w_dev", None)
        if cached is None or cached.shape[0] != len(w):
            cached = jnp.asarray(w, jnp.float32)
            self._renew_w_dev = cached
        return cached

    def class_need_train(self, class_id: int) -> bool:
        return True

    def row_state(self) -> List[Tuple[object, str, jax.Array]]:
        """Every per-row DEVICE array ``get_gradients`` reads, as
        ``(owner, attribute, array)`` triples — any attribute whose value is
        a jax array with trailing dimension ``num_data`` (``_label_dev``,
        ``_weight_dev``, binary's ``_y_dev``/``_lw_dev``, multiclass's
        ``[K, N]`` one-hot, OVA's nested per-class copies).

        The data-parallel chunked trainer (models/gbdt.py) row-shards these
        over the device mesh and swaps the shard-local blocks in for the
        trace, so the elementwise gradient program runs on ``[.., N/D]``
        shards unchanged. Only valid when ``supports_row_sharding``; the
        generic scan is deliberate — a subclass that adds a per-row device
        array is covered without remembering a registry."""
        out: List[Tuple[object, str, jax.Array]] = []
        owners = [self] + list(getattr(self, "_binary", []))
        for ow in owners:
            for attr, val in vars(ow).items():
                if (
                    isinstance(val, jax.Array)
                    and val.ndim >= 1
                    and val.shape[-1] == self.num_data
                ):
                    out.append((ow, attr, val))
        return out

    def to_string(self) -> str:
        return self.name

    def _apply_weight(self, grad, hess):
        if self._weight_dev is None:
            return grad, hess
        return grad * self._weight_dev, hess * self._weight_dev


# ---------------------------------------------------------------------------
# regression family (regression_objective.hpp)
# ---------------------------------------------------------------------------

class RegressionL2Loss(ObjectiveFunction):
    name = "regression"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lab = np.sign(self.label) * np.sqrt(np.abs(self.label))
            self._label_dev = jnp.asarray(lab, jnp.float32)
            self._trans_label = lab

    def get_gradients(self, score):
        grad = score - self._label_dev
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = np.asarray(self._label_dev)
        if self.weight is not None:
            return float(np.sum(lab * self.weight) / np.sum(self.weight))
        return float(np.mean(lab))

    def convert_output(self, scores):
        if self.sqrt:
            return np.sign(scores) * scores * scores
        return scores

    @property
    def is_constant_hessian(self):
        return self.weight is None

    def to_string(self):
        return self.name + (" sqrt" if self.sqrt else "")


class RegressionL1Loss(RegressionL2Loss):
    name = "regression_l1"

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = np.asarray(self._label_dev)
        return _percentile_maybe_weighted(lab, self.weight, 0.5)

    @property
    def is_renew_tree_output(self):
        return True

    def _renew_alpha(self):
        return 0.5

    def _renew_weights(self):
        return self.weight

    def renew_leaf_outputs(self, score, leaf_id, bag_mask, num_leaves, leaf_outputs):
        lab = np.asarray(self._label_dev, np.float64)
        residual = lab - np.asarray(score, np.float64)
        w = self._renew_weights()
        out = np.array(leaf_outputs, dtype=np.float64)
        sel_all = np.ones(len(residual), bool) if bag_mask is None else np.asarray(bag_mask) > 0
        alpha = self._renew_alpha()
        for leaf in range(num_leaves):
            sel = (leaf_id == leaf) & sel_all
            if not sel.any():
                continue
            r = residual[sel]
            out[leaf] = _percentile_maybe_weighted(r, None if w is None else w[sel], alpha)
        return out

    def renew_leaf_outputs_device(
        self, score, leaf_id, bag_mask, num_leaves: int, leaf_outputs
    ):
        """Device-side RenewTreeOutput: segment percentiles, no host round-trip
        of N-sized arrays between boosting iterations."""
        w = self._renew_weights()
        w_dev = self._renew_weights_dev()
        residual = self._label_dev - score
        sel = (
            jnp.ones(residual.shape, bool) if bag_mask is None else bag_mask > 0
        )
        return segment_percentile(
            residual,
            leaf_id,
            sel,
            w_dev,
            leaf_outputs,
            num_leaves=num_leaves,
            alpha=float(self._renew_alpha()),
            weighted=w is not None,
        )

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionHuberLoss(RegressionL2Loss):
    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        self.sqrt = False

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff, jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    @property
    def is_constant_hessian(self):
        return False


class RegressionFairLoss(RegressionL2Loss):
    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self._label_dev
        ax = jnp.abs(x)
        grad = self.c * x / (ax + self.c)
        hess = self.c * self.c / ((ax + self.c) ** 2)
        return self._apply_weight(grad, hess)

    @property
    def is_constant_hessian(self):
        return False


class RegressionPoissonLoss(RegressionL2Loss):
    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.min(self.label) < 0:
            log.fatal("[%s]: at least one target label is negative" % self.name)
        if np.sum(self.label) == 0:
            log.fatal("[%s]: sum of labels is zero" % self.name)

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        grad = exp_s - self._label_dev
        hess = jnp.exp(score + self.max_delta_step)
        return self._apply_weight(grad, hess)

    def convert_output(self, scores):
        return _stable_expish(lambda: np.exp(scores))

    def boost_from_score(self, class_id=0):
        mean = RegressionL2Loss.boost_from_score(self, class_id)
        return math.log(mean) if mean > 0 else -np.inf

    @property
    def is_constant_hessian(self):
        return False


class RegressionQuantileLoss(RegressionL2Loss):
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        assert 0.0 < self.alpha < 1.0

    def get_gradients(self, score):
        delta = score - self._label_dev
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        return _percentile_maybe_weighted(np.asarray(self._label_dev), self.weight, self.alpha)

    @property
    def is_renew_tree_output(self):
        return True

    renew_leaf_outputs = RegressionL1Loss.renew_leaf_outputs
    renew_leaf_outputs_device = RegressionL1Loss.renew_leaf_outputs_device

    def _renew_alpha(self):
        return self.alpha

    def _renew_weights(self):
        return self.weight

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionMAPELoss(RegressionL1Loss):
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(np.abs(self.label) < 1):
            log.warning(
                "Met 'abs(label) < 1', will convert them to '1' in MAPE objective and metric"
            )
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weight is not None:
            lw = lw * self.weight
        self.label_weight = lw.astype(np.float32)
        self._label_weight_dev = jnp.asarray(self.label_weight)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff) * self._label_weight_dev
        if self._weight_dev is None:
            hess = jnp.ones_like(score)
        else:
            hess = self._weight_dev * jnp.ones_like(score)
        return grad, hess

    def boost_from_score(self, class_id=0):
        return weighted_percentile(np.asarray(self._label_dev), self.label_weight, 0.5)

    def _renew_weights(self):
        return self.label_weight

    @property
    def is_constant_hessian(self):
        return True


class RegressionGammaLoss(RegressionPoissonLoss):
    name = "gamma"

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        grad = 1.0 - self._label_dev / exp_s
        hess = self._label_dev / exp_s
        return self._apply_weight(grad, hess)


class RegressionTweedieLoss(RegressionPoissonLoss):
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        lab = self._label_dev
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -lab * e1 + e2
        hess = -lab * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        return self._apply_weight(grad, hess)


# ---------------------------------------------------------------------------
# binary (binary_objective.hpp)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config: Config, is_pos: Optional[Callable] = None) -> None:
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid parameter %g should be greater than zero" % self.sigmoid)
        self.is_unbalance = config.is_unbalance
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            log.fatal("Cannot set is_unbalance and scale_pos_weight at the same time")
        self._is_pos = is_pos or (lambda label: label > 0)
        self.need_train = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos = self._is_pos(self.label)
        cnt_pos = int(pos.sum())
        cnt_neg = num_data - cnt_pos
        self.need_train = not (cnt_pos == 0 or cnt_neg == 0)
        if not self.need_train:
            log.warning("Contains only one class")
        else:
            log.info("Number of positive: %d, number of negative: %d" % (cnt_pos, cnt_neg))
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        # y in {-1, +1}; per-row label weight
        self._y_dev = jnp.asarray(np.where(pos, 1.0, -1.0), jnp.float32)
        self._lw_dev = jnp.asarray(np.where(pos, w_pos, w_neg), jnp.float32)

    def get_gradients(self, score):
        if not self.need_train:
            return jnp.zeros_like(score), jnp.zeros_like(score)
        y = self._y_dev
        response = -y * self.sigmoid / (1.0 + jnp.exp(y * self.sigmoid * score))
        abs_resp = jnp.abs(response)
        grad = response * self._lw_dev
        hess = abs_resp * (self.sigmoid - abs_resp) * self._lw_dev
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        pos = self._is_pos(self.label).astype(np.float64)
        if self.weight is not None:
            pavg = float(np.sum(pos * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(pos))
        pavg = min(pavg, 1.0 - K_EPSILON)
        pavg = max(pavg, K_EPSILON)
        initscore = math.log(pavg / (1.0 - pavg)) / self.sigmoid
        log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f" % (self.name, pavg, initscore))
        return initscore

    def class_need_train(self, class_id):
        return self.need_train

    def convert_output(self, scores):
        return _stable_expish(lambda: 1.0 / (1.0 + np.exp(-self.sigmoid * scores)))

    def to_string(self):
        return "binary sigmoid:%g" % self.sigmoid


# ---------------------------------------------------------------------------
# multiclass (multiclass_objective.hpp)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self.label.astype(np.int32)
        if li.min() < 0 or li.max() >= self.num_class:
            log.fatal("Label must be in [0, %d), found invalid label" % self.num_class)
        onehot = np.zeros((self.num_class, num_data), np.float32)
        onehot[li, np.arange(num_data)] = 1.0
        self._onehot_dev = jnp.asarray(onehot)
        if self.weight is None:
            probs = np.bincount(li, minlength=self.num_class) / num_data
        else:
            probs = np.zeros(self.num_class)
            np.add.at(probs, li, self.weight)
            probs /= np.sum(self.weight)
        self.class_init_probs = probs

    def get_gradients(self, score):
        # score [K, N]
        p = jax.nn.softmax(score, axis=0)
        grad = p - self._onehot_dev
        hess = 2.0 * p * (1.0 - p)
        if self._weight_dev is not None:
            grad = grad * self._weight_dev[None, :]
            hess = hess * self._weight_dev[None, :]
        return grad, hess

    def convert_output(self, scores):
        # scores [..., K]
        e = np.exp(scores - np.max(scores, axis=-1, keepdims=True))
        return e / np.sum(e, axis=-1, keepdims=True)

    def boost_from_score(self, class_id=0):
        """multiclass_objective.hpp:142: log of the class prior."""
        return math.log(max(K_EPSILON, float(self.class_init_probs[class_id])))

    def class_need_train(self, class_id):
        p = float(self.class_init_probs[class_id])
        return not (abs(p) <= K_EPSILON or abs(p) >= 1.0 - K_EPSILON)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def to_string(self):
        return "multiclass num_class:%d" % self.num_class


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = config.num_class
        self.sigmoid = float(config.sigmoid)
        self._binary: List[BinaryLogloss] = []
        for k in range(self.num_class):
            self._binary.append(BinaryLogloss(config, is_pos=_make_is_pos(k)))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for b in self._binary:
            b.init(metadata, num_data)

    def get_gradients(self, score):
        grads, hesss = [], []
        for k in range(self.num_class):
            g, h = self._binary[k].get_gradients(score[k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads), jnp.stack(hesss)

    def boost_from_score(self, class_id=0):
        return self._binary[class_id].boost_from_score()

    def class_need_train(self, class_id):
        return self._binary[class_id].need_train

    def convert_output(self, scores):
        return _stable_expish(lambda: 1.0 / (1.0 + np.exp(-self.sigmoid * scores)))

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def to_string(self):
        return "multiclassova num_class:%d sigmoid:%g" % (self.num_class, self.sigmoid)


def _make_is_pos(k: int):
    return lambda label: np.asarray(label).astype(np.int32) == k


# ---------------------------------------------------------------------------
# cross-entropy (xentropy_objective.hpp)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    name = "xentropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0 or self.label.max() > 1:
            log.fatal("[%s]: label must be in [0, 1] interval" % self.name)
        if self.weight is not None and (self.weight.min() < 0 or self.weight.sum() == 0):
            log.fatal("[%s]: weights must be non-negative with positive sum" % self.name)

    def get_gradients(self, score):
        z = jax.nn.sigmoid(score)
        grad = z - self._label_dev
        hess = z * (1.0 - z)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = np.asarray(self._label_dev, np.float64)
        if self.weight is not None:
            pavg = float(np.sum(lab * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(lab))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, scores):
        return _stable_expish(lambda: 1.0 / (1.0 + np.exp(-scores)))


class CrossEntropyLambda(ObjectiveFunction):
    name = "xentlambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0 or self.label.max() > 1:
            log.fatal("[%s]: label must be in [0, 1] interval" % self.name)
        if self.weight is not None and self.weight.min() <= 0:
            log.fatal("[%s]: at least one weight is non-positive" % self.name)

    def get_gradients(self, score):
        if self._weight_dev is None:
            z = jax.nn.sigmoid(score)
            return z - self._label_dev, z * (1.0 - z)
        w = self._weight_dev
        y = self._label_dev
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id=0):
        lab = np.asarray(self._label_dev, np.float64)
        pavg = float(np.mean(lab))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, scores):
        return _stable_expish(lambda: np.log1p(np.exp(scores)))


# ---------------------------------------------------------------------------
# LambdaRank (rank_objective.hpp)
# ---------------------------------------------------------------------------

def default_label_gain(size: int = 31) -> np.ndarray:
    """DCGCalculator::DefaultLabelGain: 2^i - 1."""
    return (np.power(2.0, np.arange(size)) - 1.0).astype(np.float64)


def dcg_discount(positions: np.ndarray) -> np.ndarray:
    """DCGCalculator::GetDiscount: 1/log2(2+i)."""
    return 1.0 / np.log2(2.0 + positions)


@functools.partial(jax.jit, donate_argnums=())
def _lambdarank_bucket(score, idx, labs, gains, invq, weight, sigmoid):
    """Pairwise lambda/hessian for one size-bucket of queries, jitted.

    The device twin of the reference's per-query OpenMP loop
    (/root/reference/src/objective/rank_objective.hpp:74-82), restructured
    as dense [nq, S, S] pairwise tensors over size-padded query rows — the
    segment-ops formulation SURVEY §7 step 6 prescribes. Pads carry label
    -1 and are masked out of every pair.

    Args: score [N] f32; idx [nq, S] int32 row ids (N = pad); labs [nq, S]
    int32 (-1 = pad); gains [nq, S] f32 label gains; invq [nq] f32 inverse
    max DCG; weight [nq, S] f32 (or None); sigmoid scalar f32.
    Returns (g, h) [nq, S] f32 (zeros in pad lanes).
    """
    valid = labs >= 0
    s_raw = score[jnp.minimum(idx, score.shape[0] - 1)]
    s0 = jnp.where(valid, s_raw, 0.0)  # pair-difference operand (NaN-safe)
    # DCG ranks: stable descending sort of real entries, pads last — the
    # double argsort inverts the order permutation exactly
    key = jnp.where(valid, -s_raw, jnp.inf)
    order = jnp.argsort(key, axis=1, stable=True)
    rank = jnp.argsort(order, axis=1, stable=True).astype(jnp.float32)
    disc = jnp.where(valid, 1.0 / jnp.log2(2.0 + rank), 0.0)
    best = jnp.max(jnp.where(valid, s_raw, -jnp.inf), axis=1)
    worst = jnp.min(jnp.where(valid, s_raw, jnp.inf), axis=1)

    dl = (labs[:, :, None] > labs[:, None, :]) & valid[:, :, None] & valid[:, None, :]
    ds = s0[:, :, None] - s0[:, None, :]
    dcg_gap = gains[:, :, None] - gains[:, None, :]
    paired_disc = jnp.abs(disc[:, :, None] - disc[:, None, :])
    delta_ndcg = dcg_gap * paired_disc * invq[:, None, None]
    delta_ndcg = jnp.where(
        (best != worst)[:, None, None],
        delta_ndcg / (0.01 + jnp.abs(ds)),
        delta_ndcg,
    )
    p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * ds * sigmoid))
    p_hess = p_lambda * (2.0 - p_lambda)
    lam = jnp.where(dl, -p_lambda * delta_ndcg, 0.0)
    hes = jnp.where(dl, p_hess * 2.0 * delta_ndcg, 0.0)
    g = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
    h = jnp.sum(hes, axis=2) + jnp.sum(hes, axis=1)
    if weight is not None:
        g = g * weight
        h = h * weight
    return g, h


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    # query-grouped pairwise lambdas read the whole query's scores; a row
    # shard boundary through a query would silently change the gradients
    supports_row_sharding = False

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid param %g should be greater than zero" % self.sigmoid)
        lg = list(config.label_gain) if config.label_gain else list(default_label_gain())
        self.label_gain = np.asarray(lg, np.float64)
        self.optimize_pos_at = config.max_position

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = metadata.num_queries
        li = self.label.astype(np.int64)
        if li.min() < 0 or li.max() >= len(self.label_gain):
            log.fatal("Label exceeds label_gain size in lambdarank")
        # inverse max DCG per query at k = optimize_pos_at
        inv = np.zeros(self.num_queries, np.float64)
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            lab = li[lo:hi]
            k = min(self.optimize_pos_at, hi - lo)
            top = np.sort(lab)[::-1][:k]
            maxdcg = float(np.sum(self.label_gain[top] * dcg_discount(np.arange(k))))
            inv[q] = 1.0 / maxdcg if maxdcg > 0 else 0.0
        self.inverse_max_dcgs = inv
        self._build_device_plans()

    def _build_device_plans(self):
        """Group queries into power-of-two size buckets with static padded
        gather plans — query sizes are dataset constants, so bucketing is a
        trace-time decision and every jitted bucket shape is stable across
        iterations. Row-chunked so the [nq, S, S] pairwise transients stay
        ~32MB."""
        qb = np.asarray(self.query_boundaries, np.int64)
        sizes = np.diff(qb)
        li = self.label.astype(np.int64)
        n = self.num_data
        buckets = {}
        for q, c in enumerate(sizes):
            if c <= 1:
                continue  # no pairs, zero gradient
            S = 1 << max(3, int(c - 1).bit_length())
            buckets.setdefault(S, []).append(q)
        plans = []
        for S, qs in sorted(buckets.items()):
            idx = np.full((len(qs), S), n, np.int64)
            for r, q in enumerate(qs):
                lo, hi = qb[q], qb[q + 1]
                idx[r, : hi - lo] = np.arange(lo, hi)
            valid = idx < n
            safe = np.minimum(idx, n - 1)
            labs = np.where(valid, li[safe], -1)
            gains = np.where(valid, self.label_gain[np.maximum(labs, 0)], 0.0)
            invq = self.inverse_max_dcgs[qs]
            w = (
                np.where(valid, self.weight[safe], 0.0)
                if self.weight is not None
                else None
            )
            chunk = max(1, (8 << 20) // (S * S))
            for lo_r in range(0, len(qs), chunk):
                sl = slice(lo_r, lo_r + chunk)
                plans.append(
                    (
                        jnp.asarray(idx[sl], jnp.int32),
                        jnp.asarray(labs[sl], jnp.int32),
                        jnp.asarray(gains[sl], jnp.float32),
                        jnp.asarray(invq[sl], jnp.float32),
                        jnp.asarray(w[sl], jnp.float32) if w is not None else None,
                    )
                )
        self._device_plans = plans
        self._sigmoid_dev = jnp.float32(self.sigmoid)

    def get_gradients(self, score):
        """Jitted per-bucket pairwise lambdas; the whole gradient stays on
        device (VERDICT r4 item 3 — no per-query host loop)."""
        score = jnp.asarray(score, jnp.float32).reshape(-1)
        grad = jnp.zeros(self.num_data, jnp.float32)
        hess = jnp.zeros(self.num_data, jnp.float32)
        for idx, labs, gains, invq, w in self._device_plans:
            g, h = _lambdarank_bucket(
                score, idx, labs, gains, invq, w, self._sigmoid_dev
            )
            flat = idx.reshape(-1)  # pads point at N: scatter-dropped
            grad = grad.at[flat].set(g.reshape(-1))
            hess = hess.at[flat].set(h.reshape(-1))
        return grad, hess

    def _get_gradients_host(self, score):
        """Host-loop oracle (the original implementation) — kept as the
        differential reference for the jitted path (tests/test_lambdarank_device)."""
        score_np = np.asarray(score, np.float64)
        grad = np.zeros(self.num_data, np.float64)
        hess = np.zeros(self.num_data, np.float64)
        li = self.label.astype(np.int64)
        for q in range(self.num_queries):
            lo, hi = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            cnt = hi - lo
            if cnt <= 1:
                continue
            s = score_np[lo:hi]
            lab = li[lo:hi]
            inv_max_dcg = self.inverse_max_dcgs[q]
            order = np.argsort(-s, kind="stable")  # descending by score
            rank_of = np.empty(cnt, np.int64)
            rank_of[order] = np.arange(cnt)
            disc = dcg_discount(rank_of.astype(np.float64))
            gains = self.label_gain[lab]
            best, worst = s[order[0]], s[order[-1]]
            # pairwise [i, j]: i is "high" (higher label)
            dl = lab[:, None] > lab[None, :]
            ds = s[:, None] - s[None, :]
            dcg_gap = gains[:, None] - gains[None, :]
            paired_disc = np.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            if best != worst:
                delta_ndcg = delta_ndcg / (0.01 + np.abs(ds))
            p_lambda = 2.0 / (1.0 + np.exp(2.0 * ds * self.sigmoid))
            p_hess = p_lambda * (2.0 - p_lambda)
            lam = np.where(dl, -p_lambda * delta_ndcg, 0.0)
            hes = np.where(dl, p_hess * 2.0 * delta_ndcg, 0.0)
            g = lam.sum(axis=1) - lam.sum(axis=0)
            h = hes.sum(axis=1) + hes.sum(axis=0)
            if self.weight is not None:
                g *= self.weight[lo:hi]
                h *= self.weight[lo:hi]
            grad[lo:hi] = g
            hess[lo:hi] = h
        return jnp.asarray(grad, jnp.float32), jnp.asarray(hess, jnp.float32)


# ---------------------------------------------------------------------------
# factory (objective_function.cpp:15-52)
# ---------------------------------------------------------------------------

_OBJECTIVES: Dict[str, type] = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    if config.objective in ("none", "", None):
        return None
    cls = _OBJECTIVES.get(config.objective)
    if cls is None:
        log.fatal("Unknown objective type name: %s" % config.objective)
    return cls(config)


def objective_from_model_string(s: Optional[str], config: Config) -> Optional[ObjectiveFunction]:
    """Recreate an objective from its model-file string, e.g. 'binary sigmoid:1'
    (the reference's string-vector objective constructors)."""
    if not s:
        return None
    tokens = s.split()
    name = tokens[0]
    params = {}
    for tok in tokens[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            params[k] = v
        elif tok == "sqrt":
            params["reg_sqrt"] = True
    cfg_updates = {"objective": name}
    if "sigmoid" in params:
        cfg_updates["sigmoid"] = float(params["sigmoid"])
    if "num_class" in params:
        cfg_updates["num_class"] = int(params["num_class"])
    if params.get("reg_sqrt"):
        cfg_updates["reg_sqrt"] = True
    cfg = config.update(cfg_updates)
    cls = _OBJECTIVES.get(name)
    return cls(cfg) if cls is not None else None
