"""Plotting utilities (importance / metric / tree).

Mirrors the reference python package's plotting surface
(/root/reference/python-package/lightgbm/plotting.py:30 plot_importance,
:248 plot_metric, :422 plot_tree + create_tree_digraph) against this package's
Booster/eval-history objects. matplotlib and graphviz are optional; each entry
point raises ImportError with the reference's message style when missing.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError("%s must be a tuple of 2 elements." % obj_name)


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim: Optional[Tuple] = None,
    ylim: Optional[Tuple] = None,
    title: str = "Feature importance",
    xlabel: str = "Feature importance",
    ylabel: str = "Features",
    importance_type: str = "split",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize: Optional[Tuple] = None,
    grid: bool = True,
    precision: int = 3,
    **kwargs,
):
    """Plot model's feature importances (plotting.py:30-130)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance.")

    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type=importance_type)
        feature_name = booster.feature_name()
    elif hasattr(booster, "booster_"):  # sklearn wrapper
        importance = booster.booster_.feature_importance(importance_type=importance_type)
        feature_name = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel.")

    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(
            x + 1,
            y,
            ("%." + str(precision) + "f") % x if importance_type == "gain" else str(int(x)),
            va="center",
        )
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(
    booster,
    feature,
    bins=None,
    ax=None,
    width_coef: float = 0.8,
    xlim: Optional[Tuple] = None,
    ylim: Optional[Tuple] = None,
    title: str = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: str = "Feature split value",
    ylabel: str = "Count",
    figsize: Optional[Tuple] = None,
    grid: bool = True,
    **kwargs,
):
    """Plot the histogram of split thresholds for one feature
    (plotting.py plot_split_value_histogram)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError(
            "You must install matplotlib to plot split value histogram."
        )

    if isinstance(booster, Booster):
        counts, bin_edges = booster.get_split_value_histogram(feature, bins=bins)
    elif hasattr(booster, "booster_"):  # sklearn wrapper
        counts, bin_edges = booster.booster_.get_split_value_histogram(feature, bins=bins)
    else:
        raise TypeError("booster must be Booster or LGBMModel.")
    if counts.sum() == 0:
        raise ValueError(
            "Cannot plot split value histogram, "
            "because feature {} was not used in splitting".format(feature)
        )

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2.0
    widths = np.diff(bin_edges) * width_coef
    ax.bar(centers, counts, width=widths, align="center", **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(counts) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace(
            "@index/name@", "name" if isinstance(feature, str) else "index"
        ).replace("@feature@", str(feature))
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster,
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim: Optional[Tuple] = None,
    ylim: Optional[Tuple] = None,
    title: str = "Metric during training",
    xlabel: str = "Iterations",
    ylabel: str = "auto",
    figsize: Optional[Tuple] = None,
    grid: bool = True,
):
    """Plot one metric during training (plotting.py:248-360).

    ``booster`` is a dict returned by ``record_evaluation`` or a Booster (whose
    eval history is used).
    """
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")

    if isinstance(booster, Booster):
        eval_results = deepcopy(booster._gbdt.eval_history())
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    else:
        raise TypeError("booster must be dict or Booster.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    elif not dataset_names:
        raise ValueError("dataset_names cannot be empty.")

    name = dataset_names[0]
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError("more than one metric available, pick one with the metric arg.")
        metric, results = metrics_for_one.popitem()
    else:
        if metric not in metrics_for_one:
            raise ValueError("No given metric in eval results.")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)

    for name in dataset_names[1:]:
        metrics_for_one = eval_results[name]
        results = metrics_for_one[metric]
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        ax.plot(range(len(results)), results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2, max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_graphviz(
    tree_info: Dict,
    show_info: List[str],
    feature_names: List[str],
    precision: int = 3,
    **kwargs,
):
    """Convert one dumped tree to a graphviz Digraph (plotting.py:360-420)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")

    def float2str(value, precision):
        return ("%." + str(precision) + "f") % value

    def add(root, parent=None, decision=None):
        if "split_index" in root:
            name = "split%d" % root["split_index"]
            if feature_names is not None:
                label = "<B>%s</B> %s " % (
                    feature_names[root["split_feature"]],
                    root["decision_type"],
                )
            else:
                label = "feature <B>%d</B> %s " % (
                    root["split_feature"],
                    root["decision_type"],
                )
            label += "<B>%s</B>" % float2str(root["threshold"], precision)
            for info in ["split_gain", "internal_value", "internal_count"]:
                if info in show_info:
                    output = info.split("_")[-1]
                    label += "<br/>%s: %s" % (
                        output,
                        float2str(root[info], precision)
                        if "value" in info or "gain" in info
                        else str(root[info]),
                    )
            graph.node(name, label="<" + label + ">")
            add(root["left_child"], name, "yes")
            add(root["right_child"], name, "no")
        else:
            name = "leaf%d" % root["leaf_index"]
            label = "leaf %d: " % root["leaf_index"]
            label += "<B>%s</B>" % float2str(root["leaf_value"], precision)
            if "leaf_count" in show_info and "leaf_count" in root:
                label += "<br/>count: %d" % root["leaf_count"]
            graph.node(name, label="<" + label + ">")
        if parent is not None:
            graph.edge(parent, name, decision)

    graph = Digraph(**kwargs)
    add(tree_info["tree_structure"])
    return graph


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: int = 3,
    **kwargs,
):
    """Create a graphviz digraph of one tree (plotting.py:422-480)."""
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel.")
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", None)
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    if show_info is None:
        show_info = []
    return _to_graphviz(tree_infos[tree_index], show_info, feature_names, precision, **kwargs)


def plot_tree(
    booster,
    ax=None,
    tree_index: int = 0,
    figsize: Optional[Tuple] = None,
    show_info: Optional[List[str]] = None,
    precision: int = 3,
    **kwargs,
):
    """Plot one trained tree via graphviz+matplotlib (plotting.py:480-560)."""
    try:
        import matplotlib.image as image
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(
        booster=booster, tree_index=tree_index, show_info=show_info,
        precision=precision, **kwargs,
    )
    from io import BytesIO

    s = BytesIO()
    s.write(graph.pipe(format="png"))
    s.seek(0)
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
