"""Command-line application: train / predict from config files.

TPU-native counterpart of the reference Application
(/root/reference/src/application/application.cpp, src/main.cpp): parses
``key=value`` argv tokens plus an optional ``config=`` file (argv wins,
application.cpp:48-81), dispatches on ``task`` (train/predict, config.h:26),
loads train/valid data with sidecar weight/query files, runs the boosting loop
with per-iteration metric output, and saves/loads LightGBM-format models.

Usage:  python -m lightgbm_tpu task=train config=train.conf [key=value ...]
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .obs import trace as trace_mod
from .config import Config, load_config_file
from .engine import train as train_api
from .io import load_sidecar, load_text_file
from .resil.atomic import atomic_write_text
from .resil.preempt import PREEMPT_EXIT_CODE, TrainingPreempted
from .utils import log
from .utils.vfile import vopen
from .utils.log import LightGBMError


def parse_args(argv: List[str]) -> Dict[str, str]:
    params = Config.kv2map(argv)
    if "config" in params:
        file_params = load_config_file(params["config"])
        for k, v in file_params.items():
            params.setdefault(k, v)  # CLI overrides file
    return params


def _load_dataset(path: str, config: Config, reference: Optional[Dataset] = None) -> Dataset:
    from .dataset import is_binary_dataset_file

    if is_binary_dataset_file(path):
        # binary fast path (LoadFromBinFile, dataset_loader.cpp:268)
        log.info("Loading binned dataset from binary file %s" % path)
        return Dataset(path, reference=reference, params={})
    # valid files must come out as wide as the train set (sparse libsvm rows
    # may never reach the highest train feature index)
    ref_width = reference.num_feature() if reference is not None else None
    X, y, names = load_text_file(
        path,
        has_header=config.header,
        label_column=config.label_column,
        model_num_features=ref_width,
    )
    weight = load_sidecar(path, "weight")
    group = load_sidecar(path, "query")
    init_score = load_sidecar(path, "init")
    ds = Dataset(
        X,
        label=y,
        weight=weight,
        group=None if group is None else group.astype(np.int64),
        init_score=init_score,
        reference=reference,
        feature_name=names if names else "auto",
        params={},
    )
    return ds


def run_train(config: Config, params: Dict[str, str]) -> None:
    if not config.data:
        log.fatal("No training data specified (data=...)")
    log.info("Loading train data from %s" % config.data)
    train_set = _load_dataset(config.data, config)
    if config.save_binary:
        train_set.params.update(params)
        train_set.save_binary(config.data + ".bin")
        log.info("Saved binned dataset to %s.bin" % config.data)
    valid_sets = []
    valid_names = []
    for i, v in enumerate(config.valid):
        log.info("Loading validation data from %s" % v)
        valid_sets.append(_load_dataset(v, config, reference=train_set))
        valid_names.append("valid_%d" % (i + 1))

    params = dict(params)
    params.pop("config", None)
    params.pop("task", None)
    params.pop("data", None)
    params.pop("valid", None)
    params.pop("output_model", None)
    callbacks = []
    if config.snapshot_freq > 0:
        # periodic model snapshots next to the output model (gbdt.cpp:254-258)
        freq, path = config.snapshot_freq, config.output_model

        def _snapshot(env):
            if (env.iteration + 1) % freq == 0:
                snap = "%s.snapshot_iter_%d" % (path, env.iteration + 1)
                env.model.save_model(snap)
                log.info("Saved snapshot to %s" % snap)

        _snapshot.order = 100
        callbacks.append(_snapshot)
    # crash-safe full-state checkpoints (beyond the model-only snapshots
    # above): checkpoint_path=... [checkpoint_rounds=N] resume_from=...
    # restart a SIGKILLed run bit-identically (docs/FaultTolerance.md);
    # engine.train pops these from params so the model footer stays clean
    booster = train_api(
        params,
        train_set,
        num_boost_round=config.num_iterations,
        valid_sets=valid_sets or None,
        valid_names=valid_names or None,
        init_model=config.input_model or None,
        early_stopping_rounds=config.early_stopping_round or None,
        verbose_eval=config.metric_freq if config.verbosity >= 1 else False,
        callbacks=callbacks or None,
        checkpoint_path=config.checkpoint_path or None,
        checkpoint_rounds=max(config.checkpoint_rounds, 0),
        resume_from=config.resume_from or None,
        # checkpoint_keep / preempt_exit deliberately NOT passed as kwargs:
        # they ride the params map engine.train pops, where an EXPLICIT
        # preempt_exit=false wins over LIGHTGBM_TPU_PREEMPT=1 — a
        # `config.preempt_exit or None` kwarg would collapse that false to
        # "unset" and the env would re-arm the job
    )
    booster.save_model(config.output_model)
    log.info("Finished training; model saved to %s" % config.output_model)


def run_predict(config: Config, params: Dict[str, str]) -> None:
    if not config.data:
        log.fatal("No prediction data specified (data=...)")
    if not config.input_model:
        log.fatal("No model file specified (input_model=...)")
    booster = Booster(model_file=config.input_model)
    X, _, _ = load_text_file(
        config.data,
        has_header=config.header,
        label_column=config.label_column,
        model_num_features=booster.num_feature(),
    )
    preds = booster.predict(
        X,
        num_iteration=config.num_iteration_predict,
        raw_score=config.predict_raw_score,
        pred_leaf=config.predict_leaf_index,
        pred_contrib=config.predict_contrib,
        pred_early_stop=config.pred_early_stop,
        pred_early_stop_freq=config.pred_early_stop_freq,
        pred_early_stop_margin=config.pred_early_stop_margin,
    )
    out = np.asarray(preds)
    with vopen(config.output_result, "w") as fh:
        # the per-value "%.18g" formatting beats np.savetxt ~2.3x at 1M rows
        # (savetxt re-parses its row format per line; measured r4); chunked
        # joins keep peak memory bounded on huge prediction files
        if out.ndim == 1:
            step = 1 << 17
            for i in range(0, out.shape[0], step):
                fh.write("\n".join(map("%.18g".__mod__, out[i:i + step].tolist())))
                fh.write("\n")
        else:
            for row in out:
                fh.write("\t".join("%.18g" % v for v in row) + "\n")
    log.info("Finished prediction; results saved to %s" % config.output_result)


def run_convert_model(config: Config, params: Dict[str, str]) -> None:
    """task=convert_model (application.cpp:258-262): model file → standalone
    C++ source (if-else codegen)."""
    if not config.input_model:
        log.fatal("No model file specified (input_model=...)")
    from .models.model_codegen import save_model_to_ifelse

    booster = Booster(model_file=config.input_model)
    code = save_model_to_ifelse(booster._gbdt, num_iteration=-1)
    atomic_write_text(config.convert_model, code)
    log.info("Finished converting model; source saved to %s" % config.convert_model)


def run_serve(config: Config, params: Dict[str, str]) -> None:
    """task=serve: stand up the inference server on ``input_model``
    (lightgbm_tpu/serve). Extra knobs ride in as raw params:
    serve_host / serve_port / serve_mode / max_batch_rows / max_delay_ms."""
    if not config.input_model:
        log.fatal("No model file specified (input_model=...)")
    from .serve.__main__ import main as serve_main

    argv = [config.input_model,
            "--host", params.get("serve_host", "127.0.0.1"),
            "--port", params.get("serve_port", "8080"),
            "--mode", params.get("serve_mode", "exact")]
    if "max_batch_rows" in params:
        argv += ["--max-batch-rows", params["max_batch_rows"]]
    if "max_delay_ms" in params:
        argv += ["--max-delay-ms", params["max_delay_ms"]]
    serve_main(argv)


def run_refit(config: Config, params: Dict[str, str]) -> None:
    """task=refit (application.cpp:214-239): load model, predict leaves on
    data, refit leaf values on its labels, save."""
    if not config.data:
        log.fatal("No refit data specified (data=...)")
    if not config.input_model:
        log.fatal("No model file specified (input_model=...)")
    booster = Booster(model_file=config.input_model, params=dict(params))
    X, y, _ = load_text_file(
        config.data,
        has_header=config.header,
        label_column=config.label_column,
        model_num_features=booster.num_feature(),
    )
    if y is None:
        log.fatal("Refit data must contain a label column")
    refitted = booster.refit(X, y, decay_rate=config.refit_decay_rate)
    refitted.save_model(config.output_model)
    log.info("Finished RefitTree; model saved to %s" % config.output_model)


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    try:
        params = parse_args(argv)
        config = Config.from_params(params)
        # task-level obs span: with LIGHTGBM_TPU_TRACE set, the whole CLI
        # task becomes the root span the training/serving spans nest under
        with trace_mod.span("cli.%s" % config.task, cat="cli"):
            if config.task == "train":
                run_train(config, params)
            elif config.task in ("predict", "prediction", "test"):
                run_predict(config, params)
            elif config.task == "convert_model":
                run_convert_model(config, params)
            elif config.task == "refit":
                run_refit(config, params)
            elif config.task == "serve":
                run_serve(config, params)
            else:
                log.fatal("Unknown task: %s" % config.task)
    except TrainingPreempted as e:
        # the boundary-latch contracts (docs/FaultTolerance.md): a durable
        # checkpoint was published at the last boundary, and the DISTINCT
        # exit code tells orchestrators what kind of relaunch is wanted —
        # 75 "resume me as I was" (preempt; loop restart, tpu_bringup
        # run_with_retry), 76 "relaunch me at current capacity" (flexctl
        # drain, §Fleet orchestrator)
        if getattr(e, "reason", "preempt") == "drain":
            log.warning(
                "train drained for reshard (%s); checkpoint: %s — the "
                "flex controller relaunches at the new capacity; exiting %d"
                % (e, e.checkpoint_path or "<none>", e.exit_code)
            )
            return e.exit_code
        log.warning(
            "train preempted (%s); emergency checkpoint: %s — re-run with "
            "resume_from to continue; exiting %d"
            % (e, e.checkpoint_path or "<none>", PREEMPT_EXIT_CODE)
        )
        return PREEMPT_EXIT_CODE
    except LightGBMError as e:
        # application_main's catch block ("Met Exceptions", main.cpp): a clean
        # message + nonzero exit, not a traceback
        print("Met Exceptions:\n%s" % e, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
