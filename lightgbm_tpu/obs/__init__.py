"""lightgbm_tpu.obs: the unified observability layer (docs/Observability.md).

System tier (trace/retrace/memwatch/costs/prof/registry) plus the model/data
tier — :mod:`~lightgbm_tpu.obs.flight` (training flight recorder),
:mod:`~lightgbm_tpu.obs.modelstats` (importance evolution, bin occupancy,
leaf shape) and :mod:`~lightgbm_tpu.obs.report` (the self-contained HTML run
report); the serve-side drift monitor lives in serve/drift.py. One spine:

 * :mod:`~lightgbm_tpu.obs.trace`    — structured span tracer; Chrome-trace
   JSON via ``LIGHTGBM_TPU_TRACE=<path>``, Perfetto-viewable, device-aligned
   through ``jax.profiler.TraceAnnotation``.
 * :mod:`~lightgbm_tpu.obs.retrace`  — jit-compile watchdog; counts real XLA
   traces per entry point, ``LIGHTGBM_TPU_RETRACE=fail`` hard-fails on
   retraces after warmup.
 * :mod:`~lightgbm_tpu.obs.memwatch` — device-memory snapshots at named
   points + shape-math attribution of the known large carries.
 * :mod:`~lightgbm_tpu.obs.costs`    — measured XLA cost analysis per core
   executable (flops / bytes via ``lower().compile().cost_analysis()``,
   env-gated ``LIGHTGBM_TPU_COSTS=1``) + the per-``device_kind`` roofline
   peak table bench.py reads.
 * :mod:`~lightgbm_tpu.obs.prof`     — the segment profiler: tree growth as
   separately-dispatched fenced sub-steps (``LIGHTGBM_TPU_PROF_SEGMENTS``),
   proven bitwise-identical to the fused grower.
 * :mod:`~lightgbm_tpu.obs.registry` — the one metrics registry (counters /
   gauges / histograms / rates) behind the serve ``/metrics`` Prometheus
   endpoint, the training callback, and the bench/bringup run reports.
 * :mod:`~lightgbm_tpu.obs.sanitize` — the graftsan runtime sanitizer
   (``LIGHTGBM_TPU_SAN=transfer,nan,locks``): transfer guards at the jitted
   dispatch seams, NaN tripwires on the score carries, lock-order inversion
   detection (docs/StaticAnalysis.md §Runtime sanitizer).
 * :mod:`~lightgbm_tpu.obs.tune`     — the shape-aware histogram autotuner
   (``python -m lightgbm_tpu.obs.tune``): measured per-shape kernel
   routing tables, atomically persisted, frozen per training run
   (docs/HistogramRouting.md). Imported lazily (it pulls ops/ on use).
 * :mod:`~lightgbm_tpu.obs.devprof`  — the device-timeline auditor
   (``python -m lightgbm_tpu.obs.devprof``): parses the XLA profile a
   ``LIGHTGBM_TPU_PROFILE`` capture emits, attributes device self-time to
   the TraceAnnotation segment vocabulary, and classifies the run
   host- / device- / transfer-bound (docs/Observability.md §Device
   timeline). Stdlib-only parsing; imported lazily by its callers.
 * :mod:`~lightgbm_tpu.obs.podwatch` — the live fleet telemetry plane
   (``python -m lightgbm_tpu.obs.podwatch``): per-rank chunk-boundary
   time-series ring (``LIGHTGBM_TPU_TELEMETRY=<dir>``), the opt-in
   training-side scrape endpoint (``LIGHTGBM_TPU_TELEMETRY_PORT``:
   /metrics /health /timeline), and the cross-rank aggregator issuing
   straggler/stall/skew/dead verdicts from the shards + heartbeats
   (docs/Observability.md §Fleet telemetry). Not imported by this
   package's init; the aggregator half never imports jax.

Importing this package never touches a jax backend.
"""
from __future__ import annotations

from . import costs, flight, memwatch, modelstats, registry, retrace, trace  # noqa: F401
from .registry import REGISTRY, MetricsRegistry  # noqa: F401

# NOTE: obs.prof and obs.dist (the mesh-aware distributed tier: sharded
# compute-vs-collective attribution, pod-wide registry/trace merging,
# shard-skew detection) are imported lazily by their callers (they pull
# ops/ and parallel/ code paths this package promises to avoid at import
# time — dist's merge helpers themselves stay jax-lazy). obs.report is
# the run-report CLI (`python -m lightgbm_tpu.obs.report`) and is
# imported on use; `python -m lightgbm_tpu.obs.trace merge` folds
# per-process trace files into one timeline.

# cross-wiring: the default registry's watchdog/memory gauges pull live
# values at read time, so any exposition (serve /metrics, run_report) is
# current without a push site having to remember them
REGISTRY.gauge(
    "jit_traces_total"
).set_fn(lambda: float(sum(retrace.WATCHDOG.counts().values())))
REGISTRY.gauge(
    "jit_retraces_after_warmup"
).set_fn(lambda: float(retrace.WATCHDOG.total_retraces()))
REGISTRY.gauge("device_peak_bytes").set_fn(memwatch.peak_device_bytes)
# the measured-cost book rides in every run report (empty dict -> omitted)
REGISTRY.register_report_section("cost_analysis", costs.COSTS.report)

__all__ = [
    "REGISTRY",
    "MetricsRegistry",
    "costs",
    "flight",
    "memwatch",
    "modelstats",
    "registry",
    "retrace",
    "trace",
]
