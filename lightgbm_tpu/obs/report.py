"""Self-contained HTML run report: one file, zero dependencies, inline SVG.

``python -m lightgbm_tpu.obs.report`` renders a training flight log
(obs/flight.py), a metrics/run-report snapshot (obs/registry.py), optional
BENCH_*.json series and a drift snapshot into a single HTML file a browser
opens offline — the artifact a bringup round attaches next to
TPU_BRINGUP.json, and what a perf investigation passes around instead of
four JSON files and a plotting environment.

Sections (each rendered only when its input is present):

  * run manifest (config digest, dataset shape, backend, resume provenance)
  * learning curves — eval-history series per dataset/metric
  * per-tree gain + leaf count along the boosting sequence
  * cumulative gain-importance evolution of the top features
  * growth segment breakdown (obs/prof.py, PR 6)
  * device timeline audit (obs/devprof.py: busy/idle lanes, top-op table,
    segment-grouped device self-time, transfers, bound-ness verdict)
  * serve drift table (serve/drift.py PSI per feature)
  * bench series (headline value across BENCH_r*.json rounds)
  * counters/gauges digest

Usage::

    python -m lightgbm_tpu.obs.report --flight run.jsonl \
        --metrics metrics.json --bench 'BENCH_r*.json' -o report.html

``--metrics`` accepts either a bare ``run_report()`` block or a full bench
record (the ``obs_report`` key is unwrapped). Stdlib-only: importing this
module never touches a jax backend.
"""
from __future__ import annotations

import argparse
import glob
import html
import json
import math
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

Point = Tuple[float, float]
Series = Tuple[str, List[Point]]

#: categorical palette for chart series (hex, print-safe)
PALETTE = (
    "#2563eb", "#dc2626", "#059669", "#d97706", "#7c3aed",
    "#0891b2", "#be185d", "#4d7c0f", "#b45309", "#1e40af",
)

_CSS = """
body { font: 14px/1.45 system-ui, sans-serif; margin: 24px auto; max-width: 960px;
       color: #1f2430; }
h1 { font-size: 22px; } h2 { font-size: 17px; margin-top: 28px;
     border-bottom: 1px solid #d8dce4; padding-bottom: 4px; }
table { border-collapse: collapse; margin: 8px 0; }
td, th { border: 1px solid #d8dce4; padding: 3px 9px; text-align: left;
         font-size: 13px; }
th { background: #f1f3f7; }
.small { color: #6a7283; font-size: 12px; }
.alert { color: #b91c1c; font-weight: 600; }
.ok { color: #15803d; }
svg { background: #fbfcfe; border: 1px solid #e3e6ee; margin: 6px 0; }
.bar { fill: #2563eb; } .barlabel { font-size: 11px; fill: #1f2430; }
"""


def _esc(v) -> str:
    return html.escape(str(v))


def _fmt(v: float) -> str:
    if not math.isfinite(v):
        return str(v)  # a diverged run's NaN/inf must render, not crash
    a = abs(v)
    if v == int(v) and a < 1e7:
        return str(int(v))
    if a != 0 and (a < 1e-3 or a >= 1e6):
        return "%.3g" % v
    return "%.4g" % v


# ---------------------------------------------------------------------------
# inline-SVG primitives
# ---------------------------------------------------------------------------

def svg_line_chart(
    series: Sequence[Series], title: str = "", width: int = 860,
    height: int = 230, y_zero: bool = False,
) -> str:
    """Multi-series polyline chart with min/max axis labels and a legend."""
    series = [
        (name, [(x, y) for x, y in pts
                if math.isfinite(x) and math.isfinite(y)])
        for name, pts in series
    ]
    series = [(name, pts) for name, pts in series if pts]
    if not series:
        return ""
    xs = [x for _, pts in series for x, _ in pts]
    ys = [y for _, pts in series for _, y in pts]
    x0, x1 = min(xs), max(xs)
    y0, y1 = (0.0 if y_zero else min(ys)), max(ys)
    if x1 == x0:
        x1 = x0 + 1
    if y1 == y0:
        y1 = y0 + (abs(y0) if y0 else 1.0)
    ml, mr, mt, mb = 58, 12, 24, 30
    iw, ih = width - ml - mr, height - mt - mb

    def sx(x: float) -> float:
        return ml + (x - x0) / (x1 - x0) * iw

    def sy(y: float) -> float:
        return mt + (1 - (y - y0) / (y1 - y0)) * ih

    out = ['<svg width="%d" height="%d" role="img">' % (width, height)]
    if title:
        out.append(
            '<text x="%d" y="15" font-size="13" font-weight="600">%s</text>'
            % (ml, _esc(title))
        )
    # frame + y min/max + x min/max
    out.append(
        '<rect x="%d" y="%d" width="%d" height="%d" fill="none" '
        'stroke="#c4cad6"/>' % (ml, mt, iw, ih)
    )
    for y, anchor_y in ((y1, mt + 10), (y0, mt + ih)):
        out.append(
            '<text x="%d" y="%d" font-size="11" text-anchor="end" '
            'fill="#6a7283">%s</text>' % (ml - 5, anchor_y, _fmt(y))
        )
    for x, anchor in ((x0, "start"), (x1, "end")):
        out.append(
            '<text x="%d" y="%d" font-size="11" text-anchor="%s" '
            'fill="#6a7283">%s</text>'
            % (sx(x), height - 8, anchor, _fmt(x))
        )
    for i, (name, pts) in enumerate(series):
        color = PALETTE[i % len(PALETTE)]
        coord = " ".join(
            "%.1f,%.1f" % (sx(x), sy(y)) for x, y in sorted(pts)
        )
        out.append(
            '<polyline points="%s" fill="none" stroke="%s" '
            'stroke-width="1.6"/>' % (coord, color)
        )
        # legend row (right-aligned stack)
        out.append(
            '<rect x="%d" y="%d" width="10" height="10" fill="%s"/>'
            '<text x="%d" y="%d" font-size="11">%s</text>'
            % (width - 190, mt + 4 + i * 15, color,
               width - 176, mt + 13 + i * 15, _esc(name[:26]))
        )
    out.append("</svg>")
    return "".join(out)


def svg_bar_chart(
    items: Sequence[Tuple[str, float]], title: str = "", width: int = 640,
    unit: str = "",
) -> str:
    """Horizontal bars (segment breakdowns, share tables)."""
    items = [(k, v) for k, v in items if v is not None]
    if not items:
        return ""
    vmax = max(v for _, v in items) or 1.0
    row_h, ml = 22, 170
    height = 28 + row_h * len(items)
    out = ['<svg width="%d" height="%d" role="img">' % (width, height)]
    if title:
        out.append(
            '<text x="6" y="15" font-size="13" font-weight="600">%s</text>'
            % _esc(title)
        )
    for i, (name, v) in enumerate(items):
        y = 26 + i * row_h
        w = max((width - ml - 130) * v / vmax, 1.0)
        out.append(
            '<text x="%d" y="%d" font-size="12" text-anchor="end">%s</text>'
            % (ml - 6, y + 12, _esc(str(name)[:24]))
        )
        out.append(
            '<rect class="bar" x="%d" y="%d" width="%.1f" height="14"/>'
            % (ml, y, w)
        )
        out.append(
            '<text class="barlabel" x="%.1f" y="%d">%s%s</text>'
            % (ml + w + 5, y + 12, _fmt(v), _esc(unit))
        )
    out.append("</svg>")
    return "".join(out)


def svg_stacked_bars(
    items: Sequence[Tuple[str, Sequence[Tuple[str, float, str]]]],
    title: str = "", width: int = 640, unit: str = "",
) -> str:
    """Horizontal stacked bars: one row per item, each a list of
    (segment_name, value, color) parts — the comms-vs-compute split of the
    Multichip section. A legend is built from the distinct segment names."""
    items = [(k, [(n, v, c) for n, v, c in parts if v and v > 0])
             for k, parts in items]
    items = [(k, parts) for k, parts in items if parts]
    if not items:
        return ""
    vmax = max(sum(v for _, v, _ in parts) for _, parts in items) or 1.0
    row_h, ml = 22, 170
    legend: List[Tuple[str, str]] = []
    for _, parts in items:
        for n, _, c in parts:
            if (n, c) not in legend:
                legend.append((n, c))
    height = 28 + row_h * len(items) + 18
    out = ['<svg width="%d" height="%d" role="img">' % (width, height)]
    if title:
        out.append(
            '<text x="6" y="15" font-size="13" font-weight="600">%s</text>'
            % _esc(title)
        )
    for i, (name, parts) in enumerate(items):
        y = 26 + i * row_h
        out.append(
            '<text x="%d" y="%d" font-size="12" text-anchor="end">%s</text>'
            % (ml - 6, y + 12, _esc(str(name)[:24]))
        )
        x = float(ml)
        total = sum(v for _, v, _ in parts)
        for _, v, color in parts:
            w = max((width - ml - 130) * v / vmax, 1.0)
            out.append(
                '<rect x="%.1f" y="%d" width="%.1f" height="14" '
                'fill="%s"/>' % (x, y, w, color)
            )
            x += w
        out.append(
            '<text class="barlabel" x="%.1f" y="%d">%s%s</text>'
            % (x + 5, y + 12, _fmt(total), _esc(unit))
        )
    ly = 26 + row_h * len(items) + 4
    lx = ml
    for name, color in legend:
        out.append(
            '<rect x="%d" y="%d" width="10" height="10" fill="%s"/>'
            '<text x="%d" y="%d" font-size="11">%s</text>'
            % (lx, ly, color, lx + 14, ly + 9, _esc(name[:18]))
        )
        lx += 14 + 7 * min(len(name), 18) + 18
    out.append("</svg>")
    return "".join(out)


def _table(headers: Sequence[str], rows: Sequence[Sequence[object]]) -> str:
    out = ["<table><tr>"]
    out.extend("<th>%s</th>" % _esc(h) for h in headers)
    out.append("</tr>")
    for row in rows:
        out.append(
            "<tr>" + "".join("<td>%s</td>" % (c if str(c).startswith("<span")
                                              else _esc(c)) for c in row)
            + "</tr>"
        )
    out.append("</table>")
    return "".join(out)


# ---------------------------------------------------------------------------
# sections
# ---------------------------------------------------------------------------

def _section_manifest(flight: Dict) -> str:
    man = flight.get("manifest") or {}
    if not man:
        return ""
    keys = (
        "objective", "num_data", "num_features", "num_class",
        "num_boost_round", "init_iteration", "backend", "config_digest",
        "label_digest", "started_at", "resume_from", "checkpoint_path",
    )
    rows = [(k, man[k]) for k in keys if man.get(k) not in (None, "", {})]
    end = flight.get("end") or {}
    for k in ("num_trees", "iterations", "best_iteration", "stopped"):
        if k in end:
            rows.append((k, end[k]))
    return "<h2>Run manifest</h2>" + _table(("field", "value"), rows)


def _section_learning_curves(flight: Dict) -> str:
    by_key: Dict[str, List[Point]] = {}
    for it in flight.get("iterations", []):
        for (dname, mname, val) in it.get("evals", []):
            by_key.setdefault("%s/%s" % (dname, mname), []).append(
                (float(it["iteration"]) + 1, float(val))
            )
    if not by_key:
        return ""
    chart = svg_line_chart(
        sorted(by_key.items()), title="eval metrics vs iteration"
    )
    return "<h2>Learning curves</h2>" + chart


def _section_trees(flight: Dict) -> str:
    trees = flight.get("trees", [])
    if not trees:
        return ""
    gain = [(float(t["tree"]), float(t.get("total_gain", 0))) for t in trees]
    leaves = [(float(t["tree"]), float(t.get("num_leaves", 0))) for t in trees]
    depth = [(float(t["tree"]), float(t.get("max_depth", 0))) for t in trees]
    out = ["<h2>Per-tree shape</h2>"]
    out.append(svg_line_chart(
        [("total_gain", gain)], title="split gain per tree", y_zero=True,
    ))
    out.append(svg_line_chart(
        [("num_leaves", leaves), ("max_depth", depth)],
        title="leaf count / depth per tree", y_zero=True,
    ))
    return "".join(out)


def _section_importance_evolution(flight: Dict, top: int = 6) -> str:
    trees = flight.get("trees", [])
    if not trees:
        return ""
    totals: Dict[str, float] = {}
    cum: Dict[str, List[Point]] = {}
    running: Dict[str, float] = {}
    for t in trees:
        for f, g in t.get("top_gain_features", []) or []:
            key = "f%s" % f
            running[key] = running.get(key, 0.0) + float(g)
            totals[key] = running[key]
        x = float(t["tree"])
        for key, v in running.items():
            cum.setdefault(key, []).append((x, v))
    if not totals:
        return ""
    top_keys = [k for k, _ in sorted(totals.items(), key=lambda kv: -kv[1])][:top]
    series = [(k, cum[k]) for k in top_keys]
    return (
        "<h2>Importance evolution</h2>"
        '<div class="small">cumulative split gain of the top features '
        "(per-tree top-%d records; features outside a tree's top-k "
        "accumulate at their next appearance)</div>" % 5
        + svg_line_chart(series, title="cumulative gain vs tree", y_zero=True)
    )


def _metrics_block(metrics: Optional[Dict]) -> Dict:
    """Accept a run_report() block or a full bench record (obs_report key)."""
    if not metrics:
        return {}
    if "obs_report" in metrics and isinstance(metrics["obs_report"], dict):
        return metrics["obs_report"]
    return metrics


def _section_segments(metrics: Dict) -> str:
    segs = metrics.get("growth_segments_s")
    if not isinstance(segs, dict) or not segs:
        return ""
    items = sorted(segs.items(), key=lambda kv: -float(kv[1]))
    return (
        "<h2>Growth segment breakdown</h2>"
        + svg_bar_chart(
            [(k, float(v)) for k, v in items],
            title="seconds per tree (obs/prof.py)", unit=" s",
        )
    )


def _section_device_timeline(metrics: Dict) -> str:
    """The device-timeline audit (obs/devprof.py): busy/idle per lane,
    segment-grouped device self-time (``unattributed`` rendered like any
    other — loudly), the top-op table with roofline placement, transfer
    totals, and the bound-ness verdict with its evidence inline."""
    rec = metrics.get("device_timeline")
    if not isinstance(rec, dict) or not rec:
        return ""
    out = ["<h2>Device timeline</h2>"]
    v = rec.get("verdict") or {}
    if v.get("bound"):
        cls = "ok" if v["bound"] == "device-bound" else "alert"
        out.append(
            '<div><span class="%s">verdict: %s</span> — '
            '<span class="small">%s</span></div>'
            % (cls, _esc(v["bound"]), _esc(v.get("why", "")))
        )
    if rec.get("lanes_source"):
        out.append(
            '<div class="small">lanes: %s · window %ss · '
            "device_busy_fraction %s · attributed %s</div>"
            % (
                _esc(rec["lanes_source"]), _fmt(float(rec.get("window_s", 0))),
                "-" if rec.get("device_busy_fraction") is None
                else "%.3f" % rec["device_busy_fraction"],
                "-" if rec.get("attributed_fraction") is None
                else "%.0f%%" % (100 * rec["attributed_fraction"]),
            )
        )
    lanes = rec.get("lanes") or []
    if lanes:
        out.append(svg_stacked_bars(
            [
                (
                    str(ln.get("device", "?")),
                    [
                        ("busy", float(ln.get("busy_s", 0.0)), "#2563eb"),
                        ("idle",
                         max(float(rec.get("window_s", 0.0))
                             - float(ln.get("busy_s", 0.0)), 0.0),
                         "#d8dce4"),
                    ],
                )
                for ln in lanes
            ],
            title="busy vs idle per device lane", unit=" s",
        ))
    segs = rec.get("segments") or {}
    if segs:
        out.append(svg_bar_chart(
            [(k, float(s)) for k, s in segs.items()],
            title="device self-time per segment (TraceAnnotation "
                  "attribution)", unit=" s",
        ))
    tops = rec.get("top_ops") or []
    if tops:
        out.append(_table(
            ("op", "segment", "self s", "count", "share", "peak FLOPs"),
            [
                (
                    str(t.get("op", ""))[:60], t.get("segment", ""),
                    _fmt(float(t.get("self_s", 0.0))), t.get("count", 0),
                    "%.1f%%" % (100 * float(t.get("share", 0.0))),
                    "-" if t.get("peak_flops_fraction") is None
                    else "%.2f%%" % (100 * t["peak_flops_fraction"]),
                )
                for t in tops
            ],
        ))
    tr = rec.get("transfers") or {}
    if tr:
        rows = []
        for direction in ("h2d", "d2h"):
            d = tr.get(direction) or {}
            if d:
                rows.append((direction, d.get("count", 0),
                             _fmt(float(d.get("seconds", 0.0))),
                             _fmt(float(d.get("bytes", 0)))))
        if rows:
            out.append(_table(("direction", "events", "seconds", "bytes"),
                              rows))
    gaps = rec.get("dispatch_gaps") or {}
    if gaps.get("histogram"):
        out.append(svg_bar_chart(
            [(k, float(n)) for k, n in gaps["histogram"].items()],
            title="dispatch-gap (device idle) histogram", unit=" gaps",
        ))
    return "".join(out)


def _section_drift(metrics: Dict, drift: Optional[Dict]) -> str:
    # (sort key, model, feature, psi text, state) — psi sorts NUMERICALLY
    # (string sort would rank "9.0" above "12.3"); None psi sinks to the end
    rows: List[Tuple[float, str, str, str, str]] = []
    threshold = None
    if drift:
        for model, snap in (drift.get("models") or {}).items():
            threshold = snap.get("threshold")
            for name, st in (snap.get("features") or {}).items():
                if not st.get("tracked"):
                    continue
                v = st.get("psi")
                mark = (
                    '<span class="alert">ALERT</span>'
                    if st.get("alert") else '<span class="ok">ok</span>'
                )
                rows.append((
                    float("-inf") if v is None else float(v),
                    model, name, "-" if v is None else "%.4f" % v, mark,
                ))
    else:
        for key, v in (metrics.get("gauges") or {}).items():
            if not key.startswith("serve_drift_psi{"):
                continue
            body = key[len("serve_drift_psi{"):-1]
            labels = dict(
                kv.split("=", 1) for kv in body.split(",") if "=" in kv
            )
            rows.append((
                float(v), labels.get("model", ""),
                labels.get("feature", key), "%.4f" % float(v), "",
            ))
    if not rows:
        return ""
    head = "<h2>Serve drift (PSI vs training reference)</h2>"
    if threshold is not None:
        head += '<div class="small">alert threshold %s</div>' % _esc(threshold)
    rows.sort(key=lambda r: r[0], reverse=True)
    return head + _table(
        ("model", "feature", "PSI", "state"), [r[1:] for r in rows]
    )


def _multichip_efficiency(rec: Dict) -> List[Point]:
    """(devices, scaling efficiency) points: measured iters/s at D devices
    over the ideal D x (the sweep's n=1 measurement). Prefers the record's
    own ``efficiency_by_devices`` (helpers/multichip_bench.py) and falls
    back to recomputing from the scaling list."""
    eff = rec.get("efficiency_by_devices")
    if eff:
        return [(float(d), float(e)) for d, e in eff]
    pts = sorted(
        (float(p["devices"]), float(p["iters_per_sec"]))
        for p in rec.get("scaling") or []
        if p.get("iters_per_sec")
    )
    base = next((v for d, v in pts if d == 1), None)
    if not base:
        return []
    return [(d, v / (d * base)) for d, v in pts]


def _section_multichip(records: List[Tuple[str, Dict]]) -> str:
    """The Multichip page: devices-vs-iters/s scaling curves, measured-vs-
    ideal scaling efficiency, the comms/compute split (obs/dist.py
    attribution), and the latest round's per-device shard table — one
    report answers 'how fast', 'how does it scale', and 'WHY it bends'."""
    series: List[Tuple[str, List[Point]]] = []
    eff_series: List[Tuple[str, List[Point]]] = []
    stacked = []
    rows = []
    latest_devices = None
    for name, rec in records:
        pts = [
            (float(p["devices"]), float(p["iters_per_sec"]))
            for p in rec.get("scaling") or []
            if p.get("iters_per_sec")
        ]
        if not pts:
            continue
        short = name.replace(".json", "")
        series.append((short, sorted(pts)))
        eff = _multichip_efficiency(rec)
        if eff:
            eff_series.append((short, eff))
        cf = rec.get("comms_fraction")
        if cf is not None:
            cf = float(cf)
            stacked.append((short, [
                ("comms", cf * 100.0, "#dc2626"),
                ("compute", (1.0 - cf) * 100.0, "#2563eb"),
            ]))
        if rec.get("per_device"):
            latest_devices = (short, rec["per_device"])
        rows.append((
            name, rec.get("platform", "?"),
            " / ".join("%g@%d" % (v, int(d)) for d, v in sorted(pts)),
            "-" if rec.get("speedup_vs_1dev") is None
            else "%.2fx" % rec["speedup_vs_1dev"],
            "-" if rec.get("scaling_efficiency") is None
            else "%.0f%%" % (float(rec["scaling_efficiency"]) * 100),
            "-" if cf is None else "%.1f%%" % (cf * 100),
        ))
    if not series:
        return ""
    out = ["<h2>Multichip scaling</h2>"]
    out.append(svg_line_chart(
        series, title="devices vs iters/s (data-parallel sharded chunk)",
        y_zero=True,
    ))
    if eff_series:
        # ideal = 1.0 reference line spanning the measured device range
        xs = [x for _, pts in eff_series for x, _ in pts]
        eff_series = eff_series + [
            ("ideal", [(min(xs), 1.0), (max(xs), 1.0)])
        ]
        out.append(svg_line_chart(
            eff_series,
            title="scaling efficiency (measured / ideal linear)",
            y_zero=True,
        ))
    if stacked:
        out.append(svg_stacked_bars(
            stacked,
            title="tree-growth time split: collective vs compute "
                  "(obs/dist.py)",
            unit="%",
        ))
    out.append(_table(
        ("record", "platform", "iters/s @ devices", "speedup vs 1 dev",
         "scaling eff", "comms"),
        rows,
    ))
    if latest_devices:
        short, per_dev = latest_devices
        out.append(
            '<div class="small">per-device shard table (%s)</div>' % short
        )
        out.append(_table(
            ("device", "rows", "wait s"),
            [
                (d.get("device", "?"), d.get("rows", "-"),
                 "-" if d.get("wait_s") is None else "%.4f" % d["wait_s"])
                for d in per_dev
            ],
        ))
    return "".join(out)


def _section_bench(bench_records: List[Tuple[str, Dict]]) -> str:
    if not bench_records:
        return ""
    bench_records = [
        (n, r) for n, r in bench_records if not r.get("scaling")
    ]
    if not bench_records:
        return ""
    pts_v: List[Point] = []
    pts_auc: List[Point] = []
    rows = []
    for i, (name, rec) in enumerate(bench_records):
        v = rec.get("value")
        if v is not None:
            pts_v.append((float(i), float(v)))
        auc = rec.get("train_auc")
        if auc is not None:
            pts_auc.append((float(i), float(auc)))
        rows.append((
            name, rec.get("platform", "?"),
            "-" if v is None else _fmt(float(v)),
            "-" if auc is None else "%.5f" % auc,
            rec.get("roofline_source", "-"),
        ))
    out = ["<h2>Bench series</h2>"]
    out.append(svg_line_chart(
        [("iters/s", pts_v)], title="headline iters/s per round", y_zero=True,
    ))
    if pts_auc:
        out.append(svg_line_chart(
            [("train_auc", pts_auc)], title="train AUC per round",
        ))
    out.append(_table(
        ("record", "platform", "iters/s", "train_auc", "roofline"), rows
    ))
    return "".join(out)


def _section_fleet(metrics: Dict) -> str:
    """§Fleet telemetry (obs/podwatch.py): the pod view — per-rank
    progress/rate table plus the evidence-backed straggler/stall/skew/dead
    verdict list, each sentence citing the threshold it tripped."""
    rec = metrics.get("fleet_telemetry")
    if not isinstance(rec, dict) or not rec.get("ranks"):
        return ""
    out = ["<h2>Fleet telemetry</h2>"]
    out.append(
        '<div class="small">world %s · iteration spread %s · '
        "podwatch over %s</div>"
        % (_esc(rec.get("world", "?")), _esc(rec.get("iteration_spread", 0)),
           _esc(rec.get("dir", "?")))
    )
    rows = []
    for r, info in sorted(rec["ranks"].items(), key=lambda kv: int(kv[0])):
        rows.append((
            r,
            _esc(info.get("iteration", "-")),
            _esc(info.get("it_per_s", "-")),
            _esc(info.get("chunk_s", "-")),
            _esc(info.get("samples", 0)),
        ))
    out.append(_table(
        ("rank", "iteration", "it/s", "chunk s", "samples"), rows
    ))
    verdicts = rec.get("verdicts") or []
    if not verdicts:
        out.append('<div><span class="ok">no verdicts</span> — '
                   '<span class="small">pod looks healthy</span></div>')
    for v in verdicts:
        out.append(
            '<div><span class="alert">%s rank %s</span> — '
            '<span class="small">%s</span></div>'
            % (_esc(v.get("verdict")), _esc(v.get("rank")),
               _esc(v.get("why", "")))
        )
    return "".join(out)


def _section_registry_digest(metrics: Dict, limit: int = 40) -> str:
    rows: List[Tuple[str, str]] = []
    for kind in ("counters", "gauges", "rates"):
        for k, v in sorted((metrics.get(kind) or {}).items())[:limit]:
            rows.append(("%s %s" % (kind[:-1], k), _fmt(float(v))))
    if not rows:
        return ""
    return "<h2>Registry digest</h2>" + _table(("metric", "value"), rows)


# ---------------------------------------------------------------------------
# assembly + CLI
# ---------------------------------------------------------------------------

def load_bench_records(pattern: str) -> List[Tuple[str, Dict]]:
    """(basename, record) for every bench JSON matching ``pattern``: the
    driver's BENCH_r*.json wrapper is unwrapped (record under "parsed"),
    bare bench.py records pass through, anything without a "metric" key is
    skipped. The ONE adoption rule shared by the report CLI and
    helpers/tpu_bringup.py's per-round report."""
    out: List[Tuple[str, Dict]] = []
    for p in sorted(glob.glob(pattern)):
        try:
            with open(p, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = doc.get("parsed") if isinstance(doc, dict) else None
        rec = rec if isinstance(rec, dict) else doc
        if isinstance(rec, dict) and "metric" in rec:
            out.append((os.path.basename(p), rec))
    return out


def render(
    flight: Optional[Dict] = None,
    metrics: Optional[Dict] = None,
    bench_records: Optional[List[Tuple[str, Dict]]] = None,
    drift: Optional[Dict] = None,
    title: str = "lightgbm_tpu run report",
) -> str:
    """Assemble the report HTML from whatever inputs exist (each may be
    None); always returns a complete document."""
    flight = flight or {}
    mblock = _metrics_block(metrics)
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        "<title>%s</title><style>%s</style></head><body>" % (_esc(title), _CSS),
        "<h1>%s</h1>" % _esc(title),
        _section_manifest(flight),
        _section_learning_curves(flight),
        _section_trees(flight),
        _section_importance_evolution(flight),
        _section_segments(mblock),
        _section_device_timeline(mblock),
        _section_fleet(mblock),
        _section_drift(mblock, drift),
        _section_bench(bench_records or []),
        _section_multichip(bench_records or []),
        _section_registry_digest(mblock),
        "<div class='small'>generated by python -m lightgbm_tpu.obs.report"
        "</div></body></html>",
    ]
    return "".join(p for p in parts if p)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.report",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--flight", help="flight JSONL log (obs/flight.py)")
    ap.add_argument("--metrics",
                    help="run_report JSON (or a bench record; obs_report "
                         "is unwrapped)")
    ap.add_argument("--bench", help="glob of bench JSON records "
                                    "(e.g. 'BENCH_r*.json')")
    ap.add_argument("--drift", help="a /drift endpoint snapshot JSON")
    ap.add_argument("--title", default="lightgbm_tpu run report")
    ap.add_argument("-o", "--out", default="run_report.html")
    args = ap.parse_args(argv)
    if not (args.flight or args.metrics or args.bench or args.drift):
        ap.error("nothing to report: pass --flight, --metrics, --bench "
                 "and/or --drift")

    flight = None
    if args.flight:
        from . import flight as flight_mod

        flight = flight_mod.load(args.flight)
    metrics = None
    if args.metrics:
        with open(args.metrics, encoding="utf-8") as fh:
            metrics = json.load(fh)
    drift = None
    if args.drift:
        with open(args.drift, encoding="utf-8") as fh:
            drift = json.load(fh)
    bench_records = load_bench_records(args.bench) if args.bench else []
    doc = render(flight=flight, metrics=metrics, bench_records=bench_records,
                 drift=drift, title=args.title)
    with open(args.out, "w", encoding="utf-8") as fh:
        fh.write(doc)
    print("report: wrote %s (%d bytes)" % (args.out, len(doc)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
