"""Mesh-aware distributed observability (docs/Observability.md §Distributed).

PR 8 made training genuinely multi-device (`tree_learner=data` composes with
`device_chunk_size` via shard_map + psum), but the obs stack was single-
process and mesh-blind. This module is the distributed spine, three pieces:

 1. **Compute-vs-collective attribution** — the sharded data-parallel
    grower re-run as separately-dispatched, ``block_until_ready``-fenced
    shard_map sub-steps (the sharded twin of obs/prof.py): local histogram
    build, the ``_combine`` psum, the root grad/hess/count reduction, the
    split scan, the score-finish step. :func:`profile_sharded_growth`
    proves the segmented path bitwise-identical to the fused
    ``grow_tree_data_parallel`` program on identical inputs;
    :func:`segmented_train_chunk` drives a whole training chunk through the
    fenced dispatches (model strings AND score carries proven identical to
    the fused sharded chunk — helpers/dist_obs_smoke.py). Results land as
    ``growth_segment_seconds_total{segment=,collective=}`` gauges, a
    ``comms_fraction`` scalar, and estimated collective payload bytes
    (histogram shape × dtype, cross-checked against the live array nbytes).

 2. **Pod-wide aggregation** — :func:`snapshot` captures a
    ``MetricsRegistry`` as a JSON-able blob; :func:`gather_snapshots`
    allgathers blobs across ``jax.distributed`` processes (host-side; the
    single-host fallback is the file-based :func:`write_snapshot` /
    :func:`merge_snapshot_files` pair); :func:`merge_snapshots` folds them
    into ONE registry whose counters are the per-process SUMS and whose
    gauges keep per-process provenance labels (``process=``), rendered via
    the ordinary ``prometheus_text()`` / ``run_report()``. The Chrome-trace
    twin is ``python -m lightgbm_tpu.obs.trace merge`` (obs/trace.py).

 3. **Shard-skew and straggler detection** — per-shard valid row counts
    (``train_shard_rows{device=}``, published once at sharded-chunk setup,
    pure host math) and per-device dispatch-completion offsets
    (``train_shard_wait_seconds{device=}``, measured by fencing each output
    shard in device order — ONLY under ``LIGHTGBM_TPU_DIST_PROF=1`` or
    inside a profile run; zero overhead and zero new jit traces when off),
    with a ``warn_once`` on sustained imbalance.

Import cost: stdlib + numpy + the obs registry/trace modules; jax is
imported lazily inside the profiling entry points, so ``flight.py`` and the
merge helpers can use this module from jax-free processes.
"""
from __future__ import annotations

import glob as glob_mod
import json
import os
import sys
import threading

from . import sanitize as sanitize_mod
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from ..utils.log import LightGBMError
from . import registry as registry_mod
from . import trace as trace_mod
from .prof import SegmentBook, _trees_equal

ENV_DIST_PROF = "LIGHTGBM_TPU_DIST_PROF"

#: segments that ARE cross-device collectives — everything the ICI carries.
#: hist_combine is the HistogramSource psum (ops/histogram.py `_combine`);
#: root_reduce the root grad/hess/count scalar psums.
COLLECTIVE_SEGMENTS = frozenset({"hist_combine", "root_reduce"})

#: process-wide accumulator for sharded segment seconds (profile runs merge in)
DIST_SEGMENTS = SegmentBook()

_LAST_RECORD: Dict[str, object] = {}
_SECTION_REGISTERED = False

# comms seconds accumulated since the last flight-recorder boundary
# (flight.note_boundary drains it via take_boundary_comms)
_BOUNDARY = {"comms_s": 0.0}
_BOUNDARY_LOCK = sanitize_mod.make_lock("obs.dist.boundary")

_STRAGGLER = {"streak": 0, "calls": 0}


def _costs_enabled() -> bool:
    from . import costs as costs_mod

    return costs_mod.enabled()


def wait_profiling_enabled() -> bool:
    """True when per-device dispatch-wait fencing is requested
    (``LIGHTGBM_TPU_DIST_PROF=1``). Read per call so tests can flip it;
    the disabled cost is one environ lookup per chunk boundary."""
    return os.environ.get(ENV_DIST_PROF, "") not in ("", "0")


# ---------------------------------------------------------------------------
# process identity (jax-lazy: only consults an already-imported jax)
# ---------------------------------------------------------------------------

def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — (0, 1) when jax is not imported or
    jax.distributed is uninitialized (both report through the same API)."""
    jx = sys.modules.get("jax")
    if jx is None:
        return 0, 1
    try:
        return int(jx.process_index()), int(jx.process_count())
    except Exception:
        return 0, 1


def take_boundary_comms() -> float:
    """Drain the comms-seconds accumulator (flight.note_boundary's hook:
    each chunk-boundary record carries the collective seconds the segmented
    profiler measured since the previous boundary; 0.0 when idle)."""
    with _BOUNDARY_LOCK:
        v = _BOUNDARY["comms_s"]
        _BOUNDARY["comms_s"] = 0.0
    return v


# ---------------------------------------------------------------------------
# pod-wide registry aggregation
# ---------------------------------------------------------------------------

def snapshot(registry: Optional[registry_mod.MetricsRegistry] = None) -> Dict:
    """This process's registry state as a JSON-able blob, stamped with its
    process index — the unit :func:`merge_snapshots` folds."""
    reg = registry if registry is not None else registry_mod.REGISTRY
    snap = reg.snapshot()
    idx, cnt = process_info()
    snap["process"] = idx
    snap["processes"] = cnt
    return snap


def merge_snapshots(snaps: List[Dict]) -> registry_mod.MetricsRegistry:
    """Fold per-process snapshots into ONE registry: counters SUM over
    identical (name, labels) — the merged exposition's counter values equal
    the per-process sums — while gauges (and rates, re-published as gauges)
    keep per-process provenance via an added ``process=`` label. Histogram
    summaries surface as ``{name}{stat=,process=}`` gauges plus a summed
    ``{name}_count`` counter. Render with the ordinary
    ``prometheus_text()`` / ``run_report()``."""
    merged = registry_mod.MetricsRegistry()
    for snap in snaps:
        p = str(snap.get("process", 0))
        for name, entries in (snap.get("counters") or {}).items():
            c = merged.counter(name)
            for labels, v in entries:
                c.inc(float(v), **dict(labels))
        for name, entries in (snap.get("gauges") or {}).items():
            g = merged.gauge(name)
            for labels, v in entries:
                lab = dict(labels)
                lab["process"] = p
                g.set(float(v), **lab)
        for name, rate in (snap.get("rates") or {}).items():
            merged.gauge(name).set(float(rate), process=p)
        for name, stats in (snap.get("summaries") or {}).items():
            if not stats or not stats.get("count"):
                continue
            g = merged.gauge(name)
            for key in ("p50", "p95", "p99", "max", "mean"):
                if key in stats:
                    g.set(float(stats[key]), stat=key, process=p)
            merged.counter(name + "_count").inc(float(stats["count"]))
    return merged


def merged_run_report(snaps: List[Dict]) -> Dict:
    """One run-report block for the whole pod: the merged registry's
    counters/gauges plus per-process provenance."""
    merged = merge_snapshots(snaps)
    out = merged.run_report()
    out["process_count"] = len(snaps)
    out["processes"] = sorted(int(s.get("process", 0)) for s in snaps)
    return out


def _device_allgather(rows_np: np.ndarray) -> np.ndarray:
    """All-gather one int32 row per device across the whole
    ``jax.distributed`` world; returns the full [D, W] matrix on every
    process. Rides the SAME collective machinery the data-parallel trainer
    uses (shard_map + lax.all_gather over the declared 'data' axis —
    multihost_utils.process_allgather jits on process-local arrays, which
    the CPU backend refuses)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..parallel.data_parallel import shard_map

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    sharding = NamedSharding(mesh, P("data", None))
    arr = jax.make_array_from_process_local_data(sharding, rows_np)
    fn = jax.jit(shard_map(
        lambda x: jax.lax.all_gather(x, "data", axis=0, tiled=True),
        mesh=mesh, in_specs=P("data", None), out_specs=P(),
        check_vma=False,
    ))
    out = fn(arr)
    # replicated output: every process reads its own addressable shard
    return np.asarray(out.addressable_shards[0].data)


def gather_payloads(payload: bytes) -> List[bytes]:
    """Allgather one opaque byte payload per process (host-side, over the
    ``jax.distributed`` runtime): all ranks call this collectively, all
    ranks receive the full process-ordered list. With one process (or no
    distributed init) the local payload is returned alone — the
    single-host path needs no collective. Variable-length blobs ride a
    two-phase gather (lengths first, then max-padded bytes), with each
    process's payload carried by its first local device. Also the
    transport of the checkpoint digest barrier (resil/coord.py)."""
    import jax

    world = int(jax.process_count())
    if world <= 1:
        return [payload]
    blob = np.frombuffer(bytes(payload), np.uint8)
    devices = jax.devices()
    me = int(jax.process_index())
    owner_row: Dict[int, int] = {}
    for i, d in enumerate(devices):
        owner_row.setdefault(int(d.process_index), i)
    local_rows = [
        i for i, d in enumerate(devices) if int(d.process_index) == me
    ]
    my_row = owner_row[me]

    lens_local = np.zeros((len(local_rows), 1), np.int32)
    for j, i in enumerate(local_rows):
        if i == my_row:
            lens_local[j, 0] = len(blob)
    lens_all = _device_allgather(lens_local)
    width = int(lens_all.max())

    padded = np.zeros((len(local_rows), width), np.int32)
    for j, i in enumerate(local_rows):
        if i == my_row:
            padded[j, : len(blob)] = blob.astype(np.int32)
    data_all = _device_allgather(padded)

    out: List[bytes] = []
    for p in range(world):
        row = owner_row[p]
        n = int(lens_all[row, 0])
        out.append(bytes(data_all[row, :n].astype(np.uint8)))
    return out


def gather_snapshots(snap: Optional[Dict] = None) -> List[Dict]:
    """Allgather every process's registry snapshot (the JSON round-trip
    over :func:`gather_payloads`); all ranks receive the full
    process-ordered list."""
    if snap is None:
        snap = snapshot()
    return [
        json.loads(raw.decode("utf-8"))
        for raw in gather_payloads(json.dumps(snap).encode("utf-8"))
    ]


def write_snapshot(path: str,
                   registry: Optional[registry_mod.MetricsRegistry] = None,
                   ) -> str:
    """File-based fallback for single-host multi-process runs: each process
    writes ``<path>.rank<N>.json`` and any later process (or the driver)
    merges with :func:`merge_snapshot_files`."""
    from ..resil.atomic import atomic_write_text

    idx, _ = process_info()
    out = "%s.rank%d.json" % (path, idx)
    # atomic publish: a sibling rank polling for this file must never read
    # a torn half-written blob
    atomic_write_text(out, json.dumps(snapshot(registry)) + "\n")
    return out


def merge_snapshot_files(pattern_or_paths) -> List[Dict]:
    """Load snapshot blobs from a glob pattern or explicit path list,
    ordered by recorded process index (unreadable files are skipped — a
    half-written rank must not take the merge down)."""
    if isinstance(pattern_or_paths, str):
        paths = sorted(glob_mod.glob(pattern_or_paths))
    else:
        paths = list(pattern_or_paths)
    snaps = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                snaps.append(json.load(fh))
        except (OSError, ValueError) as e:
            log.warning("dist: skipping snapshot %r (%s)" % (p, e))
    return sorted(snaps, key=lambda s: int(s.get("process", 0)))


# ---------------------------------------------------------------------------
# shard skew + straggler detection
# ---------------------------------------------------------------------------

def shard_valid_counts(num_data: int, num_shards: int) -> List[int]:
    """Per-shard VALID (unpadded) row counts under the ONE padding rule
    (parallel/mesh.shard_rows: zero-padding appended at the tail, so
    trailing shards absorb it). N=1003 over 8 -> seven shards of 126 and
    one of 121."""
    n_loc = -(-num_data // num_shards)
    return [
        int(min(max(num_data - i * n_loc, 0), n_loc))
        for i in range(num_shards)
    ]


def publish_shard_rows(mesh, counts: List[int], registry=None) -> None:
    """``train_shard_rows{device=}`` gauges: how many REAL rows each mesh
    device holds. Pure host math — no device reads, no jit traces."""
    reg = registry if registry is not None else registry_mod.REGISTRY
    g = reg.gauge(
        "train_shard_rows",
        "valid (unpadded) training rows per mesh device",
    )
    for dev, cnt in zip(np.asarray(mesh.devices).flat, counts):
        g.set(float(cnt), device=str(dev))


def note_dispatch_waits(arr, registry=None) -> Dict[str, float]:
    """Fence each shard of ``arr`` and record the completion offset from
    the fence start as ``train_shard_wait_seconds{device=}`` gauges. The
    offsets are observed host-side in sequence, so every fence after the
    first absorbs earlier waits — and a slow FIRST-fenced device would
    flatten the spread entirely. The fence order therefore ROTATES across
    calls (device-id order, shifted by a call counter), so a persistent
    straggler is fenced non-first on most chunks and shows up as a
    sustained spread, which warns once. Profiling mode only (the caller
    gates on :func:`wait_profiling_enabled`)."""
    import jax

    try:
        shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    except Exception:
        return {}
    rot = _STRAGGLER["calls"] % max(len(shards), 1)
    _STRAGGLER["calls"] += 1
    shards = shards[rot:] + shards[:rot]
    reg = registry if registry is not None else registry_mod.REGISTRY
    g = reg.gauge(
        "train_shard_wait_seconds",
        "per-device dispatch-completion offset (profiling mode)",
    )
    t0 = time.perf_counter()
    waits: Dict[str, float] = {}
    for sh in shards:
        jax.block_until_ready(sh.data)
        waits[str(sh.device)] = time.perf_counter() - t0
    for dev, w in waits.items():
        g.set(w, device=dev)
    if len(waits) > 1:
        vals = sorted(waits.values())
        spread = vals[-1] - vals[0]
        if spread > 0.005 and spread > 0.5 * max(vals[0], 1e-9):
            _STRAGGLER["streak"] += 1
            if _STRAGGLER["streak"] >= 3:
                worst = max(waits, key=waits.get)
                log.warn_once(
                    "dist-straggler",
                    "sustained shard imbalance: device %s completes %.1fms "
                    "after the fastest shard (3+ consecutive dispatches); "
                    "check shard row skew (train_shard_rows) or a slow chip"
                    % (worst, spread * 1e3),
                )
        else:
            _STRAGGLER["streak"] = 0
    return waits


# ---------------------------------------------------------------------------
# sharded segment profiler (the obs/prof.py twin for the data-parallel mesh)
# ---------------------------------------------------------------------------

def sharded_unsupported_reason(gbdt) -> Optional[str]:
    """Why the sharded segment profiler cannot reproduce this trainer's
    data-parallel grower bitwise (None = supported). Mirrors
    obs/prof.unsupported_reason plus the mesh-specific gates."""
    cfg = getattr(gbdt, "config", None)
    if cfg is None or getattr(gbdt, "train_set", None) is None:
        return "no training setup (loaded model?)"
    if gbdt._learner_kind() != "data":
        return "tree_learner %r is not the mesh data-parallel learner" % (
            cfg.tree_learner,
        )
    if gbdt.objective is None:
        return "custom objective (host-computed gradients)"
    if gbdt.train_set.num_features <= 0:
        return "no usable features"
    if cfg.num_leaves <= 1:
        return "num_leaves <= 1 grows no splits"
    if cfg.tpu_hist_mode != "bucketed":
        return "hist_mode %r (segments exist only for the bucketed layout)" % (
            cfg.tpu_hist_mode,
        )
    if gbdt.cegb_params.enabled:
        return "CEGB re-ranks candidates per split (order-dependent)"
    if gbdt._forced_splits:
        return "forced-splits preamble"
    slots = gbdt._hist_pool_slots()
    if slots is not None and slots < cfg.num_leaves:
        return "histogram pool (per-split slot state)"
    if gbdt.num_group_bins is not None:
        return "EFB-bundled bins (group remap not segmented)"
    from ..ops.grow import _ENV_SPLIT_IMPL

    if _ENV_SPLIT_IMPL == "pallas":
        return "LIGHTGBM_TPU_SPLIT_IMPL=pallas (kernelized split scan)"
    return None


def _build_kernels(gbdt):
    """Jitted shard_map sub-step kernels for the data-parallel grower.

    Local-compute segments are shard_map programs with NO collectives whose
    per-shard partials come out STACKED (``P('data', ...)``); each
    collective is its own shard_map wrapping exactly the psum the fused
    program runs (the HistogramSource seam, ops/histogram.py), so the
    combined values are the identical reduction. Replicated sub-steps
    (wiring, subtraction, split scan) are plain jits on post-psum state.
    The replicated bodies mirror obs/prof.py's sequential kernels op for
    op — profile_sharded_growth's bitwise assertion pins the mirror, so
    any drift between this copy and the fused grower is a loud failure,
    never a silent mis-attribution."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.grow import (
        PackedTree,
        _BEST_I,
        _LAUX_MAX,
        _LAUX_MIN,
        _LAUX_ND,
        _LAUX_SG,
        _LAUX_SH,
        _NODE_I_COLS,
        _pack_best,
        _unpack_tree,
        make_bucket_kernels,
    )
    from ..ops.histogram import histogram_source, leaf_histogram, leaf_values
    from ..ops.split import calculate_leaf_output, find_best_split
    from ..parallel.data_parallel import shard_map

    cfg = gbdt.config
    mesh = gbdt._mesh()
    feature_meta = gbdt.feature_meta
    meta_keys = sorted(feature_meta.keys())
    meta_vals = tuple(feature_meta[k] for k in meta_keys)
    n_meta = len(meta_keys)
    params = gbdt.split_params
    two_way = gbdt._two_way
    M = cfg.num_leaves
    B = gbdt.num_bins
    F = feature_meta["num_bin"].shape[0]
    max_depth = cfg.max_depth
    chunk = cfg.tpu_hist_chunk
    hist_dtype = cfg.tpu_hist_dtype
    # the run's FROZEN histogram route: every per-shard segment must trace
    # the exact kernels the fused data-parallel program routed to, or the
    # bitwise-identity proof against it compares different arithmetic
    hist_route = getattr(gbdt, "_hist_route", None)
    f32 = jnp.float32
    neg_inf = jnp.float32(-jnp.inf)
    mono_arr = feature_meta["monotone"].astype(jnp.int32)
    src = histogram_source("data")

    row = P("data")
    rep = P()
    col = P(None, "data")
    stk = P("data", None)

    def smap(body, in_specs, out_specs):
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))

    # ---- root: local build, then the two collectives ---------------------
    def root_local_body(grad, hess, bag, bins_l):
        vals_all = leaf_values(grad, hess, bag)
        lhist = leaf_histogram(
            bins_l, vals_all, B, chunk=chunk, hist_dtype=hist_dtype,
            route=hist_route,
        )
        lsum = jnp.stack([
            jnp.sum(grad * bag), jnp.sum(hess * bag), jnp.sum(bag),
        ])
        n_loc = grad.shape[0]
        order0 = jnp.arange(n_loc, dtype=jnp.int32)
        lb0 = jnp.zeros((M,), jnp.int32)
        lp0 = jnp.zeros((M,), jnp.int32).at[0].set(n_loc)
        return vals_all, lhist[None], lsum[None], order0, lb0[None], lp0[None]

    root_local = smap(
        root_local_body,
        in_specs=(row, row, row, col),
        out_specs=(stk, P("data", None, None, None), stk, row, stk, stk),
    )

    # the _combine psum of ops/histogram.py as its OWN fenced dispatch:
    # each shard psums its stacked partial — the identical collective the
    # fused program's HistogramSource seam runs
    hist_combine = smap(
        lambda p: src.combine(p[0]),
        in_specs=(P("data", None, None, None),),
        out_specs=rep,
    )

    def root_reduce_body(s1):
        s = s1[0]
        return jnp.stack([
            src.combine(s[0]), src.combine(s[1]), src.combine(s[2]),
        ])

    root_reduce = smap(root_reduce_body, in_specs=(stk,), out_specs=rep)

    # ---- replicated sub-steps (post-psum state; mirror obs/prof.py) ------
    def root_setup_fn(root_hist, root_sums, fmask):
        root_g, root_h, root_n = root_sums[0], root_sums[1], root_sums[2]
        no_con_min = jnp.full((M,), -jnp.inf, f32)
        no_con_max = jnp.full((M,), jnp.inf, f32)
        tree0 = PackedTree(
            num_leaves=jnp.int32(1),
            node_f=jnp.zeros((M, 3), f32),
            node_i=jnp.zeros((M, 4), jnp.int32),
            node_b=jnp.zeros((M, 1 + B), bool),
            leaf_f=jnp.zeros((M, 3), f32).at[0].set(
                jnp.stack([
                    calculate_leaf_output(root_g, root_h, params),
                    root_n, root_h,
                ])
            ),
            leaf_i=jnp.concatenate(
                [jnp.full((M, 1), -1, jnp.int32),
                 jnp.zeros((M, 1), jnp.int32)],
                axis=1,
            ),
        )
        hist0 = jnp.zeros((M, F, B, 3), f32).at[0].set(root_hist)
        laux0 = jnp.stack(
            [
                jnp.zeros((M,), f32).at[0].set(root_g),
                jnp.zeros((M,), f32).at[0].set(root_h),
                jnp.zeros((M,), f32).at[0].set(root_n),
                no_con_min,
                no_con_max,
            ],
            axis=-1,
        )
        root_split = find_best_split(
            root_hist, root_g, root_h, root_n, no_con_min[0], no_con_max[0],
            feature_meta, fmask, params, two_way=two_way,
        )
        pk = _pack_best(root_split)
        f0 = jnp.zeros((M, pk.f.shape[-1]), f32).at[:, 0].set(-jnp.inf)
        best_f = f0.at[0].set(pk.f)
        best_i = jnp.zeros((M, len(_BEST_I)), jnp.int32).at[0].set(pk.i)
        best_b = jnp.zeros((M, pk.b.shape[-1]), bool).at[0].set(pk.b)
        return tree0, best_f, best_i, best_b, laux0, hist0

    def select_fn(best_f):
        return (
            jnp.argmax(best_f[:, 0]).astype(jnp.int32),
            jnp.max(best_f[:, 0]),
        )

    def wiring_fn(tree, laux, best_f, best_i, best_b, best_leaf, new_leaf):
        t = tree
        node = new_leaf - 1  # sequential invariant: it == num_leaves - 1
        f = best_i[best_leaf, 0]
        thr = best_i[best_leaf, 1]
        child_idx = jnp.stack([best_leaf, new_leaf])
        parent = t.leaf_i[best_leaf, 0]
        prow = jnp.where(parent >= 0, parent, M - 1)
        enc_old = -(best_leaf + 1)
        old_plc = t.node_i[prow, 2]
        old_prc = t.node_i[prow, 3]
        new_plc = jnp.where((parent >= 0) & (old_plc == enc_old), node, old_plc)
        new_prc = jnp.where((parent >= 0) & (old_prc == enc_old), node, old_prc)
        depth_child = t.leaf_i[best_leaf, 1] + 1
        parent_aux = laux[best_leaf]
        parent_value = calculate_leaf_output(
            parent_aux[_LAUX_SG], parent_aux[_LAUX_SH], params
        )
        node_i = t.node_i.at[
            jnp.stack([node, node, node, node, prow, prow]),
            _NODE_I_COLS,
        ].set(
            jnp.stack([
                f, thr, -(best_leaf + 1), -(new_leaf + 1), new_plc, new_prc,
            ])
        )
        tree2 = PackedTree(
            num_leaves=t.num_leaves + 1,
            node_f=t.node_f.at[node].set(
                jnp.stack([best_f[best_leaf, 0], parent_value,
                           parent_aux[_LAUX_ND]])
            ),
            node_i=node_i,
            node_b=t.node_b.at[node].set(best_b[best_leaf].astype(bool)),
            leaf_f=t.leaf_f.at[child_idx].set(
                jnp.stack([
                    jnp.stack([best_f[best_leaf, 7], best_f[best_leaf, 3],
                               best_f[best_leaf, 2]]),
                    jnp.stack([best_f[best_leaf, 8], best_f[best_leaf, 6],
                               best_f[best_leaf, 5]]),
                ])
            ),
            leaf_i=t.leaf_i.at[child_idx].set(
                jnp.stack([
                    jnp.stack([node, depth_child]),
                    jnp.stack([node, depth_child]),
                ])
            ),
        )
        mono_f = mono_arr[f]
        mid = (best_f[best_leaf, 7] + best_f[best_leaf, 8]) / 2.0
        pmin = parent_aux[_LAUX_MIN]
        pmax = parent_aux[_LAUX_MAX]
        l_min = jnp.where(mono_f < 0, mid, pmin)
        l_max = jnp.where(mono_f > 0, mid, pmax)
        r_min = jnp.where(mono_f > 0, mid, pmin)
        r_max = jnp.where(mono_f < 0, mid, pmax)
        laux2 = laux.at[child_idx].set(
            jnp.stack([
                jnp.stack([best_f[best_leaf, 1], best_f[best_leaf, 2],
                           best_f[best_leaf, 3], l_min, l_max]),
                jnp.stack([best_f[best_leaf, 4], best_f[best_leaf, 5],
                           best_f[best_leaf, 6], r_min, r_max]),
            ])
        )
        return tree2, laux2, depth_child

    def subtract_fn(hist, small_hist, best_f, best_leaf, new_leaf):
        left_smaller = best_f[best_leaf, 3] <= best_f[best_leaf, 6]
        small_idx = jnp.where(left_smaller, best_leaf, new_leaf)
        large_idx = jnp.where(left_smaller, new_leaf, best_leaf)
        parent_hist = hist[best_leaf]
        large_hist = parent_hist - small_hist
        return hist.at[jnp.stack([small_idx, large_idx])].set(
            jnp.stack([small_hist, large_hist])
        )

    def depth_gate(gain, depth):
        if max_depth > 0:
            return jnp.where(depth >= max_depth, neg_inf, gain)
        return gain

    def scan_fn(best_fio, hist, laux, fmask, best_leaf, new_leaf, depth_child):
        best_fa, best_ia, best_ba = best_fio
        child_idx = jnp.stack([best_leaf, new_leaf])
        ch_hist = hist[child_idx]
        ch_aux = laux[child_idx]
        ch_split = jax.vmap(
            lambda h, sg, sh, nd, mn, mx: find_best_split(
                h, sg, sh, nd, mn, mx, feature_meta, fmask, params,
                two_way=two_way,
            )
        )(ch_hist, ch_aux[:, _LAUX_SG], ch_aux[:, _LAUX_SH],
          ch_aux[:, _LAUX_ND], ch_aux[:, _LAUX_MIN], ch_aux[:, _LAUX_MAX])
        ch_gain = depth_gate(ch_split.gain, depth_child)
        pb2 = _pack_best(ch_split._replace(gain=ch_gain))
        return (
            best_fa.at[child_idx].set(pb2.f),
            best_ia.at[child_idx].set(pb2.i),
            best_ba.at[child_idx].set(pb2.b),
        )

    # ---- per-shard sub-steps (shard_map over the local row blocks) -------
    def partition_body(order, lb1, lp1, best_i, best_b, best_leaf, new_leaf,
                       bins_l, *meta_flat):
        meta = dict(zip(meta_keys, meta_flat))
        kern = make_bucket_kernels(
            bins_l, meta, B, num_group_bins=None, bins_nf=None,
            chunk=chunk, hist_dtype=hist_dtype, kb=0,
            hist_route=hist_route,
        )
        lb = lb1[0]
        lp = lp1[0]
        f = best_i[best_leaf, 0]
        thr = best_i[best_leaf, 1]
        dleft = best_b[best_leaf, 0]
        member = best_b[best_leaf, 1:]
        pbegin = lb[best_leaf]
        pphys = lp[best_leaf]
        order2, left_cnt = kern.partition_batch(
            order, pbegin[None], pphys[None], f[None], thr[None],
            dleft[None], member[None],
        )
        left_phys = left_cnt[0]
        lb2 = lb.at[new_leaf].set(pbegin + left_phys)
        lp2 = lp.at[best_leaf].set(left_phys).at[new_leaf].set(
            pphys - left_phys
        )
        return order2, lb2[None], lp2[None]

    partition = smap(
        partition_body,
        in_specs=(row, stk, stk, rep, rep, rep, rep, col)
        + (rep,) * n_meta,
        out_specs=(row, stk, stk),
    )

    def hist_local_body(vals_all, order, lb1, lp1, best_f, best_leaf,
                        new_leaf, bins_l, *meta_flat):
        meta = dict(zip(meta_keys, meta_flat))
        kern = make_bucket_kernels(
            bins_l, meta, B, num_group_bins=None, bins_nf=None,
            chunk=chunk, hist_dtype=hist_dtype, kb=0,
            hist_route=hist_route,
        )
        lb = lb1[0]
        lp = lp1[0]
        pbegin = lb[best_leaf]
        left_phys = lp[best_leaf]
        right_phys = lp[new_leaf]
        # the smaller-child choice uses the GLOBAL bagged counts (best_f
        # cols 3/6) so every shard histograms the SAME child before the
        # psum; begin/count are this shard's local segment
        left_smaller = best_f[best_leaf, 3] <= best_f[best_leaf, 6]
        small_begin = jnp.where(left_smaller, pbegin, pbegin + left_phys)
        small_cnt = jnp.where(left_smaller, left_phys, right_phys)
        return kern.segment_histogram_batch(
            vals_all, order, small_begin[None], small_cnt[None]
        )

    hist_local = smap(
        hist_local_body,
        in_specs=(stk, row, stk, stk, rep, rep, rep, col) + (rep,) * n_meta,
        out_specs=P("data", None, None, None),
    )

    def final_leaf_body(order, lb1, lp1):
        # leaf-id reconstruction, verbatim from grow_tree's bucketed tail,
        # over this shard's local rows
        lb = lb1[0]
        lp = lp1[0]
        n_loc = order.shape[0]
        key = jnp.where(
            lp > 0, lb, n_loc + jnp.arange(M, dtype=jnp.int32)
        )
        ordl = jnp.argsort(key)
        slot = jnp.searchsorted(
            key[ordl], jnp.arange(n_loc, dtype=jnp.int32), side="right"
        ) - 1
        pos_leaf = ordl[jnp.clip(slot, 0, M - 1)].astype(jnp.int32)
        return jnp.zeros((n_loc,), jnp.int32).at[order].set(pos_leaf)

    final_leaf = smap(final_leaf_body, in_specs=(row, stk, stk),
                      out_specs=row)

    jit = jax.jit
    return {
        "root_local": root_local,
        "hist_combine": hist_combine,
        "root_reduce": root_reduce,
        "root_setup": jit(root_setup_fn, donate_argnums=(0,)),
        "select": jit(select_fn),
        "partition": partition,
        "wiring": jit(wiring_fn, donate_argnums=(0, 1)),
        "hist_local": hist_local,
        "subtract": jit(subtract_fn, donate_argnums=(0, 1)),
        "scan": jit(scan_fn, donate_argnums=(0,)),
        "final_tree": jit(lambda tree: _unpack_tree(tree, M)),
        "final_leaf": final_leaf,
        "_meta_vals": meta_vals,
        "_meta": {
            "key": _kernel_key(gbdt),
            # per-combine collective payload via the HistogramSource seam
            # (F x B x 3 f32 — the [F, B, 3] partial each shard psums)
            "hist_payload_bytes": src.payload_bytes((F, B, 3), 4),
        },
    }


def _kernel_key(gbdt):
    cfg = gbdt.config
    return (
        gbdt._mesh(), cfg.num_leaves, gbdt.num_bins, cfg.max_depth,
        cfg.tpu_hist_chunk, cfg.tpu_hist_dtype, gbdt._two_way,
        gbdt.split_params,
    )


def _get_kernels(gbdt):
    kernels = getattr(gbdt, "_dist_seg_kernels", None)
    if kernels is None or kernels["_meta"]["key"] != _kernel_key(gbdt):
        kernels = _build_kernels(gbdt)
        gbdt._dist_seg_kernels = kernels
    return kernels


def _timed(book: SegmentBook, name: str, fn, *args, waits=None, wait_idx=0):
    """One fenced sub-step: dispatch, (optionally) fence each shard of
    output ``wait_idx`` in device order recording per-device completion
    offsets, then block on everything. Collective segments also feed the
    flight-recorder boundary accumulator."""
    import jax

    with trace_mod.span("dist.%s" % name, cat="dist.segment"):
        t0 = time.perf_counter()
        out = fn(*args)
        if waits is not None:
            target = out[wait_idx] if isinstance(out, (tuple, list)) else out
            try:
                shards = sorted(
                    target.addressable_shards, key=lambda s: s.device.id
                )
            except Exception:
                shards = []
            for sh in shards:
                jax.block_until_ready(sh.data)
                dev = str(sh.device)
                waits[dev] = waits.get(dev, 0.0) + (time.perf_counter() - t0)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        book.add(name, dt)
    if name in COLLECTIVE_SEGMENTS:
        with _BOUNDARY_LOCK:
            _BOUNDARY["comms_s"] += dt
    return out


def _segmented_sharded_tree(gbdt, kernels, bins_s, grad_s, hess_s, bag_s,
                            fmask, book: SegmentBook, waits=None):
    """Grow ONE tree on the sharded inputs via the fenced shard_map
    sub-steps; returns (TreeArrays, leaf_id [Np] row-sharded, splits) —
    bitwise-equal to ``grow_tree_data_parallel`` on the same inputs."""
    meta_vals = kernels["_meta_vals"]
    M = gbdt.config.num_leaves

    with trace_mod.span("dist.segmented_tree", cat="dist"):
        vals, lhist, lsums, order, lb, lp = _timed(
            book, "root_init", kernels["root_local"],
            grad_s, hess_s, bag_s, bins_s, waits=waits, wait_idx=1,
        )
        root_hist = _timed(book, "hist_combine", kernels["hist_combine"],
                           lhist)
        root_sums = _timed(book, "root_reduce", kernels["root_reduce"],
                           lsums)
        tree, best_f, best_i, best_b, laux, hist = _timed(
            book, "root_scan", kernels["root_setup"],
            root_hist, root_sums, fmask,
        )
        it = 0
        while it < M - 1:
            best_leaf, gain = _timed(book, "select", kernels["select"],
                                     best_f)
            if not float(np.asarray(gain)) > 0.0:
                break
            new_leaf = it + 1  # sequential invariant (host int)
            order, lb, lp = _timed(
                book, "partition", kernels["partition"],
                order, lb, lp, best_i, best_b, best_leaf, new_leaf,
                bins_s, *meta_vals,
            )
            tree, laux, depth_child = _timed(
                book, "leaf_update", kernels["wiring"],
                tree, laux, best_f, best_i, best_b, best_leaf, new_leaf,
            )
            small_part = _timed(
                book, "hist_build", kernels["hist_local"],
                vals, order, lb, lp, best_f, best_leaf, new_leaf,
                bins_s, *meta_vals, waits=waits,
            )
            small_hist = _timed(book, "hist_combine",
                                kernels["hist_combine"], small_part)
            hist = _timed(
                book, "hist_subtract", kernels["subtract"],
                hist, small_hist, best_f, best_leaf, new_leaf,
            )
            best_f, best_i, best_b = _timed(
                book, "split_scan", kernels["scan"],
                (best_f, best_i, best_b), hist, laux, fmask, best_leaf,
                new_leaf, depth_child,
            )
            it += 1
        ta = _timed(book, "finalize", kernels["final_tree"], tree)
        leaf_id = _timed(book, "finalize", kernels["final_leaf"],
                         order, lb, lp)
    return ta, leaf_id, it


def segmented_train_chunk(gbdt, n: int, book: Optional[SegmentBook] = None):
    """Run up to ``n`` boosting iterations through the FENCED segmented
    sharded dispatches — the profiling twin of the fused sharded
    ``train_chunk``. Reuses the trainer's own per-iteration machinery
    (gradients, bagging stream, finish step, deferred-stop bookkeeping) so
    the trained model and score carries are bitwise-identical to the fused
    chunk path (helpers/dist_obs_smoke.py proves model strings AND score
    carries); only tree GROWTH is swapped for the segmented grower, and
    ``grad`` / ``score_finish`` are timed around the original steps.
    Returns (iterations_run, stopped). The first-ever iteration must
    already have run (it is host-side: boost_from_average)."""
    import jax

    reason = sharded_unsupported_reason(gbdt)
    if reason is None:
        reason = gbdt.device_chunk_fallback_reason()
    if reason is not None:
        raise LightGBMError(
            "segmented sharded chunk unsupported here: %s" % reason
        )
    if not gbdt._device_trees:
        raise LightGBMError(
            "segmented sharded chunk needs the sequential first iteration "
            "(run one update() first, like train_chunk does)"
        )
    local = book if book is not None else SegmentBook()
    kernels = _get_kernels(gbdt)
    orig_finish = gbdt._finish_tree
    orig_grad = gbdt._compute_gradients

    def seg_train_tree(grad_k, hess_k):
        fmask = gbdt._sample_features()
        bins_s, grad_s, hess_s, bag_s = gbdt._shard_rows(grad_k, hess_k)
        ta, leaf_id, _ = _segmented_sharded_tree(
            gbdt, kernels, bins_s, grad_s, hess_s, bag_s, fmask, local
        )
        return ta, leaf_id[: gbdt.num_data]

    def timed_finish(tree_arrays, leaf_id, k, nl_dev):
        t0 = time.perf_counter()
        out = orig_finish(tree_arrays, leaf_id, k, nl_dev)
        jax.block_until_ready(gbdt.scores)
        local.add("score_finish", time.perf_counter() - t0)
        return out

    def timed_grad(init_scores):
        t0 = time.perf_counter()
        grad, hess = orig_grad(init_scores)
        jax.block_until_ready((grad, hess))
        local.add("grad", time.perf_counter() - t0)
        return grad, hess

    gbdt._train_tree = seg_train_tree
    gbdt._finish_tree = timed_finish
    gbdt._compute_gradients = timed_grad
    done = 0
    stopped = False
    try:
        for _ in range(max(n, 1)):
            stopped = gbdt.train_one_iter()
            if stopped:
                break
            done += 1
    finally:
        # the instance attributes shadow the class methods; deleting them
        # restores the original bound methods
        for name in ("_train_tree", "_finish_tree", "_compute_gradients"):
            gbdt.__dict__.pop(name, None)
    if book is None:
        DIST_SEGMENTS.merge(local)
    return done, stopped


def profile_sharded_growth(booster_or_gbdt, iters: int = 1,
                           registry=None) -> Dict[str, object]:
    """Run ``iters`` profiling iterations on the data-parallel mesh: per
    class, grow one tree FUSED (``grow_tree_data_parallel``, timed as the
    reference) and once SEGMENTED (fenced shard_map sub-steps, timed per
    segment), from identical sharded inputs, and verify the trees are
    bitwise-identical. Never mutates the trainer. Returns the attribution
    record (``comms_fraction``, per-segment seconds, collective payload
    bytes, per-device rows/waits) and publishes the gauges."""
    import jax

    from ..parallel.data_parallel import grow_tree_data_parallel

    gbdt = getattr(booster_or_gbdt, "_gbdt", booster_or_gbdt)
    reason = sharded_unsupported_reason(gbdt)
    if reason is not None:
        raise LightGBMError(
            "sharded segment profiler unsupported here: %s" % reason
        )
    gbdt._unshard_chunk_carries()
    cfg = gbdt.config
    K = gbdt.num_tree_per_iteration
    grad_all, hess_all = gbdt._compute_gradients([0.0] * K)
    if cfg.feature_fraction >= 1.0:
        fmask = gbdt._fmask_all
    else:
        # draw WITHOUT consuming the trainer's RNG stream (obs/prof.py)
        state = gbdt._feat_rng.get_state()
        fmask = gbdt._sample_features()
        gbdt._feat_rng.set_state(state)
    mesh = gbdt._mesh()
    D = int(mesh.shape["data"])
    common = dict(
        num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
        num_bins=gbdt.num_bins, num_group_bins=gbdt.num_group_bins,
        params=gbdt.split_params, chunk=cfg.tpu_hist_chunk,
        hist_dtype=cfg.tpu_hist_dtype, hist_mode=cfg.tpu_hist_mode,
        two_way=gbdt._two_way, forced_splits=gbdt._forced_splits,
        cegb=gbdt.cegb_params, cegb_state=None,
        hist_pool_slots=gbdt._hist_pool_slots(),
    )
    kernels = _get_kernels(gbdt)
    payload = kernels["_meta"]["hist_payload_bytes"]
    book = SegmentBook()
    warm = SegmentBook()  # warmup pass: compiles land here, not the record
    waits: Dict[str, float] = {}
    fused_s = 0.0
    bitwise = True
    splits_total = 0
    trees = 0
    for i in range(max(iters, 1) + 1):
        timed = i > 0
        if i == 1:
            # the warmup pass's collective segments included their shard_map
            # COMPILES; discard them from the flight-boundary accumulator so
            # comms_s never misreports compilation as ICI time (the record's
            # seconds already exclude warmup via the separate warm book)
            take_boundary_comms()
        for k in range(K if timed else 1):
            grad_k, hess_k = grad_all[k], hess_all[k]
            bins_s, grad_s, hess_s, bag_s = gbdt._shard_rows(grad_k, hess_k)
            with trace_mod.span("dist.fused_tree", cat="dist"):
                t0 = time.perf_counter()
                ta_f, lid_f = grow_tree_data_parallel(
                    mesh, bins_s, grad_s, hess_s, bag_s, fmask,
                    gbdt.feature_meta, **common,
                )
                jax.block_until_ready((ta_f, lid_f))
                if timed:
                    fused_s += time.perf_counter() - t0
            ta_s, lid_s, splits = _segmented_sharded_tree(
                gbdt, kernels, bins_s, grad_s, hess_s, bag_s, fmask,
                book if timed else warm, waits=waits if timed else None,
            )
            bitwise = bitwise and _trees_equal(ta_f, lid_f, ta_s, lid_s)
            if timed:
                splits_total += splits
                trees += 1
    DIST_SEGMENTS.merge(book)

    if _costs_enabled():
        # LIGHTGBM_TPU_COSTS=1: put the collective's measured cost analysis
        # (flops/bytes of the psum executable) in the cost book next to the
        # shape-math payload estimate — harvest declines gracefully when
        # the backend cannot lower the sharded program ahead of time
        from . import costs as costs_mod

        F = gbdt.feature_meta["num_bin"].shape[0]
        costs_mod.COSTS.harvest(
            "dist.hist_combine", kernels["hist_combine"],
            (jax.ShapeDtypeStruct((D, int(F), gbdt.num_bins, 3),
                                  np.float32),),
        )

    per_tree = {
        name: round(s / max(trees, 1), 6)
        for name, s in sorted(book.seconds.items())
    }
    seg_sum = sum(book.seconds.values()) / max(trees, 1)
    comms = sum(
        s for n_, s in book.seconds.items() if n_ in COLLECTIVE_SEGMENTS
    ) / max(trees, 1)
    fused_per_tree = fused_s / max(trees, 1)
    counts = dict(sorted(book.counts.items()))
    hist_combines = counts.get("hist_combine", 0) / max(trees, 1)
    root_reduces = counts.get("root_reduce", 0) / max(trees, 1)
    row_counts = shard_valid_counts(gbdt.num_data, D)
    per_device = [
        {
            "device": str(dev),
            "rows": int(row_counts[i]),
            "wait_s": round(waits.get(str(dev), 0.0) / max(trees, 1), 6),
        }
        for i, dev in enumerate(np.asarray(mesh.devices).flat)
    ]
    record: Dict[str, object] = {
        "devices": D,
        "iters": iters,
        "trees": trees,
        "rows": int(gbdt.num_data),
        "num_leaves": int(cfg.num_leaves),
        "splits_per_tree": round(splits_total / max(trees, 1), 2),
        "segments_per_tree_s": per_tree,
        "segment_counts": counts,
        "collective_segments": sorted(COLLECTIVE_SEGMENTS),
        "segment_sum_s_per_tree": round(seg_sum, 6),
        "comms_s_per_tree": round(comms, 6),
        "comms_fraction": round(comms / max(seg_sum, 1e-12), 4),
        "collective_bytes_per_split": payload,
        "collective_bytes_per_tree": int(
            hist_combines * payload + root_reduces * 3 * 4
        ),
        "fused_growth_s_per_tree": round(fused_per_tree, 6),
        "segment_sum_ratio": round(seg_sum / max(fused_per_tree, 1e-12), 4),
        "bitwise_identical": bool(bitwise),
        "per_device": per_device,
    }
    publish_shard_rows(mesh, row_counts, registry=registry)
    _publish(record, book, registry)
    return record


def _report_section():
    return dict(_LAST_RECORD) if _LAST_RECORD else {}


def _publish(record: Dict[str, object], book: SegmentBook,
             registry=None) -> None:
    global _SECTION_REGISTERED
    reg = registry if registry is not None else registry_mod.REGISTRY
    g = reg.gauge("growth_segment_seconds_total")
    for name, secs in DIST_SEGMENTS.seconds.items():
        # sharded="true" keeps these entries disjoint from the serial
        # profiler's (obs/prof.py publishes the same segment names for the
        # unsharded grower; without the label the later run would clobber
        # the other's attribution)
        g.set(
            secs, segment=name, sharded="true",
            collective="true" if name in COLLECTIVE_SEGMENTS else "false",
        )
    reg.gauge("comms_fraction").set(float(record["comms_fraction"]))
    reg.gauge("dist_collective_bytes_total").set(
        float(record["collective_bytes_per_tree"]) * record["trees"]
    )
    wg = reg.gauge("train_shard_wait_seconds")
    for ent in record.get("per_device") or []:
        if ent.get("wait_s"):
            wg.set(float(ent["wait_s"]), device=ent["device"])
    _LAST_RECORD.clear()
    _LAST_RECORD.update(record)
    if reg is not registry_mod.REGISTRY:
        reg.register_report_section("dist_segments", _report_section)
    elif not _SECTION_REGISTERED:
        _SECTION_REGISTERED = True
        reg.register_report_section("dist_segments", _report_section)


def last_record() -> Dict[str, object]:
    return dict(_LAST_RECORD)


def reset() -> None:
    DIST_SEGMENTS.reset()
    _LAST_RECORD.clear()
    _STRAGGLER["streak"] = 0
    with _BOUNDARY_LOCK:
        _BOUNDARY["comms_s"] = 0.0
