"""Device-timeline auditor: parse the XLA profile, attribute device time.

``LIGHTGBM_TPU_PROFILE=<dir>`` has always captured a full ``jax.profiler``
trace (utils/timer.py ``maybe_profile``), and PR 4's span tracer enters
``jax.profiler.TraceAnnotation`` so device timelines carry our segment
names — but nothing in the repo ever READ the emitted artifacts. This
module closes that loop: it parses the Chrome-trace JSON(.gz) the profiler
writes under ``<dir>/plugins/profile/<session>/`` (stdlib only — no jax,
no tensorboard) and answers the question the bench numbers cannot:
is the chip idle (host-bound dispatch), busy on the wrong ops
(device-bound), or stalled on transfers (transfer-bound)?

Outputs, from one capture:

 * **op-level attribution** — top-K ops by device SELF time, each grouped
   into the existing segment vocabulary via the ``TraceAnnotation`` names
   PR 4/PR 6 already emit (``prof.hist_build``, the PhaseTimers phase
   names, ``train.iteration`` …). Ops covered by no annotation are
   bucketed loudly as ``unattributed`` — never dropped.
 * **bound-ness verdict** — ``device_busy_fraction``, a dispatch-gap
   (device-idle) histogram, H2D/D2H transfer seconds + bytes, and a
   host-bound / device-bound / transfer-bound classification with the
   evidence inline (:data:`HOST_BOUND_BUSY`, :data:`TRANSFER_BOUND_FRAC`).
 * **per-op roofline placement** — achieved FLOP/s and bytes/s per
   attributed op (from the per-op cost args the TPU profiler embeds)
   against ``costs.CHIP_PEAKS``, naming the op that pins MFU.

Results publish as ``devprof_*`` gauges on the one MetricsRegistry and as
the ``device_timeline`` run-report section (rendered by obs/report.py);
bench.py stamps ``device_busy_fraction``/``transfer_seconds`` into every
bench record and helpers/bench_diff.py WARNs (never FAILs) on their drift.

Capture contract (``capture()`` below, and the CLI ``capture`` command):

 * the profile dir comes from ``LIGHTGBM_TPU_PROFILE`` (or an explicit
   path) and is rank-suffixed (``.rank<N>``) under an initialized
   ``jax.distributed`` world — the same clobber fix PR 9 gave
   ``LIGHTGBM_TPU_TRACE``; :func:`find_trace_files` folds the per-rank
   dirs back together at parse time;
 * segment names reach the device timeline only while an obs tracer is
   live (``trace.span`` is what enters ``TraceAnnotation``), so
   ``capture()`` arms a throwaway tracer when none is active;
 * host-only captures (the CPU backend emits no ``/device:`` lanes)
   degrade to the executor-event proxy (``lanes_source:
   "host_executor"``): ``TfrtCpuExecutable::Execute`` &co stand in for
   device busy time, which on the synchronous CPU runtime they are.

CLI::

    python -m lightgbm_tpu.obs.devprof parse <profile-dir-or-trace.json[.gz]>
        [--top 15] [--device-kind v5e] [--iters N] [--json out.json]
        [--report out.html]
    python -m lightgbm_tpu.obs.devprof capture [--rows 20000] [--iters 8]
        [--dir DIR] [--mode train|predict] ...   # capture, then parse

docs/Observability.md §Device timeline documents the full contract.
"""
from __future__ import annotations

import bisect
import contextlib
import glob as glob_mod
import gzip
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import log
from . import registry as registry_mod

ENV_PROFILE = "LIGHTGBM_TPU_PROFILE"  # shared with utils/timer.maybe_profile

# ---------------------------------------------------------------------------
# verdict thresholds (module constants so the evidence can cite them)
# ---------------------------------------------------------------------------

#: busy fraction below which a run reads host-bound: the device spent most
#: of the window waiting for the host to dispatch
HOST_BOUND_BUSY = 0.40
#: transfer time share of the window above which a run reads
#: transfer-bound (checked before the busy-fraction split: a device kept
#: busy shuffling bytes is still transfer-bound)
TRANSFER_BOUND_FRAC = 0.25

#: dispatch-gap histogram bucket upper bounds, milliseconds (last = +inf)
GAP_BUCKETS_MS = (0.1, 1.0, 10.0)

# ---------------------------------------------------------------------------
# segment vocabulary: TraceAnnotation name -> segment label
# ---------------------------------------------------------------------------

#: PhaseTimers phase names (utils/timer.py call sites in models/gbdt.py) —
#: they enter TraceAnnotation verbatim whenever an obs tracer is live
_PHASE_SPANS = frozenset({
    "boosting(grad)", "bagging", "tree growth", "renew+score update",
    "valid scores", "chunked boosting",
})

#: span namespaces that name a segment directly; prof./dist. are the
#: segment profilers' namespaces and are STRIPPED so the attribution lands
#: in the same vocabulary as growth_segment_seconds_total (hist_build,
#: partition, split_scan, hist_combine, ...)
_STRIP_PREFIXES = ("prof.", "dist.")
_KEEP_PREFIXES = (
    "train.", "serve.", "loop.", "cli.", "resil.", "bringup.", "devprof.",
)


def segment_for_span(name: str) -> Optional[str]:
    """The segment label a host annotation span maps to (None = not one of
    ours — an arbitrary profiler-internal host event, never an anchor)."""
    if name in _PHASE_SPANS:
        return name
    for p in _STRIP_PREFIXES:
        if name.startswith(p) and len(name) > len(p):
            return name[len(p):]
    for p in _KEEP_PREFIXES:
        if name.startswith(p):
            return name
    return None


# ---------------------------------------------------------------------------
# event classification
# ---------------------------------------------------------------------------

#: a process lane holding real device op events ("/device:TPU:0", and the
#: "TPU:0"-style spellings some exporter versions use)
_DEVICE_PID_RE = re.compile(r"/device:|^TPU(?: core)?[ :]?\d", re.IGNORECASE)

#: host events that ARE the device work on synchronous backends (CPU):
#: the per-dispatch executable execution — the busy-time proxy when the
#: capture has no /device: lanes at all
_EXEC_RE = re.compile(
    r"::Execute\b|ExecuteSharded|ExecuteOnLocal|ExecuteComputation"
    r"|XlaLocalLaunch|EagerExecute"
)

#: transfer-event vocabulary, host-to-device vs device-to-host. Covers the
#: TPU exporter spellings (TransferToDevice / TransferFromDevice, infeed /
#: outfeed) and the stream-executor ones (MemcpyH2D / MemcpyD2H)
_H2D_RE = re.compile(
    r"TransferToDevice|MemcpyH2D|Memcpy.*HToD|InfeedEnqueue|"
    r"BufferFromHost|CopyToDevice|host_to_device|h2d", re.IGNORECASE)
_D2H_RE = re.compile(
    r"TransferFromDevice|MemcpyD2H|Memcpy.*DToH|OutfeedDequeue|"
    r"BufferToHost|CopyFromDevice|device_to_host|d2h|TransferLiteral",
    re.IGNORECASE)

#: args keys that carry a byte count on transfer/op events
_BYTES_KEYS = (
    "bytes", "num_bytes", "size", "bytes_transferred", "buffer_size",
    "bytes accessed", "bytes_accessed", "requested_bytes",
)
#: args keys that carry a FLOP count on op events (TPU op lanes embed
#: these; absent elsewhere — roofline rows exist only where they do)
_FLOPS_KEYS = ("flops", "model_flops")


def _arg_num(args: Optional[Dict], keys: Sequence[str]) -> Optional[float]:
    if not args:
        return None
    for k in keys:
        v = args.get(k)
        if v is None:
            continue
        try:
            return float(str(v).replace(",", ""))
        except (TypeError, ValueError):
            continue
    return None


class _Ev:
    """One complete ('X') event on the shared profiler clock."""

    __slots__ = ("name", "pkey", "tid", "ts", "dur", "args", "self_us",
                 "segment")

    def __init__(self, name, pkey, tid, ts, dur, args):
        self.name = name
        self.pkey = pkey
        self.tid = tid
        self.ts = ts
        self.dur = dur
        self.args = args
        self.self_us = dur
        self.segment: Optional[str] = None

    @property
    def end(self) -> float:
        return self.ts + self.dur


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_chrome_trace(path: str) -> Dict:
    """One Chrome-trace document, transparently gunzipping ``*.gz``."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as fh:
        return json.load(fh)


def find_trace_files(profile_dir: str, include_ranks: bool = True,
                     latest_only: bool = True) -> List[str]:
    """The Chrome-trace files of a profiler capture dir.

    Looks under ``<dir>/plugins/profile/<session>/*.trace.json(.gz)``
    (newest session per dir when ``latest_only``) and — the multi-process
    story — folds sibling ``<dir>.rank<N>`` dirs in, so one parse sees the
    whole pod. A direct file path passes through untouched.
    """
    if os.path.isfile(profile_dir):
        return [profile_dir]
    dirs = [profile_dir]
    if include_ranks:
        dirs += sorted(glob_mod.glob(glob_mod.escape(profile_dir) + ".rank*"))
    out: List[str] = []
    for d in dirs:
        sessions = sorted(glob_mod.glob(
            os.path.join(glob_mod.escape(d), "plugins", "profile", "*")))
        sessions = [s for s in sessions if os.path.isdir(s)]
        if latest_only and sessions:
            sessions = sessions[-1:]
        for s in sessions:
            hits = sorted(
                glob_mod.glob(os.path.join(glob_mod.escape(s),
                                           "*.trace.json.gz"))
                + glob_mod.glob(os.path.join(glob_mod.escape(s),
                                             "*.trace.json"))
            )
            out.extend(hits)
    return out


class Timeline:
    """Events + process/thread metadata from one or more trace files.

    pids are keyed ``(file_index, pid)`` internally so per-rank files with
    colliding pids can never interleave (same rule as obs/trace.py merge).
    """

    def __init__(self) -> None:
        self.files: List[str] = []
        self.processes: Dict[Tuple[int, object], str] = {}
        self.threads: Dict[Tuple[Tuple[int, object], object], str] = {}
        self.events: List[_Ev] = []

    @classmethod
    def load(cls, paths: Sequence[str]) -> "Timeline":
        tl = cls()
        for i, p in enumerate(paths):
            try:
                doc = load_chrome_trace(p)
            except (OSError, ValueError) as e:
                # a torn/absent per-rank file must not kill the whole parse
                log.warn_once("devprof:load:%s" % p,
                              "devprof: skipping unreadable trace %s (%r)"
                              % (p, e))
                continue
            tl.files.append(p)
            tl._ingest(doc, i)
        return tl

    @classmethod
    def from_docs(cls, docs: Sequence[Dict]) -> "Timeline":
        """Already-parsed Chrome-trace documents (tests, in-process use)."""
        tl = cls()
        for i, doc in enumerate(docs):
            tl.files.append("<doc %d>" % i)
            tl._ingest(doc, i)
        return tl

    def _ingest(self, doc: Dict, i: int) -> None:
        for ev in doc.get("traceEvents") or []:
            ph = ev.get("ph")
            pkey = (i, ev.get("pid", 0))
            if ph == "M":
                if ev.get("name") == "process_name":
                    self.processes[pkey] = str(
                        (ev.get("args") or {}).get("name", ""))
                elif ev.get("name") == "thread_name":
                    self.threads[(pkey, ev.get("tid"))] = str(
                        (ev.get("args") or {}).get("name", ""))
            elif ph == "X":
                try:
                    ts = float(ev["ts"])
                    dur = float(ev.get("dur", 0.0))
                except (KeyError, TypeError, ValueError):
                    continue
                self.events.append(_Ev(
                    str(ev.get("name", "")), pkey, ev.get("tid"),
                    ts, max(dur, 0.0), ev.get("args"),
                ))

    @classmethod
    def from_dir(cls, profile_dir: str, **kw) -> "Timeline":
        return cls.load(find_trace_files(profile_dir, **kw))

    # -- classification ----------------------------------------------------

    def device_pkeys(self) -> List[Tuple[int, object]]:
        return sorted(
            (k for k, name in self.processes.items()
             if _DEVICE_PID_RE.search(name)),
            key=lambda k: (k[0], str(k[1])),
        )

    def device_ops(self) -> Tuple[List[_Ev], str]:
        """(op events, lanes_source). Real ``/device:`` lanes when present;
        else the host executor-event proxy; else an empty list."""
        dev = set(self.device_pkeys())
        if dev:
            ops = [e for e in self.events if e.pkey in dev
                   and not _H2D_RE.search(e.name)
                   and not _D2H_RE.search(e.name)]
            if ops:
                return ops, "device"
        ops = [e for e in self.events if _EXEC_RE.search(e.name)]
        return ops, ("host_executor" if ops else "none")

    def annotations(self) -> List[_Ev]:
        """Host spans that name a segment (TraceAnnotation entries of the
        obs tracer's spans), innermost attribution anchors."""
        dev = set(self.device_pkeys())
        anns = []
        for e in self.events:
            if e.pkey in dev:
                continue
            seg = segment_for_span(e.name)
            if seg is not None:
                e.segment = seg
                anns.append(e)
        return anns

    def transfers(self) -> Dict[str, List[_Ev]]:
        out: Dict[str, List[_Ev]] = {"h2d": [], "d2h": []}
        for e in self.events:
            if _H2D_RE.search(e.name):
                out["h2d"].append(e)
            elif _D2H_RE.search(e.name):
                out["d2h"].append(e)
        return out

    def window_us(self) -> float:
        if not self.events:
            return 0.0
        t0 = min(e.ts for e in self.events)
        t1 = max(e.end for e in self.events)
        return max(t1 - t0, 0.0)


# ---------------------------------------------------------------------------
# interval math
# ---------------------------------------------------------------------------

def _compute_self_times(events: List[_Ev]) -> None:
    """Self time per lane: an event's duration minus the time covered by
    events nested inside it on the SAME (pkey, tid) lane. Sorting by
    (ts, -dur) makes any container precede its contents; partial overlaps
    (ill-nested exporter artifacts) subtract only the overlapping part.
    Resets self_us first so re-analyzing one Timeline never
    double-subtracts."""
    for e in events:
        e.self_us = e.dur
    lanes: Dict[Tuple, List[_Ev]] = {}
    for e in events:
        lanes.setdefault((e.pkey, e.tid), []).append(e)
    for lane in lanes.values():
        lane.sort(key=lambda e: (e.ts, -e.dur))
        stack: List[_Ev] = []
        for e in lane:
            while stack and e.ts >= stack[-1].end - 1e-9:
                stack.pop()
            if stack:
                top = stack[-1]
                top.self_us -= max(
                    0.0, min(e.end, top.end) - e.ts)
            stack.append(e)
    for e in events:
        e.self_us = max(e.self_us, 0.0)


def _merge_intervals(
    iv: List[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for a, b in iv[1:]:
        if a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _attribute(ops: List[_Ev], anns: List[_Ev]) -> None:
    """Assign each op the segment of the annotation span it overlaps most;
    ties break to the SHORTEST (innermost) span. No overlap -> None
    (bucketed as ``unattributed`` downstream, never dropped). Resets op
    segments first so re-analyzing one Timeline starts clean."""
    for op in ops:
        op.segment = None
    if not anns:
        return
    anns = sorted(anns, key=lambda a: a.ts)
    starts = [a.ts for a in anns]
    max_dur = max(a.dur for a in anns)
    for op in ops:
        # candidates: anns with ts < op.end and end > op.ts; anything
        # starting before op.ts - max_dur has necessarily ended
        lo = bisect.bisect_left(starts, op.ts - max_dur)
        hi = bisect.bisect_right(starts, op.end)
        best, best_ov, best_dur = None, 0.0, 0.0
        for a in anns[lo:hi]:
            ov = min(op.end, a.end) - max(op.ts, a.ts)
            if ov <= 0:
                continue
            if ov > best_ov + 1e-9 or (
                abs(ov - best_ov) <= 1e-9 and a.dur < best_dur
            ):
                best, best_ov, best_dur = a, ov, a.dur
        if best is not None:
            op.segment = best.segment


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

def analyze(
    timeline: Timeline,
    device_kind: Optional[str] = None,
    platform: Optional[str] = None,
    iters: Optional[int] = None,
    top_k: int = 15,
) -> Dict[str, object]:
    """The full device-timeline record (the ``device_timeline`` section).

    ``device_kind``/``platform`` feed the roofline peak lookup
    (costs.chip_peaks); ``iters`` — the number of boosting iterations the
    profiled window covered — adds per-iteration transfer rates.
    """
    from . import costs as costs_mod

    rec: Dict[str, object] = {
        "files": [os.path.basename(p) for p in timeline.files],
        "events": len(timeline.events),
    }
    ops, source = timeline.device_ops()
    rec["lanes_source"] = source
    anns = timeline.annotations()
    tr_all = [e for evs in timeline.transfers().values() for e in evs]
    # the analysis window spans the events the verdict reasons about —
    # NOT every host event: the profiler exports long-lived bookkeeping
    # spans (e.g. its own start_trace frame) that would dilute busy/idle
    # fractions to meaninglessness
    considered = ops + anns + tr_all
    if considered:
        window_us = (max(e.end for e in considered)
                     - min(e.ts for e in considered))
    else:
        window_us = timeline.window_us()
    rec["window_s"] = round(window_us / 1e6, 6)
    if source == "none" or window_us <= 0:
        rec["verdict"] = {
            "bound": "empty",
            "why": "no device lanes and no executor events in the capture",
        }
        return rec

    _compute_self_times(ops)
    _attribute(ops, anns)

    # -- per-device busy/idle ---------------------------------------------
    by_dev: Dict[str, List[_Ev]] = {}
    for op in ops:
        label = timeline.processes.get(op.pkey, "") or "pid %s" % (op.pkey,)
        if source == "host_executor":
            label = "host executor (%s)" % label.strip("/ ") if label else \
                "host executor"
        by_dev.setdefault(label, []).append(op)
    lanes = []
    gaps_ms: List[float] = []
    busy_us_total = 0.0
    for label in sorted(by_dev):
        devops = by_dev[label]
        merged = _merge_intervals([(e.ts, e.end) for e in devops])
        busy = sum(b - a for a, b in merged)
        busy_us_total += busy
        for (a0, b0), (a1, _b1) in zip(merged, merged[1:]):
            gaps_ms.append((a1 - b0) / 1e3)
        lanes.append({
            "device": label,
            "ops": len(devops),
            "busy_s": round(busy / 1e6, 6),
            "busy_fraction": round(busy / window_us, 4),
        })
    n_lanes = max(len(lanes), 1)
    busy_fraction = busy_us_total / (window_us * n_lanes)
    rec["lanes"] = lanes
    rec["device_busy_fraction"] = round(busy_fraction, 4)
    rec["busy_seconds"] = round(busy_us_total / 1e6, 6)
    rec["idle_seconds"] = round(
        max(window_us * n_lanes - busy_us_total, 0.0) / 1e6, 6)

    hist: Dict[str, int] = {}
    edges = ["<%gms" % GAP_BUCKETS_MS[0]] + [
        "%g-%gms" % (a, b)
        for a, b in zip(GAP_BUCKETS_MS, GAP_BUCKETS_MS[1:])
    ] + [">=%gms" % GAP_BUCKETS_MS[-1]]
    for label in edges:
        hist[label] = 0
    for g in gaps_ms:
        idx = bisect.bisect_right(GAP_BUCKETS_MS, g)
        hist[edges[idx]] += 1
    rec["dispatch_gaps"] = {
        "count": len(gaps_ms),
        "total_ms": round(sum(gaps_ms), 3),
        "max_ms": round(max(gaps_ms), 3) if gaps_ms else 0.0,
        "histogram": hist,
    }

    # -- transfers ---------------------------------------------------------
    tr = timeline.transfers()
    transfers: Dict[str, object] = {}
    transfer_us = 0.0
    for direction, evs in tr.items():
        merged = _merge_intervals([(e.ts, e.end) for e in evs])
        secs = sum(b - a for a, b in merged)
        transfer_us += secs
        nbytes = sum(
            v for v in (_arg_num(e.args, _BYTES_KEYS) for e in evs)
            if v is not None
        )
        transfers[direction] = {
            "count": len(evs),
            "seconds": round(secs / 1e6, 6),
            "bytes": int(nbytes),
        }
    transfers["total_seconds"] = round(transfer_us / 1e6, 6)
    if iters:
        transfers["per_iteration"] = {
            "seconds": round(transfer_us / 1e6 / iters, 6),
            "bytes": int(sum(
                transfers[d]["bytes"] for d in ("h2d", "d2h")) / iters),
        }
        rec["iters"] = int(iters)
    rec["transfers"] = transfers
    transfer_fraction = transfer_us / window_us
    rec["transfer_fraction"] = round(transfer_fraction, 4)

    # -- op attribution ----------------------------------------------------
    seg_self: Dict[str, float] = {}
    op_groups: Dict[Tuple[str, str], Dict[str, float]] = {}
    total_self = 0.0
    for op in ops:
        seg = op.segment or "unattributed"
        total_self += op.self_us
        seg_self[seg] = seg_self.get(seg, 0.0) + op.self_us
        g = op_groups.setdefault((op.name, seg), {
            "self_us": 0.0, "count": 0.0, "flops": 0.0, "bytes": 0.0,
        })
        g["self_us"] += op.self_us
        g["count"] += 1
        g["flops"] += _arg_num(op.args, _FLOPS_KEYS) or 0.0
        g["bytes"] += _arg_num(op.args, _BYTES_KEYS) or 0.0

    rec["segments"] = {
        k: round(v / 1e6, 6)
        for k, v in sorted(seg_self.items(), key=lambda kv: -kv[1])
    }
    attributed = total_self - seg_self.get("unattributed", 0.0)
    rec["attributed_fraction"] = (
        round(attributed / total_self, 4) if total_self else 0.0
    )

    peaks = costs_mod.chip_peaks(device_kind, platform=platform)
    top = sorted(op_groups.items(), key=lambda kv: -kv[1]["self_us"])
    top_ops = []
    for (name, seg), g in top[:top_k]:
        row: Dict[str, object] = {
            "op": name,
            "segment": seg,
            "self_s": round(g["self_us"] / 1e6, 6),
            "count": int(g["count"]),
            "share": round(g["self_us"] / total_self, 4) if total_self else 0.0,
        }
        if g["flops"] and g["self_us"]:
            achieved = g["flops"] / (g["self_us"] / 1e6)
            row["flops"] = g["flops"]
            row["achieved_flops_per_s"] = round(achieved, 1)
            row["peak_flops_fraction"] = round(
                achieved / float(peaks["peak_flops"]), 6)
        if g["bytes"] and g["self_us"]:
            bw = g["bytes"] / (g["self_us"] / 1e6)
            row["bytes"] = int(g["bytes"])
            row["achieved_bytes_per_s"] = round(bw, 1)
            row["peak_bw_fraction"] = round(
                bw / float(peaks["peak_bw"]), 6)
        top_ops.append(row)
    rec["top_ops"] = top_ops

    # the op pinning MFU: the largest device self-time sink, with its
    # roofline placement when the capture carried per-op cost args
    if top_ops:
        pin = dict(top_ops[0])
        pin_extra = {
            "why": "largest device self-time share (%.1f%% of %s)"
            % (100.0 * pin["share"], "device time"),
        }
        pin.update(pin_extra)
        rec["mfu_pin"] = pin
    rec["roofline_chip"] = peaks["chip"]

    # -- verdict -----------------------------------------------------------
    gaps = rec["dispatch_gaps"]
    evidence = {
        "device_busy_fraction": rec["device_busy_fraction"],
        "transfer_fraction": rec["transfer_fraction"],
        "transfer_seconds": transfers["total_seconds"],
        "idle_gap_total_ms": gaps["total_ms"],
        "idle_gap_max_ms": gaps["max_ms"],
        "lanes_source": source,
        "window_s": rec["window_s"],
    }
    if transfer_fraction >= TRANSFER_BOUND_FRAC:
        bound = "transfer-bound"
        why = (
            "transfers cover %.0f%% of the %.3fs window "
            "(threshold %.0f%%); the chip waits on bytes, not dispatch"
            % (100 * transfer_fraction, rec["window_s"],
               100 * TRANSFER_BOUND_FRAC)
        )
    elif busy_fraction < HOST_BOUND_BUSY:
        bound = "host-bound"
        why = (
            "device busy only %.0f%% of the window (threshold %.0f%%): "
            "%.1fms of dispatch gaps (max %.1fms) — the host is the "
            "bottleneck, the chip is idle between dispatches"
            % (100 * busy_fraction, 100 * HOST_BOUND_BUSY,
               gaps["total_ms"], gaps["max_ms"])
        )
    else:
        bound = "device-bound"
        top_name = top_ops[0]["op"] if top_ops else "?"
        why = (
            "device busy %.0f%% of the window with transfers at %.0f%%; "
            "time goes to on-device ops (top: %s)"
            % (100 * busy_fraction, 100 * transfer_fraction, top_name)
        )
    if source == "host_executor":
        why += " [host-executor proxy: no /device: lanes in this capture]"
    rec["verdict"] = {"bound": bound, "why": why, "evidence": evidence}
    return rec


def analyze_dir(profile_dir: str, **kw) -> Dict[str, object]:
    """find_trace_files + Timeline.load + analyze, one call."""
    return analyze(Timeline.from_dir(profile_dir),
                   **kw)


# ---------------------------------------------------------------------------
# publication: gauges + run-report section
# ---------------------------------------------------------------------------

_LAST_RECORD: Dict[str, object] = {}
_SECTION_REGISTERED = False


def _report_section() -> Dict[str, object]:
    return dict(_LAST_RECORD)


def publish(record: Dict[str, object], registry=None) -> None:
    """``devprof_*`` gauges on the one registry + the ``device_timeline``
    run-report section (obs/report.py renders it)."""
    global _SECTION_REGISTERED
    reg = registry if registry is not None else registry_mod.REGISTRY
    if record.get("device_busy_fraction") is not None:
        reg.gauge("devprof_device_busy_fraction").set(
            float(record["device_busy_fraction"]))
    if record.get("attributed_fraction") is not None:
        reg.gauge("devprof_attributed_fraction").set(
            float(record["attributed_fraction"]))
    tr = record.get("transfers") or {}
    for direction in ("h2d", "d2h"):
        d = tr.get(direction)
        if d:
            reg.gauge("devprof_transfer_seconds").set(
                float(d["seconds"]), direction=direction)
            reg.gauge("devprof_transfer_bytes").set(
                float(d["bytes"]), direction=direction)
    for seg, secs in (record.get("segments") or {}).items():
        reg.gauge("devprof_segment_self_seconds").set(
            float(secs), segment=seg)
    verdict = (record.get("verdict") or {}).get("bound")
    if verdict:
        # zero the other labels so a re-publish with a changed verdict
        # never leaves two devprof_bound{verdict=}=1 rows on one scrape
        for known in ("host-bound", "device-bound", "transfer-bound",
                      "empty"):
            reg.gauge("devprof_bound").set(
                1.0 if known == str(verdict) else 0.0, verdict=known)
        if str(verdict) not in ("host-bound", "device-bound",
                                "transfer-bound", "empty"):
            reg.gauge("devprof_bound").set(1.0, verdict=str(verdict))
    _LAST_RECORD.clear()
    _LAST_RECORD.update(record)
    if registry is None:
        if not _SECTION_REGISTERED:
            reg.register_report_section("device_timeline", _report_section)
            _SECTION_REGISTERED = True
    else:
        reg.register_report_section("device_timeline", _report_section)


def reset() -> None:
    _LAST_RECORD.clear()


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def capture(out_dir: Optional[str] = None, ensure_annotations: bool = True):
    """Scoped ``jax.profiler`` trace around a profiled window.

    ``out_dir`` defaults to ``LIGHTGBM_TPU_PROFILE`` (the maybe_profile env
    contract) and is rank-suffixed under a multi-process world. Segment
    names reach the device timeline only through a live obs tracer
    (trace.span enters TraceAnnotation), so when none is active a
    throwaway one is armed for the window and stopped after. Yields the
    resolved capture dir.
    """
    from . import trace as trace_mod

    target = out_dir or os.environ.get(ENV_PROFILE, "")
    if not target:
        raise ValueError(
            "devprof.capture() needs a dir (or set %s)" % ENV_PROFILE)
    target = trace_mod.rank_suffixed(target)
    started = False
    if ensure_annotations and trace_mod.active() is None:
        os.makedirs(target, exist_ok=True)
        try:
            trace_mod.start(os.path.join(target, "host_spans.trace.json"))
            started = True
        except (ValueError, OSError) as e:
            log.debug("devprof: could not arm host tracer: %r" % (e,))
    import jax

    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()
        if started:
            trace_mod.stop()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _print_summary(rec: Dict[str, object], stream=None) -> None:
    out = stream or sys.stdout
    v = rec.get("verdict") or {}
    print("devprof: %d event(s) from %d file(s), window %.3fs, lanes=%s"
          % (rec.get("events", 0), len(rec.get("files") or []),
             rec.get("window_s", 0.0), rec.get("lanes_source")), file=out)
    if rec.get("device_busy_fraction") is not None:
        print("  device_busy_fraction = %.3f   transfer_fraction = %.3f   "
              "attributed = %.0f%%"
              % (rec["device_busy_fraction"], rec.get("transfer_fraction", 0),
                 100 * rec.get("attributed_fraction", 0.0)), file=out)
    for seg, secs in list((rec.get("segments") or {}).items())[:10]:
        print("  segment %-24s %10.6fs" % (seg, secs), file=out)
    for row in (rec.get("top_ops") or [])[:10]:
        extraf = ""
        if row.get("peak_flops_fraction") is not None:
            extraf = "  peak=%.2f%%" % (100 * row["peak_flops_fraction"])
        print("  op %-40s %-18s %9.6fs x%d%s"
              % (row["op"][:40], row["segment"][:18], row["self_s"],
                 row["count"], extraf), file=out)
    print("VERDICT: %s — %s" % (v.get("bound"), v.get("why")), file=out)


def _cmd_parse(args) -> int:
    tl = Timeline.from_dir(args.target)
    if not tl.files:
        print("devprof: no trace files under %r" % args.target,
              file=sys.stderr)
        return 1
    rec = analyze(tl, device_kind=args.device_kind, platform=args.platform,
                  iters=args.iters, top_k=args.top)
    publish(rec)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=1)
        print("devprof: wrote %s" % args.json)
    if args.report:
        from . import report as report_mod

        doc = report_mod.render(
            metrics={"device_timeline": rec},
            title="lightgbm_tpu device timeline",
        )
        with open(args.report, "w", encoding="utf-8") as fh:
            fh.write(doc)
        print("devprof: wrote %s" % args.report)
    _print_summary(rec)
    return 0


def _cmd_capture(args) -> int:
    """Capture a profiled window of real training (or packed predict)
    dispatch, then parse it — the zero-to-verdict path."""
    import numpy as np

    out_dir = args.dir or os.environ.get(ENV_PROFILE, "") or "devprof_capture"
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    X = rng.rand(args.rows, args.features).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.rand(args.rows) > 0.6).astype(np.float32)
    params = {
        "objective": "binary", "num_leaves": args.leaves,
        "max_bin": args.bins, "learning_rate": 0.1, "verbosity": -1,
    }
    if args.device_type:
        params["device_type"] = args.device_type
    booster = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    import jax

    # warmup outside the capture: the multi-minute XLA compile would
    # otherwise dominate the window and every verdict would read host-bound
    for _ in range(2):
        booster.update()
    jax.block_until_ready(booster._gbdt.scores)
    mode = args.mode
    with capture(out_dir) as target:
        if mode == "predict":
            pk = booster.to_packed()
            xd = jax.device_put(X[: min(args.rows, 1 << 14)])
            for _ in range(args.iters):
                out = pk.fused_scores(xd)
            jax.block_until_ready(out)
        else:
            for _ in range(args.iters):
                booster.update()
            jax.block_until_ready(booster._gbdt.scores)
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = None
    rec = analyze_dir(target, device_kind=kind,
                      platform=jax.default_backend(), iters=args.iters,
                      top_k=args.top)
    publish(rec)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=1)
        print("devprof: wrote %s" % args.json)
    _print_summary(rec)
    print("devprof: capture dir %s" % target)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.devprof",
        description="Device-timeline auditor (obs/devprof.py)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    pp = sub.add_parser("parse", help="parse an existing profile capture")
    pp.add_argument("target", help="profile dir (LIGHTGBM_TPU_PROFILE "
                                   "target) or a trace.json(.gz) file")
    pp.add_argument("--top", type=int, default=15)
    pp.add_argument("--device-kind", default=None,
                    help="roofline chip lookup (e.g. 'TPU v5e'); default "
                         "cpu-nominal")
    pp.add_argument("--platform", default=None)
    pp.add_argument("--iters", type=int, default=None,
                    help="iterations the window covered (per-iter rates)")
    pp.add_argument("--json", help="write the full record as JSON")
    pp.add_argument("--report", help="write a device_timeline HTML page")
    pp.set_defaults(fn=_cmd_parse)
    cp = sub.add_parser("capture", help="profile a training window, then "
                                        "parse it")
    cp.add_argument("--dir", default=None)
    cp.add_argument("--rows", type=int, default=20000)
    cp.add_argument("--features", type=int, default=16)
    cp.add_argument("--leaves", type=int, default=31)
    cp.add_argument("--bins", type=int, default=63)
    cp.add_argument("--iters", type=int, default=8)
    cp.add_argument("--mode", choices=("train", "predict"), default="train")
    cp.add_argument("--device-type", default=None,
                    help="forwarded as the device_type param (e.g. 'cpu' "
                         "for the native host learner — the profiled-window "
                         "escape hatch on the CPU backend, where per-thunk "
                         "host events scale with rows x leaves and a "
                         "1M-row XLA-grower window exhausts memory; the "
                         "same reason bench.py trains native off-chip)")
    cp.add_argument("--top", type=int, default=15)
    cp.add_argument("--json", help="write the full record as JSON")
    cp.set_defaults(fn=_cmd_capture)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
