"""graftir: jaxpr/StableHLO-level program auditor — lint what XLA actually sees.

graftlint (tools/graftlint, JX001-13) polices *source* idioms and graftsan
(obs/sanitize.py) polices *runtime* behavior — but every exactness and perf
regression this repo has shipped or narrowly dodged lived in the layer
between: the traced program XLA compiles. The serial-learner FMA contraction
that moved model bytes by 1 ulp (PR 8/11), implicit per-iteration
host->device uploads (PR 10), psum grouping drift (PR 14) and recompile
churn are all *visible in the ClosedJaxpr and the lowered StableHLO* before
a single chip cycle is spent. This module traces the canonical jitted entry
points with abstract arguments over the run's real shape lattice
(ops/grow.bucket_sizes + the HistRoute shape classes) and runs a rule
engine over the IR:

  IR001  forbidden primitives in hot paths — host callbacks
         (debug/pure/io_callback), in-program transfers (device_put),
         infeed/outfeed: each is a host sync or upload inside compiled code.
  IR002  dtype discipline — no f64 anywhere (TPUs have none; x64 drift),
         score/carry accumulation stays f32, convert_element_type churn
         counted against a per-entry budget.
  IR003  large baked-in constants — a host (numpy) constvar over the
         threshold is re-uploaded per executable and re-folded per trace
         (recompile + HBM duplication hazard). Device-resident captures
         (the bins closure) are accounted but intentional.
  IR004  donation honored — declared donate_argnums must survive into the
         lowered module as input/output aliases (``tf.aliasing_output``);
         silently-dropped donation doubles peak HBM.
  IR005  collective audit — psum/all_gather axis names must be declared
         mesh axes for the entry, an expected-collective program must
         actually contain one, and the combine payload must match the
         ``HistogramSource.payload_bytes`` seam estimate.
  IR006  exactness fences — the materialized-output / per-row-select FMA
         pins on the score-carry adds (PR 8, _finish_step) must survive
         into the IR: a scatter-add carry update whose addend is neither a
         program output nor select-fed is one fusion pass from a 1-ulp
         model drift.

On top of the rules sits a per-entry-point, per-shape-class
**program-fingerprint contract** (irscan_contract.json, checked in like the
graftlint baseline): digests of the lowered modules plus their op-count
histograms. Unexplained program drift fails loudly with an op-level diff,
and a static trace-count budget per entry point is the compile-time twin of
obs/retrace's runtime gauge. Findings follow the graftlint baseline
workflow (irscan_baseline.txt — line-number-free keys, mandatory
justifications, exit 1 on new findings OR stale entries).

Run::

    python -m lightgbm_tpu.obs.irscan              # scan vs baseline+contract
    python -m lightgbm_tpu.obs.irscan --full       # the whole bucket lattice
    python -m lightgbm_tpu.obs.irscan --write-contract   # refresh fingerprints
    python -m lightgbm_tpu.obs.irscan --selfcheck  # seeded violations caught?

Wired as ``helpers/check.sh --ir`` and the ``irscan`` bringup stage
(helpers/tpu_bringup.py runs helpers/irscan_smoke.py by file path — the
driver stays jax-free). Docs: docs/StaticAnalysis.md §Program-level audit.
"""
from __future__ import annotations

import argparse
import contextlib
import hashlib
import json
import os
import re
import sys
import warnings
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "irscan_baseline.txt")
DEFAULT_CONTRACT = os.path.join(_HERE, "irscan_contract.json")

#: a host (numpy) constant baked into a program above this is IR003 —
#: re-folded on every trace and duplicated per executable
NP_CONST_LIMIT = 64 * 1024

#: convert_element_type eqns tolerated per program before IR002 flags churn
DEFAULT_CONVERT_BUDGET = 128

#: primitives that are a host sync / transfer inside compiled code (IR001).
#: ``device_put`` is handled separately: traced as a bare aliasing
#: annotation (devices=[None], CopySemantics.ALIAS) it is a no-op the real
#: tree legitimately contains; with a concrete destination/source or copy
#: semantics it is an in-program transfer and IR001 fires.
FORBIDDEN_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "callback",
    "infeed", "outfeed", "copy_to_host",
})


def _device_put_is_transfer(params: dict) -> bool:
    if any(d is not None for d in params.get("devices", ())):
        return True
    if any(s is not None for s in params.get("srcs", ())):
        return True
    return any(
        "ALIAS" not in str(cs) for cs in params.get("copy_semantics", ())
    )

#: cross-device collectives whose axis names IR005 validates
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "all_gather", "all_reduce", "reduce_scatter",
    "all_to_all", "ppermute", "pmax", "pmin",
})
#: collectives that ship one operand-sized payload per participant —
#: cross-checked against HistogramSource.payload_bytes (IR005)
PAYLOAD_PRIMS = frozenset({"psum", "psum2", "all_reduce"})
#: axis-name-bearing but payload-free primitives (still axis-validated)
AXIS_PRIMS = COLLECTIVE_PRIMS | frozenset({"axis_index"})

RULES: Dict[str, str] = {
    "IR001": "forbidden primitive in a hot-path program",
    "IR002": "dtype discipline: f64 / non-f32 carry / convert churn",
    "IR003": "large host constant baked into the program",
    "IR004": "declared donation dropped by lowering",
    "IR005": "collective axis/payload audit",
    "IR006": "FMA exactness fence stripped from the IR",
}


# ---------------------------------------------------------------------------
# findings + baseline (graftlint's workflow, program-scoped keys)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Finding:
    rule: str
    entry: str
    shape: str
    detail: str
    message: str

    @property
    def key(self) -> str:
        """Line-number-free content key: RULE:entry:shape:detail."""
        return "%s:%s:%s:%s" % (self.rule, self.entry, self.shape, self.detail)

    def format(self) -> str:
        return "%s %s[%s] %s — %s" % (
            self.rule, self.entry, self.shape, self.detail, self.message
        )


def load_baseline(path: str) -> Tuple[Counter, Dict[str, str]]:
    """-> (multiset of suppressed keys, key -> justification). Same file
    format as tools/graftlint/baseline.txt."""
    keys: Counter = Counter()
    notes: Dict[str, str] = {}
    if not os.path.exists(path):
        return keys, notes
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "  # " in line:
                key, note = line.split("  # ", 1)
                key = key.strip()
                notes[key] = note.strip()
            else:
                key = line
            keys[key] += 1
    return keys, notes


def compare_to_baseline(
    findings: Sequence[Finding], baseline: Counter
) -> Tuple[List[Finding], Counter]:
    """-> (unsuppressed findings, stale baseline keys)."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        if remaining[f.key] > 0:
            remaining[f.key] -= 1
        else:
            new.append(f)
    stale = Counter({k: n for k, n in remaining.items() if n > 0})
    return new, stale


def write_baseline(
    path: str, findings: Sequence[Finding], notes: Optional[Dict[str, str]] = None
) -> None:
    notes = notes or {}
    entries: Counter = Counter(f.key for f in findings)
    lines = [
        "# graftir baseline — accepted IR findings, one per line:",
        "#   <RULE:entry:shape:detail>  # <one-line justification>",
        "# Regenerate with: python -m lightgbm_tpu.obs.irscan --write-baseline",
        "",
    ]
    for key in sorted(entries):
        lines.append("%s  # %s" % (key, notes.get(key, "TODO: justify or fix")))
        lines.extend([key] * (entries[key] - 1))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------
def _sub_jaxprs(value) -> Iterable[Tuple[Any, list]]:
    """Yield (Jaxpr, consts) pairs reachable from an eqn param value."""
    import jax

    if isinstance(value, jax.core.Jaxpr):
        yield value, []
    elif isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr, list(value.consts)
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_jaxprs(closed) -> Iterable[Tuple[Any, list]]:
    """(jaxpr, consts) for the top program and every nested sub-program
    (scan/while/cond bodies, pjit calls, shard_map regions, ...)."""
    seen = []
    stack = [(closed.jaxpr, list(closed.consts))]
    while stack:
        jx, consts = stack.pop()
        if any(jx is s for s in seen):
            continue
        seen.append(jx)
        yield jx, consts
        for eqn in jx.eqns:
            for v in eqn.params.values():
                stack.extend(_sub_jaxprs(v))


def iter_eqns(closed) -> Iterable[Any]:
    for jx, _ in iter_jaxprs(closed):
        for eqn in jx.eqns:
            yield eqn


def _aval(v):
    return getattr(v, "aval", None)


def _aval_nbytes(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * int(np.dtype(aval.dtype).itemsize)


# ---------------------------------------------------------------------------
# entry specs + per-program audit records
# ---------------------------------------------------------------------------
@dataclass
class EntrySpec:
    """One registered jitted entry point and its audit contract."""

    name: str
    #: [(shape_label, jit_fn, args, kwargs)] — abstract (ShapeDtypeStruct)
    #: traced operands; statics ride in args/kwargs as concrete values
    variants: List[Tuple[str, Any, tuple, dict]]
    hot: bool = True                 # IR001 engages
    donated_min: int = 0             # IR004: >= this many lowered aliases
    pin: str = "none"                # IR006: none | materialized | select
    axes: frozenset = frozenset()    # IR005: declared mesh axes
    expect_collective: bool = False  # IR005: program must contain one
    carry_out: Optional[int] = None  # IR002: this output must stay f32
    convert_budget: int = DEFAULT_CONVERT_BUDGET
    np_const_limit: int = NP_CONST_LIMIT
    x64: bool = False                # trace under enable_x64 (seeded tests)


@dataclass
class Audit:
    """The scan record for one (entry, shape) program."""

    entry: str
    shape: str
    findings: List[Finding] = field(default_factory=list)
    digest: str = ""
    ops: Dict[str, int] = field(default_factory=dict)
    convert_count: int = 0
    np_const_bytes: int = 0
    device_const_bytes: int = 0
    donation_aliases: int = 0
    collectives: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------
def _rule_ir001(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    if not spec.hot:
        return
    seen = set()
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name == "device_put" and _device_put_is_transfer(eqn.params):
            name = "device_put[transfer]"
        if name in FORBIDDEN_PRIMS or name == "device_put[transfer]":
            if name in seen:
                continue
            seen.add(name)
            audit.findings.append(Finding(
                "IR001", spec.name, shape, "prim=%s" % name,
                "forbidden primitive %r in a hot-path program — a host "
                "callback/transfer inside compiled code serializes the "
                "dispatch pipeline (the IR-level form of JX001)" % name,
            ))


def _rule_ir002(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    f64_prims = set()
    converts = 0
    for eqn in iter_eqns(closed):
        if eqn.primitive.name == "convert_element_type":
            converts += 1
        for v in list(eqn.outvars) + list(eqn.invars):
            aval = _aval(v)
            dt = getattr(aval, "dtype", None)
            if dt is not None and np.dtype(dt) == np.float64:
                f64_prims.add(eqn.primitive.name)
    audit.convert_count = converts
    for prim in sorted(f64_prims):
        audit.findings.append(Finding(
            "IR002", spec.name, shape, "f64=%s" % prim,
            "float64 value flows through %r — TPUs have no f64 (silent "
            "downcast with x64 off, bandwidth/precision drift with it on; "
            "the IR-level form of JX006)" % prim,
        ))
    if spec.carry_out is not None:
        outvars = closed.jaxpr.outvars
        if spec.carry_out < len(outvars):
            dt = getattr(_aval(outvars[spec.carry_out]), "dtype", None)
            if dt is not None and np.dtype(dt) != np.float32:
                audit.findings.append(Finding(
                    "IR002", spec.name, shape,
                    "carry_dtype=%s" % np.dtype(dt).name,
                    "score/carry output %d accumulates in %s, not float32 — "
                    "the exactness contract pins f32 carries"
                    % (spec.carry_out, np.dtype(dt).name),
                ))
    if converts > spec.convert_budget:
        audit.findings.append(Finding(
            "IR002", spec.name, shape, "convert_churn=%d" % converts,
            "%d convert_element_type eqns exceed this entry's budget of %d "
            "— dtype churn costs bandwidth every dispatch"
            % (converts, spec.convert_budget),
        ))


def _rule_ir003(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    import jax

    np_bytes = dev_bytes = 0
    for _, consts in iter_jaxprs(closed):
        for c in consts:
            if isinstance(c, np.ndarray):
                np_bytes += int(c.nbytes)
                if c.nbytes > spec.np_const_limit:
                    audit.findings.append(Finding(
                        "IR003", spec.name, shape,
                        "const_bytes=%d" % int(c.nbytes),
                        "host constant of %d bytes (%s%s) baked into the "
                        "program (> %d limit) — re-folded on every trace "
                        "and duplicated per executable; hoist to a "
                        "device-resident argument or module-level buffer"
                        % (int(c.nbytes), np.dtype(c.dtype).name,
                           list(c.shape), spec.np_const_limit),
                    ))
            elif isinstance(c, jax.Array):
                dev_bytes += int(getattr(c, "nbytes", 0))
    audit.np_const_bytes = np_bytes
    audit.device_const_bytes = dev_bytes


def _rule_ir004(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    # an immediately-aliasable donation lowers to tf.aliasing_output; a
    # donation whose aliasing is decided by XLA's own pass (sharded
    # programs) survives as jax.buffer_donor — both honor the declaration,
    # a DROPPED donation leaves neither attribute
    aliases = len(re.findall(r"tf\.aliasing_output", hlo)) + len(
        re.findall(r"jax\.buffer_donor", hlo)
    )
    audit.donation_aliases = aliases
    if spec.donated_min and aliases < spec.donated_min:
        audit.findings.append(Finding(
            "IR004", spec.name, shape,
            "aliases=%d<%d" % (aliases, spec.donated_min),
            "declared donation was dropped by lowering: %d input/output "
            "aliases in the StableHLO module, >= %d expected — the donated "
            "buffer stays live across the call, doubling peak HBM (the "
            "runtime fate JX005 can only guess at)"
            % (aliases, spec.donated_min),
        ))


def _axis_names(params: dict) -> List[str]:
    names = []
    for key in ("axes", "axis_name"):
        v = params.get(key)
        if v is None:
            continue
        for item in v if isinstance(v, (tuple, list)) else (v,):
            if isinstance(item, str):
                names.append(item)
    return names


def _rule_ir005(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    from ..ops.histogram import HistogramSource

    bad_axes = set()
    payload_drift = []
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name not in AXIS_PRIMS:
            continue
        if name in COLLECTIVE_PRIMS:
            audit.collectives.append(name)
        for ax in _axis_names(eqn.params):
            if ax not in spec.axes:
                bad_axes.add((name, ax))
        if name in PAYLOAD_PRIMS:
            for v in eqn.invars:
                aval = _aval(v)
                if aval is None or not hasattr(aval, "dtype"):
                    continue
                actual = _aval_nbytes(aval)
                est = HistogramSource.payload_bytes(
                    aval.shape, np.dtype(aval.dtype).itemsize
                )
                if est != actual:
                    payload_drift.append((name, actual, est))
    for name, ax in sorted(bad_axes):
        audit.findings.append(Finding(
            "IR005", spec.name, shape, "axis=%s" % ax,
            "collective %r runs over axis %r which is not a declared mesh "
            "axis for this entry (declared: %s) — a typo'd axis fails only "
            "at run time, on the hardware (the IR-level form of JX007)"
            % (name, ax, sorted(spec.axes) or "none"),
        ))
    for name, actual, est in payload_drift:
        audit.findings.append(Finding(
            "IR005", spec.name, shape, "payload=%d!=%d" % (actual, est),
            "%r combine payload is %d bytes but the "
            "HistogramSource.payload_bytes seam estimates %d — the "
            "observability layer's comms accounting has drifted from the "
            "program" % (name, actual, est),
        ))
    if spec.expect_collective and not audit.collectives:
        audit.findings.append(Finding(
            "IR005", spec.name, shape, "collective_missing",
            "entry is declared collective (sharded combine expected) but "
            "the traced program contains no cross-device collective — "
            "shard partials are never combined",
        ))


def _producer_map(jaxpr) -> Dict[Any, Any]:
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


def _is_select_producer(eqn) -> bool:
    """The update operand was produced by a per-row select — directly, or
    through the jnp.where pjit wrapper (`_where`)."""
    if eqn.primitive.name == "select_n":
        return True
    if eqn.primitive.name == "pjit":
        if "_where" in str(eqn.params.get("name", "")):
            return True
        sub = eqn.params.get("jaxpr")
        if sub is not None:
            return any(
                q.primitive.name == "select_n" for q in sub.jaxpr.eqns
            )
    return False


def _rule_ir006(spec: EntrySpec, shape: str, closed, hlo: str, audit: Audit):
    if spec.pin == "none":
        return
    scatter_adds = 0
    pinned = False
    if spec.pin == "materialized":
        # the per-iteration form: the addend is a PROGRAM OUTPUT (and the
        # scatter-add's update operand) — a materialized output cannot be
        # recomputed-and-contracted inside the add kernel (PR 8)
        top = closed.jaxpr
        outset = set(top.outvars)
        for eqn in top.eqns:
            if eqn.primitive.name == "scatter-add":
                scatter_adds += 1
                if len(eqn.invars) >= 3 and eqn.invars[2] in outset:
                    pinned = True
    else:  # select: the scan/shard_map form — update fed by a per-row select
        for jx, _ in iter_jaxprs(closed):
            produced = _producer_map(jx)
            for eqn in jx.eqns:
                if eqn.primitive.name != "scatter-add":
                    continue
                scatter_adds += 1
                if len(eqn.invars) < 3:
                    continue
                prod = produced.get(eqn.invars[2])
                if prod is not None and _is_select_producer(prod):
                    pinned = True
    if scatter_adds == 0:
        audit.findings.append(Finding(
            "IR006", spec.name, shape, "pin_site_missing",
            "entry declares an FMA-pinned score add (%s mode) but the "
            "program contains no scatter-add carry update — the pinned "
            "seam has been rewritten; re-audit the exactness fence"
            % spec.pin,
        ))
    elif not pinned:
        audit.findings.append(Finding(
            "IR006", spec.name, shape, "fma_pin_stripped",
            "score-carry scatter-add has no surviving FMA pin (%s mode "
            "expected): the addend is neither a materialized program "
            "output nor select-fed — one fusion pass from the 1-ulp model "
            "drift PR 8 measured (the IR-level proof JX012 cannot give)"
            % spec.pin,
        ))


_RULE_FNS = (
    _rule_ir001, _rule_ir002, _rule_ir003, _rule_ir004, _rule_ir005,
    _rule_ir006,
)


# ---------------------------------------------------------------------------
# tracing + fingerprints
# ---------------------------------------------------------------------------
_LOC_RE = re.compile(r"\s*loc\([^)]*\)")
_OP_RE = re.compile(r"\b(?:stablehlo|mhlo|chlo|func)\.[\w.]+")


def _normalize_hlo(text: str) -> str:
    """Strip location metadata so fingerprints track the program, not the
    source file layout that traced it."""
    lines = [
        _LOC_RE.sub("", ln) for ln in text.splitlines()
        if not ln.lstrip().startswith("#loc")
    ]
    return "\n".join(lines)


def op_histogram(hlo: str) -> Dict[str, int]:
    return dict(Counter(_OP_RE.findall(hlo)))


def audit_program(spec: EntrySpec, shape: str, fn, args, kwargs) -> Audit:
    """Trace one entry variant abstractly and run every IR rule."""
    import jax

    audit = Audit(entry=spec.name, shape=shape)
    ctx = (
        jax.experimental.enable_x64()
        if spec.x64 else contextlib.nullcontext()
    )
    with warnings.catch_warnings():
        # a dropped donation warns at lowering; IR004 is the loud version
        warnings.simplefilter("ignore")
        with ctx:
            traced = fn.trace(*args, **kwargs)
            closed = traced.jaxpr
            lowered = traced.lower() if hasattr(traced, "lower") else (
                fn.lower(*args, **kwargs)
            )
            hlo = _normalize_hlo(lowered.as_text())
    audit.digest = hashlib.sha256(hlo.encode("utf-8")).hexdigest()[:16]
    audit.ops = op_histogram(hlo)
    for rule_fn in _RULE_FNS:
        rule_fn(spec, shape, closed, hlo, audit)
    return audit


def audit_entry(spec: EntrySpec) -> List[Audit]:
    return [
        audit_program(spec, shape, fn, args, kwargs)
        for shape, fn, args, kwargs in spec.variants
    ]


# ---------------------------------------------------------------------------
# the fingerprint contract
# ---------------------------------------------------------------------------
def contract_env() -> Dict[str, Any]:
    import jax

    return {
        "platform": jax.default_backend(),
        "jax": jax.__version__,
        "devices": len(jax.devices()),
    }


def load_contract(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_contract(
    path: str, audits: Sequence[Audit], trace_counts: Dict[str, int]
) -> Dict[str, Any]:
    entries: Dict[str, Any] = {}
    for a in audits:
        ent = entries.setdefault(a.entry, {"trace_budget": 0, "shapes": {}})
        ent["shapes"][a.shape] = {"digest": a.digest, "ops": a.ops}
    for name, n in trace_counts.items():
        if name in entries:
            entries[name]["trace_budget"] = n
    doc = {"env": contract_env(), "entries": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def _op_diff(old: Dict[str, int], new: Dict[str, int]) -> str:
    """Human op-level diff between two fingerprint op histograms."""
    parts = []
    for op in sorted(set(old) | set(new)):
        d = new.get(op, 0) - old.get(op, 0)
        if d:
            parts.append("%+d %s" % (d, op))
    return ", ".join(parts) if parts else "same op mix (order/shape change)"


def check_contract(
    contract: Optional[Dict[str, Any]],
    audits: Sequence[Audit],
    trace_counts: Dict[str, int],
) -> Tuple[List[str], Optional[str]]:
    """-> (problems, skip_reason). A missing contract or a foreign
    environment skips LOUDLY (the reason is printed) instead of comparing
    digests that can never match across jax versions/backends."""
    if contract is None:
        return [], "no contract file — run --write-contract to pin"
    env = contract_env()
    if contract.get("env") != env:
        return [], (
            "contract recorded for %s, this environment is %s — "
            "fingerprints not comparable; re-pin with --write-contract"
            % (contract.get("env"), env)
        )
    problems: List[str] = []
    entries = contract.get("entries", {})
    for a in audits:
        ent = entries.get(a.entry)
        if ent is None:
            problems.append(
                "unpinned entry point %r — program drift or a new entry; "
                "review and --write-contract" % a.entry
            )
            continue
        rec = ent.get("shapes", {}).get(a.shape)
        if rec is None:
            problems.append(
                "unpinned shape class %s[%s] — review and --write-contract"
                % (a.entry, a.shape)
            )
            continue
        if rec.get("digest") != a.digest:
            problems.append(
                "program drift at %s[%s]: digest %s -> %s; op diff: %s"
                % (a.entry, a.shape, rec.get("digest"), a.digest,
                   _op_diff(rec.get("ops", {}), a.ops))
            )
    for name, n in trace_counts.items():
        ent = entries.get(name)
        if ent is None:
            continue
        budget = int(ent.get("trace_budget", 0))
        if budget and n > budget:
            problems.append(
                "trace-count budget exceeded for %r: %d traces > budget %d "
                "— a shape/static-arg class multiplied (the compile-time "
                "twin of obs/retrace's runtime gauge)" % (name, n, budget)
            )
    return problems, None


# ---------------------------------------------------------------------------
# the real entry-point registry (the corpus)
# ---------------------------------------------------------------------------
ENV_ROWS = "LIGHTGBM_TPU_IRSCAN_ROWS"


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _sds_like(a):
    import jax

    return jax.ShapeDtypeStruct(tuple(a.shape), np.dtype(a.dtype))


@dataclass
class Corpus:
    """Tiny deterministic bootstrap models whose live jit seams the
    registry traces — the args are ABSTRACTED (ShapeDtypeStruct), so no
    program in the scan ever executes."""

    bst: Any
    g: Any
    bst_data: Optional[Any] = None
    g_data: Optional[Any] = None
    pk: Optional[Any] = None
    chunk: int = 3


def build_corpus(
    rows: int = 384, chunk: int = 3, include_data: bool = True,
    include_serve: bool = True,
) -> Corpus:
    import jax

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    X = rng.rand(rows, 8).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.rand(rows) > 0.6).astype(np.float32)
    params = {
        "objective": "binary", "num_leaves": 7, "max_bin": 31,
        "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 5,
        "device_chunk_size": chunk,
    }
    bst = lgb.train(params, lgb.Dataset(X, label=y), 2 * chunk + 1)
    g = bst._gbdt
    reason = g.device_chunk_fallback_reason()
    if reason is not None:
        raise RuntimeError(
            "irscan corpus cannot reach the chunked device path: %s" % reason
        )
    corpus = Corpus(bst=bst, g=g, chunk=chunk)
    if include_data and len(jax.devices()) >= 2:
        bst2 = lgb.train(
            dict(params, tree_learner="data", num_machines=2),
            lgb.Dataset(X, label=y), 2 * chunk + 1,
        )
        corpus.bst_data, corpus.g_data = bst2, bst2._gbdt
    if include_serve:
        corpus.pk = bst.to_packed()
    return corpus


def _lattice_buckets(full: bool) -> List[int]:
    from ..ops.grow import bucket_sizes

    n = int(os.environ.get(ENV_ROWS, "4096"))
    buckets = list(bucket_sizes(n))
    if full or len(buckets) <= 3:
        return buckets
    # quick scan: smallest, a middle class, largest — the full sweep rides
    # --full (check.sh --ir) and the slow-marked lattice test
    return [buckets[0], buckets[len(buckets) // 2], buckets[-1]]


def _serve_buckets(full: bool) -> List[int]:
    from ..serve.cache import DEFAULT_MIN_ROWS

    top = 11 if full else 8  # 2^11 = 2048 full ladder, 256 quick
    return [1 << b for b in range(DEFAULT_MIN_ROWS.bit_length() - 1, top)]


def _spec_serial_chunk(c: Corpus) -> EntrySpec:
    g = c.g
    fn = g._chunk_fn(c.chunk)
    fmasks = g._sample_feature_masks(c.chunk)
    args = (
        _sds_like(g.scores), _sds_like(g._bag_mask), _sds((), np.int32),
        fmasks, _sds((), np.float32), g._pin_all,
    )
    return EntrySpec(
        name="gbdt.train_chunk[serial]",
        variants=[("rows=%d" % g.num_data, fn, args, {})],
        donated_min=2, pin="select", carry_out=0,
    )


def _spec_data_chunk(c: Corpus) -> Optional[EntrySpec]:
    g = c.g_data
    if g is None:
        return None
    extra = g._sharded_chunk_args()  # places the sharded carries
    fn = g._chunk_fn(c.chunk)
    fmasks = g._sample_feature_masks(c.chunk)
    args = (
        _sds_like(g.scores), _sds_like(g._bag_mask), _sds((), np.int32),
        fmasks, _sds((), np.float32),
    ) + tuple(extra)
    return EntrySpec(
        name="gbdt.train_chunk[data]",
        variants=[("rows=%d" % g.num_data, fn, args, {})],
        donated_min=2, pin="select", carry_out=0,
        axes=frozenset({"data"}), expect_collective=True,
    )


def _spec_grow_tree(c: Corpus) -> EntrySpec:
    from ..ops.grow import grow_tree, spec_batch_slots
    from ..ops.histogram import route_rows_variant

    g = c.g
    cfg = g.config
    M = cfg.num_leaves
    F = g.feature_meta["num_bin"].shape[0]
    N = g.num_data
    slots = g._hist_pool_slots()
    rows = slots if slots is not None else M
    buf = _sds((rows, F, g.num_bins, 3), np.float32)
    sbuf = None
    donated = 1
    if spec_batch_slots(
        M, hist_mode=cfg.tpu_hist_mode,
        has_lazy_cegb=g.cegb_params.has_lazy,
        pooled=slots is not None and slots < M,
        cegb_on=g.cegb_params.enabled,
        route_rows_variant=route_rows_variant(
            g._hist_route, num_bins=g.num_group_bins or g.num_bins,
            hist_dtype=cfg.tpu_hist_dtype, n_rows=N,
        ),
    ):
        sbuf = _sds((M, F, g.num_bins, 3), np.float32)
        donated += 1
    kwargs = dict(
        num_leaves=M, max_depth=cfg.max_depth, num_bins=g.num_bins,
        num_group_bins=g.num_group_bins, params=g.split_params,
        chunk=cfg.tpu_hist_chunk, hist_dtype=cfg.tpu_hist_dtype,
        hist_mode=cfg.tpu_hist_mode, two_way=g._two_way,
        hist_route=g._hist_route, forced_splits=g._forced_splits,
        cegb=g.cegb_params, cegb_state=g._cegb_state, hist_buf=buf,
        bins_nf=g.bins_dev_nf, hist_pool_slots=slots, spec_buf=sbuf,
    )
    args = (
        g.bins_dev, _sds((N,), np.float32), _sds((N,), np.float32),
        _sds_like(g._bag_mask), g._sample_features(), g.feature_meta,
    )
    return EntrySpec(
        name="ops.grow_tree",
        variants=[("rows=%d" % N, grow_tree, args, kwargs)],
        donated_min=donated,
    )


def _spec_finish_step(c: Corpus) -> EntrySpec:
    import jax

    g = c.g
    _, step = g._finish_step(0)
    fn = jax.jit(step, donate_argnums=(0,))
    ta, _ = g._device_trees[-1]
    args = (
        _sds_like(g.scores), _sds_like(ta.leaf_value),
        _sds_like(ta.internal_value), _sds((g.num_data,), np.int32),
        _sds_like(g._bag_mask), _sds((), np.int32), _sds((), np.float32),
    )
    return EntrySpec(
        name="gbdt.finish_step",
        variants=[("rows=%d" % g.num_data, fn, args, {})],
        donated_min=1, pin="materialized", carry_out=0,
    )


def _spec_leaf_histograms(c: Corpus, full: bool) -> List[EntrySpec]:
    from ..ops import histogram as hist_mod

    g = c.g
    cfg = g.config
    B = g.num_group_bins or g.num_bins
    F = g.feature_meta["num_bin"].shape[0]
    bins_dtype = np.dtype(c.g.bins_dev.dtype)
    buckets = _lattice_buckets(full)
    default = hist_mod.default_impl()
    impls = {default, "xla"}  # the routed default + the exactness oracle
    if g._hist_route is not None:
        impls |= g._hist_route.effective_impls(
            default, B, 3, cfg.tpu_hist_dtype, buckets
        )
    # every routing contender this backend can serve at the corpus width is
    # pinned (ISSUE 17): a tune table written later can route to any of
    # them without first widening the contract, and an IR drift in a
    # not-currently-routed kernel still trips the scan
    impls |= {
        i for i in hist_mod.IMPLS if hist_mod.impl_supported(i, B)
    }
    specs = []
    for impl in sorted(impls):
        if not hist_mod.impl_supported(impl, B):
            continue
        variants = []
        for rb in buckets:
            kwargs = dict(
                num_bins=B, chunk=min(cfg.tpu_hist_chunk, rb), impl=impl,
                hist_dtype=cfg.tpu_hist_dtype,
            )
            variants.append((
                "rows=%d" % rb, hist_mod.leaf_histogram,
                (_sds((F, rb), bins_dtype), _sds((rb, 3), np.float32)),
                kwargs,
            ))
        specs.append(EntrySpec(
            name="ops.leaf_histogram[%s]" % impl, variants=variants,
            carry_out=0,
        ))
    return specs


def _spec_serve(c: Corpus, full: bool) -> List[EntrySpec]:
    from ..ops import predict as predict_mod

    pk = c.pk
    if pk is None:
        return []
    F = pk.num_features
    buckets = _serve_buckets(full)
    if not full:
        buckets = [buckets[0], buckets[-1]]
    leaves, values, binrows = [], [], []
    for r in buckets:
        codes = _sds((r, F), np.int32)
        isnan = _sds((r, F), np.bool_)
        label = "rows=%d" % r
        leaves.append((
            label, predict_mod.packed_predict_leaves,
            (codes, isnan, pk.packed), {},
        ))
        values.append((
            label, predict_mod.packed_predict_values,
            (codes, isnan, pk.packed),
            dict(num_class=pk.num_class, average_output=pk.average_output),
        ))
        binrows.append((
            label, predict_mod.packed_bin_rows,
            (_sds((r, F), np.float32), pk.bounds_dev, pk.is_cat_dev), {},
        ))
    return [
        EntrySpec(name="serve.packed_predict_leaves", variants=leaves),
        EntrySpec(name="serve.packed_predict_values", variants=values,
                  carry_out=0),
        EntrySpec(name="serve.packed_bin_rows", variants=binrows),
    ]


def build_registry(
    corpus: Corpus, full: bool = False,
    include: Optional[Sequence[str]] = None,
) -> Tuple[List[EntrySpec], List[str]]:
    """-> (entry specs, loudly-skipped entry names)."""
    skipped: List[str] = []
    specs: List[EntrySpec] = [
        _spec_serial_chunk(corpus),
        _spec_grow_tree(corpus),
        _spec_finish_step(corpus),
    ]
    data = _spec_data_chunk(corpus)
    if data is not None:
        specs.append(data)
    else:
        skipped.append(
            "gbdt.train_chunk[data] (needs >= 2 devices and a data-learner "
            "corpus)"
        )
    specs.extend(_spec_leaf_histograms(corpus, full))
    if corpus.pk is not None:
        specs.extend(_spec_serve(corpus, full))
    else:
        skipped.append("serve.packed_* (corpus built without a packed model)")
    if include:
        keep = [
            s for s in specs if any(tok in s.name for tok in include)
        ]
        skipped.extend(
            "%s (filtered by --entries)" % s.name
            for s in specs if s not in keep
        )
        specs = keep
    return specs, skipped


# ---------------------------------------------------------------------------
# seeded-violation self-check: one poisoned program per rule, proven caught
# ---------------------------------------------------------------------------
def seeded_specs() -> List[Tuple[str, EntrySpec]]:
    """[(rule expected to fire, poisoned EntrySpec)] — the golden 'bad
    fixtures' of the IR rule set (tests/test_irscan.py + the --ir smoke
    prove each is caught, and that its healthy twin in the real registry
    is clean)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    f32 = np.float32
    out: List[Tuple[str, EntrySpec]] = []

    def bad_callback(x):
        jax.debug.print("x={}", x)
        return x * 2
    out.append(("IR001", EntrySpec(
        name="seeded.ir001", variants=[
            ("rows=8", jax.jit(bad_callback), (_sds((8,), f32),), {}),
        ],
    )))

    def bad_f64(x):
        return (x.astype(jnp.float64) * 1.5).astype(jnp.float32)
    out.append(("IR002", EntrySpec(
        name="seeded.ir002", variants=[
            ("rows=8", jax.jit(bad_f64), (_sds((8,), f32),), {}),
        ],
        x64=True,
    )))

    big = np.arange(NP_CONST_LIMIT // 2, dtype=np.float32)  # 2x the limit

    def bad_const(x):
        return x + jnp.asarray(big)[: x.shape[0]]
    out.append(("IR003", EntrySpec(
        name="seeded.ir003", variants=[
            ("rows=8", jax.jit(bad_const), (_sds((8,), f32),), {}),
        ],
    )))

    # shape-changing output: XLA cannot alias it, donation silently drops
    dropped = jax.jit(lambda x: x[:2], donate_argnums=(0,))
    out.append(("IR004", EntrySpec(
        name="seeded.ir004", variants=[
            ("rows=8", dropped, (_sds((8,), f32),), {}),
        ],
        donated_min=1,
    )))

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    undeclared = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ))
    out.append(("IR005", EntrySpec(
        name="seeded.ir005", variants=[
            ("rows=8", undeclared, (_sds((8, 4), f32),), {}),
        ],
        axes=frozenset({"batch"}),  # the program's "data" is undeclared
        expect_collective=True,
    )))

    def stripped_pin(scores, leaf, lid):
        add = leaf[lid]  # no per-row select, add not returned: pin stripped
        return scores.at[0].add(add)
    out.append(("IR006", EntrySpec(
        name="seeded.ir006", variants=[
            ("rows=8", jax.jit(stripped_pin),
             (_sds((2, 8), f32), _sds((4,), f32), _sds((8,), np.int32)), {}),
        ],
        pin="select",
    )))

    def dropped_pin_output(scores, leaf, lid, pin):
        add = jnp.where(pin, leaf[lid], jnp.float32(0.0))
        return scores.at[0].add(add)  # pinned add NOT materialized as output
    out.append(("IR006", EntrySpec(
        name="seeded.ir006_materialized", variants=[
            ("rows=8", jax.jit(dropped_pin_output),
             (_sds((2, 8), f32), _sds((4,), f32), _sds((8,), np.int32),
              _sds((8,), np.bool_)), {}),
        ],
        pin="materialized",
    )))
    return out


def run_selfcheck() -> Dict[str, bool]:
    """rule id -> was its seeded violation caught (every value must be
    True). Entries seeded twice (IR006's two pin modes) must BOTH fire."""
    results: Dict[str, bool] = {}
    for rule, spec in seeded_specs():
        audits = audit_entry(spec)
        caught = any(f.rule == rule for a in audits for f in a.findings)
        results.setdefault(rule, True)
        results[rule] = results[rule] and caught
    return results


# ---------------------------------------------------------------------------
# scan driver + CLI
# ---------------------------------------------------------------------------
@dataclass
class ScanResult:
    audits: List[Audit]
    findings: List[Finding]
    trace_counts: Dict[str, int]
    skipped: List[str]


def run_scan(
    corpus: Optional[Corpus] = None, full: bool = False,
    include: Optional[Sequence[str]] = None,
) -> ScanResult:
    if corpus is None:
        corpus = build_corpus()
    specs, skipped = build_registry(corpus, full=full, include=include)
    audits: List[Audit] = []
    trace_counts: Dict[str, int] = {}
    for spec in specs:
        got = audit_entry(spec)
        audits.extend(got)
        trace_counts[spec.name] = len(got)
    findings = [f for a in audits for f in a.findings]
    return ScanResult(audits, findings, trace_counts, skipped)


def _list_rules() -> str:
    lines = []
    for rid in sorted(RULES):
        lines.append("%s — %s" % (rid, RULES[rid]))
    lines.append("")
    lines.append("Details: docs/StaticAnalysis.md §Program-level audit")
    return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    return "%.1fKiB" % (n / 1024.0) if n >= 1024 else "%dB" % n


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.irscan",
        description="jaxpr/StableHLO-level audit of the jitted entry points",
    )
    parser.add_argument("--full", action="store_true",
                        help="trace the whole bucket lattice / serve ladder")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--contract", default=DEFAULT_CONTRACT)
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true")
    parser.add_argument("--write-contract", action="store_true",
                        help="re-pin program fingerprints (implies --full)")
    parser.add_argument("--entries", action="append", metavar="SUBSTR",
                        help="audit only entry names containing SUBSTR")
    parser.add_argument("--selfcheck", action="store_true",
                        help="run the seeded-violation self-check and exit")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="write the scan record as JSON")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    # the sharded entry needs a multi-device mesh; on CPU hosts force the
    # same virtual 8-device platform the test mesh and multichip smoke use
    # (must happen before the backend initializes — a no-op afterwards)
    if os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu"):
        from ..utils.platform import force_cpu_devices

        force_cpu_devices(8)
    import jax  # noqa: F401  (backend is configured above)

    if args.selfcheck:
        results = run_selfcheck()
        for rule in sorted(results):
            print("%s seeded violation: %s"
                  % (rule, "caught" if results[rule] else "MISSED"))
        return 0 if all(results.values()) else 1

    full = args.full or args.write_contract
    env = contract_env()
    print("irscan: building the bootstrap corpus (platform=%s jax=%s "
          "devices=%d)" % (env["platform"], env["jax"], env["devices"]))
    result = run_scan(full=full, include=args.entries)
    for reason in result.skipped:
        print("irscan: SKIPPED %s" % reason)
    for a in result.audits:
        print(
            "  %-32s %-10s ops=%-4d convert=%-3d np-consts=%-8s "
            "dev-consts=%-9s aliases=%d digest=%s"
            % (a.entry, a.shape, sum(a.ops.values()), a.convert_count,
               _fmt_bytes(a.np_const_bytes),
               _fmt_bytes(a.device_const_bytes), a.donation_aliases,
               a.digest)
        )
    print("irscan: %d entry point(s), %d program variant(s) traced"
          % (len(result.trace_counts), len(result.audits)))

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump({
                "env": env,
                "audits": [vars(a) | {
                    "findings": [f.format() for f in a.findings],
                } for a in result.audits],
                "trace_counts": result.trace_counts,
                "skipped": result.skipped,
            }, fh, indent=1, default=str)
            fh.write("\n")

    rc = 0
    if args.write_contract:
        write_contract(args.contract, result.audits, result.trace_counts)
        print("irscan: wrote %d fingerprint(s) to %s"
              % (len(result.audits), args.contract))
    else:
        problems, skip = check_contract(
            load_contract(args.contract), result.audits, result.trace_counts
        )
        if skip is not None:
            print("irscan: contract check skipped — %s" % skip)
        elif problems:
            for p in problems:
                print("irscan: CONTRACT: %s" % p)
            rc = 1
        else:
            print("irscan: contract OK (%d fingerprint(s) match, trace "
                  "budgets honored)" % len(result.audits))

    if args.write_baseline:
        _, notes = load_baseline(args.baseline)
        write_baseline(args.baseline, result.findings, notes)
        print("irscan: wrote %d finding(s) to %s"
              % (len(result.findings), args.baseline))
        return rc
    if args.no_baseline:
        for f in result.findings:
            print(f.format())
        print("irscan: %d finding(s)" % len(result.findings))
        return 1 if (result.findings or rc) else 0

    baseline, _ = load_baseline(args.baseline)
    new, stale = compare_to_baseline(result.findings, baseline)
    for f in new:
        print(f.format())
    for key, n in sorted(stale.items()):
        print("stale baseline entry (finding no longer present x%d): %s"
              % (n, key))
    if new or stale:
        print("irscan: %d new finding(s), %d stale baseline entr%s"
              % (len(new), sum(stale.values()),
                 "y" if sum(stale.values()) == 1 else "ies"))
        return 1
    print("irscan: clean (%d finding(s) baselined, %d rules)"
          % (len(result.findings), len(RULES)))
    return rc


if __name__ == "__main__":
    sys.exit(main())
