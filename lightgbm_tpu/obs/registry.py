"""One metrics registry for train + serve (counters, gauges, histograms).

The reference LightGBM has no metrics surface at all — timing hid behind the
compile-time TIMETAG flag and everything else went to stderr. This module is
the single spine every lightgbm_tpu metric hangs off:

 * ``Counter`` — monotonically increasing totals (requests, retraces,
   boosting iterations), optionally labeled.
 * ``Gauge`` — last-value or pull-callback instruments (queue depth, device
   peak bytes, per-phase seconds), optionally labeled.
 * ``Histogram`` — a bounded ring of recent observations. Percentiles are
   EXACT over the ring (at serving rates the last few thousand samples are
   the steady state; a log-bucketed histogram would be approximate).
 * ``RateMeter`` — sliding-window event rate (QPS, rows/s).

``MetricsRegistry`` hands out get-or-create instruments by name and renders
them all as Prometheus text exposition (``prometheus_text``) or a JSON-able
run report (``run_report`` — the same block bench.py and tpu_bringup.py embed
in their output JSON). ``REGISTRY`` is the process-wide default: training
(engine.py, utils/timer.py), the retrace watchdog and memwatch all publish
here; each ServeApp keeps its own instance for isolation and the /metrics
endpoint concatenates both (serve/server.py).

Stdlib + numpy only and lock-guarded throughout — HTTP handler threads, the
batcher worker and the training loop all touch these concurrently.
"""
from __future__ import annotations

import re
import threading

from . import sanitize as sanitize_mod
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# every exposed metric name is prefixed at exposition time, so raw names stay
# short in code ("qps") and scrape configs match one family ("lgbtpu_*")
PROM_PREFIX = "lgbtpu_"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return PROM_PREFIX + _NAME_OK.sub("_", name)


def _prom_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (
            _NAME_OK.sub("_", k),
            # full label-value escaping per the exposition format: a raw
            # newline inside a quoted value would break the whole scrape
            str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n"),
        )
        for k, v in labels
    )
    return "{%s}" % body


def _label_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter, optionally labeled: ``c.inc(3, model="prod")``."""

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._values: Dict[Tuple, float] = {}
        self._lock = sanitize_mod.make_lock("obs.registry.counter")

    def inc(self, by: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + by

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def values(self) -> Dict[Tuple, float]:
        with self._lock:
            return dict(self._values)


class Gauge:
    """Last-value gauge; ``set_fn`` turns it into a pull gauge whose value is
    computed at read time (queue depth, device memory)."""

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._values: Dict[Tuple, float] = {}
        self._fn: Optional[Callable[[], float]] = None
        self._lock = sanitize_mod.make_lock("obs.registry.gauge")

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    def values(self) -> Dict[Tuple, float]:
        with self._lock:
            out = dict(self._values)
            fn = self._fn
        if fn is not None:
            try:
                out[()] = float(fn())
            except Exception:
                # a pull gauge must never take /metrics down with it
                out.setdefault((), 0.0)
        return out


class Histogram:
    """Ring buffer of recent observations; exact percentiles over the ring,
    plus an all-time count and sum for Prometheus summary semantics."""

    QUANTILES = (0.50, 0.95, 0.99)

    def __init__(self, size: int = 4096) -> None:
        self._buf = np.zeros(size, np.float64)
        self._n = 0  # total ever recorded
        self._sum = 0.0  # all-time sum (Prometheus _sum)
        self._lock = sanitize_mod.make_lock("obs.registry.histogram")

    def record(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1
            self._sum += value

    def snapshot(self) -> Dict[str, float]:
        """count/sum are all-time; quantiles/max/mean are over the ring."""
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return {"count": 0}
            window = np.sort(self._buf[:n])
            total, total_sum = self._n, self._sum

        def pct(p):
            return float(window[min(int(p * n), n - 1)])

        return {
            "count": total,
            "sum": total_sum,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "max": float(window[-1]),
            "mean": float(window.mean()),
        }


class RateMeter:
    """Sliding-window event rate (QPS / rows-per-second).

    Timestamps default to ``time.perf_counter`` — they only ever feed
    deltas, and a wall-clock (NTP) step would smear or empty the window.
    Callers passing explicit ``now`` values must use one consistent clock.
    """

    def __init__(self, window_s: float = 60.0) -> None:
        self.window_s = window_s
        self._events: deque = deque()  # (t, weight)
        self._lock = sanitize_mod.make_lock("obs.registry.rate")

    def record(self, weight: float = 1.0, now: Optional[float] = None) -> None:
        t = time.perf_counter() if now is None else now
        with self._lock:
            self._events.append((t, weight))
            self._trim(t)

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        t = time.perf_counter() if now is None else now
        with self._lock:
            self._trim(t)
            if not self._events:
                return 0.0
            span = max(t - self._events[0][0], 1e-9)
            # a single burst shorter than the window divides by its true
            # span, not the full window, so cold-start rates aren't diluted
            return sum(w for _, w in self._events) / min(span, self.window_s)


class MetricsRegistry:
    """Name -> instrument, get-or-create; renders every registered
    instrument as Prometheus text or a JSON run report."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._sections: Dict[str, Callable[[], object]] = {}
        self._lock = sanitize_mod.make_lock("obs.registry")

    def register_report_section(
        self, name: str, fn: Callable[[], object]
    ) -> None:
        """Attach a pull section to ``run_report()``: ``fn()`` is called at
        report time and its JSON-able return lands under ``name`` (skipped
        when empty/None or raising — a section must never break a report).
        The cost-analysis book (obs/costs.py) and the segment profiler
        (obs/prof.py) publish their structured blocks this way."""
        with self._lock:
            self._sections[name] = fn

    def _get_or_create(self, name: str, factory, kind) -> object:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    "metric %r already registered as %s"
                    % (name, type(m).__name__)
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help), Gauge)

    def histogram(self, name: str, size: int = 4096) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(size), Histogram)

    def rate(self, name: str, window_s: float = 60.0) -> RateMeter:
        return self._get_or_create(
            name, lambda: RateMeter(window_s), RateMeter
        )

    def attach(self, name: str, metric):
        """Adopt an externally built instrument under ``name``; returns the
        already-registered one when the name exists (shared by design —
        callers must keep using the returned object)."""
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, type(metric)) and not isinstance(
                    metric, type(existing)
                ):
                    raise TypeError(
                        "metric %r already registered as %s"
                        % (name, type(existing).__name__)
                    )
                return existing
            self._metrics[name] = metric
            return metric

    def _items(self) -> List[Tuple[str, object]]:
        with self._lock:
            return sorted(self._metrics.items())

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def counters(self) -> Dict[str, int]:
        """{name: summed-over-labels value} for every registered Counter."""
        out: Dict[str, int] = {}
        for name, m in self._items():
            if isinstance(m, Counter):
                out[name] = int(sum(m.values().values()))
        return out

    def snapshot(self) -> Dict[str, object]:
        """Label-preserving JSON-able capture of every instrument — the
        unit the pod-wide merge folds (obs/dist.py merge_snapshots):
        counters/gauges as ``{name: [[[k, v] label pairs, value], ...]}``,
        rates as scalars, histograms as their summary snapshot."""
        counters: Dict[str, list] = {}
        gauges: Dict[str, list] = {}
        rates: Dict[str, float] = {}
        summaries: Dict[str, Dict[str, float]] = {}
        for name, m in self._items():
            if isinstance(m, Counter):
                counters[name] = [
                    [[list(kv) for kv in labels], v]
                    for labels, v in sorted(m.values().items())
                ]
            elif isinstance(m, Gauge):
                gauges[name] = [
                    [[list(kv) for kv in labels], v]
                    for labels, v in sorted(m.values().items())
                ]
            elif isinstance(m, RateMeter):
                rates[name] = round(m.rate(), 6)
            elif isinstance(m, Histogram):
                summaries[name] = Histogram.snapshot(m)
        return {
            "counters": counters,
            "gauges": gauges,
            "rates": rates,
            "summaries": summaries,
        }

    # -- renderers ---------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format version 0.0.4) of everything
        registered: counters as ``counter`` (``_total`` suffix enforced),
        gauges and rates as ``gauge``, histograms as ``summary`` with exact
        ring quantiles + all-time _count/_sum. ``# HELP`` lines carry each
        instrument's help string (escaped per the format: backslash and
        newline only — HELP values are not quoted, so ``"`` stays raw)."""
        lines: List[str] = []
        for name, m in self._items():
            if isinstance(m, Counter):
                pname = _prom_name(name)
                if not pname.endswith("_total"):
                    pname += "_total"
                _help_line(lines, pname, m.help)
                lines.append("# TYPE %s counter" % pname)
                vals = m.values() or {(): 0.0}
                for labels, v in sorted(vals.items()):
                    lines.append("%s%s %s" % (pname, _prom_labels(labels), _num(v)))
            elif isinstance(m, Gauge):
                pname = _prom_name(name)
                _help_line(lines, pname, m.help)
                lines.append("# TYPE %s gauge" % pname)
                vals = m.values() or {(): 0.0}
                for labels, v in sorted(vals.items()):
                    lines.append("%s%s %s" % (pname, _prom_labels(labels), _num(v)))
            elif isinstance(m, RateMeter):
                pname = _prom_name(name)
                lines.append("# TYPE %s gauge" % pname)
                lines.append("%s %s" % (pname, _num(m.rate())))
            elif isinstance(m, Histogram):
                pname = _prom_name(name)
                # base-class snapshot explicitly: subclasses may re-render
                # their snapshot for humans (serve's millisecond keys), but
                # the exposition needs the raw native-unit quantiles
                snap = Histogram.snapshot(m)
                lines.append("# TYPE %s summary" % pname)
                for q in Histogram.QUANTILES:
                    key = "p%d" % int(q * 100)
                    lines.append(
                        '%s{quantile="%g"} %s'
                        % (pname, q, _num(snap.get(key, 0.0)))
                    )
                lines.append("%s_sum %s" % (pname, _num(snap.get("sum", 0.0))))
                lines.append("%s_count %d" % (pname, snap.get("count", 0)))
        return "\n".join(lines) + "\n"

    def run_report(self) -> Dict[str, object]:
        """JSON-able block of every instrument's current state — the shared
        structured run report bench.py and helpers/tpu_bringup.py embed."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        summaries: Dict[str, Dict[str, float]] = {}
        rates: Dict[str, float] = {}
        for name, m in self._items():
            if isinstance(m, Counter):
                for labels, v in m.values().items():
                    counters[_report_key(name, labels)] = v
            elif isinstance(m, Gauge):
                for labels, v in m.values().items():
                    gauges[_report_key(name, labels)] = round(float(v), 6)
            elif isinstance(m, RateMeter):
                rates[name] = round(m.rate(), 3)
            elif isinstance(m, Histogram):
                snap = Histogram.snapshot(m)
                summaries[name] = {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in snap.items()
                }
        out: Dict[str, object] = {
            "counters": counters,
            "gauges": gauges,
            "summaries": summaries,
            "rates": rates,
        }
        with self._lock:
            sections = list(self._sections.items())
        for name, fn in sorted(sections):
            try:
                block = fn()
            except Exception:
                continue  # a report section must never break the report
            if block:
                out[name] = block
        return out


def _num(v: float) -> str:
    """Prometheus number formatting: integers bare, floats via repr,
    non-finite values as the format's ``NaN``/``+Inf``/``-Inf`` tokens.
    The finiteness check must come FIRST: ``int(nan)`` raises ValueError
    and ``int(inf)`` OverflowError, and either would have taken the whole
    /metrics scrape down with it (a pull gauge can legitimately yield
    inf — e.g. a rate denominator of zero upstream)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _help_line(lines: List[str], pname: str, help_text: str) -> None:
    """Append the ``# HELP`` line for ``pname`` when a help string exists.
    HELP values are raw (not quoted), so only backslash and newline need
    escaping — escaping ``"`` here would render literal backslashes in
    scrape UIs."""
    if help_text:
        lines.append(
            "# HELP %s %s"
            % (pname,
               str(help_text).replace("\\", "\\\\").replace("\n", "\\n"))
        )


def _report_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    return "%s{%s}" % (name, ",".join("%s=%s" % kv for kv in labels))


#: process-wide default registry (training side, watchdogs, memwatch)
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY
