"""jit-trace watchdog: count XLA compilations per entry point, police them.

``jax.jit`` re-traces (and re-compiles — seconds of XLA work) whenever an
argument's shape/dtype or a static value changes. On the serving path one
stray unpadded batch, or on the training path one drifting trace-time
constant, silently turns a millisecond dispatch into a multi-second compile.
The serve bucket cache asserted this privately (serve/cache.py counts
first-seen buckets); this module generalizes the discipline to every hot
entry point.

Mechanics: each watched jit function calls :func:`note_trace(name)` at the
TOP of its traced body. Under jit the python body runs only when XLA traces,
so the count of ``note_trace`` calls IS the real compile count — no reliance
on jax-internal cache introspection. Instrumented entry points:

  * ``ops.grow_tree``              — the tree grower (ops/grow.py)
  * ``gbdt.train_chunk``           — the fused K-iteration scan (models/gbdt.py)
  * ``ops.packed_predict_leaves``  — packed serving traversal (ops/predict.py)
  * ``ops.packed_predict_values``  — fused scores (ops/predict.py)
  * ``ops.packed_bin_rows``        — fused raw->rank binning (ops/predict.py)

After warmup, call :func:`arm` to snapshot the counts. Any later trace of an
armed name is a RETRACE: it always warns once per name (utils/log.warn_once)
and, with ``LIGHTGBM_TPU_RETRACE=fail``, raises ``LightGBMError`` — turning a
silent performance cliff into a loud failure. ``LIGHTGBM_TPU_RETRACE=warn``
is the explicit spelling of the default. Counts feed the metrics registry as
``jit_traces_total`` / ``jit_retraces_after_warmup`` (obs/__init__.py wires
the gauges), so /metrics and bench reports carry them per run.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, Optional

from ..utils import log
from ..utils.log import LightGBMError
from . import registry as registry_mod
from . import sanitize as sanitize_mod

ENV_RETRACE = "LIGHTGBM_TPU_RETRACE"


def _mode() -> str:
    """Read per event, not at import: tests and long-lived servers flip it."""
    return os.environ.get(ENV_RETRACE, "").lower()


class RetraceWatchdog:
    """Per-name compile counts + an armed warm baseline."""

    def __init__(self) -> None:
        self._counts: Dict[str, int] = {}
        self._warm: Dict[str, int] = {}
        self._armed = False
        self._lock = sanitize_mod.make_lock("obs.retrace")

    def note_trace(self, name: str) -> None:
        """Called from inside a traced body — once per real XLA trace."""
        with self._lock:
            count = self._counts[name] = self._counts.get(name, 0) + 1
            retrace = self._armed and name in self._warm
        # labeled per-name compile count, published next to the xla_cost_*
        # gauges (obs/costs.py) so ONE /metrics scrape answers "what
        # compiled, how big, how hot" — the aggregate jit_traces_total pull
        # gauge (obs/__init__.py) stays for dashboards that sum anyway
        try:
            registry_mod.REGISTRY.gauge("jit_traces").set(count, name=name)
        except TypeError as e:
            # the ONE error this call can actually raise: a metric-kind
            # collision in MetricsRegistry._get_or_create ("jit_traces"
            # already registered as a counter/histogram). Gauge.set itself
            # is float()+dict-store and cannot fail on an int count. Metrics
            # must never break a trace, so log and continue — but anything
            # ELSE propagates rather than being silently swallowed (JX008's
            # own standard, applied to obs code)
            log.debug("retrace: jit_traces gauge update failed: %r" % e)
        if retrace:
            msg = (
                "jit retrace after warmup: %r compiled again (%d traces "
                "total) — a shape/dtype/static-arg drifted on the hot path; "
                "set %s=fail to hard-fail here" % (name, count, ENV_RETRACE)
            )
            if _mode() == "fail":
                raise LightGBMError(msg)
            log.warn_once("retrace:%s" % name, msg)

    def arm(self, names: Optional[Iterable[str]] = None) -> None:
        """Snapshot current counts as the warm baseline. With ``names``,
        only those entry points are policed (unknown names are armed at 0
        so their very first compile counts as a retrace)."""
        with self._lock:
            if names is None:
                self._warm = dict(self._counts)
            else:
                for n in names:
                    self._warm[n] = self._counts.get(n, 0)
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._warm = {}

    def reset(self) -> None:
        with self._lock:
            self._counts = {}
            self._warm = {}
            self._armed = False

    @property
    def armed(self) -> bool:
        return self._armed

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def retraces_after_warmup(self) -> Dict[str, int]:
        """name -> traces since arm(), for armed names only (empty unarmed)."""
        with self._lock:
            if not self._armed:
                return {}
            return {
                n: self._counts.get(n, 0) - base
                for n, base in self._warm.items()
                if self._counts.get(n, 0) > base
            }

    def total_retraces(self) -> int:
        return sum(self.retraces_after_warmup().values())


#: process-wide watchdog; ops/grow.py, ops/predict.py and models/gbdt.py
#: note into it, serve warmup arms it
WATCHDOG = RetraceWatchdog()


def note_trace(name: str) -> None:
    WATCHDOG.note_trace(name)


def arm(names: Optional[Iterable[str]] = None) -> None:
    WATCHDOG.arm(names)


def disarm() -> None:
    WATCHDOG.disarm()


def reset() -> None:
    WATCHDOG.reset()


def counts() -> Dict[str, int]:
    return WATCHDOG.counts()


def retraces_after_warmup() -> Dict[str, int]:
    return WATCHDOG.retraces_after_warmup()
