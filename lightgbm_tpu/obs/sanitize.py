"""graftsan runtime sanitizer: exactness & concurrency teeth, env-gated.

``LIGHTGBM_TPU_SAN=transfer,nan,locks`` (or ``=all`` / ``=1``) arms one or
more modes; unset, the module is provably free — every hook is a single
module-boolean check, ``transfer_scope`` hands back one shared nullcontext,
``make_lock`` returns a plain ``threading.Lock`` (zero wrapper allocation),
and nothing new traces or compiles (tests/test_sanitize.py pins all three).

Modes
-----
``transfer``
    Scoped ``jax.transfer_guard_host_to_device("disallow")`` around the
    boosting dispatch (engine._boost_loop) and the serve dispatch
    (serve/cache.py) — the runtime teeth behind graftlint JX001. Inside a
    guarded scope every host→device byte must be an EXPLICIT
    ``jax.device_put``/``jnp.asarray``; an implicit upload (a numpy operand
    sneaking into a jitted call, a host constant rebuilt per dispatch) is
    exactly the silent per-iteration transfer the lint rule hunts, and here
    it raises instead of costing latency quietly. Device→host readbacks
    are not guarded: boundary evals and result fetches are the loop's job.

``nan``
    NaN/inf tripwires on the training score carries at chunk boundaries:
    the first boundary whose carry goes non-finite raises
    :class:`SanitizerError` naming the iteration — instead of the
    divergence surfacing dozens of iterations later as an AUC collapse
    with no provenance.

``locks``
    :func:`make_lock` returns instrumented locks that record per-thread
    acquisition order into a process-global order graph and fail on the
    first lock-order INVERSION (lock B acquired under A somewhere, A under
    B elsewhere — the deadlock shape review keeps missing). The runtime
    twin of graftlint JX013; driven in anger by the concurrency stress
    smoke (helpers/san_smoke.py: concurrent predict + hot-swap + drain +
    drift + /metrics scrape).

jax is imported lazily (transfer mode only), so the lock/nan machinery —
and every importer of this module — stays usable in jax-free drivers.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.log import LightGBMError

ENV_SAN = "LIGHTGBM_TPU_SAN"

_ALL_MODES = ("transfer", "nan", "locks")


class SanitizerError(LightGBMError):
    """A sanitizer tripwire fired (never raised when LIGHTGBM_TPU_SAN is
    unset)."""


def _parse_modes(raw: Optional[str]) -> frozenset:
    if raw is None:
        return frozenset()
    raw = raw.strip().lower()
    if raw in ("", "0", "off", "false"):
        return frozenset()
    if raw in ("1", "all", "on", "true"):
        return frozenset(_ALL_MODES)
    modes = frozenset(
        tok for tok in (t.strip() for t in raw.split(",")) if tok
    )
    unknown = modes - frozenset(_ALL_MODES)
    if unknown:
        raise LightGBMError(
            "%s: unknown sanitizer mode(s) %s (known: %s)"
            % (ENV_SAN, ", ".join(sorted(unknown)), ", ".join(_ALL_MODES))
        )
    return modes


#: armed modes — set once at import; tests re-read with :func:`refresh`
MODES: frozenset = frozenset()
TRANSFER: bool = False
NAN: bool = False
LOCKS: bool = False


def refresh() -> frozenset:
    """Re-read LIGHTGBM_TPU_SAN (tests and subprocess drivers); returns the
    armed mode set."""
    global MODES, TRANSFER, NAN, LOCKS
    MODES = _parse_modes(os.environ.get(ENV_SAN))
    TRANSFER = "transfer" in MODES
    NAN = "nan" in MODES
    LOCKS = "locks" in MODES
    return MODES


refresh()


# --------------------------------------------------------------------------
# transfer mode
# --------------------------------------------------------------------------
#: the ONE nullcontext every un-armed transfer_scope() call returns — the
#: off path allocates nothing per call
_NULL = contextlib.nullcontext()


class _TransferScope:
    """``jax.transfer_guard_host_to_device("disallow")`` with the sanitizer
    nameplate on the error: a tripped guard raises SanitizerError naming
    the guarded site, chaining jax's own transfer description."""

    __slots__ = ("site", "_cm")

    def __init__(self, site: str) -> None:
        self.site = site
        self._cm = None

    def __enter__(self) -> "_TransferScope":
        import jax

        self._cm = jax.transfer_guard_host_to_device("disallow")
        self._cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        cm, self._cm = self._cm, None
        suppress = bool(cm.__exit__(exc_type, exc, tb)) if cm else False
        if (
            not suppress
            and exc is not None
            and "Disallowed host-to-device transfer" in str(exc)
        ):
            raise SanitizerError(
                "sanitizer(transfer): implicit host->device transfer inside "
                "the guarded %r scope — the silent per-dispatch upload "
                "graftlint JX001 polices statically; make the upload an "
                "explicit jax.device_put/jnp.asarray outside the hot path "
                "(original: %s)" % (self.site, str(exc)[:300])
            ) from exc
        return suppress


def transfer_scope(site: str = "dispatch"):
    """Context manager for a no-implicit-upload region. The off path returns
    one shared nullcontext (no allocation, no jax import)."""
    if not TRANSFER:
        return _NULL
    return _TransferScope(site)


class _AllowScope:
    """Re-allow implicit uploads inside a guarded region — the audited-site
    suppression (kept a named scope so suppressions are grep-able, the
    in-code analogue of a baseline entry)."""

    __slots__ = ("site", "_cm")

    def __init__(self, site: str) -> None:
        self.site = site
        self._cm = None

    def __enter__(self) -> "_AllowScope":
        import jax

        self._cm = jax.transfer_guard_host_to_device("allow")
        self._cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        cm, self._cm = self._cm, None
        return bool(cm.__exit__(exc_type, exc, tb)) if cm else False


def allow_transfers(site: str):
    """Suppress the transfer guard for an AUDITED eager host poke inside a
    guarded scope (e.g. the first-iteration init-score `.at[k].add`, whose
    python-int index uploads implicitly but runs at most K times per run).
    Off path: the shared nullcontext."""
    if not TRANSFER:
        return _NULL
    return _AllowScope(site)


# --------------------------------------------------------------------------
# nan mode
# --------------------------------------------------------------------------
def check_scores(gbdt, iteration: int) -> None:
    """Boundary tripwire: raise if the training score carry holds any
    NaN/inf. Callers gate on ``sanitize.NAN`` so the off path is one
    module-boolean read."""
    import numpy as np

    scores = np.asarray(gbdt.scores_canonical_np())
    finite = np.isfinite(scores)
    if bool(finite.all()):
        return
    bad = int(scores.size - int(finite.sum()))
    first = np.unravel_index(int(np.argmin(finite.reshape(-1))), scores.shape)
    raise SanitizerError(
        "sanitizer(nan): training score carry went non-finite at the "
        "boundary after iteration %d (%d bad value(s); first at index %s "
        "= %r) — check the objective's gradients, the learning rate, and "
        "any custom fobj for overflow"
        % (iteration, bad, tuple(int(i) for i in first),
           float(scores[first]))
    )


# --------------------------------------------------------------------------
# locks mode
# --------------------------------------------------------------------------
#: process-global lock-order graph: (id(a), id(b)) -> (name_a, name_b, where)
#: meaning "b was acquired while holding a". Guarded by the meta-lock (a
#: PLAIN threading.Lock — instrumenting the instrument would recurse).
_edges: Dict[Tuple[int, int], Tuple[str, str, str]] = {}
_meta = threading.Lock()
_tls = threading.local()


def _held_stack() -> List["_SanLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class _SanLock:
    """A non-reentrant lock that records per-thread acquisition order and
    raises on the first lock-order inversion. Duck-types threading.Lock
    (acquire/release/locked/context manager), so threading.Condition can
    wrap it."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()

    def _note_acquired(self) -> None:
        stack = _held_stack()
        me = id(self)
        for held in stack:
            h = id(held)
            if h == me:
                continue
            with _meta:
                back = _edges.get((me, h))
                if back is not None:
                    raise SanitizerError(
                        "sanitizer(locks): lock-order inversion — acquiring "
                        "%r while holding %r, but %r was previously acquired "
                        "while holding %r (at %s); pick ONE order and "
                        "declare it (_LOCK_ORDER, graftlint JX013)"
                        % (self.name, held.name, back[1], back[0], back[2])
                    )
                _edges.setdefault(
                    (h, me),
                    (held.name, self.name, threading.current_thread().name),
                )
        stack.append(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except SanitizerError:
                self._lock.release()
                raise
        return got

    def release(self) -> None:
        self._lock.release()
        stack = _held_stack()
        # remove the most recent entry for this lock (non-LIFO releases are
        # legal for plain locks; Condition.wait releases out of order)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:
        return "<_SanLock %r %s>" % (
            self.name, "locked" if self.locked() else "unlocked"
        )


def make_lock(name: str = "lock"):
    """The lock factory the serve/obs stack builds its locks through: a
    plain ``threading.Lock`` (zero wrapper allocation) unless the ``locks``
    sanitizer mode is armed, then an order-recording :class:`_SanLock`."""
    if not LOCKS:
        return threading.Lock()
    return _SanLock(name)


def lock_edges() -> List[Tuple[str, str]]:
    """The recorded acquisition-order edges (outer, inner) — diagnostics for
    tests and the stress smoke's final report."""
    with _meta:
        return sorted(set((a, b) for (a, b, _w) in _edges.values()))


def reset_lock_graph() -> None:
    """Forget recorded orders (tests; each smoke phase starts clean)."""
    with _meta:
        _edges.clear()
