"""podwatch: the live fleet telemetry plane (docs/Observability.md §Fleet
telemetry).

Everything else in obs/ answers questions about a run after it happened
(devprof parses a finished profile, flight stamps provenance, report renders
a finished run); podwatch answers them WHILE the pod is training:

 * **Per-rank time-series recorder** — env-gated by
   ``LIGHTGBM_TPU_TELEMETRY=<dir>``: at every chunk boundary the boost loop
   samples the one metrics registry (cumulative train/resil/hist counters),
   the TIMETAG phase accumulators (per-boundary deltas, when armed), the
   memwatch device-bytes gauge, and the boundary's own wall time into a
   bounded ring buffer persisted as ``<dir>/timeline.rank<N>.jsonl`` through
   resil/atomic. Each sample also refreshes this rank's heartbeat
   (``<dir>/pod.hb.rank<N>.json``, resil/coord) enriched with the chunk
   seconds and cumulative iteration rate — so liveness and rate evidence
   live together for the aggregator. Off (env unset) the whole plane costs
   one env read per gate at train() start: no threads, no ring, no files.

 * **Training-side scrape endpoint** — opt-in
   ``LIGHTGBM_TPU_TELEMETRY_PORT=<port>``: a daemon-thread HTTP listener
   (serve/httpbase plumbing) exposing ``/metrics`` (the registry's
   Prometheus text exposition), ``/health`` (rank, iteration,
   last-boundary age, preempt/watchdog state) and ``/timeline`` (the recent
   ring-buffer window as JSON). The listener outlives individual train()
   calls by design — a pod is watched across warm-start retrains — and a
   failure to bind is a warning, never a training failure.

 * **Cross-rank aggregator + verdicts** — ``python -m
   lightgbm_tpu.obs.podwatch <dir>`` (and :func:`pod_summary` as a library)
   folds every rank's timeline shard and heartbeat into one pod view and
   issues evidence-backed verdicts in the devprof style, each citing the
   module-constant threshold it tripped: *straggler* (a named rank whose
   mean chunk seconds exceed the pod median by ``STRAGGLER_FACTOR``, with
   the segment that diverges — the synthetic ``host_other`` bucket catches
   time no TIMETAG phase claims), *stall* (a rank's recent iteration rate
   collapsed vs its own trailing window by ``STALL_FACTOR``), *skew*
   (iteration spread across ranks beyond ``SKEW_ITERATIONS``) and *dead*
   (via resil/coord.stale_ranks, heartbeat evidence attached). Verdicts
   surface as ``podwatch_*`` gauges, a run_report() ``fleet_telemetry``
   section (report.py renders it as §Fleet telemetry), bench stamps and
   WARN-never-FAIL bench_diff rows.

The aggregator half is stdlib-only and never imports jax — it must run on
an operator's laptop against an NFS dir while the pod is still training.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import registry as registry_mod
from . import sanitize as sanitize_mod
from ..utils import log

ENV_TELEMETRY = "LIGHTGBM_TPU_TELEMETRY"
ENV_TELEMETRY_PORT = "LIGHTGBM_TPU_TELEMETRY_PORT"

#: ring capacity per rank — at one sample per chunk boundary this spans the
#: recent past (a 512-boundary window) while bounding both memory and the
#: per-boundary shard rewrite (the whole ring is re-published atomically,
#: so a scraper never reads a torn line)
RING_SIZE = 512

#: cumulative-counter families sampled into each boundary record
COUNTER_PREFIXES = ("train_", "resil_", "hist_")

# ---------------------------------------------------------------------------
# verdict thresholds — module constants so the evidence can cite them
# ---------------------------------------------------------------------------

#: boundaries dropped from the front of every rank's window before any
#: verdict math: the first boundary pays the serial-path jit compile and the
#: second pays the train_chunk compile (the boost loop bootstraps one
#: per-iteration step before chunking) — either would dominate every mean
WARMUP_SKIP = 2
#: recent-past window (samples) the per-rank statistics are computed over
WINDOW = 32
#: straggler: a rank's mean chunk seconds vs the pod median
STRAGGLER_FACTOR = 1.5
#: minimum post-warmup samples before a rank can be judged at all
MIN_SAMPLES = 3
#: stall: recent-rate samples compared against the rank's own trailing rate
STALL_RECENT = 3
STALL_FACTOR = 3.0
#: minimum post-warmup samples before the stall comparison is meaningful
STALL_MIN_SAMPLES = 8
#: skew: iteration spread across ranks (leader minus laggard)
SKEW_ITERATIONS = 32
#: dead: heartbeat age beyond this is a dead-rank verdict
DEAD_MAX_AGE_S = 60.0

#: synthetic segment: boundary seconds no TIMETAG phase accounts for
#: (callbacks, eval host math, GC, a seeded sleep) — named honestly instead
#: of silently vanishing from the attribution
HOST_OTHER = "host_other"

_TIMELINE_RE = re.compile(r"timeline\.rank(\d+)\.jsonl$")


def env_dir() -> Optional[str]:
    """The telemetry output dir, or None when recording is off."""
    return os.environ.get(ENV_TELEMETRY) or None


def env_port() -> Optional[int]:
    raw = os.environ.get(ENV_TELEMETRY_PORT) or None
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        log.warn_once(
            "podwatch-bad-port",
            "podwatch: %s=%r is not an integer port; scrape endpoint off"
            % (ENV_TELEMETRY_PORT, raw),
        )
        return None


def timeline_path(out_dir: str, rank: int) -> str:
    return os.path.join(out_dir, "timeline.rank%d.jsonl" % rank)


def heartbeat_base(out_dir: str) -> str:
    """The coord.heartbeat base path: rank files land as
    ``<dir>/pod.hb.rank<N>.json``."""
    return os.path.join(out_dir, "pod")


# ---------------------------------------------------------------------------
# per-rank recorder (training side)
# ---------------------------------------------------------------------------

class TelemetryRecorder:
    """Bounded per-rank boundary ring, persisted as a rank-suffixed JSONL
    shard through resil/atomic at every sample. Built by :func:`maybe_start`
    inside train(); tests construct it directly (jax-free — ``rank`` is
    explicit and nothing here touches a backend)."""

    def __init__(self, out_dir: str, rank: int, world: int = 1) -> None:
        self.out_dir = out_dir
        self.rank = int(rank)
        self.world = int(world)
        self.path = timeline_path(out_dir, self.rank)
        self._ring: deque = deque(maxlen=RING_SIZE)
        self._lock = sanitize_mod.make_lock("obs.podwatch.ring")
        self._start_mono = time.monotonic()
        self._iters_done = 0
        self._prev_counters: Dict[str, int] = {}
        self._prev_phases: Dict[str, float] = {}
        self.last_mono: Optional[float] = None
        self.last_iteration: Optional[int] = None
        os.makedirs(out_dir, exist_ok=True)

    # -- sampling ----------------------------------------------------------

    def _counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for name, v in registry_mod.REGISTRY.counters().items():
            if name.startswith(COUNTER_PREFIXES):
                out[name] = int(v)
        return out

    def _phase_deltas(self, gbdt) -> Dict[str, float]:
        """Per-boundary deltas of the TIMETAG phase accumulators — empty
        when LIGHTGBM_TPU_TIMETAG is off (the dict never grows then)."""
        seconds = dict(getattr(getattr(gbdt, "timers", None), "seconds",
                               None) or {})
        if not seconds:
            return {}
        out = {}
        for name, total in seconds.items():
            d = float(total) - self._prev_phases.get(name, 0.0)
            if d > 0:
                out[name] = round(d, 6)
        self._prev_phases = {k: float(v) for k, v in seconds.items()}  # unlocked: written only by the training thread (sample()); scrape threads never read it
        return out

    @staticmethod
    def _mem_bytes() -> Optional[float]:
        try:
            vals = registry_mod.REGISTRY.gauge("device_peak_bytes").values()
            v = vals.get(())
            return float(v) if v else None
        except Exception:
            return None

    def sample(self, iteration: int, chunk: int, dt_s: float,
               gbdt=None) -> Dict:
        """One boundary record: append to the ring, republish the shard,
        refresh this rank's enriched heartbeat. Returns the record (tests
        assert on it); any persistence failure is the caller's to swallow
        (note_boundary does — observability must never fail the run)."""
        now_mono = time.monotonic()
        self._iters_done += int(chunk)  # unlocked: single writer (the training thread); the lock below guards the RING the scrape threads read
        cum_rate = self._iters_done / max(now_mono - self._start_mono, 1e-9)
        rec = {
            "v": 1,
            "rank": self.rank,
            "t": round(time.time(), 6),
            "mono": round(now_mono, 6),
            "iteration": int(iteration),
            "chunk": int(chunk),
            "dt_s": round(float(dt_s), 6),
            "it_per_s": round(int(chunk) / max(float(dt_s), 1e-9), 6),
            "cum_it_per_s": round(cum_rate, 6),
            "counters": self._counters(),
            "segments": self._phase_deltas(gbdt) if gbdt is not None else {},
        }
        mem = self._mem_bytes()
        if mem is not None:
            rec["mem_bytes"] = mem
        with self._lock:
            self._ring.append(rec)
            lines = [json.dumps(r) for r in self._ring]
        from ..resil.atomic import atomic_write_text

        atomic_write_text(self.path, "\n".join(lines) + "\n", fsync=False)
        from ..resil import coord

        coord.heartbeat(
            heartbeat_base(self.out_dir), int(iteration), rank=self.rank,
            extra={"last_chunk_s": round(float(dt_s), 6),
                   "it_per_s": round(cum_rate, 6)},
        )
        self.last_mono = now_mono
        self.last_iteration = int(iteration)
        return rec

    def window(self, n: int = RING_SIZE) -> List[Dict]:
        with self._lock:
            items = list(self._ring)
        return items[-n:]


# ---------------------------------------------------------------------------
# module lifecycle (mirrors obs/flight.py: one active recorder, start/stop,
# a no-op fast path when off)
# ---------------------------------------------------------------------------

_LOCK = sanitize_mod.make_lock("obs.podwatch")
_ACTIVE: Optional[TelemetryRecorder] = None
_SERVER: Optional["TelemetryServer"] = None
_PREEMPT_FN: Optional[Callable[[], bool]] = None


def active() -> Optional[TelemetryRecorder]:
    return _ACTIVE


def maybe_start(preempt_watcher=None) -> Optional[TelemetryRecorder]:
    """The train() entry point: one env read per gate; both unset means
    nothing happens — no threads, no ring, no instance (the off-path pins
    in tests/test_podwatch.py hold this to account). Returns the recorder
    (None when only the scrape endpoint is armed, or on any failure —
    observability must never fail the training run)."""
    out_dir = env_dir()
    port = env_port()
    if out_dir is None and port is None:
        return None
    global _PREEMPT_FN
    if preempt_watcher is not None:
        _PREEMPT_FN = preempt_watcher.requested
    if port is not None:
        ensure_server(port)
    if out_dir is None:
        return None
    try:
        from . import dist as dist_mod

        rank, world = dist_mod.process_info()
        return start(out_dir, rank=rank, world=world)
    except Exception as e:
        log.warning("podwatch: recorder start failed (%s: %s); telemetry "
                    "off for this run" % (type(e).__name__, str(e)[:160]))
        return None


def start(out_dir: str, rank: int = 0,
          world: int = 1) -> Optional[TelemetryRecorder]:
    """Arm the per-rank recorder; None (recording stays off) when another
    recorder is already active — nested train() calls (the loop
    controller's warm-start retrain inside a recorded run) keep the outer
    run's telemetry."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None:
            log.warn_once(
                "podwatch-nested",
                "podwatch: a telemetry recorder is already active (%s); "
                "nested run not recorded" % _ACTIVE.path,
            )
            return None
        try:
            rec = TelemetryRecorder(out_dir, rank, world)
        except OSError as e:
            log.warning("podwatch: cannot record to %s (%s)" % (out_dir, e))
            return None
        _ACTIVE = rec
        return rec


def note_boundary(iteration: int, chunk: int, dt_s: float, gbdt=None) -> None:
    """Per-chunk-boundary hook (engine._boost_loop): no-op when off."""
    rec = _ACTIVE
    if rec is None:
        return
    try:
        rec.sample(iteration, chunk, dt_s, gbdt=gbdt)
    except Exception as e:
        log.debug("podwatch: boundary sample failed: %r" % (e,))


def stop() -> None:
    """Close the active recorder (the shard on disk is already current —
    every boundary republished it). The scrape listener, if any, stays up:
    a pod is watched across train() calls."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


# ---------------------------------------------------------------------------
# scrape endpoint (training side)
# ---------------------------------------------------------------------------

def health_payload() -> Dict:
    """Liveness + progress for /health: cheap enough to poll every second."""
    rec = _ACTIVE
    fn = _PREEMPT_FN
    reg = registry_mod.REGISTRY
    payload: Dict[str, object] = {
        "status": "ok",
        "pid": os.getpid(),
        "telemetry_armed": rec is not None,
        "iteration": int(reg.counter("train_iterations").value()),
        "preempt_requested": bool(fn()) if fn is not None else False,
        "watchdog_deadline_total": int(
            reg.counter("resil_collective_deadline").value()
        ),
    }
    if rec is not None:
        payload["rank"] = rec.rank
        payload["world"] = rec.world
        payload["last_iteration"] = rec.last_iteration
        payload["last_boundary_age_s"] = (
            round(time.monotonic() - rec.last_mono, 3)
            if rec.last_mono is not None else None
        )
    return payload


def timeline_payload(n: int = RING_SIZE) -> Dict:
    rec = _ACTIVE
    if rec is None:
        return {"telemetry_armed": False, "samples": []}
    return {
        "telemetry_armed": True,
        "rank": rec.rank,
        "world": rec.world,
        "samples": rec.window(n),
    }


def _make_handler():
    """Build the handler class lazily: serve/httpbase is a sibling package
    import, and podwatch's aggregator half must import cleanly even if the
    serve package ever grows heavier."""
    from ..serve import httpbase

    class PodwatchHandler(httpbase.JsonHandler):
        server_version = "lightgbm-tpu-podwatch/1.0"
        log_prefix = "podwatch"

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._text(
                        200, registry_mod.REGISTRY.prometheus_text(),
                        httpbase.PROM_CONTENT_TYPE,
                    )
                elif path == "/health":
                    self._json(200, health_payload())
                elif path == "/timeline":
                    self._json(200, timeline_payload())
                else:
                    self._json(404, {"error": "unknown path %s" % path})
            except Exception as e:  # a scrape must never kill the listener
                self._json(500, {"error": "%s: %s" % (type(e).__name__, e)})

    return PodwatchHandler


class TelemetryServer:
    """The opt-in scrape listener: one daemon serve_forever thread, handler
    threads daemonized by serve/httpbase.DaemonHTTPServer. ``port`` is the
    BOUND port (pass 0 to pick a free one — tests do)."""

    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        from ..serve import httpbase

        self._httpd = httpbase.DaemonHTTPServer((host, port), _make_handler())
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="podwatch-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def ensure_server(port: int) -> Optional[TelemetryServer]:
    """Start (or return) the process-wide scrape listener. A bind failure
    is a warning — the port may be held by this very process's previous
    listener after a port-env change, or by an unrelated tenant — and
    training proceeds unscrapable rather than dead."""
    global _SERVER
    with _LOCK:
        if _SERVER is not None:
            return _SERVER
    try:
        srv = TelemetryServer(port)
    except OSError as e:
        log.warning(
            "podwatch: cannot bind scrape endpoint on port %d (%s); "
            "training continues without it" % (port, e)
        )
        return None
    with _LOCK:
        if _SERVER is None:
            _SERVER = srv
            log.info("podwatch: scrape endpoint on 127.0.0.1:%d "
                     "(/metrics /health /timeline)" % srv.port)
            return srv
    srv.close()  # lost the race to a concurrent ensure_server
    with _LOCK:
        return _SERVER


def shutdown_server() -> None:
    """Tear the listener down (tests; training never calls this)."""
    global _SERVER
    with _LOCK:
        srv = _SERVER
        _SERVER = None
    if srv is not None:
        srv.close()


# ---------------------------------------------------------------------------
# aggregator + verdicts (stdlib-only; runs anywhere the shared dir mounts)
# ---------------------------------------------------------------------------

def load_timelines(out_dir: str) -> Dict[int, List[Dict]]:
    """{rank: samples} from every ``timeline.rank*.jsonl`` shard, torn
    tails tolerated line-by-line (the writer republishes atomically, but an
    operator may point podwatch at a half-copied dir)."""
    out: Dict[int, List[Dict]] = {}
    for path in sorted(glob.glob(os.path.join(out_dir, "timeline.rank*.jsonl"))):
        m = _TIMELINE_RE.search(os.path.basename(path))
        if not m:
            continue
        samples: List[Dict] = []
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        samples.append(rec)
        except OSError:
            continue
        out[int(m.group(1))] = samples
    return out


def _window(samples: List[Dict]) -> List[Dict]:
    return samples[WARMUP_SKIP:][-WINDOW:]


def _mean(xs: List[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def _median(xs: List[float]) -> float:
    """Lower median: for even counts take the lower of the two middle
    elements instead of averaging. In a 2-rank pod the averaged median sits
    halfway between the healthy rank and the straggler — diluted by the very
    rank under judgment — while the lower median stays anchored to the
    healthy one."""
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[(len(s) - 1) // 2]


def _segment_means(window: List[Dict]) -> Dict[str, float]:
    """Mean seconds per boundary per segment, including the synthetic
    ``host_other`` bucket (boundary time no TIMETAG phase claims)."""
    totals: Dict[str, float] = {}
    other = 0.0
    for s in window:
        segs = s.get("segments") or {}
        for k, v in segs.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        other += max(float(s.get("dt_s", 0.0)) - sum(
            float(v) for v in segs.values()), 0.0)
    n = max(len(window), 1)
    out = {k: v / n for k, v in totals.items()}
    out[HOST_OTHER] = other / n
    return out


def _diverging_segment(
    rank: int, seg_means: Dict[int, Dict[str, float]]
) -> Tuple[str, float, float]:
    """(segment, rank_s, pod_median_s): the segment where ``rank``'s mean
    boundary seconds exceed the pod median by the most absolute time."""
    mine = seg_means.get(rank, {})
    best, best_excess = HOST_OTHER, float("-inf")
    best_mine, best_pod = 0.0, 0.0
    for seg in sorted(set(k for sm in seg_means.values() for k in sm)):
        pod = _median([sm.get(seg, 0.0) for r, sm in seg_means.items()
                       if r != rank]) if len(seg_means) > 1 else 0.0
        excess = mine.get(seg, 0.0) - pod
        if excess > best_excess:
            best, best_excess = seg, excess
            best_mine, best_pod = mine.get(seg, 0.0), pod
    return best, best_mine, best_pod


def compute_verdicts(
    timelines: Dict[int, List[Dict]],
    stale: Optional[List] = None,
) -> List[Dict]:
    """Evidence-backed verdict list (devprof style: ``verdict``/``why``/
    ``evidence``, thresholds cited by value so the sentence stands alone).
    Deterministic order: stragglers, stalls, skew, dead — each by rank."""
    verdicts: List[Dict] = []
    windows = {r: _window(s) for r, s in timelines.items()}

    # -- straggler: mean chunk seconds vs the pod median -------------------
    chunk_means = {
        r: _mean([float(s.get("dt_s", 0.0)) for s in w])
        for r, w in windows.items() if len(w) >= MIN_SAMPLES
    }
    if len(chunk_means) >= 2:
        med = _median(list(chunk_means.values()))
        seg_means = {r: _segment_means(w) for r, w in windows.items()
                     if r in chunk_means}
        for r in sorted(chunk_means):
            mine = chunk_means[r]
            if med > 0 and mine > STRAGGLER_FACTOR * med:
                seg, seg_mine, seg_pod = _diverging_segment(r, seg_means)
                verdicts.append({
                    "verdict": "straggler",
                    "rank": r,
                    "why": "rank %d chunk %.3fs = %.2fx pod median %.3fs "
                           "(threshold %.2fx); diverging segment %s "
                           "(%.3fs vs pod %.3fs per boundary)"
                           % (r, mine, mine / med, med, STRAGGLER_FACTOR,
                              seg, seg_mine, seg_pod),
                    "evidence": {
                        "rank_chunk_s": round(mine, 6),
                        "pod_median_chunk_s": round(med, 6),
                        "factor": round(mine / med, 3),
                        "threshold": STRAGGLER_FACTOR,
                        "segment": seg,
                        "segment_rank_s": round(seg_mine, 6),
                        "segment_pod_s": round(seg_pod, 6),
                        "samples": len(windows[r]),
                    },
                })

    # -- stall: recent rate collapse vs the rank's OWN trailing window -----
    for r in sorted(windows):
        if not windows[r]:
            continue
        # compare like with like: a chunked run's per-iteration tail
        # legitimately divides it/s by the chunk size (per-boundary overhead
        # amortizes over fewer iterations) — that is a schedule change, not
        # a stall, so only boundaries sharing the newest sample's chunk size
        # enter the comparison
        tail_chunk = int(windows[r][-1].get("chunk", 1))
        rates = [float(s.get("it_per_s", 0.0)) for s in windows[r]
                 if int(s.get("chunk", 1)) == tail_chunk]
        if len(rates) < STALL_MIN_SAMPLES:
            continue
        recent = _mean(rates[-STALL_RECENT:])
        trailing = _median(rates[:-STALL_RECENT])
        if trailing > 0 and recent < trailing / STALL_FACTOR:
            verdicts.append({
                "verdict": "stall",
                "rank": r,
                "why": "rank %d recent rate %.3f it/s is %.1fx below its "
                       "own trailing median %.3f it/s (threshold %.1fx "
                       "over the last %d boundaries)"
                       % (r, recent, trailing / max(recent, 1e-9), trailing,
                          STALL_FACTOR, STALL_RECENT),
                "evidence": {
                    "recent_it_per_s": round(recent, 6),
                    "trailing_it_per_s": round(trailing, 6),
                    "collapse": round(trailing / max(recent, 1e-9), 3),
                    "threshold": STALL_FACTOR,
                    "recent_boundaries": STALL_RECENT,
                    "samples": len(rates),
                },
            })

    # -- skew: iteration spread across ranks -------------------------------
    last_iter = {
        r: int(s[-1].get("iteration", 0))
        for r, s in timelines.items() if s
    }
    if len(last_iter) >= 2:
        leader = max(last_iter, key=lambda r: (last_iter[r], -r))
        laggard = min(last_iter, key=lambda r: (last_iter[r], r))
        spread = last_iter[leader] - last_iter[laggard]
        if spread > SKEW_ITERATIONS:
            verdicts.append({
                "verdict": "skew",
                "rank": laggard,
                "why": "iteration spread %d across the pod exceeds %d: "
                       "rank %d is at %d while rank %d leads at %d"
                       % (spread, SKEW_ITERATIONS, laggard,
                          last_iter[laggard], leader, last_iter[leader]),
                "evidence": {
                    "spread": spread,
                    "threshold": SKEW_ITERATIONS,
                    "laggard": laggard,
                    "laggard_iteration": last_iter[laggard],
                    "leader": leader,
                    "leader_iteration": last_iter[leader],
                },
            })

    # -- dead: stale/missing heartbeats (resil/coord.stale_ranks) ----------
    for entry in sorted(stale or []):
        r, age = entry[0], entry[1]
        evidence = dict(getattr(entry, "evidence", None) or {})
        why = (
            "rank %d heartbeat is %.1fs old (stale past %.0fs); last seen "
            "at iteration %s" % (r, age, DEAD_MAX_AGE_S,
                                 evidence.get("iteration", "?"))
            if age is not None
            else "rank %d has no readable heartbeat file" % r
        )
        verdicts.append({
            "verdict": "dead",
            "rank": r,
            "why": why,
            # age_source: which clock judged the age (coord.heartbeat_age —
            # "wall" from the blob's time stamp, "mtime" when a foreign/
            # legacy writer omitted it; never the per-process mono clock)
            "evidence": {"age_s": None if age is None else round(age, 3),
                         "threshold_s": DEAD_MAX_AGE_S,
                         "age_source": evidence.get("age_source"),
                         "heartbeat": evidence},
        })
    return verdicts


#: flexctl's decision table (docs/FaultTolerance.md §Fleet orchestrator):
#: what an orchestrator should DO about each verdict kind. Only *dead*
#: triggers an automatic reshard — and only when its evidence shows a rank
#: that heartbeat and then went silent (``age_s`` present); a missing
#: heartbeat file is startup-ambiguous and stays advisory. straggler/stall
#: are performance findings (the run is correct, just slow) and *skew* on
#: a healthy pod means the collectives are already keeping ranks honest.
VERDICT_ACTIONS = {
    "dead": "drain_survivors",
    "stall": "watch",
    "straggler": "watch",
    "skew": "watch",
}


def actions_for(summary: Dict) -> List[Dict]:
    """The verdict→action plumbing flexctl consumes: one record per
    verdict with the action from :data:`VERDICT_ACTIONS` (*dead* without
    age evidence is demoted to ``watch``, see the table's doc)."""
    out: List[Dict] = []
    for v in summary.get("verdicts") or []:
        action = VERDICT_ACTIONS.get(v.get("verdict"), "watch")
        if (v.get("verdict") == "dead"
                and (v.get("evidence") or {}).get("age_s") is None):
            action = "watch"
        out.append({
            "rank": v.get("rank"),
            "verdict": v.get("verdict"),
            "action": action,
            "why": v.get("why", ""),
        })
    return out


def pod_summary(out_dir: str, now: Optional[float] = None,
                max_age_s: float = DEAD_MAX_AGE_S) -> Dict:
    """Fold every rank's shards + heartbeats into one pod view. ``now`` is
    the wall clock the dead-rank ages are judged against (tests pin it)."""
    from ..resil import coord

    timelines = load_timelines(out_dir)
    hb_base = heartbeat_base(out_dir)
    hb_world = 0
    for path in glob.glob(hb_base + ".hb.rank*.json"):
        m = re.search(r"\.hb\.rank(\d+)\.json$", path)
        if m:
            hb_world = max(hb_world, int(m.group(1)) + 1)
    world = max(hb_world, (max(timelines) + 1) if timelines else 0)
    heartbeats = coord.read_heartbeats(hb_base, world)
    stale = (coord.stale_ranks(hb_base, world, max_age_s, now=now)
             if world else [])
    ranks: Dict[str, Dict] = {}
    for r in sorted(set(timelines) | set(heartbeats)):
        samples = timelines.get(r) or []
        w = _window(samples)
        hb = heartbeats.get(r) or {}
        ranks[str(r)] = {
            "samples": len(samples),
            "iteration": (int(samples[-1]["iteration"]) if samples
                          else hb.get("iteration")),
            "chunk_s": round(_mean([float(s.get("dt_s", 0.0)) for s in w]), 6),
            "it_per_s": round(
                float(samples[-1].get("cum_it_per_s", 0.0)), 6
            ) if samples else hb.get("it_per_s"),
            "heartbeat": {k: hb[k] for k in
                          ("iteration", "time", "mono", "last_chunk_s",
                           "it_per_s", "pid") if k in hb},
        }
    last_iters = [int(s[-1]["iteration"]) for s in timelines.values() if s]
    return {
        "dir": out_dir,
        "world": world,
        "ranks": ranks,
        "iteration_spread": (max(last_iters) - min(last_iters)
                             if len(last_iters) >= 2 else 0),
        "verdicts": compute_verdicts(timelines, stale=stale),
    }


# ---------------------------------------------------------------------------
# publication: podwatch_* gauges + the run_report section
# ---------------------------------------------------------------------------

VERDICT_KINDS = ("straggler", "stall", "skew", "dead")

_SECTION_REGISTERED = False
_LAST_SUMMARY: Dict = {}


def _report_section() -> Dict:
    return dict(_LAST_SUMMARY)


def publish(summary: Dict, registry=None) -> None:
    """Land the pod view on the registry: ``podwatch_verdicts{verdict=}``
    (every kind set, so a cleared verdict re-publishes as 0),
    ``podwatch_iteration_spread``, per-rank iteration/chunk gauges, and the
    ``fleet_telemetry`` run_report section (report.py §Fleet telemetry)."""
    global _SECTION_REGISTERED
    reg = registry if registry is not None else registry_mod.REGISTRY
    counts = {k: 0 for k in VERDICT_KINDS}
    for v in summary.get("verdicts") or []:
        k = v.get("verdict")
        if k in counts:
            counts[k] += 1
    g = reg.gauge("podwatch_verdicts",
                  "fleet-telemetry verdicts by kind (obs/podwatch.py)")
    for k, n in counts.items():
        g.set(n, verdict=k)
    reg.gauge("podwatch_iteration_spread",
              "pod iteration spread: leader minus laggard").set(
        float(summary.get("iteration_spread") or 0))
    g_it = reg.gauge("podwatch_rank_iteration",
                     "last recorded iteration per rank")
    g_ch = reg.gauge("podwatch_rank_chunk_seconds",
                     "mean chunk-boundary seconds per rank (recent window)")
    for r, rec in (summary.get("ranks") or {}).items():
        if rec.get("iteration") is not None:
            g_it.set(float(rec["iteration"]), rank=str(r))
        if rec.get("chunk_s") is not None:
            g_ch.set(float(rec["chunk_s"]), rank=str(r))
    _LAST_SUMMARY.clear()
    _LAST_SUMMARY.update(summary)
    if reg is not registry_mod.REGISTRY:
        reg.register_report_section("fleet_telemetry", _report_section)
    elif not _SECTION_REGISTERED:
        _SECTION_REGISTERED = True
        reg.register_report_section("fleet_telemetry", _report_section)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _print_human(summary: Dict) -> None:
    print("podwatch: %s — world %d, iteration spread %d"
          % (summary["dir"], summary["world"], summary["iteration_spread"]))
    for r, rec in sorted(summary["ranks"].items(), key=lambda kv: int(kv[0])):
        print("  rank %s: iter %s, %s it/s, chunk %ss (%d samples)"
              % (r, rec.get("iteration"), rec.get("it_per_s"),
                 rec.get("chunk_s"), rec.get("samples", 0)))
    if not summary["verdicts"]:
        print("  verdicts: none — pod looks healthy")
    for v in summary["verdicts"]:
        print("  VERDICT %s rank %s: %s" % (v["verdict"], v["rank"], v["why"]))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.podwatch",
        description="Fold per-rank telemetry shards + heartbeats into one "
                    "pod view with straggler/stall/skew/dead verdicts",
    )
    ap.add_argument("dir", help="the LIGHTGBM_TPU_TELEMETRY directory")
    ap.add_argument("--json", action="store_true",
                    help="emit the pod summary as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="exit 3 when any straggler/stall/dead verdict "
                         "fires (skew alone stays informational)")
    ap.add_argument("--max-age-s", type=float, default=DEAD_MAX_AGE_S,
                    help="heartbeat age beyond which a rank is dead "
                         "(default %(default)s)")
    ap.add_argument("--now", type=float, default=None,
                    help="wall-clock override for the dead-rank judgement "
                         "(tests/replays)")
    args = ap.parse_args(argv)
    summary = pod_summary(args.dir, now=args.now, max_age_s=args.max_age_s)
    publish(summary)
    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        _print_human(summary)
    if args.strict and any(
        v["verdict"] in ("straggler", "stall", "dead")
        for v in summary["verdicts"]
    ):
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
