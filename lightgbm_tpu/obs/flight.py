"""Training flight recorder: a JSONL log of what each boosting round learned.

The system-observability tier (trace/retrace/memwatch, PR 4) answers "where
did the time go"; this module answers "what did the MODEL do": one compact
record per iteration/chunk boundary (eval-history values, wall time), one
record per materialized tree (gain totals, leaf shape, top gain features —
the same per-node ``split_gain``/counts the reference exposes in its model
text), and run-boundary events (early stop, no-split stop, resume
provenance). The file opens with a run manifest (config digest, dataset
shape + label digest, jax/backend versions) so two flight logs are diffable
without the repos that produced them.

Enablement — disabled by default, zero work when off:

  * ``LIGHTGBM_TPU_FLIGHT=<path>`` environment variable, or
  * ``flight_record=<path>`` training parameter (engine.train pops it so the
    model's parameters footer stays byte-identical with/without recording).

Recording only READS host-side state (eval tuples, materialized numpy tree
arrays, perf_counter deltas); it never touches the jitted programs, so the
final model is bitwise-identical and the retrace watchdog stays silent with
recording on (tests/test_model_obs.py proves both).

Read a log back with :func:`load` — it groups records by event kind for
programmatic diffing::

    rec = flight.load("run.jsonl")
    rec["manifest"]["config_digest"], rec["iterations"], rec["trees"]

Format: line 1 is the manifest (``event="manifest"``), every later line one
event object; ``seq`` is a monotonically increasing record index and ``t_s``
the perf_counter offset from recorder start. Torn tails (a killed run's last
partial line) are skipped by :func:`load`, never fatal — a flight log is
evidence, not state the trainer depends on.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import log
from . import sanitize as sanitize_mod

ENV_FLIGHT = "LIGHTGBM_TPU_FLIGHT"

#: top-k gain features recorded per tree (keeps tree records compact even at
#: num_leaves=255 on wide datasets)
TREE_TOP_K = 5


def env_path() -> Optional[str]:
    """The env-gated flight-log path (read per call: tests flip it)."""
    return os.environ.get(ENV_FLIGHT) or None


class FlightRecorder:
    """One training run's JSONL event stream (thread-safe appends)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = sanitize_mod.make_lock("obs.flight")
        self._seq = 0
        self._t0 = time.perf_counter()
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        # plain buffered text; NOT the atomic publisher — a flight log is an
        # append-only event stream whose torn tail load() tolerates, and the
        # whole point is having the records a crashed run got to write
        self._fh = open(path, "w", encoding="utf-8")

    def record(self, event: str, **fields: Any) -> None:
        rec = {"event": event, "seq": 0,
               "t_s": round(time.perf_counter() - self._t0, 6)}
        rec.update(fields)
        rec["event"], rec["seq"] = event, 0  # keys win over field collisions
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._fh.write(json.dumps(rec, default=_jsonable) + "\n")

    def close(self) -> str:
        with self._lock:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass
            self._fh.close()
        return self.path


def _jsonable(obj):
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("flight record value %r is not JSON-serializable" % (obj,))


# ---------------------------------------------------------------------------
# module-level active recorder (engine.train scopes it per run, like trace)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FlightRecorder] = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def start(path: str, manifest: Dict[str, Any]) -> Optional[FlightRecorder]:
    """Open a recorder at ``path`` and write the run manifest. Returns None
    (recording stays off) when the file cannot be opened — observability
    must never fail the training run it observes."""
    global _ACTIVE
    if _ACTIVE is not None:
        # nested/overlapping train() calls: the outer run keeps the log
        log.warn_once(
            "flight-nested",
            "flight recorder already active (%s); nested run not recorded"
            % _ACTIVE.path,
        )
        return None
    try:
        rec = FlightRecorder(path)
        rec.record("manifest", **manifest)
    except OSError as e:
        log.warning("flight: cannot open %r (%s); recording disabled"
                    % (path, e))
        return None
    _ACTIVE = rec
    return rec


def stop(summary: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Write the end record (with ``summary`` fields), close, return path."""
    global _ACTIVE
    rec = _ACTIVE
    if rec is None:
        return None
    _ACTIVE = None
    try:
        rec.record("end", **(summary or {}))
        return rec.close()
    except (OSError, ValueError) as e:
        log.warning("flight: close failed: %r" % (e,))
        return rec.path


# ---------------------------------------------------------------------------
# manifest / record builders (host-side reads only)
# ---------------------------------------------------------------------------

def config_digest(config) -> str:
    """THE digest resil/checkpoint.py stamps (imported, not reimplemented),
    so a flight log and a checkpoint taken from one run agree on the config
    identity by construction."""
    from ..resil.checkpoint import _config_digest

    return _config_digest(config)


def manifest_digest(manifest: Dict[str, Any]) -> str:
    """Stable identity of one recorded run: sha1 over the sorted-key JSON of
    its manifest record (recorder bookkeeping fields excluded, so the digest
    recomputed from a flight file on disk matches the one computed from the
    in-memory manifest at train time). The continuous-training controller
    journals this next to the published model — a serving-side rollback
    decision can then name exactly which training run produced the bytes it
    is about to drop (docs/ContinuousTraining.md)."""
    body = {k: v for k, v in manifest.items()
            if k not in ("event", "seq", "t_s")}
    return hashlib.sha1(
        json.dumps(body, sort_keys=True, default=_jsonable).encode("utf-8")
    ).hexdigest()


def build_manifest(
    booster,
    num_boost_round: int,
    init_iteration: int,
    resume_from: Optional[str] = None,
    checkpoint_path: Optional[str] = None,
    parent_fingerprint: Optional[str] = None,
) -> Dict[str, Any]:
    """Run-identity header: config digest, dataset shape + label digest,
    jax/backend versions, resume provenance (PR 5 checkpoints)."""
    gbdt = booster._gbdt
    ds = gbdt.train_set
    label = getattr(ds.metadata, "label", None) if ds is not None else None
    label_digest = (
        hashlib.sha1(np.ascontiguousarray(label).tobytes()).hexdigest()[:16]
        if label is not None else ""
    )
    versions: Dict[str, str] = {}
    backend = ""
    try:
        import jax

        versions["jax"] = getattr(jax, "__version__", "")
        backend = jax.default_backend()
    except Exception as e:  # manifest must never fail the run
        log.debug("flight: backend/version probe failed: %r" % (e,))
    # THE process-identity helper (obs/dist.py) — one rank-determination
    # rule shared with the pod-wide snapshot merge
    from . import dist as dist_mod

    process_index, process_count = dist_mod.process_info()
    # mesh provenance (resil/checkpoint's ONE mesh descriptor): pod ranks'
    # flight logs are load()-joinable by iteration only if each records
    # which shard layout produced it
    mesh = None
    try:
        from ..resil.checkpoint import _mesh_desc

        mesh = _mesh_desc(gbdt)
    except Exception as e:
        log.debug("flight: mesh probe failed: %r" % (e,))
    man: Dict[str, Any] = {
        "config_digest": config_digest(gbdt.config),
        "objective": gbdt.config.objective,
        "num_class": int(gbdt.num_class),
        "num_data": int(ds.num_data) if ds is not None else 0,
        "num_features": int(ds.num_features) if ds is not None else 0,
        "num_total_features": (
            int(ds.num_total_features) if ds is not None else 0
        ),
        "label_digest": label_digest,
        "num_boost_round": int(num_boost_round),
        "init_iteration": int(init_iteration),
        "backend": backend,
        "versions": versions,
        "process_index": process_index,
        "process_count": process_count,
        "started_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if mesh is not None:
        man["mesh"] = mesh
    # the frozen histogram tune route (ops/histogram.HistRoute, ISSUE 13):
    # the digest IS the run's routing identity — two flight logs with equal
    # digests trained under byte-identical kernel routing, and bench_diff
    # treats a digest change as "throughput rows reflect routing, not
    # regression" (docs/HistogramRouting.md)
    route = getattr(gbdt, "_hist_route", None)
    if route is not None:
        man["hist_route_digest"] = route.digest
        man["hist_tune_source"] = route.source
    if resume_from:
        man["resume_from"] = str(resume_from)
        man["resumed_at_iteration"] = int(gbdt.iter_)
    if checkpoint_path:
        man["checkpoint_path"] = str(checkpoint_path)
    if parent_fingerprint:
        # continued training (init_model): which model this run grew from —
        # the lineage edge the serve side surfaces (docs/ContinuousTraining.md)
        man["parent_fingerprint"] = str(parent_fingerprint)
    return man


def note_boundary(
    iteration: int, done: int, dt_s: float, evaluation_result_list
) -> None:
    """One record per iteration/chunk boundary (no-op when not recording)."""
    rec = _ACTIVE
    if rec is None:
        return
    evals = [
        [str(d), str(m), float(v)]
        for (d, m, v, _b) in (evaluation_result_list or [])
    ]
    extra: Dict[str, Any] = {}
    try:
        # collective seconds the sharded segment profiler measured since
        # the previous boundary (obs/dist.py; 0.0 — and no field — unless
        # distributed profiling ran inside this window)
        from . import dist as dist_mod

        comms = dist_mod.take_boundary_comms()
        if comms > 0:
            extra["comms_s"] = round(comms, 6)
    except Exception as e:  # recording must never fail the boundary
        log.debug("flight: comms probe failed: %r" % (e,))
    rec.record(
        "iteration", iteration=int(iteration), chunk=int(done),
        dt_s=round(float(dt_s), 6), evals=evals, **extra,
    )


def note_event(event: str, **fields: Any) -> None:
    """Run-boundary events: early_stop, no_split_stop, checkpoint, ..."""
    rec = _ACTIVE
    if rec is None:
        return
    rec.record(event, **fields)


def tree_record(tree, index: int, class_id: int) -> Dict[str, Any]:
    """Compact stats of one materialized host Tree (models/tree.py): the
    per-node split_gain / leaf shape the reference model text carries,
    reduced to totals + the top-k gain features."""
    n1 = max(tree.num_leaves - 1, 0)
    gains = np.asarray(tree.split_gain[:n1], np.float64)
    feats = np.asarray(tree.split_feature[:n1], np.int64)
    rec: Dict[str, Any] = {
        "tree": int(index),
        "class": int(class_id),
        "num_leaves": int(tree.num_leaves),
        "max_depth": int(tree.max_depth()),
        "total_gain": round(float(gains.sum()), 6) if n1 else 0.0,
        "max_gain": round(float(gains.max()), 6) if n1 else 0.0,
        "shrinkage": float(tree.shrinkage),
    }
    if n1:
        per_feat: Dict[int, float] = {}
        for f, g in zip(feats, gains):
            per_feat[int(f)] = per_feat.get(int(f), 0.0) + float(g)
        top = sorted(per_feat.items(), key=lambda kv: -kv[1])[:TREE_TOP_K]
        rec["top_gain_features"] = [[f, round(g, 6)] for f, g in top]
        leaf_counts = np.asarray(tree.leaf_count[: tree.num_leaves], np.int64)
        rec["min_leaf_count"] = int(leaf_counts.min())
        rec["max_leaf_count"] = int(leaf_counts.max())
    return rec


def finish_training(booster) -> Optional[str]:
    """Materialize the model, emit one ``tree`` record per tree and the end
    summary, close the log. Called by engine.train when recording."""
    rec = _ACTIVE
    if rec is None:
        return None
    try:
        gbdt = booster._gbdt
        trees = gbdt.trees()  # materializes (deterministic, model unchanged)
        K = max(gbdt.num_tree_per_iteration, 1)
        for i, t in enumerate(trees):
            if t is None:
                continue
            rec.record("tree", **tree_record(t, i, i % K))
        summary = {
            "num_trees": len(trees),
            "iterations": int(gbdt.current_iteration),
            "best_iteration": int(booster.best_iteration),
            "stopped": bool(getattr(gbdt, "_stopped", False)),
        }
    except Exception as e:  # recording must never fail training
        log.warning("flight: tree harvest failed: %r" % (e,))
        summary = {"error": repr(e)}
    return stop(summary)


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

def load(path: str) -> Dict[str, Any]:
    """Parse a flight log into {"manifest", "iterations", "trees",
    "events", "end"} for programmatic diffing. Torn trailing lines (a
    SIGKILLed run's final partial record) are skipped."""
    manifest: Dict[str, Any] = {}
    iterations: List[Dict] = []
    trees: List[Dict] = []
    events: List[Dict] = []
    end: Optional[Dict] = None
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail of a crashed run
            kind = rec.get("event")
            if kind == "manifest":
                manifest = rec
            elif kind == "iteration":
                iterations.append(rec)
            elif kind == "tree":
                trees.append(rec)
            elif kind == "end":
                end = rec
            else:
                events.append(rec)
    return {
        "manifest": manifest,
        "iterations": iterations,
        "trees": trees,
        "events": events,
        "end": end,
    }
