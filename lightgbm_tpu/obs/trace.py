"""Structured span tracer: Chrome-trace-format JSON, Perfetto-viewable.

Env-gated with ``LIGHTGBM_TPU_TRACE=<path>``: when set, every ``span()``
context in the process records a Chrome "complete" event (``ph: "X"`` with
pid/tid/ts/dur, microseconds) and the buffer is written to ``<path>`` at
``stop()``/``flush()`` or process exit. Load the file in Perfetto
(https://ui.perfetto.dev) or chrome://tracing; events on one thread nest by
time containment, so a ``train.iteration`` span visually contains its
``tree growth`` / ``renew+score update`` phase spans.

Span sites (cat → where):
  * ``train.phase``   — every PhaseTimers phase (utils/timer.py)
  * ``train``         — per-iteration / per-chunk spans (engine._boost_loop)
  * ``serve``         — request lifecycle: queue wait → batch gather →
                        dispatch → reply (serve/server.py, serve/batcher.py)
  * ``bringup``       — per-stage spans in helpers/tpu_bringup.py
  * ``cli``           — task-level spans (cli.py)

Device correlation: when jax is already imported and a tracer is active,
``span()`` additionally enters ``jax.profiler.TraceAnnotation(name)`` so the
host span shows up inside the XLA/TPU profile that ``LIGHTGBM_TPU_PROFILE``
captures — the host and device timelines line up by annotation name.

One trace file per PROCESS: a subprocess inheriting the env var would clobber
the parent's file at exit, so drivers that fan out stages rewrite the path
per child (helpers/tpu_bringup.py appends ``.stage_<name>``).

Disabled cost: one dict lookup per ``span()`` call. Thread-safe throughout.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading

from . import sanitize as sanitize_mod
import time
from typing import Dict, List, Optional

ENV_TRACE = "LIGHTGBM_TPU_TRACE"

_EPOCH = time.perf_counter()


def now_us() -> float:
    """Microseconds on the tracer's (monotonic) clock."""
    return (time.perf_counter() - _EPOCH) * 1e6


#: buffer cap: ~160 bytes/event dict puts 1M events around 160MB — enough
#: for hours of phase spans or minutes of per-request serve spans, small
#: enough that a traced long-lived server cannot OOM from the tracer
MAX_EVENTS = 1_000_000


class Tracer:
    """In-memory Chrome-trace event buffer bound to one output path.

    The buffer is CAPPED at ``max_events``: once full, further events are
    counted (``dropped``) but not stored, and the flushed file carries a
    ``dropped_events`` marker — tracing a long-lived serve process degrades
    to a truncated-but-loadable trace instead of unbounded memory growth.
    """

    def __init__(self, path: str, max_events: int = MAX_EVENTS) -> None:
        self.path = path
        self.pid = os.getpid()
        self.max_events = max_events
        self.dropped = 0
        self._events: List[Dict] = []
        self._lock = sanitize_mod.make_lock("obs.trace.buffer")
        self._tids: Dict[int, int] = {}  # thread ident -> small stable tid

    def _append(self, ev: Dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids)
                self._tids[ident] = tid
                name = threading.current_thread().name
                # metadata rides outside the cap: a handful of threads
                self._events.insert(tid, {
                    "ph": "M", "name": "thread_name", "pid": self.pid,
                    "tid": tid, "args": {"name": name},
                })
            return tid

    def complete(
        self, name: str, cat: str, ts_us: float, dur_us: float,
        args: Optional[Dict] = None, tid: Optional[int] = None,
    ) -> None:
        ev = {
            "ph": "X", "name": name, "cat": cat or "lgbtpu",
            "pid": self.pid, "tid": self._tid() if tid is None else tid,
            "ts": round(ts_us, 3), "dur": round(max(dur_us, 0.0), 3),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def instant(self, name: str, cat: str = "", args: Optional[Dict] = None) -> None:
        ev = {
            "ph": "i", "s": "t", "name": name, "cat": cat or "lgbtpu",
            "pid": self.pid, "tid": self._tid(), "ts": round(now_us(), 3),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, value: float) -> None:
        self._append({
            "ph": "C", "name": name, "cat": "lgbtpu", "pid": self.pid,
            "tid": 0, "ts": round(now_us(), 3),
            "args": {"value": float(value)},
        })

    def flush(self) -> str:
        """Write the full buffer (Chrome trace object form) to ``path``."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "lightgbm_tpu.obs.trace"},
        }
        if dropped:
            payload["otherData"]["dropped_events"] = dropped
        with open(self.path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
            fh.write("\n")
        return self.path

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)


_TRACER: Optional[Tracer] = None
_LOCK = sanitize_mod.make_lock("obs.trace")
_ATEXIT_ARMED = False


def start(path: Optional[str] = None) -> Tracer:
    """Start (or return) the process tracer; ``path`` defaults to the
    LIGHTGBM_TPU_TRACE env var. Idempotent while a tracer is live."""
    global _TRACER, _ATEXIT_ARMED
    with _LOCK:
        if _TRACER is not None:
            return _TRACER
        target = path or os.environ.get(ENV_TRACE, "")
        if not target:
            raise ValueError(
                "trace.start() needs a path (or set %s)" % ENV_TRACE
            )
        if path is None:
            # jax.distributed runs: every rank inherits the SAME env var, so
            # an env-derived default path gets a .rank<N> suffix — two ranks
            # must never clobber one trace file. Explicit paths are the
            # caller's responsibility (bringup already appends .stage_*).
            target = rank_suffixed(target)
        _TRACER = Tracer(target)
        if not _ATEXIT_ARMED:
            _ATEXIT_ARMED = True
            atexit.register(_atexit_flush)
        return _TRACER


def rank_suffixed(target: str) -> str:
    """``<target>.rank<N>`` when a multi-process jax.distributed world is
    initialized (consults only an already-imported jax; never imports it).
    Shared clobber fix for every env-derived per-process artifact path:
    the tracer's LIGHTGBM_TPU_TRACE file here, utils/timer.maybe_profile's
    LIGHTGBM_TPU_PROFILE dir, and obs/devprof.capture's profile window —
    devprof.find_trace_files folds the ``.rank<N>`` siblings back together
    at parse time."""
    if ".rank" in target:
        return target
    jx = sys.modules.get("jax")
    if jx is None:
        return target
    try:
        if int(jx.process_count()) > 1:
            return "%s.rank%d" % (target, int(jx.process_index()))
    except Exception as e:
        # a half-initialized runtime must not break tracing; the
        # single-file default stands
        from ..utils import log

        log.debug("trace: rank probe failed: %r" % (e,))
    return target


def stop() -> Optional[str]:
    """Flush and detach the tracer; returns the written path (None when no
    tracer was live). A later ``span()`` re-arms from the env var, so tests
    can start/stop repeatedly."""
    global _TRACER
    with _LOCK:
        tr, _TRACER = _TRACER, None
    if tr is None:
        return None
    return tr.flush()


def _atexit_flush() -> None:
    with _LOCK:
        tr = _TRACER
    if tr is not None:
        try:
            tr.flush()
        except OSError:
            pass  # a dead target dir must not break interpreter shutdown


def active() -> Optional[Tracer]:
    """The live tracer, auto-starting from the env var on first use."""
    tr = _TRACER
    if tr is not None:
        return tr
    if os.environ.get(ENV_TRACE, ""):
        try:
            return start()
        except (ValueError, OSError):
            return None
    return None


def enabled() -> bool:
    return active() is not None


@contextlib.contextmanager
def span(name: str, cat: str = "", **args):
    """Record a complete event around the body; no-op without a tracer.

    Keyword args land in the event's ``args`` dict (JSON-able values only).
    When jax is already imported, the span also enters
    ``jax.profiler.TraceAnnotation`` so device profiles carry the same name.
    """
    tr = active()
    if tr is None:
        yield
        return
    ann = None
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            ann = jx.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None  # profiler unavailable on this backend/version
    t0 = now_us()
    try:
        yield
    finally:
        t1 = now_us()
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception as e:
                # annotation teardown must never mask the body's result
                from ..utils import log

                log.debug("trace: TraceAnnotation teardown failed: %r", e)
        tr.complete(name, cat, t0, t1 - t0, args or None)


def complete_at(name: str, cat: str, t0_us: float, t1_us: float,
                **args) -> None:
    """Record a complete event with explicit start/end (``now_us`` clock) —
    for spans measured across threads, e.g. a request's queue wait."""
    tr = active()
    if tr is not None:
        tr.complete(name, cat, t0_us, t1_us - t0_us, args or None)


def instant(name: str, cat: str = "", **args) -> None:
    tr = active()
    if tr is not None:
        tr.instant(name, cat, args or None)


# ---------------------------------------------------------------------------
# multi-file merge: fold per-process/per-rank traces into ONE timeline
# ---------------------------------------------------------------------------

def merge_traces(out_path: str, in_paths) -> Dict:
    """Fold several Chrome-trace files (a bringup's per-stage ``.stage_*``
    children, a pod's per-rank ``.rank<N>`` files, a sweep's ``.dev<D>``
    workers) into ONE Perfetto-loadable timeline. Every source (file, pid)
    pair is remapped to a fresh DISJOINT pid with a ``process_name``
    metadata row naming its origin, so same-pid events from different
    processes can never interleave; ``dropped_events`` markers are summed
    and preserved. Gzipped inputs (``*.json.gz`` — the XLA profiler's own
    export format) load transparently, so per-rank LIGHTGBM_TPU_PROFILE
    captures merge next to the host-span files.
    Returns {files, events, pids, dropped, path}."""
    from . import devprof as devprof_mod  # one gz-transparent loader

    events: List[Dict] = []
    pid_map: Dict = {}
    dropped = 0
    n_events = 0
    files = 0
    for i, p in enumerate(in_paths):
        try:
            doc = devprof_mod.load_chrome_trace(str(p))
        except (OSError, ValueError):
            continue  # a torn/absent child trace must not kill the merge
        files += 1
        dropped += int((doc.get("otherData") or {}).get("dropped_events", 0)
                       or 0)
        label = os.path.basename(str(p))
        for ev in doc.get("traceEvents") or []:
            old = ev.get("pid", 0)
            key = (i, old)
            new = pid_map.get(key)
            if new is None:
                new = pid_map[key] = len(pid_map) + 1
                events.append({
                    "ph": "M", "name": "process_name", "pid": new, "tid": 0,
                    "args": {"name": "%s (pid %s)" % (label, old)},
                })
            ev2 = dict(ev)
            ev2["pid"] = new
            events.append(ev2)
            n_events += 1
    payload: Dict = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "lightgbm_tpu.obs.trace merge"},
    }
    if dropped:
        payload["otherData"]["dropped_events"] = dropped
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
        fh.write("\n")
    return {
        "files": files, "events": n_events, "pids": len(pid_map),
        "dropped": dropped, "path": out_path,
    }


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m lightgbm_tpu.obs.trace merge -o out.json in1 in2 ...``
    (globs welcome) — the pod-wide timeline merge. Stdlib only."""
    import argparse
    import glob as glob_mod

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.trace",
        description="Chrome-trace utilities (obs/trace.py)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    mg = sub.add_parser(
        "merge", help="fold per-process trace files into one timeline "
                      "with disjoint pids",
    )
    mg.add_argument("inputs", nargs="+",
                    help="trace files, shell-unexpanded globs, or "
                         "LIGHTGBM_TPU_PROFILE capture dirs (expanded to "
                         "their per-rank trace.json.gz files)")
    mg.add_argument("-o", "--out", default="trace_merged.json")
    args = ap.parse_args(argv)
    paths: List[str] = []
    for item in args.inputs:
        hits = sorted(glob_mod.glob(item))
        for hit in hits if hits else [item]:
            if os.path.isdir(hit):
                # a profiler capture dir: fold its (and its .rank<N>
                # siblings') Chrome traces in — obs/devprof.py owns the
                # directory-layout knowledge, stdlib only like this module
                from . import devprof as devprof_mod

                paths.extend(devprof_mod.find_trace_files(hit))
            else:
                paths.append(hit)
    # a dir and its .rank<N> sibling both matching the glob would fold the
    # same files twice — order-preserving dedupe
    paths = list(dict.fromkeys(paths))
    stats = merge_traces(args.out, paths)
    print(
        "trace merge: %(files)d file(s) -> %(path)s "
        "(%(events)d events, %(pids)d pids, %(dropped)d dropped)" % stats
    )
    return 0 if stats["files"] else 1


if __name__ == "__main__":
    sys.exit(main())
