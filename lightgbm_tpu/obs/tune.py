"""Shape-aware histogram autotuner (docs/HistogramRouting.md, ISSUE 13).

``hist_build`` owns ~69% of tree-growth segment time (obs/prof.py at the 1M
bench shape) — yet until this module the kernel that served it was picked by
ONE import-time env default. The bucketed grower actually emits histogram
calls at a *distribution* of shapes (the {2^k} ∪ {3·2^(k-1)} bucket lattice,
ops/grow.py ``bucket_sizes``), and the winner measurably differs per shape:
on this CPU box the static default (scatter) loses at EVERY lattice shape —
8.7x to ``xla`` at 512x16, 1.3x to ``xla_radix`` at 65536x256 — and the r5
on-silicon notes found the same class of inversion for small buckets.

This module closes the loop, the same move the reference makes by keeping a
family of histogram256.cl variants and selecting by workload (PAPER.md
layer 4):

 * :func:`sweep` — micro-bench every supported impl
   (ops/histogram.IMPLS, gated by ``impl_supported`` + the chip's
   ``vmem_bytes`` from obs/costs.CHIP_PEAKS for the Pallas contenders) at
   the exact bucket-shape distribution the grower emits, recording
   per-shape medians and the winner.
 * a persisted JSON cache (``save_table`` / ``load_table``) published
   through resil/atomic — a reader sees the old table or the new table,
   never a torn one; a digest over the entries detects tampering and a
   schema stamp makes stale caches REFUSE loudly instead of mis-routing.
 * :func:`active_table` — the adoption seam ``GBDT._setup_train`` calls to
   FREEZE the route for a run (param ``hist_tune`` > env
   ``LIGHTGBM_TPU_HIST_TUNE`` > nothing); bench.py auto-adopts a
   ``TUNE_HIST.json`` next to it, and the bringup ``tune`` stage
   regenerates that file each chip window (helpers/tpu_bringup.py).

The CLI::

    python -m lightgbm_tpu.obs.tune --out TUNE_HIST.json \
        --rows 1048576 --bins 15,63,255 --features 28

Exactness: this module only MEASURES and WRITES; routing consumes the table
through the frozen ``HistRoute`` (ops/histogram.py), so nothing here can
perturb a training run in flight.
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import log
from ..utils.log import LightGBMError

#: bump when the table layout changes: a loaded table with a different
#: schema is REFUSED (never reinterpreted) — mis-parsed routing would
#: silently send shapes to the wrong kernel
SCHEMA = 1

ENV_PATH = "LIGHTGBM_TPU_HIST_TUNE"


# ---------------------------------------------------------------------------
# table build / digest / persistence
# ---------------------------------------------------------------------------

def entries_digest(entries: Sequence[Dict]) -> str:
    """Content digest over the routing-relevant entry fields — the value
    the flight manifest and bench records stamp, and the tamper check
    ``load_table`` verifies."""
    import hashlib

    canon = sorted(
        (int(e["B"]), int(e["K"]), str(e["hist_dtype"]),
         int(e["rows_bucket"]), str(e["impl"]))
        for e in entries
    )
    return hashlib.sha256(repr(canon).encode("utf-8")).hexdigest()[:16]


def build_table(
    entries: Sequence[Dict],
    backend: Optional[str] = None,
    device_kind: Optional[str] = None,
    device_family: Optional[str] = None,
    sweep_meta: Optional[Dict] = None,
) -> Dict:
    """Assemble a schema-stamped, digest-sealed table dict from entries
    (each ``{B, K, hist_dtype, rows_bucket, impl[, times_ms]}``). Shared by
    :func:`sweep` and the tests' hand-built tables (e.g. the tune smoke's
    default-pinned table), so every table in existence carries a valid
    digest."""
    if backend is None or device_family is None:
        from ..ops import histogram as hist_mod

        if backend is None:
            backend = hist_mod._default_backend()
        if device_family is None:
            device_family = hist_mod.device_family() or backend
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = ""
    try:
        import jax

        jax_version = getattr(jax, "__version__", "")
    except Exception:
        jax_version = ""
    ents = [dict(e) for e in entries]
    return {
        "schema": SCHEMA,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend,
        "device_kind": device_kind,
        "device_family": device_family,
        "jax": jax_version,
        "digest": entries_digest(ents),
        "entries": ents,
        "sweep": dict(sweep_meta or {}),
    }


def save_table(table: Dict, path: str) -> str:
    """Atomically publish ``table`` at ``path`` (resil/atomic: temp +
    fsync + rename — a SIGKILL mid-write leaves the previous complete
    table, never a prefix). Returns ``path``."""
    from ..resil.atomic import atomic_write_text

    return atomic_write_text(
        path, json.dumps(table, indent=1, sort_keys=True) + "\n"
    )


def load_table(path: str) -> Dict:
    """Load + validate a tune table. Raises :class:`LightGBMError` on a
    missing/torn file, a stale schema, or a digest mismatch — a cache this
    function cannot vouch for must never route kernels."""
    try:
        with open(path) as fh:
            table = json.load(fh)
    except (OSError, ValueError) as e:
        raise LightGBMError(
            "histogram tune cache %s is unreadable: %s" % (path, e)
        )
    if not isinstance(table, dict) or table.get("schema") != SCHEMA:
        raise LightGBMError(
            "histogram tune cache %s has schema %r but this build expects "
            "%d; refusing stale routing — regenerate it with "
            "`python -m lightgbm_tpu.obs.tune --out %s`"
            % (path, table.get("schema") if isinstance(table, dict) else None,
               SCHEMA, path)
        )
    entries = table.get("entries")
    if not isinstance(entries, list):
        raise LightGBMError(
            "histogram tune cache %s carries no entries list" % path
        )
    want = table.get("digest")
    got = entries_digest(entries)
    if want != got:
        raise LightGBMError(
            "histogram tune cache %s failed its digest check (%s != %s) — "
            "hand-edited or corrupted tables must not route kernels; "
            "regenerate it" % (path, want, got)
        )
    return table


def active_table(param: str = "") -> Tuple[Optional[Dict], str]:
    """The tune table a training run should freeze, or (None, "").

    ``param`` is the ``hist_tune`` config value: an explicit path (load
    failures RAISE — the user asked for this table), ``"off"`` (disable
    even the env var), or ``""`` (consult ``LIGHTGBM_TPU_HIST_TUNE``;
    ambient adoption, so failures warn once and fall back to static
    routing instead of killing the run)."""
    param = (param or "").strip()
    if param.lower() == "off":
        return None, ""
    explicit = bool(param)
    path = param or os.environ.get(ENV_PATH, "").strip()
    if not path or path.lower() == "off":
        return None, ""
    try:
        return load_table(path), path
    except LightGBMError:
        if explicit:
            raise
        log.warn_once(
            "hist-tune-env-load:%s" % path,
            "LIGHTGBM_TPU_HIST_TUNE=%s could not be loaded; continuing "
            "with static histogram routing" % path,
        )
        return None, ""


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def sweep_shapes(
    n_rows: int,
    bins_list: Sequence[int],
    num_features: int,
    k: int = 3,
    dtypes: Sequence[str] = ("float32",),
) -> List[Dict]:
    """The shape set a training at this (rows, bins) geometry will emit:
    one shape per (bucket-lattice row class, B, dtype). Row classes come
    from the grower's own lattice (ops/grow.py ``bucket_sizes``) folded
    through ``rows_bucket`` so each swept row count IS its route key."""
    from ..ops.grow import bucket_sizes
    from ..ops.histogram import rows_bucket

    rows = sorted({rows_bucket(s) for s in bucket_sizes(int(n_rows))})
    return [
        {"rows": r, "B": int(b), "K": int(k), "F": int(num_features),
         "hist_dtype": str(d)}
        for d in dtypes
        for b in bins_list
        for r in rows
    ]


def _vmem_ok(impl: str) -> bool:
    """Gate Pallas contenders on this chip's VMEM ceiling: the kernels
    budget ``hist_pallas._VMEM_BUDGET`` of scoped allocation per grid step,
    and a chip whose ``vmem_bytes`` (obs/costs.CHIP_PEAKS — the same table
    graftlint JX011 bounds blocks against) cannot hold that budget would
    fail Mosaic lowering mid-sweep instead of being skipped."""
    if not impl.startswith("pallas"):
        return True
    from ..ops import hist_pallas
    from ..ops.histogram import _default_backend
    from . import costs as costs_mod

    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        kind = None
    backend = _default_backend()
    peaks = costs_mod.chip_peaks(
        kind, platform="tpu" if backend == "tpu" else None
    )
    return float(peaks.get("vmem_bytes", 0)) >= float(
        hist_pallas._VMEM_BUDGET
    )


def candidate_impls(num_bins: int, backend: Optional[str] = None) -> List[str]:
    """The impls worth racing at a shape on this backend: supported
    (ops/histogram.impl_supported — the router's own vocabulary) and
    VMEM-feasible for the Pallas family."""
    from ..ops import histogram as hist_mod

    b = backend if backend is not None else hist_mod._default_backend()
    return [
        impl
        for impl in hist_mod.IMPLS
        if hist_mod.impl_supported(impl, num_bins, b) and _vmem_ok(impl)
    ]


def _time_impl(impl, bins, values, num_bins, chunk, hist_dtype, repeats):
    """Median wall seconds of a fully-dispatched leaf_histogram call (one
    untimed warmup run absorbs the XLA/Mosaic compile)."""
    import jax

    from ..ops.histogram import leaf_histogram

    def run():
        return leaf_histogram(
            bins, values, num_bins, chunk=chunk, impl=impl,
            hist_dtype=hist_dtype,
        )

    jax.block_until_ready(run())  # compile
    times = []
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def sweep(
    shapes: Sequence[Dict],
    repeats: int = 3,
    chunk: int = 16384,
    seed: int = 0,
) -> Dict:
    """Race every candidate impl at every shape; returns the table dict
    (save with :func:`save_table`).

    Each entry records the winner AND the per-impl medians (``times_ms``)
    so downstream gates — the tune smoke's "no slower anywhere, strictly
    faster somewhere" assertion, the bringup stage record — can audit the
    decision without re-measuring."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import histogram as hist_mod

    backend = hist_mod._default_backend()
    rng = np.random.RandomState(seed)
    entries: List[Dict] = []
    skipped: List[str] = []
    for sh in shapes:
        rows, B, K, F = (int(sh["rows"]), int(sh["B"]), int(sh["K"]),
                         int(sh["F"]))
        dt = str(sh["hist_dtype"])
        impls = candidate_impls(B, backend)
        if not impls:
            skipped.append("B=%d rows=%d (no supported impl)" % (B, rows))
            continue
        bins = jnp.asarray(rng.randint(0, B, (F, rows)).astype(np.uint8))
        vals = jnp.asarray(rng.randn(rows, K).astype(np.float32))
        times = {}
        for impl in impls:
            try:
                times[impl] = _time_impl(
                    impl, bins, vals, B, chunk, dt, repeats
                )
            except Exception as e:  # a contender that fails to lower loses
                log.warn_once(
                    "hist-tune-sweep-fail:%s:%d:%d" % (impl, B, rows),
                    "tune sweep: impl=%s failed at B=%d rows=%d (%s); "
                    "excluded from this shape's race"
                    % (impl, B, rows, str(e)[:200]),
                )
        if not times:
            skipped.append("B=%d rows=%d (every impl failed)" % (B, rows))
            continue
        winner = min(times, key=times.get)
        entries.append({
            "B": B, "K": K, "hist_dtype": dt,
            "rows_bucket": hist_mod.rows_bucket(rows), "rows": rows, "F": F,
            "impl": winner,
            "times_ms": {k: round(v * 1e3, 4) for k, v in times.items()},
        })
        # release the shape's buffers before the next allocation
        del bins, vals
    meta = {"repeats": int(repeats), "chunk": int(chunk), "seed": int(seed),
            "n_shapes": len(shapes)}
    if skipped:
        # never a silent cap: a table that skipped shapes says so
        meta["skipped"] = skipped
    return build_table(entries, backend=backend, sweep_meta=meta)


# ---------------------------------------------------------------------------
# CLI: python -m lightgbm_tpu.obs.tune
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.tune",
        description="Measure histogram kernels at the grower's bucket-shape "
        "distribution and persist the routing table "
        "(docs/HistogramRouting.md).",
    )
    ap.add_argument("--out", required=True, help="table path (atomic write)")
    ap.add_argument("--rows", type=int, default=1048576,
                    help="training row count whose bucket lattice to sweep")
    ap.add_argument("--bins", default="15,63,255",
                    help="comma-separated histogram widths (B) to sweep — "
                    "use the widths trainings actually emit (num_bin <= "
                    "max_bin: 255 for max_bin=255), NOT round powers of "
                    "two; route keys match exactly")
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--k", type=int, default=3,
                    help="value channels (grad, hess, count)")
    ap.add_argument("--dtypes", default="float32",
                    help="comma-separated hist_dtype list "
                    "(float32[,bfloat16])")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=16384)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    bins_list = [int(b) for b in args.bins.split(",") if b]
    dtypes = [d for d in args.dtypes.split(",") if d]
    shapes = sweep_shapes(
        args.rows, bins_list, args.features, k=args.k, dtypes=dtypes
    )
    t0 = time.perf_counter()
    table = sweep(shapes, repeats=args.repeats, chunk=args.chunk,
                  seed=args.seed)
    save_table(table, args.out)
    winners: Dict[str, str] = {}
    for e in table["entries"]:
        winners["B=%d,dt=%s,rows=%d" % (e["B"], e["hist_dtype"],
                                        e["rows_bucket"])] = e["impl"]
    # one-line JSON result: the bringup stage runner parses the first
    # '{'-prefixed stdout line (helpers/tpu_bringup.py _parse_result)
    print(json.dumps({
        "ok": bool(table["entries"]),
        "path": args.out,
        "digest": table["digest"],
        "backend": table["backend"],
        "device_family": table["device_family"],
        "entries": len(table["entries"]),
        "sweep_s": round(time.perf_counter() - t0, 1),
        "winners": winners,
    }), flush=True)
    return 0 if table["entries"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
