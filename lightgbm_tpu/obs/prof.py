"""Segment profiler: tree growth as separately-dispatched, fenced sub-steps.

BENCH_r05's breakdown ends at "tree growth = 95% of the iteration" — one
opaque fused XLA program. This module re-runs that program as SIX
separately-jitted, ``block_until_ready``-fenced dispatches per split, timing
each, so device time inside tree growth finally has names:

  * ``root_init``      — per-tree setup: [N, 3] accumulands, full-N root
                         histogram, root split scan
  * ``select``         — argmax over cached per-leaf best gains (+ the
                         host sync that reads the loop condition)
  * ``partition``      — node partition: the segment-permutation split
                         (DataPartition::Split analogue)
  * ``leaf_update``    — leaf-value/tree wiring scatters + leaf aux and
                         monotone windows (the gather-based score add is
                         the separate "renew+score update" phase the
                         engine timers already record)
  * ``hist_build``     — smaller-child segment histogram
  * ``hist_subtract``  — sibling-histogram subtraction + the 2-row
                         histogram-carry commit
  * ``split_scan``     — both children's split-gain scan + candidate
                         refresh

The segmented loop is built from the SAME kernels the fused grower traces —
``ops.grow.make_bucket_kernels`` (the segment seams) plus verbatim copies
of the sequential body's wiring — and :func:`profile_growth` runs the fused
``grow_tree`` on identical inputs and asserts the final models are
BITWISE-identical, so the breakdown is proven to measure the real
computation, not a lookalike.

Scope: the sequential bucketed path (the r5 default everywhere except
spec mode's batching, whose applied-split sequence is identical by design).
Configs the segmented loop does not reproduce — CEGB, histogram pools,
forced splits, EFB bundling, masked mode, parallel learners, the native
host learner, the Pallas split kernel — are refused via
:func:`unsupported_reason`; the fused path is NEVER altered by this module.

Env gating: ``LIGHTGBM_TPU_PROF_SEGMENTS=N`` makes ``engine.train`` run N
profiling iterations after training (1 when set to a non-integer truthy
value); bench.py and ``helpers/tpu_bringup.py``'s ``prof`` stage call
:func:`profile_growth` directly. Results land in the default registry as
``growth_segment_seconds_total{segment=...}`` gauges, in ``run_report()``
as a ``growth_segments_s`` section, and as ``prof.*`` Chrome-trace spans
whenever the obs tracer is live (docs/Observability.md).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..utils.log import LightGBMError
from . import registry as registry_mod
from . import sanitize as sanitize_mod
from . import trace as trace_mod

ENV_SEGMENTS = "LIGHTGBM_TPU_PROF_SEGMENTS"

#: the per-split segments (root_init/select ride alongside)
CORE_SEGMENTS = (
    "partition", "leaf_update", "hist_build", "hist_subtract", "split_scan",
)


def segments_enabled() -> bool:
    return os.environ.get(ENV_SEGMENTS, "") not in ("", "0")


def segments_iters(default: int = 1) -> int:
    """Profiling-iteration count from the env var (``=3`` -> 3 iterations;
    any non-integer truthy value -> ``default``)."""
    raw = os.environ.get(ENV_SEGMENTS, "")
    try:
        return max(int(raw), 1)
    except ValueError:
        return default


class SegmentBook:
    """Accumulated seconds/counts per segment name (thread-safe)."""

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._lock = sanitize_mod.make_lock("obs.prof.segments")

    def add(self, name: str, dt: float) -> None:
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def merge(self, other: "SegmentBook") -> None:
        with other._lock:
            items = list(other.seconds.items())
            counts = dict(other.counts)
        with self._lock:
            for k, v in items:
                self.seconds[k] = self.seconds.get(k, 0.0) + v
                self.counts[k] = self.counts.get(k, 0) + counts.get(k, 0)

    def reset(self) -> None:
        with self._lock:
            self.seconds.clear()
            self.counts.clear()


#: process-wide accumulator (every profile_growth run merges in)
SEGMENTS = SegmentBook()

#: the most recent profile_growth record — run_report()'s
#: ``growth_segments_s`` section reads it
_LAST_RECORD: Dict[str, object] = {}
_SECTION_REGISTERED = False


def _report_section():
    return dict(_LAST_RECORD.get("segments_per_tree_s") or {})


def unsupported_reason(gbdt) -> Optional[str]:
    """Why the segmented profiler cannot reproduce this trainer's grower
    bitwise (None = supported). Mirrors the gates grow_tree itself keys
    on; anything here runs the fused path untouched."""
    cfg = getattr(gbdt, "config", None)
    if cfg is None or getattr(gbdt, "train_set", None) is None:
        return "no training setup (loaded model?)"
    if gbdt.objective is None:
        return "custom objective (host-computed gradients)"
    if gbdt.train_set.num_features <= 0:
        return "no usable features"
    if cfg.num_leaves <= 1:
        return "num_leaves <= 1 grows no splits"
    if gbdt._learner_kind() != "serial":
        return "parallel learner (%s)" % gbdt._learner_kind()
    from ..ops import grow_native

    if (
        grow_native.unsupported_reason(
            cfg, gbdt.feature_meta, gbdt._forced_splits, gbdt.cegb_params,
            gbdt.num_bins, gbdt.num_group_bins,
        )
        is None
    ):
        return "native host learner in use (device_type=cpu)"
    if cfg.tpu_hist_mode != "bucketed":
        return "hist_mode %r (segments exist only for the bucketed layout)" % (
            cfg.tpu_hist_mode,
        )
    if gbdt.cegb_params.enabled:
        return "CEGB re-ranks candidates per split (order-dependent)"
    if gbdt._forced_splits:
        return "forced-splits preamble"
    slots = gbdt._hist_pool_slots()
    if slots is not None and slots < cfg.num_leaves:
        return "histogram pool (per-split slot state)"
    if gbdt.num_group_bins is not None:
        return "EFB-bundled bins (group remap not segmented)"
    from ..ops.grow import _ENV_SPLIT_IMPL

    if _ENV_SPLIT_IMPL == "pallas":
        return "LIGHTGBM_TPU_SPLIT_IMPL=pallas (kernelized split scan)"
    return None


# --------------------------------------------------------------------------
# segment kernels: jitted sub-steps mirroring grow_tree's sequential body
# --------------------------------------------------------------------------

def _build_kernels(gbdt):
    """Build (once per trainer) the jitted segment functions. Bodies mirror
    grow_tree's sequential bucketed path op for op — the partition and
    segment-histogram kernels are literally shared via make_bucket_kernels,
    and profile_growth's bitwise check pins the rest."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..ops.grow import (
        PackedBest,
        PackedTree,
        _BEST_I,
        _LAUX_MAX,
        _LAUX_MIN,
        _LAUX_ND,
        _LAUX_SG,
        _LAUX_SH,
        _NODE_I_COLS,
        _pack_best,
        _unpack_tree,
        make_bucket_kernels,
    )
    from ..ops.histogram import leaf_histogram, leaf_values
    from ..ops.split import calculate_leaf_output, find_best_split

    cfg = gbdt.config
    bins = gbdt.bins_dev
    bins_nf = gbdt.bins_dev_nf
    feature_meta = gbdt.feature_meta
    params = gbdt.split_params
    two_way = gbdt._two_way
    M = cfg.num_leaves
    B = gbdt.num_bins
    N = bins.shape[1]
    max_depth = cfg.max_depth
    chunk = cfg.tpu_hist_chunk
    hist_dtype = cfg.tpu_hist_dtype
    # the run's FROZEN histogram route: the segmented kernels must resolve
    # every shape class to the same impl the fused grower traced, or the
    # bitwise-identity proof below would compare different arithmetic
    hist_route = getattr(gbdt, "_hist_route", None)
    f32 = jnp.float32
    neg_inf = jnp.float32(-jnp.inf)
    mono_arr = feature_meta["monotone"].astype(jnp.int32)

    kern = make_bucket_kernels(
        bins, feature_meta, B, num_group_bins=None, bins_nf=bins_nf,
        chunk=chunk, hist_dtype=hist_dtype, kb=0, hist_route=hist_route,
    )

    def depth_gate(gain, depth):
        if max_depth > 0:
            return jnp.where(depth >= max_depth, neg_inf, gain)
        return gain

    def best_scan(hist2, sg2, sh2, nd2, mn2, mx2, fmask):
        return jax.vmap(
            lambda h, sg, sh, nd, mn, mx: find_best_split(
                h, sg, sh, nd, mn, mx, feature_meta, fmask, params,
                two_way=two_way,
            )
        )(hist2, sg2, sh2, nd2, mn2, mx2)

    def root_fn(grad, hess, bag_mask, fmask):
        vals_all = leaf_values(grad, hess, bag_mask)
        root_hist = leaf_histogram(
            bins, vals_all, B, chunk=chunk, hist_dtype=hist_dtype,
            route=hist_route,
        )
        root_g = jnp.sum(grad * bag_mask)
        root_h = jnp.sum(hess * bag_mask)
        root_n = jnp.sum(bag_mask)
        no_con_min = jnp.full((M,), -jnp.inf, f32)
        no_con_max = jnp.full((M,), jnp.inf, f32)
        tree0 = PackedTree(
            num_leaves=jnp.int32(1),
            node_f=jnp.zeros((M, 3), f32),
            node_i=jnp.zeros((M, 4), jnp.int32),
            node_b=jnp.zeros((M, 1 + B), bool),
            leaf_f=jnp.zeros((M, 3), f32).at[0].set(
                jnp.stack([
                    calculate_leaf_output(root_g, root_h, params),
                    root_n, root_h,
                ])
            ),
            leaf_i=jnp.concatenate(
                [jnp.full((M, 1), -1, jnp.int32), jnp.zeros((M, 1), jnp.int32)],
                axis=1,
            ),
        )
        hist0 = jnp.zeros((M, bins.shape[0], B, 3), f32).at[0].set(root_hist)
        laux0 = jnp.stack(
            [
                jnp.zeros((M,), f32).at[0].set(root_g),
                jnp.zeros((M,), f32).at[0].set(root_h),
                jnp.zeros((M,), f32).at[0].set(root_n),
                no_con_min,
                no_con_max,
            ],
            axis=-1,
        )
        root_split = find_best_split(
            root_hist, root_g, root_h, root_n, no_con_min[0], no_con_max[0],
            feature_meta, fmask, params, two_way=two_way,
        )
        row = _pack_best(root_split)
        f0 = jnp.zeros((M, row.f.shape[-1]), f32).at[:, 0].set(-jnp.inf)
        best0 = PackedBest(
            f0.at[0].set(row.f),
            jnp.zeros((M, len(_BEST_I)), jnp.int32).at[0].set(row.i),
            jnp.zeros((M, row.b.shape[-1]), bool).at[0].set(row.b),
        )
        order0 = jnp.arange(N, dtype=jnp.int32)
        leaf_begin0 = jnp.zeros((M,), jnp.int32)
        leaf_phys0 = jnp.zeros((M,), jnp.int32).at[0].set(N)
        return vals_all, tree0, best0, laux0, hist0, order0, leaf_begin0, leaf_phys0

    def select_fn(best_f):
        return (
            jnp.argmax(best_f[:, 0]).astype(jnp.int32),
            jnp.max(best_f[:, 0]),
        )

    def partition_fn(order, leaf_begin, leaf_phys, best_i, best_b,
                     best_leaf, new_leaf):
        f = best_i[best_leaf, 0]
        thr = best_i[best_leaf, 1]
        dleft = best_b[best_leaf, 0]
        member = best_b[best_leaf, 1:]
        pbegin = leaf_begin[best_leaf]
        pphys = leaf_phys[best_leaf]
        order2, left_cnt = kern.partition_batch(
            order, pbegin[None], pphys[None], f[None], thr[None],
            dleft[None], member[None],
        )
        left_phys = left_cnt[0]
        right_phys = pphys - left_phys
        leaf_begin2 = leaf_begin.at[new_leaf].set(pbegin + left_phys)
        leaf_phys2 = (
            leaf_phys.at[best_leaf].set(left_phys).at[new_leaf].set(right_phys)
        )
        return order2, leaf_begin2, leaf_phys2

    def wiring_fn(tree, laux, best_f, best_i, best_b, best_leaf, new_leaf):
        # exactly apply_split's tree-wiring + leaf-aux block (ops/grow.py)
        t = tree
        node = new_leaf - 1  # sequential invariant: it == num_leaves - 1
        f = best_i[best_leaf, 0]
        thr = best_i[best_leaf, 1]
        child_idx = jnp.stack([best_leaf, new_leaf])
        parent = t.leaf_i[best_leaf, 0]
        prow = jnp.where(parent >= 0, parent, M - 1)
        enc_old = -(best_leaf + 1)
        old_plc = t.node_i[prow, 2]
        old_prc = t.node_i[prow, 3]
        new_plc = jnp.where((parent >= 0) & (old_plc == enc_old), node, old_plc)
        new_prc = jnp.where((parent >= 0) & (old_prc == enc_old), node, old_prc)
        depth_child = t.leaf_i[best_leaf, 1] + 1
        parent_aux = laux[best_leaf]
        parent_value = calculate_leaf_output(
            parent_aux[_LAUX_SG], parent_aux[_LAUX_SH], params
        )
        node_i = t.node_i.at[
            jnp.stack([node, node, node, node, prow, prow]),
            _NODE_I_COLS,
        ].set(
            jnp.stack([
                f, thr, -(best_leaf + 1), -(new_leaf + 1), new_plc, new_prc,
            ])
        )
        tree2 = PackedTree(
            num_leaves=t.num_leaves + 1,
            node_f=t.node_f.at[node].set(
                jnp.stack([best_f[best_leaf, 0], parent_value,
                           parent_aux[_LAUX_ND]])
            ),
            node_i=node_i,
            node_b=t.node_b.at[node].set(best_b[best_leaf].astype(bool)),
            leaf_f=t.leaf_f.at[child_idx].set(
                jnp.stack([
                    jnp.stack([best_f[best_leaf, 7], best_f[best_leaf, 3],
                               best_f[best_leaf, 2]]),
                    jnp.stack([best_f[best_leaf, 8], best_f[best_leaf, 6],
                               best_f[best_leaf, 5]]),
                ])
            ),
            leaf_i=t.leaf_i.at[child_idx].set(
                jnp.stack([
                    jnp.stack([node, depth_child]),
                    jnp.stack([node, depth_child]),
                ])
            ),
        )
        mono_f = mono_arr[f]
        mid = (best_f[best_leaf, 7] + best_f[best_leaf, 8]) / 2.0
        pmin = parent_aux[_LAUX_MIN]
        pmax = parent_aux[_LAUX_MAX]
        l_min = jnp.where(mono_f < 0, mid, pmin)
        l_max = jnp.where(mono_f > 0, mid, pmax)
        r_min = jnp.where(mono_f > 0, mid, pmin)
        r_max = jnp.where(mono_f < 0, mid, pmax)
        laux2 = laux.at[child_idx].set(
            jnp.stack([
                jnp.stack([best_f[best_leaf, 1], best_f[best_leaf, 2],
                           best_f[best_leaf, 3], l_min, l_max]),
                jnp.stack([best_f[best_leaf, 4], best_f[best_leaf, 5],
                           best_f[best_leaf, 6], r_min, r_max]),
            ])
        )
        return tree2, laux2, depth_child

    def hist_fn(vals_all, order, leaf_begin, leaf_phys, best_f, best_leaf,
                new_leaf):
        pbegin = leaf_begin[best_leaf]
        left_phys = leaf_phys[best_leaf]
        right_phys = leaf_phys[new_leaf]
        left_smaller = best_f[best_leaf, 3] <= best_f[best_leaf, 6]
        small_begin = jnp.where(left_smaller, pbegin, pbegin + left_phys)
        small_cnt = jnp.where(left_smaller, left_phys, right_phys)
        return kern.segment_histogram_batch(
            vals_all, order, small_begin[None], small_cnt[None]
        )[0]

    def subtract_fn(hist, small_hist, best_f, best_leaf, new_leaf):
        left_smaller = best_f[best_leaf, 3] <= best_f[best_leaf, 6]
        small_idx = jnp.where(left_smaller, best_leaf, new_leaf)
        large_idx = jnp.where(left_smaller, new_leaf, best_leaf)
        parent_hist = hist[best_leaf]
        large_hist = parent_hist - small_hist
        return hist.at[jnp.stack([small_idx, large_idx])].set(
            jnp.stack([small_hist, large_hist])
        )

    def scan_fn(best_fio, hist, laux, fmask, best_leaf, new_leaf, depth_child):
        best_fa, best_ia, best_ba = best_fio
        child_idx = jnp.stack([best_leaf, new_leaf])
        ch_hist = hist[child_idx]
        ch_aux = laux[child_idx]
        ch_split = best_scan(
            ch_hist, ch_aux[:, _LAUX_SG], ch_aux[:, _LAUX_SH],
            ch_aux[:, _LAUX_ND], ch_aux[:, _LAUX_MIN], ch_aux[:, _LAUX_MAX],
            fmask,
        )
        ch_gain = depth_gate(ch_split.gain, depth_child)
        pb2 = _pack_best(ch_split._replace(gain=ch_gain))
        return (
            best_fa.at[child_idx].set(pb2.f),
            best_ia.at[child_idx].set(pb2.i),
            best_ba.at[child_idx].set(pb2.b),
        )

    def final_fn(tree, order, leaf_begin, leaf_phys):
        # leaf-id reconstruction, verbatim from grow_tree's bucketed tail
        key = jnp.where(
            leaf_phys > 0,
            leaf_begin,
            N + jnp.arange(M, dtype=jnp.int32),
        )
        ordl = jnp.argsort(key)
        slot = jnp.searchsorted(
            key[ordl], jnp.arange(N, dtype=jnp.int32), side="right"
        ) - 1
        pos_leaf = ordl[jnp.clip(slot, 0, M - 1)].astype(jnp.int32)
        out_leaf_id = jnp.zeros((N,), jnp.int32).at[order].set(pos_leaf)
        return _unpack_tree(tree, M), out_leaf_id

    jit = jax.jit
    return {
        "root": jit(root_fn),
        "select": jit(select_fn),
        "partition": jit(partition_fn, donate_argnums=(0, 1, 2)),
        "wiring": jit(wiring_fn, donate_argnums=(0, 1)),
        "hist": jit(hist_fn),
        "subtract": jit(subtract_fn, donate_argnums=(0,)),
        "scan": jit(scan_fn, donate_argnums=(0,)),
        "final": jit(final_fn),
        "_meta": {
            "key": (M, N, B, max_depth, chunk, hist_dtype, two_way, params),
        },
    }


def _timed(book: SegmentBook, name: str, fn, *args):
    import jax

    with trace_mod.span("prof.%s" % name, cat="prof.segment"):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        book.add(name, time.perf_counter() - t0)
    return out


def segmented_grow_tree(gbdt, grad, hess, bag_mask, fmask,
                        book: Optional[SegmentBook] = None):
    """Grow ONE tree via the fenced segment dispatches; returns
    (TreeArrays, leaf_id [N]) bitwise-equal to the fused grower's, with the
    per-segment seconds accumulated into ``book`` (and SEGMENTS)."""
    reason = unsupported_reason(gbdt)
    if reason is not None:
        raise LightGBMError("segment profiler unsupported here: %s" % reason)
    cfg = gbdt.config
    key = (
        cfg.num_leaves, gbdt.bins_dev.shape[1], gbdt.num_bins, cfg.max_depth,
        cfg.tpu_hist_chunk, cfg.tpu_hist_dtype, gbdt._two_way,
        gbdt.split_params,
    )
    kernels = getattr(gbdt, "_prof_seg_kernels", None)
    if kernels is None or kernels["_meta"]["key"] != key:
        kernels = _build_kernels(gbdt)
        gbdt._prof_seg_kernels = kernels
    local = book if book is not None else SegmentBook()
    M = cfg.num_leaves

    with trace_mod.span("prof.segmented_tree", cat="prof"):
        (vals_all, tree, best, laux, hist, order, leaf_begin,
         leaf_phys) = _timed(
            local, "root_init", kernels["root"], grad, hess, bag_mask, fmask
        )
        best_f, best_i, best_b = best
        it = 0
        while it < M - 1:
            best_leaf, gain = _timed(local, "select", kernels["select"], best_f)
            if not float(np.asarray(gain)) > 0.0:
                break
            # == tree.num_leaves on the sequential path; a host int, NOT the
            # device scalar aliasing the donated tree carry (donate(a), a)
            new_leaf = it + 1
            order, leaf_begin, leaf_phys = _timed(
                local, "partition", kernels["partition"],
                order, leaf_begin, leaf_phys, best_i, best_b, best_leaf,
                new_leaf,
            )
            tree, laux, depth_child = _timed(
                local, "leaf_update", kernels["wiring"],
                tree, laux, best_f, best_i, best_b, best_leaf, new_leaf,
            )
            small_hist = _timed(
                local, "hist_build", kernels["hist"],
                vals_all, order, leaf_begin, leaf_phys, best_f, best_leaf,
                new_leaf,
            )
            hist = _timed(
                local, "hist_subtract", kernels["subtract"],
                hist, small_hist, best_f, best_leaf, new_leaf,
            )
            best_f, best_i, best_b = _timed(
                local, "split_scan", kernels["scan"],
                (best_f, best_i, best_b), hist, laux, fmask, best_leaf,
                new_leaf, depth_child,
            )
            it += 1
        ta, leaf_id = _timed(
            local, "finalize", kernels["final"], tree, order, leaf_begin,
            leaf_phys,
        )
    if book is None:
        SEGMENTS.merge(local)
    return ta, leaf_id, it, local


def _trees_equal(ta_a, lid_a, ta_b, lid_b) -> bool:
    for a, b in zip(ta_a, ta_b):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False
    return bool(np.array_equal(np.asarray(lid_a), np.asarray(lid_b)))


def profile_growth(booster_or_gbdt, iters: int = 2,
                   registry=None) -> Dict[str, object]:
    """Run ``iters`` profiling iterations: per iteration, grow one tree
    FUSED (timed as the reference) and once SEGMENTED (timed per segment),
    from identical inputs, and verify the two models are bitwise-identical.

    Never mutates the trainer: gradients come from the current scores, no
    tree is appended and no score is updated, so profiling can run after a
    bench/training pass without perturbing its state. Returns the record
    (also stored for run_report()'s ``growth_segments_s`` section and
    published as registry gauges). Raises LightGBMError when
    :func:`unsupported_reason` says the config cannot be segmented.
    """
    import jax

    from ..ops.grow import grow_tree, spec_batch_slots
    from ..ops.histogram import leaf_histogram
    from . import costs as costs_mod

    gbdt = getattr(booster_or_gbdt, "_gbdt", booster_or_gbdt)
    reason = unsupported_reason(gbdt)
    if reason is not None:
        raise LightGBMError("segment profiler unsupported here: %s" % reason)
    cfg = gbdt.config
    K = gbdt.num_tree_per_iteration
    grad_all, hess_all = gbdt._compute_gradients([0.0] * K)
    bag = gbdt._bag_mask
    if cfg.feature_fraction >= 1.0:
        fmask = gbdt._fmask_all
    else:
        # draw a mask WITHOUT consuming the trainer's RNG stream — the
        # never-mutates guarantee includes the feature-sampling position
        # (the checkpoint layer snapshots it for byte-identical resume)
        state = gbdt._feat_rng.get_state()
        fmask = gbdt._sample_features()
        gbdt._feat_rng.set_state(state)
    common = dict(
        num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
        num_bins=gbdt.num_bins, num_group_bins=None,
        params=gbdt.split_params, chunk=cfg.tpu_hist_chunk,
        hist_dtype=cfg.tpu_hist_dtype, hist_mode="bucketed",
        two_way=gbdt._two_way, bins_nf=gbdt.bins_dev_nf,
        hist_route=getattr(gbdt, "_hist_route", None),
    )
    from ..ops.histogram import route_rows_variant as _rrv

    kb = spec_batch_slots(
        cfg.num_leaves, hist_mode="bucketed",
        route_rows_variant=_rrv(
            getattr(gbdt, "_hist_route", None), num_bins=gbdt.num_bins,
            hist_dtype=cfg.tpu_hist_dtype,
            n_rows=int(gbdt.bins_dev.shape[1]),
        ),
    )
    book = SegmentBook()
    warm_book = SegmentBook()  # warmup pass: compiles land here, not in the record
    fused_s = 0.0
    bitwise = True
    splits_total = 0
    trees = 0
    # pass 0 is an UNTIMED warmup: it compiles the fused program and every
    # segment kernel, so the recorded seconds are steady-state device+dispatch
    # time — the quantity the 15%-of-fused acceptance bound is about
    for i in range(max(iters, 1) + 1):
        timed = i > 0
        for k in range(K if timed else 1):
            grad, hess = grad_all[k], hess_all[k]
            with trace_mod.span("prof.fused_tree", cat="prof"):
                t0 = time.perf_counter()
                ta_f, lid_f = grow_tree(
                    gbdt.bins_dev, grad, hess, bag, fmask, gbdt.feature_meta,
                    **common,
                )
                jax.block_until_ready((ta_f, lid_f))
                if timed:
                    fused_s += time.perf_counter() - t0
            ta_s, lid_s, splits, _ = segmented_grow_tree(
                gbdt, grad, hess, bag, fmask,
                book=book if timed else warm_book,
            )
            bitwise = bitwise and _trees_equal(ta_f, lid_f, ta_s, lid_s)
            if timed:
                splits_total += splits
                trees += 1
    SEGMENTS.merge(book)

    if costs_mod.enabled():
        costs_mod.COSTS.harvest(
            "ops.grow_tree", grow_tree,
            (gbdt.bins_dev, grad_all[0], hess_all[0], bag, fmask,
             gbdt.feature_meta),
            common,
        )
        costs_mod.COSTS.harvest(
            "ops.leaf_histogram", leaf_histogram,
            (gbdt.bins_dev,
             jax.ShapeDtypeStruct((gbdt.bins_dev.shape[1], 3),
                                  np.float32),
             gbdt.num_bins),
            dict(chunk=cfg.tpu_hist_chunk, hist_dtype=cfg.tpu_hist_dtype,
                 route=getattr(gbdt, "_hist_route", None)),
        )

    per_tree = {
        name: round(s / max(trees, 1), 6)
        for name, s in sorted(book.seconds.items())
    }
    seg_sum = sum(book.seconds.values()) / max(trees, 1)
    fused_per_tree = fused_s / max(trees, 1)
    record: Dict[str, object] = {
        "iters": iters,
        "trees": trees,
        "rows": int(gbdt.bins_dev.shape[1]),
        "num_leaves": int(cfg.num_leaves),
        "splits_per_tree": round(splits_total / max(trees, 1), 2),
        "grow_mode": "spec" if kb else "seq",
        "segments_per_tree_s": per_tree,
        "segment_counts": dict(sorted(book.counts.items())),
        "segment_sum_s_per_tree": round(seg_sum, 6),
        "fused_growth_s_per_tree": round(fused_per_tree, 6),
        "segment_sum_ratio": round(seg_sum / max(fused_per_tree, 1e-12), 4),
        "bitwise_identical": bool(bitwise),
    }
    _publish(record, registry)
    return record


def _publish(record: Dict[str, object], registry=None) -> None:
    global _SECTION_REGISTERED
    reg = registry if registry is not None else registry_mod.REGISTRY
    g = reg.gauge("growth_segment_seconds_total")
    for name, secs in SEGMENTS.seconds.items():
        # the serial profiler's segments are all on-device compute; the
        # sharded profiler (obs/dist.py) publishes its psum segments into
        # the same family with collective="true"
        g.set(secs, segment=name, collective="false")
    reg.gauge("growth_segment_sum_ratio").set(
        float(record.get("segment_sum_ratio") or 0.0)
    )
    reg.gauge("growth_segments_bitwise_ok").set(
        1.0 if record.get("bitwise_identical") else 0.0
    )
    _LAST_RECORD.clear()
    _LAST_RECORD.update(record)
    # register the report section on the SAME registry the gauges landed on
    # (the default registers once; a custom registry gets its own hookup)
    if reg is not registry_mod.REGISTRY:
        reg.register_report_section("growth_segments_s", _report_section)
    elif not _SECTION_REGISTERED:
        _SECTION_REGISTERED = True
        reg.register_report_section("growth_segments_s", _report_section)


def last_record() -> Dict[str, object]:
    return dict(_LAST_RECORD)


def reset() -> None:
    SEGMENTS.reset()
    _LAST_RECORD.clear()
