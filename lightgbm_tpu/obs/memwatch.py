"""Device-memory accounting: snapshots at named points + shape-math attribution.

HBM is the binding resource on chip: the histogram carry, the spec-mode
right-child cache (``spec_rhist``), the ``[K, N]`` score matrix and the
packed serving tensors together decide whether a shape fits. This module
makes their footprint visible per run instead of rediscovered by advisors:

 * :func:`snapshot` — record device ``memory_stats()`` (bytes_in_use /
   peak_bytes_in_use where the backend reports them; the CPU backend
   reports None) plus the live-buffer census from ``jax.live_arrays()``
   at a named point. Training takes one post-bin (models/gbdt.py) and the
   bench one post-run; serving exposes the device gauges on every /metrics
   scrape. Automatic per-chunk snapshots are opt-in via
   ``LIGHTGBM_TPU_MEMWATCH=1`` (``auto_snapshot``) — ``light=True`` skips
   the live-buffer walk so chunk boundaries stay cheap.
 * shape-math attribution — :func:`attribute_training` /
   :func:`attribute_packed` compute the KNOWN large carries' sizes from
   their shapes alone (hist buffer, spec_rhist, scores, bin matrix, packed
   ensemble tensors), so a memory regression names its tensor.
   tests/test_obs.py pins the shape math to the actual buffer sizes.

Registry wiring: every snapshot sets ``device_bytes_in_use`` /
``device_peak_bytes`` / ``live_buffer_bytes`` gauges on the default
registry; obs/__init__.py additionally registers ``device_peak_bytes`` as a
pull gauge so a /metrics scrape is always current. jax is imported lazily —
importing this module never touches a backend.
"""
from __future__ import annotations

import os
import threading

from . import sanitize as sanitize_mod
import time
from collections import deque
from typing import Dict, List, Optional

from . import registry as registry_mod

ENV_MEMWATCH = "LIGHTGBM_TPU_MEMWATCH"

_SNAPSHOTS: deque = deque(maxlen=256)
_LOCK = sanitize_mod.make_lock("obs.memwatch")

F32_BYTES = 4


def memwatch_enabled() -> bool:
    return os.environ.get(ENV_MEMWATCH, "") not in ("", "0")


def _device_stats() -> List[Dict[str, float]]:
    """Per-device memory_stats dicts (empty on backends that report none)."""
    import jax

    out = []
    try:
        devices = jax.local_devices()
    except RuntimeError:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except (AttributeError, NotImplementedError):
            stats = None
        if stats:
            out.append({
                "device": str(d),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            })
    return out


def live_buffer_bytes() -> Dict[str, int]:
    """Census of live device arrays: {count, bytes} via jax.live_arrays()."""
    import jax

    n = 0
    total = 0
    try:
        for a in jax.live_arrays():
            n += 1
            try:
                total += int(a.nbytes)
            except (AttributeError, TypeError):
                pass
    except RuntimeError:
        pass  # backend not initialized yet: nothing lives on it either
    return {"count": n, "bytes": total}


def peak_device_bytes() -> float:
    """Max per-device peak_bytes_in_use, falling back to the live-buffer
    total where the backend keeps no allocator stats (CPU). The /metrics
    ``device_peak_bytes`` gauge pulls this."""
    stats = _device_stats()
    if stats:
        return float(max(s["peak_bytes_in_use"] for s in stats))
    return float(live_buffer_bytes()["bytes"])


def snapshot(tag: str, registry=None, light: bool = False) -> Dict[str, object]:
    """Record device memory at a named point; returns (and stores) the record.

    ``light=True`` skips the live-buffer walk (allocator stats only) for
    points inside hot loops (per-chunk boundaries)."""
    reg = registry if registry is not None else registry_mod.REGISTRY
    rec: Dict[str, object] = {"tag": tag, "t": time.time()}
    stats = _device_stats()
    if stats:
        rec["bytes_in_use"] = max(s["bytes_in_use"] for s in stats)
        rec["peak_bytes_in_use"] = max(s["peak_bytes_in_use"] for s in stats)
        rec["devices"] = stats
    if not light:
        live = live_buffer_bytes()
        rec["live_buffer_count"] = live["count"]
        rec["live_buffer_bytes"] = live["bytes"]
        reg.gauge("live_buffer_bytes").set(live["bytes"])
    if "bytes_in_use" in rec:
        reg.gauge("device_bytes_in_use").set(rec["bytes_in_use"])
        reg.gauge("device_peak_bytes").set(rec["peak_bytes_in_use"])
    elif "live_buffer_bytes" in rec:
        # CPU backend: the live census is the only footprint signal
        reg.gauge("device_peak_bytes").set(rec["live_buffer_bytes"])
    with _LOCK:
        _SNAPSHOTS.append(rec)
    return rec


def auto_snapshot(tag: str, light: bool = False) -> Optional[Dict[str, object]]:
    """``snapshot`` gated on LIGHTGBM_TPU_MEMWATCH — the hook training code
    calls unconditionally at its named points."""
    if not memwatch_enabled():
        return None
    try:
        return snapshot(tag, light=light)
    except Exception:
        return None  # accounting must never take training down


def snapshots() -> List[Dict[str, object]]:
    with _LOCK:
        return list(_SNAPSHOTS)


def reset() -> None:
    with _LOCK:
        _SNAPSHOTS.clear()


# --------------------------------------------------------------------------
# shape-math attribution of the known large carries
# --------------------------------------------------------------------------

def hist_carry_bytes(rows: int, num_features: int, num_bins: int) -> int:
    """[rows, F, B, 3] f32 histogram carry (rows = pool slots or num_leaves)."""
    return rows * num_features * num_bins * 3 * F32_BYTES


def spec_rhist_bytes(num_leaves: int, num_features: int, num_bins: int) -> int:
    """[M, F, B, 3] f32 spec-mode right-child cache — same shape family as
    the hist carry, i.e. spec mode ~doubles the histogram-carry footprint
    (ADVICE round-5 #2). Donated across trees since the obs PR."""
    return num_leaves * num_features * num_bins * 3 * F32_BYTES


def scores_bytes(num_class: int, num_data: int) -> int:
    return num_class * num_data * F32_BYTES


def attribute_training(gbdt) -> Dict[str, object]:
    """Shape-math footprint of a GBDT trainer's resident device carries.

    Reads shapes (never data) defensively — works mid-training and on
    loaded boosters missing the training attributes."""
    out: Dict[str, object] = {}
    meta = getattr(gbdt, "feature_meta", None)
    cfg = getattr(gbdt, "config", None)
    if meta is None or cfg is None:
        return out
    F = int(meta["num_bin"].shape[0])
    B = int(getattr(gbdt, "num_bins", 0))
    M = int(cfg.num_leaves)
    slots = gbdt._hist_pool_slots()
    rows = slots if slots is not None else M
    out["hist_carry"] = {
        "shape": [rows, F, B, 3],
        "bytes": hist_carry_bytes(rows, F, B),
        "donated": getattr(gbdt, "_hist_buf", None) is not None,
    }
    from ..ops.grow import spec_batch_slots
    from ..ops.histogram import route_rows_variant

    kb = spec_batch_slots(
        M,
        hist_mode=cfg.tpu_hist_mode,
        has_lazy_cegb=gbdt.cegb_params.has_lazy,
        pooled=slots is not None and slots < M,
        cegb_on=gbdt.cegb_params.enabled,
        route_rows_variant=route_rows_variant(
            getattr(gbdt, "_hist_route", None),
            num_bins=getattr(gbdt, "num_group_bins", None) or B,
            hist_dtype=cfg.tpu_hist_dtype,
            n_rows=getattr(gbdt, "num_data", None),
        ),
    )
    if kb:
        out["spec_rhist"] = {
            "shape": [M, F, B, 3],
            "bytes": spec_rhist_bytes(M, F, B),
            "donated": getattr(gbdt, "_spec_buf", None) is not None,
            "spec_k": kb,
        }
    K = int(getattr(gbdt, "num_tree_per_iteration", 1))
    N = int(getattr(gbdt, "num_data", 0))
    out["scores"] = {"shape": [K, N], "bytes": scores_bytes(K, N)}
    bins = getattr(gbdt, "bins_dev", None)
    if bins is not None:
        out["bins"] = {
            "shape": list(bins.shape), "bytes": int(bins.nbytes),
        }
    out["total_bytes"] = sum(
        v["bytes"] for v in out.values() if isinstance(v, dict)
    )
    return out


def attribute_packed(ensemble) -> Dict[str, object]:
    """Per-tensor footprint of a PackedEnsemble's device arrays."""
    packed = ensemble.packed
    fields: Dict[str, int] = {}
    total = 0
    for name, arr in zip(packed._fields, packed):
        b = int(arr.nbytes)
        fields[name] = b
        total += b
    return {
        "num_trees": int(ensemble.num_trees),
        "fields_bytes": fields,
        "total_bytes": total,
    }
