"""Model statistics: importance evolution, bin occupancy, leaf shape.

The second piece of the model/data observability tier (docs/Observability.md
§Model & data observability). Everything here is derived from HOST state —
materialized trees (models/tree.py) and the numpy binned matrix — so it never
touches the jitted programs: enabling it cannot retrace, and the trained
model is bitwise-unaffected.

Three surfaces, all pull-based and disabled by default
(``LIGHTGBM_TPU_MODELSTATS=1`` or the ``model_stats`` training parameter):

  * **importance evolution** — cumulative gain/split feature importance
    sampled along the boosting sequence (building on
    ``GBDT.feature_importance``), answering "when did feature 7 take over".
  * **train bin occupancy** — per-feature histograms of the binned training
    matrix, computed once from the host bins; the reference distribution
    the serve-time drift monitor (serve/drift.py) compares live traffic to.
  * **leaf shape** — leaf-depth and split-gain distributions over the trees.

``publish(booster)`` sets registry gauges (``model_feature_importance``,
``model_leaf_depth``, ``model_split_gain``, ``model_trees``) and registers a
``model_stats`` run-report section so bench/bringup artifacts and /metrics
carry the same numbers.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..utils import log
from . import registry as registry_mod

ENV_MODELSTATS = "LIGHTGBM_TPU_MODELSTATS"

#: features kept in the labeled importance gauges / report tables
TOP_K_FEATURES = 10
#: sample points along the boosting sequence for the evolution series
EVOLUTION_POINTS = 10


def env_enabled() -> bool:
    return os.environ.get(ENV_MODELSTATS, "") not in ("", "0")


# ---------------------------------------------------------------------------
# derivations (pure host numpy)
# ---------------------------------------------------------------------------

def importance_evolution(
    gbdt, points: int = EVOLUTION_POINTS, top_k: int = TOP_K_FEATURES
) -> List[Dict]:
    """Cumulative feature importance sampled at ``points`` iteration marks:
    ``[{"iteration": i, "gain": {feat: v, ...}, "split": {...}}, ...]``.
    One pass over the trees — O(total splits), not points x trees."""
    trees = gbdt.trees()
    K = max(gbdt.num_tree_per_iteration, 1)
    n_iter = len(trees) // K
    if n_iter == 0:
        return []
    F = gbdt.max_feature_idx + 1
    marks = sorted({
        max(1, round(n_iter * (p + 1) / points)) for p in range(points)
    })
    gain = np.zeros(F, np.float64)
    split = np.zeros(F, np.float64)
    out: List[Dict] = []
    mi = 0
    for it in range(n_iter):
        for k in range(K):
            t = trees[it * K + k]
            if t is None or t.num_leaves <= 1:
                continue
            n1 = t.num_leaves - 1
            np.add.at(gain, t.split_feature[:n1], t.split_gain[:n1].astype(np.float64))
            np.add.at(split, t.split_feature[:n1], 1.0)
        while mi < len(marks) and it + 1 == marks[mi]:
            out.append({
                "iteration": it + 1,
                "gain": _top(gain, top_k),
                "split": _top(split, top_k),
            })
            mi += 1
    return out


def _top(arr: np.ndarray, k: int) -> Dict[str, float]:
    idx = np.argsort(-arr)[:k]
    return {
        str(int(i)): round(float(arr[i]), 6) for i in idx if arr[i] > 0
    }


def train_bin_occupancy(binned) -> Optional[List[np.ndarray]]:
    """Per used-feature bin-count histograms of the training matrix, from
    the host bins (one bincount per feature — ~N*F int reads, done once).
    Returns None for EFB-bundled datasets (bins are group-encoded there;
    decoding per-feature occupancy would rebuild the bundler's remap)."""
    if binned is None or getattr(binned, "is_bundled", False):
        return None
    bins = np.asarray(binned.bins)
    out: List[np.ndarray] = []
    for f, m in enumerate(binned.mappers):
        out.append(np.bincount(bins[f].astype(np.int64), minlength=m.num_bin))
    return out


def occupancy_summary(hists: Optional[List[np.ndarray]], binned) -> List[Dict]:
    """Compact per-feature occupancy digest for the report section: bins
    used, top-bin share, normalized entropy (1.0 = uniform over used bins)."""
    if hists is None or binned is None:
        return []
    out: List[Dict] = []
    names = binned.feature_names
    for f, h in enumerate(hists):
        total = float(h.sum())
        if total <= 0:
            continue
        p = h[h > 0] / total
        ent = float(-(p * np.log(p)).sum())
        norm = float(np.log(len(p))) if len(p) > 1 else 1.0
        orig = binned.used_feature_idx[f]
        out.append({
            "feature": names[orig] if orig < len(names) else str(orig),
            "bins_used": int((h > 0).sum()),
            "num_bin": int(len(h)),
            "top_bin_share": round(float(h.max()) / total, 4),
            "entropy_ratio": round(ent / norm if norm else 1.0, 4),
        })
    return out


def leaf_stats(trees) -> Dict[str, object]:
    """Leaf-depth and split-gain distributions over the materialized trees."""
    depths: List[int] = []
    gains: List[float] = []
    leaves: List[int] = []
    for t in trees:
        if t is None or t.num_leaves <= 1:
            continue
        depths.extend(int(d) for d in t.leaf_depths())
        gains.extend(float(g) for g in t.split_gain[: t.num_leaves - 1])
        leaves.append(int(t.num_leaves))
    if not leaves:
        return {"trees_with_splits": 0}
    d = np.asarray(depths, np.float64)
    g = np.asarray(gains, np.float64)
    return {
        "trees_with_splits": len(leaves),
        "leaves_mean": round(float(np.mean(leaves)), 2),
        "depth_mean": round(float(d.mean()), 3),
        "depth_max": int(d.max()),
        "depth_p90": float(np.percentile(d, 90)),
        "gain_total": round(float(g.sum()), 4),
        "gain_max": round(float(g.max()), 4),
        "gain_p50": round(float(np.percentile(g, 50)), 6),
    }


# ---------------------------------------------------------------------------
# publication (gauges + run-report section)
# ---------------------------------------------------------------------------

def publish(booster, registry=None, top_k: int = TOP_K_FEATURES) -> Dict:
    """Compute the model-stats block ONCE, publish gauges, and register the
    ``model_stats`` run-report section over the precomputed block. The
    section closes over the (small) dict, NOT the booster: pinning the
    booster in the process-wide registry would keep its whole training set
    alive for the process lifetime and re-derive every stat per scrape.
    Returns the block for callers that embed it."""
    reg = registry if registry is not None else registry_mod.REGISTRY
    gbdt = booster._gbdt
    try:
        block = stats_block(booster, top_k=top_k)
    except Exception as e:  # observability must never fail training
        log.warning("modelstats: derivation failed: %r" % (e,))
        return {}
    names = _feature_names(gbdt)
    g_imp = reg.gauge("model_feature_importance")
    for typ in ("gain", "split"):
        for fid, v in (block.get("importance_%s_top" % typ) or {}).items():
            label = names.get(fid, fid)
            g_imp.set(v, feature=label, type=typ)
    ls = block.get("leaf_stats") or {}
    if ls.get("trees_with_splits"):
        reg.gauge("model_leaf_depth").set(ls["depth_mean"], stat="mean")
        reg.gauge("model_leaf_depth").set(ls["depth_max"], stat="max")
        reg.gauge("model_split_gain").set(ls["gain_total"], stat="total")
        reg.gauge("model_split_gain").set(ls["gain_max"], stat="max")
    reg.gauge("model_trees").set(block.get("num_trees", 0))
    reg.register_report_section("model_stats", lambda: block)
    return block


def stats_block(booster, top_k: int = TOP_K_FEATURES) -> Dict:
    """The JSON-able model_stats section (run_report / flight summary)."""
    gbdt = booster._gbdt
    trees = gbdt.trees()
    names = _feature_names(gbdt)

    def named(d: Dict[str, float]) -> Dict[str, float]:
        return {names.get(k, k): v for k, v in d.items()}

    gain = gbdt.feature_importance("gain")
    split = gbdt.feature_importance("split")
    evo = importance_evolution(gbdt, top_k=top_k)
    ds = getattr(gbdt, "train_set", None)
    occ = occupancy_summary(
        gbdt.train_bin_occupancy()
        if hasattr(gbdt, "train_bin_occupancy")
        else train_bin_occupancy(ds),
        ds,
    )
    return {
        "num_trees": len(trees),
        "importance_gain_top": named(_top(gain, top_k)),
        "importance_split_top": named(_top(split, top_k)),
        "importance_evolution": [
            dict(e, gain=named(e["gain"]), split=named(e["split"]))
            for e in evo
        ],
        "leaf_stats": leaf_stats(trees),
        "train_bin_occupancy": occ,
    }


def _feature_names(gbdt) -> Dict[str, str]:
    ds = getattr(gbdt, "train_set", None)
    names = getattr(ds, "feature_names", None) if ds is not None else None
    if not names:
        names = getattr(gbdt, "feature_names", None) or []
    return {str(i): str(n) for i, n in enumerate(names)}
