"""Measured XLA cost analysis per executable + the roofline peak table.

The reference ships only coarse wall-clock utilities (``utils/log.h``/
TIMETAG); on a TPU-native stack the compiler itself knows what every
executable costs. This module harvests
``jit(fn).lower(avals).compile().cost_analysis()`` (flops, bytes accessed)
and ``.memory_analysis()`` (argument/output/temp bytes) for the core
executables — keyed by the SAME names the retrace watchdog counts
(``ops.grow_tree``, ``gbdt.train_chunk``, ``ops.packed_predict_values``,
``ops.packed_bin_rows``, ``ops.leaf_histogram``) — so one scrape answers
"what compiled, how big, how hot":

 * every harvested record publishes ``xla_cost_*`` gauges (labeled by
   executable) on the default metrics registry, next to the watchdog's
   per-name ``jit_traces`` compile counts;
 * ``run_report()`` carries the whole book as a ``cost_analysis`` section
   (bench.py and tpu_bringup.py embed it in their artifacts);
 * bench.py's roofline uses the measured flops/bytes when a harvest for
   the headline executable exists, falling back to the analytic work model
   — every report is stamped ``roofline_source: "measured" | "analytic"``
   so BENCH_r*.json comparisons are never apples-to-oranges.

Harvesting is env-gated (``LIGHTGBM_TPU_COSTS=1``): ``lower().compile()``
is a SECOND XLA compile of the executable (the AOT path does not share the
jit dispatch cache), which the persistent compilation cache makes cheap on
re-runs but which plain training should not pay silently. Call sites
(models/gbdt.py, serve/packed.py, obs/prof.py) check :func:`enabled` and
dedupe per (name, arg-shape signature), so the steady-state overhead is a
dict lookup.

The chip peak table replaces bench.py's hardcoded two-entry guess: an
explicit ``device_kind -> (peak_flops, peak_bw)`` map covering
v4/v5e/v5p/v6e plus the cpu-nominal fallback, with the normalized chip
label and an ``assumed`` flag carried into every roofline record.

Stdlib + jax-lazy: importing this module never touches a backend.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..utils import log
from . import registry as registry_mod
from . import sanitize as sanitize_mod

ENV_COSTS = "LIGHTGBM_TPU_COSTS"


def enabled() -> bool:
    """Read per call, not at import: bench/bringup flip it in-process."""
    return os.environ.get(ENV_COSTS, "") not in ("", "0")


# --------------------------------------------------------------------------
# per-device_kind peak table (dense f32-accumulating matmul peak + HBM BW)
# --------------------------------------------------------------------------

#: device_kind family -> peaks. ``peak_flops`` is the f32-accumulation MXU
#: peak the MFU numbers divide by (histograms accumulate f32 via
#: preferred_element_type even with bf16 operands); ``peak_flops_bf16`` is
#: the headline bf16 rate for context; ``peak_bw`` is HBM bytes/s;
#: ``vmem_bytes`` is the per-core VMEM a Pallas kernel's resident blocks
#: must fit (the Mosaic scoped-allocation ceiling ops/hist_pallas.py
#: budgets against, and the bound graftlint JX011 statically enforces by
#: reading THIS table — the smallest vmem_bytes gates every kernel).
#: Sources: public TPU system specs (v4 275 TF bf16 / 1228 GB/s; v5e 197 TF
#: bf16 / 819 GB/s; v5p 459 TF bf16 / 2765 GB/s; v6e 918 TF bf16 /
#: 1640 GB/s); cpu-nominal keeps the pre-existing bench placeholder (and
#: mirrors the TPU VMEM ceiling so interpret-mode shapes stay portable).
CHIP_PEAKS: Dict[str, Dict[str, float]] = {
    "v4": {"peak_flops": 137e12, "peak_flops_bf16": 275e12,
           "peak_bw": 1228e9, "vmem_bytes": 16 * 2 ** 20},
    "v5e": {"peak_flops": 99e12, "peak_flops_bf16": 197e12,
            "peak_bw": 819e9, "vmem_bytes": 16 * 2 ** 20},
    "v5p": {"peak_flops": 229e12, "peak_flops_bf16": 459e12,
            "peak_bw": 2765e9, "vmem_bytes": 16 * 2 ** 20},
    "v6e": {"peak_flops": 459e12, "peak_flops_bf16": 918e12,
            "peak_bw": 1640e9, "vmem_bytes": 32 * 2 ** 20},
    "cpu": {"peak_flops": 1e11, "peak_flops_bf16": 1e11,
            "peak_bw": 2e10, "vmem_bytes": 16 * 2 ** 20},
}

#: the chip assumed when a TPU device_kind string matches no family —
#: the only generation this project has ever measured on (BENCH_NOTES.md)
_DEFAULT_TPU = "v5e"


def normalize_device_kind(device_kind: Optional[str]) -> Optional[str]:
    """Map a jax ``device.device_kind`` string onto a CHIP_PEAKS family.

    Handles the spellings seen in the wild: "TPU v4", "TPU v5e",
    "TPU v5 lite"/"TPU v5litepod", "TPU v5p"/"TPU v5", "TPU v6e",
    "TPU v6 lite"/"Trillium", and cpu hosts. Returns None when unknown.
    """
    if not device_kind:
        return None
    k = device_kind.lower().replace("_", " ")
    if "cpu" in k:
        return "cpu"
    if "trillium" in k or "v6" in k:
        return "v6e"
    if "v5p" in k:
        return "v5p"
    if "v5" in k:  # v5e / v5 lite / v5litepod; bare "v5" maps to v5p
        if "lite" in k or "v5e" in k:
            return "v5e"
        return "v5p"
    if "v4" in k:
        return "v4"
    return None


def chip_peaks(
    device_kind: Optional[str] = None, platform: Optional[str] = None
) -> Dict[str, object]:
    """Resolve the roofline peaks for a device.

    Returns ``{peak_flops, peak_flops_bf16, peak_bw, chip, assumed}`` —
    ``chip`` is the normalized family label annotated with the raw
    device_kind, ``assumed`` is True when the kind matched no family and a
    default was substituted (the pre-obs bench guessed silently; now every
    roofline record says so).
    """
    fam = normalize_device_kind(device_kind)
    assumed = False
    if fam is None:
        fam = "cpu" if platform not in ("tpu", "axon") else _DEFAULT_TPU
        assumed = platform in ("tpu", "axon")
    rec = CHIP_PEAKS[fam]
    label = fam if fam != "cpu" else "cpu-nominal"
    if device_kind:
        label = "%s (device_kind=%s%s)" % (
            label, device_kind, "; assumed" if assumed else ""
        )
    elif assumed:
        label = "%s (assumed; no device_kind)" % label
    return {
        "peak_flops": rec["peak_flops"],
        "peak_flops_bf16": rec["peak_flops_bf16"],
        "peak_bw": rec["peak_bw"],
        # the Pallas scoped-VMEM ceiling: the histogram autotuner
        # (obs/tune.py) gates pallas contenders on it before timing them
        "vmem_bytes": rec["vmem_bytes"],
        "chip": label,
        "assumed": assumed,
    }


# --------------------------------------------------------------------------
# the harvest book
# --------------------------------------------------------------------------

def _to_aval(x):
    """jax arrays -> ShapeDtypeStructs so a harvest never needs the live
    (possibly donated-away) buffers; everything else passes through."""
    import jax

    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def sds_args(args: tuple, kwargs: dict):
    """Abstract (args, kwargs) for a later harvest call — snapshot BEFORE
    invoking a donating jit, while the buffers still have shapes."""
    import jax

    return jax.tree_util.tree_map(_to_aval, (tuple(args), dict(kwargs)))


def _normalize_cost(ca) -> Dict[str, float]:
    """compiled.cost_analysis() returns a dict on TPU and a 1-element list
    of dicts on CPU/GPU (jax<=0.4.x); flatten to the keys we publish."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: Dict[str, float] = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    if "bytes accessedout{}" in ca:
        out["bytes_accessed_out"] = float(ca["bytes accessedout{}"])
    if "transcendentals" in ca:
        out["transcendentals"] = float(ca["transcendentals"])
    return out


class CostBook:
    """name -> harvested cost/memory record, deduped per argument-shape
    signature, published as labeled gauges on the default registry."""

    def __init__(self) -> None:
        self._records: Dict[str, Dict[str, object]] = {}
        self._seen: set = set()
        self._lock = sanitize_mod.make_lock("obs.costs")

    def harvest(self, name: str, jit_fn, args=(), kwargs=None,
                registry=None) -> Optional[Dict[str, object]]:
        """Lower+compile ``jit_fn`` at the (abstracted) call signature and
        record its cost analysis under ``name``. ``args``/``kwargs`` may be
        live arrays, ShapeDtypeStructs, or the pre-snapshotted pair from
        :func:`sds_args`. Returns the record, the cached one on a repeat
        signature, or None when the backend/compile declines — a failed
        harvest must never take training or serving down.
        """
        kwargs = kwargs or {}
        try:
            a_args, a_kwargs = sds_args(args, kwargs)
        except Exception as e:
            log.warn_once(
                "costs:%s" % name,
                "cost-analysis harvest for %r failed abstracting args: %r"
                % (name, e),
            )
            return None
        try:
            key = (name, _sig(a_args), _sig(tuple(sorted(a_kwargs.items()))))
        except Exception:
            key = None
        if key is not None:
            with self._lock:
                if key in self._seen:
                    return self._records.get(name)
        try:
            compiled = jit_fn.lower(*a_args, **a_kwargs).compile()
            rec: Dict[str, object] = dict(_normalize_cost(compiled.cost_analysis()))
            try:
                ma = compiled.memory_analysis()
                rec["argument_bytes"] = int(ma.argument_size_in_bytes)
                rec["output_bytes"] = int(ma.output_size_in_bytes)
                rec["temp_bytes"] = int(ma.temp_size_in_bytes)
                rec["alias_bytes"] = int(ma.alias_size_in_bytes)
            except Exception as e:
                # some backends ship cost analysis but no memory stats;
                # keep the flops record rather than dropping the harvest
                log.debug("costs: memory_analysis unavailable for %r: %r"
                          % (name, e))
        except Exception as e:
            log.warn_once(
                "costs:%s" % name,
                "cost-analysis harvest for %r failed: %s: %s"
                % (name, type(e).__name__, str(e)[:160]),
            )
            return None
        with self._lock:
            if key is not None:
                self._seen.add(key)
            self._records[name] = rec
        self._publish(name, rec, registry)
        return rec

    def _publish(self, name: str, rec: Dict[str, object], registry=None) -> None:
        reg = registry if registry is not None else registry_mod.REGISTRY
        gauges = {
            "flops": "xla_cost_flops",
            "bytes_accessed": "xla_cost_bytes_accessed",
            "argument_bytes": "xla_cost_argument_bytes",
            "output_bytes": "xla_cost_output_bytes",
            "temp_bytes": "xla_cost_temp_bytes",
        }
        for field, gname in gauges.items():
            v = rec.get(field)
            if v is not None:
                reg.gauge(gname).set(float(v), executable=name)

    def get(self, name: str) -> Optional[Dict[str, object]]:
        with self._lock:
            rec = self._records.get(name)
            return dict(rec) if rec is not None else None

    def report(self) -> Dict[str, Dict[str, object]]:
        """The whole book — run_report()'s ``cost_analysis`` section."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._records.items())}

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._seen.clear()


def _sig(obj) -> str:
    """Hashable-ish signature of an abstracted arg tree (shapes/dtypes and
    static values rendered to a string; stable across processes)."""
    import jax

    parts = []

    def walk(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            parts.append("%s%s" % (x.dtype, tuple(x.shape)))
        else:
            parts.append(repr(x)[:80])

    jax.tree_util.tree_map(walk, obj)
    return "|".join(parts)


#: process-wide cost book; gbdt/serve/prof harvest into it when enabled()
COSTS = CostBook()
