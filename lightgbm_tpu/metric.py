"""Evaluation metrics.

TPU-native counterpart of the reference metric family (/root/reference/src/metric/,
factory metric.cpp:16-60, interface include/LightGBM/metric.h). Metrics run on host
in vectorized numpy double precision (they are O(N) and off the training hot path).
Like the reference, ``eval`` receives the raw ensemble scores plus the objective so
link inversions (sigmoid/exp/softmax) happen inside the metric.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .dataset import Metadata
from .objective import ObjectiveFunction, dcg_discount, default_label_gain
from .utils import log

K_EPSILON = 1e-15


class Metric:
    """One metric; ``eval`` returns a list of (name, value, bigger_is_better)."""

    names: List[str] = []
    bigger_is_better = False

    def __init__(self, config: Config) -> None:
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = (
            metadata.label if metadata.label is not None else np.zeros(num_data, np.float32)
        ).astype(np.float64)
        self.weight = None if metadata.weight is None else metadata.weight.astype(np.float64)
        self.sum_weights = float(num_data) if self.weight is None else float(np.sum(self.weight))
        self.metadata = metadata

    def eval(self, score: np.ndarray, objective: Optional[ObjectiveFunction]):
        raise NotImplementedError


class _AverageLossMetric(Metric):
    """Shared shape of regression_metric.hpp: weighted mean of a pointwise loss."""

    def point_loss(self, score: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            return objective.convert_output(score)
        return score

    def eval(self, score, objective):
        s = self.transform(np.asarray(score, np.float64), objective)
        losses = self.point_loss(s)
        if self.weight is not None:
            val = float(np.sum(losses * self.weight) / self.sum_weights)
        else:
            val = float(np.mean(losses))
        return [(self.names[0], self.finalize(val), self.bigger_is_better)]

    def finalize(self, v: float) -> float:
        return v


class L2Metric(_AverageLossMetric):
    names = ["l2"]

    def point_loss(self, s):
        return (s - self.label) ** 2


class RMSEMetric(L2Metric):
    names = ["rmse"]

    def finalize(self, v):
        return float(np.sqrt(v))


class L1Metric(_AverageLossMetric):
    names = ["l1"]

    def point_loss(self, s):
        return np.abs(s - self.label)


class QuantileMetric(_AverageLossMetric):
    names = ["quantile"]

    def point_loss(self, s):
        alpha = self.config.alpha
        d = self.label - s
        return np.where(d >= 0, alpha * d, (alpha - 1.0) * d)


class HuberLossMetric(_AverageLossMetric):
    names = ["huber"]

    def point_loss(self, s):
        alpha = self.config.alpha
        d = np.abs(s - self.label)
        return np.where(d <= alpha, 0.5 * d * d, alpha * (d - 0.5 * alpha))


class FairLossMetric(_AverageLossMetric):
    names = ["fair"]

    def point_loss(self, s):
        c = self.config.fair_c
        x = np.abs(s - self.label)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_AverageLossMetric):
    names = ["poisson"]

    def point_loss(self, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        return s - self.label * np.log(s)


class GammaMetric(_AverageLossMetric):
    names = ["gamma"]

    def point_loss(self, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        # -log(likelihood) with shape k=1: x/theta + log(theta), theta=s, x=label
        return self.label / s + np.log(s)


class GammaDevianceMetric(_AverageLossMetric):
    names = ["gamma-deviance"]

    def point_loss(self, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        r = self.label / s
        return 2.0 * (np.log(np.maximum(1e-300, 1.0 / np.maximum(r, 1e-300))) + r - 1.0)

    def finalize(self, v):
        return v


class TweedieMetric(_AverageLossMetric):
    names = ["tweedie"]

    def point_loss(self, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        a = self.label * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


class MAPEMetric(_AverageLossMetric):
    names = ["mape"]

    def point_loss(self, s):
        return np.abs((self.label - s)) / np.maximum(1.0, np.abs(self.label))


class BinaryLoglossMetric(_AverageLossMetric):
    names = ["binary_logloss"]

    def point_loss(self, prob):
        eps = 1e-15
        p = np.clip(prob, eps, 1.0 - eps)
        is_pos = (self.label > 0).astype(np.float64)
        return -is_pos * np.log(p) - (1.0 - is_pos) * np.log(1.0 - p)


class BinaryErrorMetric(_AverageLossMetric):
    names = ["binary_error"]

    def point_loss(self, prob):
        pred_pos = prob > 0.5
        is_pos = self.label > 0
        return (pred_pos != is_pos).astype(np.float64)


class AUCMetric(Metric):
    names = ["auc"]
    bigger_is_better = True

    def eval(self, score, objective):
        s = np.asarray(score, np.float64)
        order = np.argsort(-s, kind="stable")
        lab = self.label[order]
        w = np.ones(self.num_data) if self.weight is None else self.weight[order]
        pos_w = np.where(lab > 0, w, 0.0)
        neg_w = np.where(lab <= 0, w, 0.0)
        # group ties on score: per unique threshold, accum += neg*(pos/2 + sum_pos_before)
        ss = s[order]
        # boundaries of tie groups
        new_grp = np.empty(self.num_data, bool)
        new_grp[0] = True
        new_grp[1:] = ss[1:] != ss[:-1]
        gid = np.cumsum(new_grp) - 1
        ngroups = gid[-1] + 1
        gpos = np.zeros(ngroups)
        gneg = np.zeros(ngroups)
        np.add.at(gpos, gid, pos_w)
        np.add.at(gneg, gid, neg_w)
        sum_pos_before = np.concatenate([[0.0], np.cumsum(gpos)[:-1]])
        accum = float(np.sum(gneg * (gpos * 0.5 + sum_pos_before)))
        sum_pos = float(np.sum(gpos))
        if sum_pos > 0 and sum_pos != self.sum_weights:
            return [("auc", accum / (sum_pos * (self.sum_weights - sum_pos)), True)]
        return [("auc", 1.0, True)]


class MultiLoglossMetric(Metric):
    names = ["multi_logloss"]

    def eval(self, score, objective):
        # score [K, N] raw -> convert per row
        K, N = score.shape
        probs = objective.convert_output(np.asarray(score, np.float64).T) if objective else score.T
        li = self.label.astype(np.int64)
        p = np.clip(probs[np.arange(N), li], 1e-15, None)
        losses = -np.log(p)
        if self.weight is not None:
            val = float(np.sum(losses * self.weight) / self.sum_weights)
        else:
            val = float(np.mean(losses))
        return [("multi_logloss", val, False)]


class MultiErrorMetric(Metric):
    names = ["multi_error"]

    def eval(self, score, objective):
        K, N = score.shape
        pred = np.argmax(np.asarray(score), axis=0)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        if self.weight is not None:
            val = float(np.sum(err * self.weight) / self.sum_weights)
        else:
            val = float(np.mean(err))
        return [("multi_error", val, False)]


class CrossEntropyMetric(_AverageLossMetric):
    names = ["xentropy"]

    def point_loss(self, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        y = self.label
        return -y * np.log(p) - (1 - y) * np.log(1 - p)


class CrossEntropyLambdaMetric(Metric):
    names = ["xentlambda"]

    def eval(self, score, objective):
        s = np.asarray(score, np.float64)
        # hhat = log1p(exp(score)); loss per xentropy_metric.hpp (lambda parameterization)
        hhat = np.log1p(np.exp(s))
        w = np.ones(self.num_data) if self.weight is None else self.weight
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        y = self.label
        losses = -y * np.log(z) - (1 - y) * np.log(1 - z)
        return [("xentlambda", float(np.mean(losses)), False)]


class KLDivMetric(Metric):
    names = ["kldiv"]

    def eval(self, score, objective):
        s = np.asarray(score, np.float64)
        p = 1.0 / (1.0 + np.exp(-s))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        losses = y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p))
        w = np.ones(self.num_data) if self.weight is None else self.weight
        return [("kldiv", float(np.sum(losses * w) / self.sum_weights), False)]


class NDCGMetric(Metric):
    names = ["ndcg"]
    bigger_is_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]
        lg = list(config.label_gain) if config.label_gain else list(default_label_gain())
        self.label_gain = np.asarray(lg, np.float64)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("The NDCG metric requires query information")
        self.qb = metadata.query_boundaries
        self.num_queries = metadata.num_queries
        self.query_weights = metadata.query_weights()
        self.sum_query_weights = (
            float(self.num_queries) if self.query_weights is None else float(np.sum(self.query_weights))
        )

    def eval(self, score, objective):
        s = np.asarray(score, np.float64)
        li = self.label.astype(np.int64)
        ks = self.eval_at
        totals = np.zeros(len(ks))
        for q in range(self.num_queries):
            lo, hi = int(self.qb[q]), int(self.qb[q + 1])
            lab = li[lo:hi]
            qw = 1.0 if self.query_weights is None else float(self.query_weights[q])
            ideal = np.sort(lab)[::-1]
            order = np.argsort(-s[lo:hi], kind="stable")
            ranked = lab[order]
            for j, k in enumerate(ks):
                kk = min(k, hi - lo)
                disc = dcg_discount(np.arange(kk))
                maxdcg = float(np.sum(self.label_gain[ideal[:kk]] * disc))
                if maxdcg <= 0:
                    totals[j] += qw  # all-negative query counts as NDCG 1
                else:
                    dcg = float(np.sum(self.label_gain[ranked[:kk]] * disc))
                    totals[j] += qw * dcg / maxdcg
        return [
            ("ndcg@%d" % k, float(totals[j] / self.sum_query_weights), True)
            for j, k in enumerate(ks)
        ]


class MapMetric(Metric):
    names = ["map"]
    bigger_is_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("The MAP metric requires query information")
        self.qb = metadata.query_boundaries
        self.num_queries = metadata.num_queries
        self.query_weights = metadata.query_weights()
        self.sum_query_weights = (
            float(self.num_queries) if self.query_weights is None else float(np.sum(self.query_weights))
        )

    def eval(self, score, objective):
        s = np.asarray(score, np.float64)
        li = (self.label > 0).astype(np.int64)
        ks = self.eval_at
        totals = np.zeros(len(ks))
        for q in range(self.num_queries):
            lo, hi = int(self.qb[q]), int(self.qb[q + 1])
            qw = 1.0 if self.query_weights is None else float(self.query_weights[q])
            order = np.argsort(-s[lo:hi], kind="stable")
            rel = li[lo:hi][order]
            hits = np.cumsum(rel)
            prec_at = hits / (np.arange(len(rel)) + 1.0)
            for j, k in enumerate(ks):
                kk = min(k, hi - lo)
                nrel = int(hits[kk - 1]) if kk > 0 else 0
                if nrel > 0:
                    ap = float(np.sum(prec_at[:kk] * rel[:kk]) / np.minimum(kk, max(int(hits[-1]), 1)))
                else:
                    ap = 0.0
                totals[j] += qw * ap
        return [
            ("map@%d" % k, float(totals[j] / self.sum_query_weights), True)
            for j, k in enumerate(ks)
        ]


_METRICS: Dict[str, type] = {
    "l2": L2Metric,
    "mean_squared_error": L2Metric,
    "mse": L2Metric,
    "regression": L2Metric,
    "rmse": RMSEMetric,
    "root_mean_squared_error": RMSEMetric,
    "l2_root": RMSEMetric,
    "l1": L1Metric,
    "mean_absolute_error": L1Metric,
    "mae": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberLossMetric,
    "fair": FairLossMetric,
    "poisson": PoissonMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "gamma-deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "mape": MAPEMetric,
    "mean_absolute_percentage_error": MAPEMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric,
    "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric,
    "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "xentropy": CrossEntropyMetric,
    "cross_entropy": CrossEntropyMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric,
    "kullback_leibler": KLDivMetric,
    "ndcg": NDCGMetric,
    "lambdarank": NDCGMetric,
    "map": MapMetric,
    "mean_average_precision": MapMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    cls = _METRICS.get(name)
    if cls is None:
        log.warning("Unknown metric type name: %s" % name)
        return None
    return cls(config)


def default_metric_for_objective(objective: str) -> str:
    """Config::GetMetricType default: metric = objective name."""
    return objective
