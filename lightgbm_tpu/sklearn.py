"""scikit-learn estimator API.

Mirrors /root/reference/python-package/lightgbm/sklearn.py: LGBMModel base with
get/set_params, fit with eval_set/early stopping, LGBMClassifier (label encoding,
predict_proba), LGBMRegressor, LGBMRanker (group arrays), plus the custom
objective/eval adapters (_ObjectiveFunctionWrapper/_EvalFunctionWrapper,
sklearn.py:18,81).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train
from .utils.log import LightGBMError


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, weight, group]) (sklearn.py:18)."""

    def __init__(self, func: Callable) -> None:
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(), dataset.get_group())
        else:
            raise TypeError("Self-defined objective should have 2, 3 or 4 arguments")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt sklearn-style feval (sklearn.py:81)."""

    def __init__(self, func: Callable) -> None:
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label() if dataset is not None else None
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError("Self-defined eval function should have 2, 3 or 4 arguments")


class LGBMModel:
    """Base estimator (sklearn.py:133)."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[str] = None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: Optional[int] = None,
        n_jobs: int = -1,
        silent: bool = True,
        importance_type: str = "split",
        **kwargs,
    ) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self._objective = objective

    # -- sklearn plumbing -------------------------------------------------

    _estimator_type: Optional[str] = None

    def __sklearn_tags__(self):
        """sklearn >= 1.6 tag protocol; built from BaseEstimator's defaults
        so model_selection tools (GridSearchCV, cross_val_score) accept
        these estimators without inheriting sklearn classes (the reference
        inherits its optional _LGBMModelBase shim instead)."""
        from sklearn.base import BaseEstimator

        tags = BaseEstimator.__sklearn_tags__(self)
        tags.estimator_type = self._estimator_type
        tags.target_tags.required = True
        if self._estimator_type == "classifier":
            from sklearn.utils import ClassifierTags

            tags.classifier_tags = ClassifierTags()
        elif self._estimator_type == "regressor":
            from sklearn.utils import RegressorTags

            tags.regressor_tags = RegressorTags()
        tags.input_tags.allow_nan = True
        tags.input_tags.sparse = True
        return tags

    def get_params(self, deep: bool = True) -> Dict:
        params = {
            k: getattr(self, k)
            for k in (
                "boosting_type num_leaves max_depth learning_rate n_estimators "
                "subsample_for_bin objective class_weight min_split_gain "
                "min_child_weight min_child_samples subsample subsample_freq "
                "colsample_bytree reg_alpha reg_lambda random_state n_jobs "
                "silent importance_type"
            ).split()
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    def _lgb_params(self) -> Dict:
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self._objective or "regression",
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            params["seed"] = self.random_state
            params["bagging_seed"] = self.random_state
            params["feature_fraction_seed"] = self.random_state
            params["drop_seed"] = self.random_state
            params["data_random_seed"] = self.random_state
        params.update(self._other_params)
        return params

    # -- fit/predict ------------------------------------------------------

    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_names=None,
        eval_sample_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        early_stopping_rounds=None,
        verbose=False,
        feature_name="auto",
        categorical_feature="auto",
        callbacks=None,
    ) -> "LGBMModel":
        params = self._lgb_params()
        fobj = None
        if callable(self._objective):
            fobj = _ObjectiveFunctionWrapper(self._objective)
            params["objective"] = "none"
        feval = _EvalFunctionWrapper(eval_metric) if callable(eval_metric) else None
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric

        train_set = Dataset(
            X,
            label=y,
            weight=sample_weight,
            group=group,
            init_score=init_score,
            feature_name=feature_name,
            categorical_feature=categorical_feature,
            params=params,
        )
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vis = eval_init_score[i] if eval_init_score else None
                vg = eval_group[i] if eval_group else None
                valid_sets.append(
                    Dataset(vx, label=vy, weight=vw, group=vg, init_score=vis, reference=train_set)
                )
        self._evals_result = {}
        self._Booster = train(
            params,
            train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=eval_names,
            fobj=fobj,
            feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result,
            verbose_eval=verbose,
            callbacks=callbacks,
        )
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1, **kwargs) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        return self._Booster.predict(X, raw_score=raw_score, num_iteration=num_iteration, **kwargs)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self.booster_.num_feature()


class LGBMRegressor(LGBMModel):
    # sklearn estimator-type tag: lets model_selection tools pick the right
    # default scorer/CV splitter (the reference inherits this from
    # sklearn.base.RegressorMixin)
    _estimator_type = "regressor"

    def fit(self, X, y, **kwargs):
        if self._objective is None:
            self._objective = "regression"
        return super().fit(X, y, **kwargs)

    def score(self, X, y, sample_weight=None) -> float:
        """Coefficient of determination R^2 (RegressorMixin.score)."""
        y = np.asarray(y, np.float64)
        pred = np.asarray(self.predict(X), np.float64)
        w = np.ones_like(y) if sample_weight is None else np.asarray(sample_weight, np.float64)
        ss_res = np.sum(w * (y - pred) ** 2)
        ss_tot = np.sum(w * (y - np.average(y, weights=w)) ** 2)
        if ss_tot > 0:
            return float(1.0 - ss_res / ss_tot)
        # constant target: r2_score semantics — perfect fit scores 1.0
        return 1.0 if ss_res == 0 else 0.0


class LGBMClassifier(LGBMModel):
    _estimator_type = "classifier"

    def score(self, X, y, sample_weight=None) -> float:
        """Mean accuracy (ClassifierMixin.score)."""
        y = np.asarray(y)
        pred = self.predict(X)
        hit = (pred == y).astype(np.float64)
        if sample_weight is not None:
            return float(np.average(hit, weights=np.asarray(sample_weight, np.float64)))
        return float(hit.mean())

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._class_map = {c: i for i, c in enumerate(self._classes)}
        y_enc = np.asarray([self._class_map[v] for v in y], np.float64)
        if self._objective is None or not callable(self._objective):
            if self._n_classes > 2:
                self._objective = self._objective or "multiclass"
                self._other_params.setdefault("num_class", self._n_classes)
            else:
                self._objective = self._objective or "binary"
        ev = kwargs.get("eval_set")
        if ev is not None:
            kwargs["eval_set"] = [
                (vx, np.asarray([self._class_map[v] for v in np.asarray(vy)], np.float64))
                for vx, vy in ev
            ]
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1, **kwargs):
        probs = self.predict_proba(X, raw_score=raw_score, num_iteration=num_iteration, **kwargs)
        if raw_score:
            return probs
        if probs.ndim == 1:
            idx = (probs > 0.5).astype(int)
        else:
            idx = np.argmax(probs, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False, num_iteration: int = -1, **kwargs):
        out = super().predict(X, raw_score=raw_score, num_iteration=num_iteration, **kwargs)
        if raw_score:
            return out
        if out.ndim == 1:
            return np.vstack([1.0 - out, out]).T if not raw_score else out
        return out

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict_proba_raw(self, X, **kwargs):
        return super().predict(X, raw_score=True, **kwargs)


class LGBMRanker(LGBMModel):
    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise LightGBMError("Should set group for ranking task")
        if self._objective is None:
            self._objective = "lambdarank"
        return super().fit(X, y, group=group, **kwargs)
