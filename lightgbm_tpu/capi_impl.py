"""Python side of the LGBM_* C ABI (handle tables + buffer marshalling).

The native shim (native/lgbt_capi.cpp) embeds/attaches to CPython and proxies
every ``LGBM_*`` call here with raw pointer addresses and scalars; this module
owns the handle tables and adapts the reference's C API semantics
(/root/reference/include/LightGBM/c_api.h:41-986, src/c_api.cpp) onto the
package's Dataset/Booster objects. Pointers are read/written with ctypes, so
no copies beyond what the API semantics require.

Handles are small positive integers (0 is the NULL handle); the C side passes
them around as opaque void*.
"""
from __future__ import annotations
from .utils.vfile import vopen

import ctypes
import itertools
from typing import Dict, Optional, Tuple

import numpy as np

from .basic import Booster, Dataset
from .config import Config

# c_api.h:24-33
DTYPE_FLOAT32 = 0
DTYPE_FLOAT64 = 1
DTYPE_INT32 = 2
DTYPE_INT64 = 3
DTYPE_INT8 = 4

PREDICT_NORMAL = 0
PREDICT_RAW_SCORE = 1
PREDICT_LEAF_INDEX = 2
PREDICT_CONTRIB = 3

_NP_DTYPE = {
    DTYPE_FLOAT32: np.float32,
    DTYPE_FLOAT64: np.float64,
    DTYPE_INT32: np.int32,
    DTYPE_INT64: np.int64,
    DTYPE_INT8: np.int8,
}

_CTYPE = {
    DTYPE_FLOAT32: ctypes.c_float,
    DTYPE_FLOAT64: ctypes.c_double,
    DTYPE_INT32: ctypes.c_int32,
    DTYPE_INT64: ctypes.c_int64,
    DTYPE_INT8: ctypes.c_int8,
}

_ids = itertools.count(1)
_datasets: Dict[int, Dataset] = {}
_boosters: Dict[int, "_CBooster"] = {}


def _read_array(ptr: int, n: int, dtype_code: int) -> np.ndarray:
    ct = _CTYPE[dtype_code]
    buf = (ct * n).from_address(ptr)
    return np.frombuffer(buf, dtype=_NP_DTYPE[dtype_code]).copy()


def _write_doubles(ptr: int, values: np.ndarray) -> None:
    values = np.ascontiguousarray(values, np.float64)
    ctypes.memmove(ptr, values.ctypes.data, values.nbytes)


def _params_str_to_dict(parameters: str) -> dict:
    return Config.kv2map(parameters.replace("\t", " ").split())


def _dataset(did: int) -> Dataset:
    try:
        return _datasets[did]
    except KeyError:
        raise ValueError("invalid DatasetHandle %d" % did)


# ---------------------------------------------------------------------------
# Dataset surface
# ---------------------------------------------------------------------------


def dataset_create_from_file(filename: str, parameters: str, ref_id: int) -> int:
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(filename, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_mat(
    data_ptr: int, data_type: int, nrow: int, ncol: int, is_row_major: int,
    parameters: str, ref_id: int,
) -> int:
    arr = _read_array(data_ptr, nrow * ncol, data_type).astype(np.float64)
    X = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_csr(
    indptr_ptr: int, indptr_type: int, indices_ptr: int, data_ptr: int,
    data_type: int, nindptr: int, nelem: int, num_col: int, parameters: str,
    ref_id: int,
) -> int:
    indptr = _read_array(indptr_ptr, nindptr, indptr_type).astype(np.int64)
    indices = _read_array(indices_ptr, nelem, DTYPE_INT32).astype(np.int64)
    data = _read_array(data_ptr, nelem, data_type).astype(np.float64)
    nrow = nindptr - 1
    X = np.zeros((nrow, num_col), np.float64)
    for r in range(nrow):
        lo, hi = indptr[r], indptr[r + 1]
        X[r, indices[lo:hi]] = data[lo:hi]
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_csc(
    col_ptr_ptr: int, col_ptr_type: int, indices_ptr: int, data_ptr: int,
    data_type: int, ncol_ptr: int, nelem: int, num_row: int, parameters: str,
    ref_id: int,
) -> int:
    col_ptr = _read_array(col_ptr_ptr, ncol_ptr, col_ptr_type).astype(np.int64)
    indices = _read_array(indices_ptr, nelem, DTYPE_INT32).astype(np.int64)
    data = _read_array(data_ptr, nelem, data_type).astype(np.float64)
    ncol = ncol_ptr - 1
    X = np.zeros((num_row, ncol), np.float64)
    for c in range(ncol):
        lo, hi = col_ptr[c], col_ptr[c + 1]
        X[indices[lo:hi], c] = data[lo:hi]
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_get_num_data(did: int) -> int:
    return int(_dataset(did)._binned.num_data)


def dataset_get_num_feature(did: int) -> int:
    return int(_dataset(did)._binned.num_total_features)


def dataset_set_field(
    did: int, field_name: str, data_ptr: int, num_element: int, dtype_code: int
) -> None:
    # Metadata::SetField name dispatch (c_api.cpp LGBM_DatasetSetField)
    ds = _dataset(did)
    arr = _read_array(data_ptr, num_element, dtype_code)
    if field_name == "label":
        ds.set_label(arr)
    elif field_name == "weight":
        ds.set_weight(arr)
    elif field_name == "init_score":
        ds.set_init_score(arr)
    elif field_name in ("group", "query"):
        ds.set_group(arr)
    else:
        raise ValueError("unknown field name %r" % field_name)


def dataset_get_field(did: int, field_name: str) -> Optional[np.ndarray]:
    ds = _dataset(did)
    if field_name == "label":
        return ds.get_label()
    if field_name == "weight":
        return ds.get_weight()
    if field_name == "init_score":
        return ds.get_init_score()
    if field_name in ("group", "query"):
        return ds.get_group()
    raise ValueError("unknown field name %r" % field_name)


def dataset_save_binary(did: int, filename: str) -> None:
    _dataset(did).save_binary(filename)


def dataset_free(did: int) -> None:
    _datasets.pop(did, None)


# ---------------------------------------------------------------------------
# Booster surface
# ---------------------------------------------------------------------------


class _CBooster:
    """Booster + its attached eval data (BoosterHandle contents, c_api.cpp)."""

    def __init__(self, booster: Booster):
        self.booster = booster


def booster_create(train_id: int, parameters: str) -> int:
    params = _params_str_to_dict(parameters)
    bst = Booster(params=params, train_set=_dataset(train_id))
    bid = next(_ids)
    _boosters[bid] = _CBooster(bst)
    return bid


def booster_create_from_modelfile(filename: str) -> Tuple[int, int]:
    bst = Booster(model_file=filename)
    bid = next(_ids)
    _boosters[bid] = _CBooster(bst)
    return bid, int(bst.current_iteration)


def booster_free(bid: int) -> None:
    _boosters.pop(bid, None)


def booster_add_valid_data(bid: int, did: int) -> None:
    _boosters[bid].booster.add_valid(_dataset(did), "valid_%d" % did)


def booster_update_one_iter(bid: int) -> int:
    return 1 if _boosters[bid].booster.update() else 0


def booster_get_eval(bid: int, data_idx: int, out_ptr: int) -> int:
    # data_idx 0 = training data, i = i-th valid set (c_api.h:585-597)
    bst = _boosters[bid].booster
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst._gbdt.valid_names[data_idx - 1]
        results = [t for t in bst.eval_valid() if t[0] == name]
    vals = np.asarray([t[2] for t in results], np.float64)
    if len(vals):
        _write_doubles(out_ptr, vals)
    return len(vals)


def booster_get_num_classes(bid: int) -> int:
    return _boosters[bid].booster.num_model_per_iteration()


def booster_get_current_iteration(bid: int) -> int:
    # c_api.h:470 LGBM_BoosterGetCurrentIteration
    return _boosters[bid].booster.current_iteration


def booster_get_eval_counts(bid: int) -> int:
    # c_api.h:528 LGBM_BoosterGetEvalCounts: number of metric values one
    # booster_get_eval call writes (callers size their buffer with this).
    # Derived from the metric list without evaluating — a booster loaded from
    # a model file has no training data attached, and the reference returns 0
    # there rather than erroring. Rank metrics emit one value per eval_at
    # position (GetName() returns one name per position in the reference).
    gbdt = getattr(_boosters[bid].booster, "_gbdt", None)
    metrics = getattr(gbdt, "training_metrics", None) or []
    return sum(len(getattr(m, "eval_at", None) or (1,)) for m in metrics)


def booster_save_model(
    bid: int, start_iteration: int, num_iteration: int, filename: str
) -> None:
    _boosters[bid].booster.save_model(
        filename, num_iteration=num_iteration, start_iteration=start_iteration
    )


def booster_predict_for_mat(
    bid: int, data_ptr: int, data_type: int, nrow: int, ncol: int,
    is_row_major: int, predict_type: int, num_iteration: int, parameter: str,
    out_ptr: int,
) -> int:
    arr = _read_array(data_ptr, nrow * ncol, data_type).astype(np.float64)
    X = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    bst = _boosters[bid].booster
    kw = dict(num_iteration=num_iteration)
    if predict_type == PREDICT_RAW_SCORE:
        out = bst.predict(X, raw_score=True, **kw)
    elif predict_type == PREDICT_LEAF_INDEX:
        out = bst.predict(X, pred_leaf=True, **kw)
    elif predict_type == PREDICT_CONTRIB:
        out = bst.predict(X, pred_contrib=True, **kw)
    else:
        out = bst.predict(X, **kw)
    out = np.ascontiguousarray(out, np.float64)
    _write_doubles(out_ptr, out)
    return int(out.size)


def booster_predict_for_file(
    bid: int, data_filename: str, data_has_header: int, predict_type: int,
    num_iteration: int, parameter: str, result_filename: str,
) -> None:
    from .io import load_text_file

    bst = _boosters[bid].booster
    X, _, _ = load_text_file(
        data_filename, has_header=bool(data_has_header), label_column=0
    )
    kw = dict(num_iteration=num_iteration)
    if predict_type == PREDICT_RAW_SCORE:
        out = bst.predict(X, raw_score=True, **kw)
    elif predict_type == PREDICT_LEAF_INDEX:
        out = bst.predict(X, pred_leaf=True, **kw)
    elif predict_type == PREDICT_CONTRIB:
        out = bst.predict(X, pred_contrib=True, **kw)
    else:
        out = bst.predict(X, **kw)
    out = np.atleast_2d(np.asarray(out, np.float64))
    if out.shape[0] == 1 and out.size > 1:
        out = out.T
    with vopen(result_filename, "w") as fh:
        for row in out:
            fh.write("\t".join(repr(float(v)) for v in np.atleast_1d(row)) + "\n")
