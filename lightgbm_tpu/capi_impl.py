"""Python side of the LGBM_* C ABI (handle tables + buffer marshalling).

The native shim (native/lgbt_capi.cpp) embeds/attaches to CPython and proxies
every ``LGBM_*`` call here with raw pointer addresses and scalars; this module
owns the handle tables and adapts the reference's C API semantics
(/root/reference/include/LightGBM/c_api.h:41-986, src/c_api.cpp) onto the
package's Dataset/Booster objects. Pointers are read/written with ctypes, so
no copies beyond what the API semantics require.

Handles are small positive integers (0 is the NULL handle); the C side passes
them around as opaque void*.
"""
from __future__ import annotations
from .utils.vfile import vopen

import ctypes
import itertools
from typing import Dict, Optional, Tuple

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .utils import log

# c_api.h:24-33
DTYPE_FLOAT32 = 0
DTYPE_FLOAT64 = 1
DTYPE_INT32 = 2
DTYPE_INT64 = 3
DTYPE_INT8 = 4

PREDICT_NORMAL = 0
PREDICT_RAW_SCORE = 1
PREDICT_LEAF_INDEX = 2
PREDICT_CONTRIB = 3

_NP_DTYPE = {
    DTYPE_FLOAT32: np.float32,
    DTYPE_FLOAT64: np.float64,
    DTYPE_INT32: np.int32,
    DTYPE_INT64: np.int64,
    DTYPE_INT8: np.int8,
}

_CTYPE = {
    DTYPE_FLOAT32: ctypes.c_float,
    DTYPE_FLOAT64: ctypes.c_double,
    DTYPE_INT32: ctypes.c_int32,
    DTYPE_INT64: ctypes.c_int64,
    DTYPE_INT8: ctypes.c_int8,
}

_ids = itertools.count(1)
_datasets: Dict[int, Dataset] = {}
_boosters: Dict[int, "_CBooster"] = {}


def _read_array(ptr: int, n: int, dtype_code: int) -> np.ndarray:
    ct = _CTYPE[dtype_code]
    buf = (ct * n).from_address(ptr)
    return np.frombuffer(buf, dtype=_NP_DTYPE[dtype_code]).copy()


def _write_doubles(ptr: int, values: np.ndarray) -> None:
    values = np.ascontiguousarray(values, np.float64)
    ctypes.memmove(ptr, values.ctypes.data, values.nbytes)


def _params_str_to_dict(parameters: str) -> dict:
    return Config.kv2map(parameters.replace("\t", " ").split())


def _dataset(did: int) -> Dataset:
    try:
        return _datasets[did]
    except KeyError:
        raise ValueError("invalid DatasetHandle %d" % did)


# ---------------------------------------------------------------------------
# Dataset surface
# ---------------------------------------------------------------------------


def dataset_create_from_file(filename: str, parameters: str, ref_id: int) -> int:
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(filename, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_mat(
    data_ptr: int, data_type: int, nrow: int, ncol: int, is_row_major: int,
    parameters: str, ref_id: int,
) -> int:
    arr = _read_array(data_ptr, nrow * ncol, data_type).astype(np.float64)
    X = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_csr(
    indptr_ptr: int, indptr_type: int, indices_ptr: int, data_ptr: int,
    data_type: int, nindptr: int, nelem: int, num_col: int, parameters: str,
    ref_id: int,
) -> int:
    # O(nnz) end to end: the scipy matrix feeds dataset._construct_sparse
    # (column-wise binning, optional EFB) with no dense intermediate —
    # VERDICT r4 item 5; reference: c_api.cpp CSR row-iterator path
    X = _abi_csr(
        indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr,
        nelem, num_col,
    )
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_create_from_csc(
    col_ptr_ptr: int, col_ptr_type: int, indices_ptr: int, data_ptr: int,
    data_type: int, ncol_ptr: int, nelem: int, num_row: int, parameters: str,
    ref_id: int,
) -> int:
    X = _abi_csc(
        col_ptr_ptr, col_ptr_type, indices_ptr, data_ptr, data_type, ncol_ptr,
        nelem, num_row,
    )
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    did = next(_ids)
    _datasets[did] = ds
    return did


def dataset_get_num_data(did: int) -> int:
    return int(_dataset(did)._binned.num_data)


def dataset_get_num_feature(did: int) -> int:
    return int(_dataset(did)._binned.num_total_features)


def dataset_set_field(
    did: int, field_name: str, data_ptr: int, num_element: int, dtype_code: int
) -> None:
    # Metadata::SetField name dispatch (c_api.cpp LGBM_DatasetSetField)
    ds = _dataset(did)
    arr = _read_array(data_ptr, num_element, dtype_code)
    if field_name == "label":
        ds.set_label(arr)
    elif field_name == "weight":
        ds.set_weight(arr)
    elif field_name == "init_score":
        ds.set_init_score(arr)
    elif field_name in ("group", "query"):
        ds.set_group(arr)
    else:
        raise ValueError("unknown field name %r" % field_name)


def dataset_get_field(did: int, field_name: str) -> Optional[np.ndarray]:
    ds = _dataset(did)
    if field_name == "label":
        return ds.get_label()
    if field_name == "weight":
        return ds.get_weight()
    if field_name == "init_score":
        return ds.get_init_score()
    if field_name in ("group", "query"):
        return ds.get_group()
    raise ValueError("unknown field name %r" % field_name)


def dataset_save_binary(did: int, filename: str) -> None:
    _dataset(did).save_binary(filename)


def dataset_free(did: int) -> None:
    _datasets.pop(did, None)


# ---------------------------------------------------------------------------
# Booster surface
# ---------------------------------------------------------------------------


class _CBooster:
    """Booster + its attached eval data (BoosterHandle contents, c_api.cpp)."""

    def __init__(self, booster: Booster):
        self.booster = booster


def booster_create(train_id: int, parameters: str) -> int:
    params = _params_str_to_dict(parameters)
    bst = Booster(params=params, train_set=_dataset(train_id))
    bid = next(_ids)
    _boosters[bid] = _CBooster(bst)
    return bid


def booster_create_from_modelfile(filename: str) -> Tuple[int, int]:
    bst = Booster(model_file=filename)
    bid = next(_ids)
    _boosters[bid] = _CBooster(bst)
    return bid, int(bst.current_iteration)


def booster_free(bid: int) -> None:
    _boosters.pop(bid, None)


def booster_add_valid_data(bid: int, did: int) -> None:
    _boosters[bid].booster.add_valid(_dataset(did), "valid_%d" % did)


def booster_update_one_iter(bid: int) -> int:
    # is_finished (1 = no split possible) lags one call behind the reference
    # C API: the deferred stop check means the splitless call returns 0 and
    # the 1 arrives on the next LGBM_BoosterUpdateOneIter, which trains
    # nothing. Model state after the loop is identical (Booster.update doc).
    return 1 if _boosters[bid].booster.update() else 0


def booster_get_eval(bid: int, data_idx: int, out_ptr: int) -> int:
    # data_idx 0 = training data, i = i-th valid set (c_api.h:585-597)
    bst = _boosters[bid].booster
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst._gbdt.valid_names[data_idx - 1]
        results = [t for t in bst.eval_valid() if t[0] == name]
    vals = np.asarray([t[2] for t in results], np.float64)
    if len(vals):
        _write_doubles(out_ptr, vals)
    return len(vals)


def booster_get_num_classes(bid: int) -> int:
    return _boosters[bid].booster.num_model_per_iteration()


def booster_get_current_iteration(bid: int) -> int:
    # c_api.h:470 LGBM_BoosterGetCurrentIteration
    return _boosters[bid].booster.current_iteration


def booster_get_eval_counts(bid: int) -> int:
    # c_api.h:528 LGBM_BoosterGetEvalCounts: number of metric values one
    # booster_get_eval call writes (callers size their buffer with this).
    # Derived from the metric list without evaluating — a booster loaded from
    # a model file has no training data attached, and the reference returns 0
    # there rather than erroring. Rank metrics emit one value per eval_at
    # position (GetName() returns one name per position in the reference).
    gbdt = getattr(_boosters[bid].booster, "_gbdt", None)
    metrics = getattr(gbdt, "training_metrics", None) or []
    return sum(len(getattr(m, "eval_at", None) or (1,)) for m in metrics)


def booster_save_model(
    bid: int, start_iteration: int, num_iteration: int, filename: str
) -> None:
    _boosters[bid].booster.save_model(
        filename, num_iteration=num_iteration, start_iteration=start_iteration
    )


def booster_predict_for_mat(
    bid: int, data_ptr: int, data_type: int, nrow: int, ncol: int,
    is_row_major: int, predict_type: int, num_iteration: int, parameter: str,
    out_ptr: int,
) -> int:
    arr = _read_array(data_ptr, nrow * ncol, data_type).astype(np.float64)
    X = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    bst = _boosters[bid].booster
    kw = dict(num_iteration=num_iteration)
    if predict_type == PREDICT_RAW_SCORE:
        out = bst.predict(X, raw_score=True, **kw)
    elif predict_type == PREDICT_LEAF_INDEX:
        out = bst.predict(X, pred_leaf=True, **kw)
    elif predict_type == PREDICT_CONTRIB:
        out = bst.predict(X, pred_contrib=True, **kw)
    else:
        out = bst.predict(X, **kw)
    out = np.ascontiguousarray(out, np.float64)
    _write_doubles(out_ptr, out)
    return int(out.size)


def booster_predict_for_file(
    bid: int, data_filename: str, data_has_header: int, predict_type: int,
    num_iteration: int, parameter: str, result_filename: str,
) -> None:
    from .io import load_text_file

    bst = _boosters[bid].booster
    X, _, _ = load_text_file(
        data_filename, has_header=bool(data_has_header), label_column=0
    )
    kw = dict(num_iteration=num_iteration)
    if predict_type == PREDICT_RAW_SCORE:
        out = bst.predict(X, raw_score=True, **kw)
    elif predict_type == PREDICT_LEAF_INDEX:
        out = bst.predict(X, pred_leaf=True, **kw)
    elif predict_type == PREDICT_CONTRIB:
        out = bst.predict(X, pred_contrib=True, **kw)
    else:
        out = bst.predict(X, **kw)
    out = np.atleast_2d(np.asarray(out, np.float64))
    if out.shape[0] == 1 and out.size > 1:
        out = out.T
    with vopen(result_filename, "w") as fh:
        for row in out:
            fh.write("\t".join(repr(float(v)) for v in np.atleast_1d(row)) + "\n")

# ---------------------------------------------------------------------------
# Full-ABI surface (round 3): the 42 remaining c_api.h entry points
# ---------------------------------------------------------------------------

_STRSEP = "\x01"  # joins string lists across the C boundary (never in names)


def _abi_csr(
    indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr, nelem,
    num_col,
):
    """ABI pointers -> scipy CSR, O(nnz) — the iterator-style no-densify
    ingestion of the reference's CSR row functions (c_api.cpp RowFunction-
    FromCSR): construct_dataset bins scipy sparse column-wise without ever
    materializing a dense matrix."""
    from scipy import sparse

    indptr = _read_array(indptr_ptr, nindptr, indptr_type).astype(np.int64)
    indices = _read_array(indices_ptr, nelem, DTYPE_INT32).astype(np.int32)
    data = _read_array(data_ptr, nelem, data_type).astype(np.float64)
    return sparse.csr_matrix(
        (data, indices, indptr), shape=(nindptr - 1, num_col)
    )


def _abi_csc(
    col_ptr_ptr, col_ptr_type, indices_ptr, data_ptr, data_type, ncol_ptr,
    nelem, num_row,
):
    from scipy import sparse

    col_ptr = _read_array(col_ptr_ptr, ncol_ptr, col_ptr_type).astype(np.int64)
    indices = _read_array(indices_ptr, nelem, DTYPE_INT32).astype(np.int32)
    data = _read_array(data_ptr, nelem, data_type).astype(np.float64)
    return sparse.csc_matrix(
        (data, indices, col_ptr), shape=(num_row, ncol_ptr - 1)
    )


def _csr_to_dense(
    indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr, nelem,
    num_col,
):
    """Dense form for the row-push ABI (caller-chosen batch size bounds it)."""
    return _abi_csr(
        indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr,
        nelem, num_col,
    ).toarray()


def _register_dataset(ds) -> int:
    did = next(_ids)
    _datasets[did] = ds
    if isinstance(ds, _PushDataset):
        ds.did = did
    return did


class _PushDataset:
    """Streaming two-round container behind LGBM_DatasetCreateByReference /
    CreateFromSampledColumn + PushRows[ByCSR] (c_api.h:86-177). Rows arrive in
    chunks; once num_total_row rows have landed the real Dataset is
    constructed (the reference's DatasetLoader::ConstructFromSampleData +
    FinishLoad flow) and REPLACES this object in the handle table, so the
    caller's handle transparently becomes the finished Dataset. Metadata set
    before the last chunk (the reference allocates metadata at create time and
    accepts SetField at any point) is buffered and applied at finish.
    """

    def __init__(self, num_total_row: int, params: dict, reference=None,
                 ncol: int = 0):
        self.num_total_row = int(num_total_row)
        self.params = params
        self.reference = reference
        self.ncol = ncol
        self.X = None
        self.pushed = 0
        self.did = 0  # handle id, filled at registration
        self._pending = {}  # field -> array, applied at finish

    def _ensure(self, ncol: int):
        if self.X is None:
            self.ncol = ncol
            self.X = np.zeros((self.num_total_row, ncol), np.float64)

    def push(self, rows: np.ndarray, start_row: int):
        self._ensure(rows.shape[1])
        self.X[start_row:start_row + rows.shape[0]] = rows
        self.pushed += rows.shape[0]
        if self.pushed >= self.num_total_row:
            self.finish()

    def finish(self):
        ds = Dataset(self.X, params=self.params, reference=self.reference)
        for field, arr in self._pending.items():
            ds.set_field(field, arr)
        ds.construct()
        if self.did:
            _datasets[self.did] = ds  # handle now IS the finished Dataset

    # pre-finish metadata (dataset_set_field dispatches to these)
    def set_label(self, v):
        self._pending["label"] = v

    def set_weight(self, v):
        self._pending["weight"] = v

    def set_group(self, v):
        self._pending["group"] = v

    def set_init_score(self, v):
        self._pending["init_score"] = v


def dataset_create_by_reference(ref_id: int, num_total_row: int) -> int:
    ref = _dataset(ref_id)
    return _register_dataset(
        _PushDataset(num_total_row, dict(getattr(ref, "params", {}) or {}),
                     reference=ref)
    )


def dataset_create_from_sampled_column(
    sample_data_pp: int, sample_indices_pp: int, ncol: int,
    num_per_col_ptr: int, num_sample_row: int, num_total_row: int,
    parameters: str,
) -> int:
    # double** / int** pointer tables (c_api.h:60-76). The sampled columns
    # seed nothing here beyond shape checking: binning happens at finish()
    # over the full pushed matrix, which subsumes the reference's
    # sample-then-bin flow (BinMapper::FindBin over samples) with exact bins.
    params = _params_str_to_dict(parameters)
    ds = _PushDataset(num_total_row, params, ncol=ncol)
    return _register_dataset(ds)


def _push_target(did: int) -> _PushDataset:
    ds = _datasets[did]
    if not isinstance(ds, _PushDataset):
        raise ValueError("DatasetHandle %d is not awaiting pushed rows" % did)
    return ds


def dataset_push_rows(
    did: int, data_ptr: int, data_type: int, nrow: int, ncol: int,
    start_row: int,
) -> None:
    rows = _read_array(data_ptr, nrow * ncol, data_type).astype(np.float64)
    _push_target(did).push(rows.reshape(nrow, ncol), start_row)


def dataset_push_rows_by_csr(
    did: int, indptr_ptr: int, indptr_type: int, indices_ptr: int,
    data_ptr: int, data_type: int, nindptr: int, nelem: int, num_col: int,
    start_row: int,
) -> None:
    rows = _csr_to_dense(
        indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr,
        nelem, num_col,
    )
    _push_target(did).push(rows, start_row)


def dataset_create_from_mats(
    nmat: int, data_pp: int, data_type: int, nrow_ptr: int, ncol: int,
    is_row_major: int, parameters: str, ref_id: int,
) -> int:
    ptrs = _read_array(data_pp, nmat, DTYPE_INT64)
    nrows = _read_array(nrow_ptr, nmat, DTYPE_INT32)
    mats = []
    for p, nr in zip(ptrs, nrows):
        arr = _read_array(int(p), int(nr) * ncol, data_type).astype(np.float64)
        mats.append(
            arr.reshape(int(nr), ncol) if is_row_major
            else arr.reshape(ncol, int(nr)).T
        )
    X = np.concatenate(mats, axis=0)
    params = _params_str_to_dict(parameters)
    ref = _datasets.get(ref_id) if ref_id else None
    ds = Dataset(X, params=params, reference=ref)
    ds.construct()
    return _register_dataset(ds)


def dataset_get_subset(
    did: int, indices_ptr: int, num_indices: int, parameters: str
) -> int:
    idx = _read_array(indices_ptr, num_indices, DTYPE_INT32)
    params = _params_str_to_dict(parameters)
    sub = _dataset(did).subset(idx, params=params or None)
    # materialize eagerly (Dataset::CopySubset): the handle's GetNumData etc.
    # read _binned directly
    sub._binned = sub.construct_subset(Config.from_params(sub.params or {}))
    return _register_dataset(sub)


def dataset_add_features_from(target_id: int, source_id: int) -> None:
    _dataset(target_id).add_features_from(_dataset(source_id))


def dataset_dump_text(did: int, filename: str) -> None:
    _dataset(did).dump_text(filename)


def dataset_set_feature_names(did: int, joined: str) -> None:
    _dataset(did).set_feature_name(joined.split(_STRSEP) if joined else [])


def dataset_get_feature_names(did: int) -> str:
    ds = _dataset(did)
    names = getattr(ds, "feature_name", None)
    if callable(names):
        names = names()
    if not names or names == "auto":
        binned = getattr(ds, "_binned", None)
        n = binned.num_total_features if binned is not None else 0
        names = ["Column_%d" % i for i in range(n)]
    return _STRSEP.join(names)


def dataset_update_param(did: int, parameters: str) -> None:
    ds = _dataset(did)
    new = _params_str_to_dict(parameters)
    cur = dict(getattr(ds, "params", {}) or {})
    cur.update(new)
    ds.params = cur


def dataset_get_field_ptr(did: int, field_name: str):
    """(addr, len, dtype_code) with the backing array kept alive on the
    Dataset (LGBM_DatasetGetField returns a borrowed pointer, c_api.h:338)."""
    ds = _dataset(did)
    arr = dataset_get_field(did, field_name)
    if arr is None:
        return 0, 0, DTYPE_FLOAT32
    if field_name in ("group", "query"):
        # the reference returns the CUMULATIVE query boundaries as int32
        arr = np.concatenate([[0], np.cumsum(np.asarray(arr, np.int64))])
        arr = arr.astype(np.int32)
        code = DTYPE_INT32
    elif field_name == "init_score":
        arr = np.ascontiguousarray(arr, np.float64)
        code = DTYPE_FLOAT64
    else:
        arr = np.ascontiguousarray(arr, np.float32)
        code = DTYPE_FLOAT32
    if not hasattr(ds, "_capi_field_refs"):
        ds._capi_field_refs = {}
    ds._capi_field_refs[field_name] = arr  # keep the buffer alive
    return int(arr.ctypes.data), int(arr.size), code


# -- booster long tail ------------------------------------------------------


def booster_load_model_from_string(model_str: str) -> Tuple[int, int]:
    bst = Booster(model_str=model_str)
    bid = next(_ids)
    _boosters[bid] = _CBooster(bst)
    return bid, int(bst.current_iteration)


def booster_save_model_to_string(
    bid: int, start_iteration: int, num_iteration: int
) -> str:
    return _boosters[bid].booster.model_to_string(
        num_iteration=num_iteration, start_iteration=start_iteration
    )


def booster_dump_model(bid: int, start_iteration: int, num_iteration: int) -> str:
    import json

    d = _boosters[bid].booster.dump_model(num_iteration=num_iteration)
    if start_iteration > 0:
        K = _boosters[bid].booster.num_model_per_iteration()
        d = dict(d)
        d["tree_info"] = d.get("tree_info", [])[start_iteration * K:]
    return json.dumps(d)


def booster_merge(bid: int, other_bid: int) -> None:
    _boosters[bid].booster._gbdt.merge_models_from(
        _boosters[other_bid].booster._gbdt
    )


def booster_get_num_feature(bid: int) -> int:
    return int(_boosters[bid].booster.num_feature())


def booster_num_model_per_iteration(bid: int) -> int:
    return int(_boosters[bid].booster.num_model_per_iteration())


def booster_number_of_total_model(bid: int) -> int:
    return int(_boosters[bid].booster.num_trees())


def _metric_value_names(gbdt) -> list:
    """Metric names in eval order, one per emitted value (rank metrics emit
    name@k per eval position — matches booster_get_eval_counts)."""
    out = []
    for m in getattr(gbdt, "training_metrics", None) or []:
        ks = getattr(m, "eval_at", None)
        if ks:
            out.extend("%s@%d" % (m.names[0], k) for k in ks)
        else:
            out.append(m.names[0])
    return out


def booster_get_eval_names(bid: int) -> str:
    gbdt = getattr(_boosters[bid].booster, "_gbdt", None)
    return _STRSEP.join(_metric_value_names(gbdt) if gbdt is not None else [])


def booster_get_feature_names(bid: int) -> str:
    return _STRSEP.join(_boosters[bid].booster.feature_name())


def booster_get_leaf_value(bid: int, tree_idx: int, leaf_idx: int) -> float:
    return float(_boosters[bid].booster.get_leaf_output(tree_idx, leaf_idx))


def booster_set_leaf_value(
    bid: int, tree_idx: int, leaf_idx: int, value: float
) -> None:
    gbdt = _boosters[bid].booster._gbdt
    trees = gbdt.trees()  # materialize hosts
    trees[tree_idx].leaf_value[leaf_idx] = value
    # drop the device copy so prediction reads the edited host tree
    if tree_idx < len(gbdt._device_trees):
        _, cid = gbdt._device_trees[tree_idx]
        gbdt._device_trees[tree_idx] = (None, cid)


def booster_rollback_one_iter(bid: int) -> None:
    _boosters[bid].booster.rollback_one_iter()


def booster_reset_parameter(bid: int, parameters: str) -> None:
    _boosters[bid].booster.reset_parameter(_params_str_to_dict(parameters))


def booster_reset_training_data(bid: int, did: int) -> None:
    # gbdt.cpp ResetTrainingData: keep the models, swap the training set.
    cb = _boosters[bid]
    old = cb.booster
    nb = Booster(dict(old.params), _dataset(did))
    if (
        nb._gbdt.num_tree_per_iteration != old._gbdt.num_tree_per_iteration
    ):
        raise ValueError(
            "Cannot reset training data: models-per-iteration mismatch"
        )
    nb._gbdt.merge_models_from(old._gbdt)
    cb.booster = nb


def booster_shuffle_models(bid: int, start_iter: int, end_iter: int) -> None:
    _boosters[bid].booster.shuffle_models(start_iter, end_iter)


def booster_update_one_iter_custom(
    bid: int, grad_ptr: int, hess_ptr: int
) -> int:
    bst = _boosters[bid].booster
    gbdt = bst._gbdt
    n = gbdt.num_data * gbdt.num_tree_per_iteration
    grad = _read_array(grad_ptr, n, DTYPE_FLOAT32)
    hess = _read_array(hess_ptr, n, DTYPE_FLOAT32)
    return 1 if gbdt.train_one_iter(grad, hess) else 0


def booster_refit(bid: int, leaf_preds_ptr: int, nrow: int, ncol: int) -> None:
    cb = _boosters[bid]
    preds = _read_array(leaf_preds_ptr, nrow * ncol, DTYPE_INT32).reshape(
        nrow, ncol
    )
    decay = getattr(cb.booster.config, "refit_decay_rate", 0.9)
    cb.booster._gbdt.refit(preds, decay)


def booster_calc_num_predict(
    bid: int, num_row: int, predict_type: int, num_iteration: int
) -> int:
    bst = _boosters[bid].booster
    K = bst.num_model_per_iteration()
    total_iter = bst.current_iteration
    it = total_iter if num_iteration <= 0 else min(num_iteration, total_iter)
    if predict_type == PREDICT_LEAF_INDEX:
        return num_row * K * it
    if predict_type == PREDICT_CONTRIB:
        return num_row * K * (bst.num_feature() + 1)
    return num_row * K


def booster_get_num_predict(bid: int, data_idx: int) -> int:
    gbdt = _boosters[bid].booster._gbdt
    if data_idx == 0:
        n = gbdt.num_data
    else:
        n = gbdt.valid_sets[data_idx - 1].num_data
    return int(n) * gbdt.num_tree_per_iteration


def booster_get_predict(bid: int, data_idx: int, out_ptr: int) -> int:
    # converted (post-objective) scores for train/valid rows
    # (GBDT::GetPredictAt, gbdt.cpp)
    bst = _boosters[bid].booster
    gbdt = bst._gbdt
    score = (
        gbdt._train_score_np() if data_idx == 0 else gbdt._valid_score_np(data_idx - 1)
    )
    out = gbdt.objective.convert_output(score) if gbdt.objective is not None else score
    out = np.ascontiguousarray(np.asarray(out, np.float64).T)  # row-major [N, K]
    _write_doubles(out_ptr, out.reshape(-1))
    return int(out.size)


def _predict_into(
    bid: int, X: np.ndarray, predict_type: int, num_iteration: int,
    parameter: str, out_ptr: int,
) -> int:
    bst = _boosters[bid].booster
    kw = dict(num_iteration=num_iteration)
    if predict_type == PREDICT_RAW_SCORE:
        out = bst.predict(X, raw_score=True, **kw)
    elif predict_type == PREDICT_LEAF_INDEX:
        out = bst.predict(X, pred_leaf=True, **kw)
    elif predict_type == PREDICT_CONTRIB:
        out = bst.predict(X, pred_contrib=True, **kw)
    else:
        out = bst.predict(X, **kw)
    out = np.ascontiguousarray(out, np.float64)
    _write_doubles(out_ptr, out)
    return int(out.size)


def _predict_sparse_into(
    bid, sp, predict_type, num_iteration, parameter, out_ptr,
    chunk_elems=16 << 20,
):
    """Row-chunked sparse prediction: peak memory O(chunk x F), not
    O(nrow x F) — the vectorized analogue of the reference's row-iterator
    predict (c_api.cpp CSR predict path). Chunks write consecutively into
    the caller's buffer (every predict type is row-major per row)."""
    n, ncol = sp.shape
    chunk = max(1, min(n, chunk_elems // max(ncol, 1)))
    csr = sp.tocsr()
    written = 0
    for lo in range(0, n, chunk):
        X = csr[lo : lo + chunk].toarray().astype(np.float64)
        written += _predict_into(
            bid, X, predict_type, num_iteration, parameter,
            out_ptr + written * 8,
        )
    return written


def booster_predict_for_csr(
    bid: int, indptr_ptr: int, indptr_type: int, indices_ptr: int,
    data_ptr: int, data_type: int, nindptr: int, nelem: int, num_col: int,
    predict_type: int, num_iteration: int, parameter: str, out_ptr: int,
) -> int:
    sp = _abi_csr(
        indptr_ptr, indptr_type, indices_ptr, data_ptr, data_type, nindptr,
        nelem, num_col,
    )
    return _predict_sparse_into(
        bid, sp, predict_type, num_iteration, parameter, out_ptr
    )


def booster_predict_for_csc(
    bid: int, col_ptr_ptr: int, col_ptr_type: int, indices_ptr: int,
    data_ptr: int, data_type: int, ncol_ptr: int, nelem: int, num_row: int,
    predict_type: int, num_iteration: int, parameter: str, out_ptr: int,
) -> int:
    sp = _abi_csc(
        col_ptr_ptr, col_ptr_type, indices_ptr, data_ptr, data_type, ncol_ptr,
        nelem, num_row,
    )
    return _predict_sparse_into(
        bid, sp, predict_type, num_iteration, parameter, out_ptr
    )


def booster_predict_for_mat_single_row(
    bid: int, data_ptr: int, data_type: int, ncol: int, is_row_major: int,
    predict_type: int, num_iteration: int, parameter: str, out_ptr: int,
) -> int:
    arr = _read_array(data_ptr, ncol, data_type).astype(np.float64)
    return _predict_into(
        bid, arr.reshape(1, ncol), predict_type, num_iteration, parameter,
        out_ptr,
    )


def booster_predict_for_mats(
    bid: int, data_pp: int, data_type: int, nrow: int, ncol: int,
    predict_type: int, num_iteration: int, parameter: str, out_ptr: int,
) -> int:
    # one pointer per ROW (c_api.h:841-870)
    ptrs = _read_array(data_pp, nrow, DTYPE_INT64)
    X = np.empty((nrow, ncol), np.float64)
    for i, p in enumerate(ptrs):
        X[i] = _read_array(int(p), ncol, data_type).astype(np.float64)
    return _predict_into(bid, X, predict_type, num_iteration, parameter, out_ptr)


# -- network ----------------------------------------------------------------

_network = {"num_machines": 1, "rank": 0}


def network_init(
    machines: str, local_listen_port: int, listen_time_out: int,
    num_machines: int,
) -> None:
    """LGBM_NetworkInit (c_api.h:975). The reference brings up its socket
    linker here; this framework's cross-host transport is the jax.distributed
    runtime + XLA collectives (parallel/mesh.py), so the ABI call records the
    topology and defers transport to the JAX runtime the same way
    tests/test_multiprocess_dist.py drives it."""
    _network.update(
        machines=machines,
        local_listen_port=int(local_listen_port),
        num_machines=int(num_machines),
    )


def network_init_with_functions(
    num_machines: int, rank: int, reduce_scatter_ptr: int, allgather_ptr: int
) -> None:
    # c_api.h:986: external collective functions. XLA owns the collectives
    # here; the pointers are recorded for callers that query them back.
    if int(num_machines) > 1:
        # callers relying on the reference seam (network.cpp:46-59) would get
        # silent no-op collectives — say so loudly (VERDICT r3 weak #6)
        log.warning(
            "LGBM_NetworkInitWithFunctions: external reduce_scatter/allgather "
            "function pointers are recorded but never invoked — this "
            "framework's collectives run inside XLA (jax.distributed + "
            "psum). Use LGBM_NetworkInit / the jax.distributed runtime for "
            "multi-machine training."
        )
    _network.update(
        num_machines=int(num_machines),
        rank=int(rank),
        reduce_scatter_ext=reduce_scatter_ptr,
        allgather_ext=allgather_ptr,
    )


def network_free() -> None:
    _network.clear()
    _network.update({"num_machines": 1, "rank": 0})


def booster_feature_importance(
    bid: int, num_iteration: int, importance_type: int, out_ptr: int
) -> int:
    # c_api.h:962: importance_type 0=split counts, 1=total gains
    bst = _boosters[bid].booster
    kind = "gain" if importance_type == 1 else "split"
    vals = bst.feature_importance(importance_type=kind, iteration=num_iteration)
    vals = np.ascontiguousarray(vals, np.float64)
    _write_doubles(out_ptr, vals)
    return int(vals.size)
