"""lightgbm_tpu: a TPU-native gradient boosting framework.

A ground-up reimplementation of LightGBM's capabilities (reference:
CharlesAuguste/LightGBM v2.2.4) designed for TPU hardware: binned features live as
dense device tensors, per-leaf gradient/hessian histograms and split-gain scans run
as JAX/XLA (and Pallas) programs, leaf-wise tree growth runs inside a single jitted
while-loop, and distributed training maps row sharding onto a jax.sharding.Mesh
with XLA collectives over ICI/DCN.

Public API mirrors the LightGBM python package: Dataset, Booster, train, cv,
sklearn-style estimators, and the callback set.
"""

from .utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()  # must run before anything initializes a jax backend

from .basic import Booster, Dataset
from .callback import early_stopping, print_evaluation, record_evaluation, reset_parameter
from .config import Config
from .engine import CVBooster, cv, train
from .utils.log import LightGBMError

try:
    from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                           plot_split_value_histogram, plot_tree)

    _PLOT = ["plot_importance", "plot_metric", "plot_split_value_histogram", "plot_tree", "create_tree_digraph"]
except ImportError:  # pragma: no cover - matplotlib/graphviz not installed
    _PLOT = []

try:
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor

    _SKLEARN = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover - sklearn not installed
    _SKLEARN = []

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "Booster",
    "Config",
    "train",
    "cv",
    "CVBooster",
    "LightGBMError",
    "early_stopping",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
] + _SKLEARN + _PLOT
