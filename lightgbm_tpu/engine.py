"""train() / cv() drivers.

Mirrors /root/reference/python-package/lightgbm/engine.py:19 (train) and :343 (cv):
callback orchestration, early stopping, init_model continuation, evals_result
recording, stratified/group k-fold cross validation.
"""
from __future__ import annotations

import collections
import copy
import os
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .obs import flight as flight_mod
from .obs import podwatch as podwatch_mod
from .obs import registry as obs_registry
from .obs import sanitize as sanitize_mod
from .obs import trace as trace_mod
from .resil import faults
from .resil import preempt as preempt_mod
from .utils import timer as timer_mod
from . import config as config_mod
from .config import Config
from .utils import log
from .utils.log import LightGBMError


def train(
    params: Dict,
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    feature_name: str = "auto",
    categorical_feature: str = "auto",
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[Dict] = None,
    verbose_eval: Union[bool, int] = True,
    learning_rates=None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_rounds: int = 0,
    resume_from: Optional[str] = None,
    checkpoint_keep: int = 0,
    preempt_exit: Optional[bool] = None,
    flex_plan: Optional[str] = None,
) -> Booster:
    params = dict(params) if params else {}
    params = Config.canonicalize(params)
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and early_stopping_rounds is None:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    # resilience params (docs/FaultTolerance.md) may ride in via params;
    # explicit kwargs win. They are POPPED so the Booster's Config (and the
    # model's parameters footer) stays independent of where a run was
    # checkpointed/resumed — the footer byte-identity the crash tests assert.
    if "checkpoint_path" in params:
        v = str(params.pop("checkpoint_path"))
        checkpoint_path = checkpoint_path or v
    if "checkpoint_rounds" in params:
        v = int(params.pop("checkpoint_rounds"))
        checkpoint_rounds = checkpoint_rounds if checkpoint_rounds > 0 else v
    if "resume_from" in params:
        v = str(params.pop("resume_from"))
        resume_from = resume_from or v
    if "checkpoint_keep" in params:
        v = int(params.pop("checkpoint_keep"))
        checkpoint_keep = checkpoint_keep if checkpoint_keep > 0 else v
    if "preempt_exit" in params:
        v = config_mod.coerce_bool(params.pop("preempt_exit"))
        preempt_exit = v if preempt_exit is None else preempt_exit
    if preempt_exit is None:
        preempt_exit = preempt_mod.env_enabled()
    # fleet orchestration (lightgbm_tpu/flex/): same pop discipline. An
    # EXPLICIT flex_plan="" disarms the env, mirroring preempt_exit=false.
    if "flex_plan" in params:
        v = str(params.pop("flex_plan"))
        flex_plan = v if flex_plan is None else flex_plan
    flex_dead_after_s = 60.0
    if "flex_dead_after_s" in params:
        flex_dead_after_s = float(params.pop("flex_dead_after_s"))
    # controller-only flex knobs ride along when the flex CLI passes its
    # whole argv to the child; pop them so the model footer stays clean
    for _k in ("flex_world", "flex_min_world", "flex_max_restarts",
               "flex_backoff_base_s", "flex_backoff_max_s",
               "flex_force_cpu", "flex_seed", "flex_max_launches",
               "flex_journal"):
        params.pop(_k, None)
    if flex_plan is None:
        # the ONE env read flexctl costs when off (the inertness contract
        # tests/test_flex.py pins); the name mirrors flex/capacity.ENV_PLAN
        flex_plan = os.environ.get("LIGHTGBM_TPU_FLEX_PLAN")
    flex_plan = flex_plan or None
    # model/data observability params (docs/Observability.md): POPPED like
    # the resil params so the model's parameters footer stays byte-identical
    # with recording on or off — the bitwise-identity contract the
    # flight-recorder tests assert
    flight_path = None
    if "flight_record" in params:
        flight_path = str(params.pop("flight_record")) or None
    flight_path = flight_path or flight_mod.env_path()
    model_stats = False
    if "model_stats" in params:
        model_stats = config_mod.coerce_bool(params.pop("model_stats"))
    if resume_from and not checkpoint_path:
        # a resumed run keeps checkpointing to the file it resumed from: the
        # crash that made the checkpoint necessary can strike again, and a
        # second preemption must not throw away all post-resume progress
        checkpoint_path = resume_from
    if checkpoint_path and checkpoint_rounds <= 0:
        # snapshot_freq parity: the reference's snapshot cadence doubles as
        # the checkpoint cadence when no explicit rounds are given; absent
        # both, default to ~10 checkpoints per run — a checkpoint serializes
        # the full model text + score carries (+fsync), so a cadence of 1
        # would turn a long run I/O-bound
        snap = int(params.get("snapshot_freq", -1) or -1)
        checkpoint_rounds = snap if snap > 0 else max(1, num_boost_round // 10)
    if resume_from and init_model is not None:
        raise LightGBMError(
            "resume_from and init_model are mutually exclusive: a checkpoint "
            "already carries its full model"
        )
    if fobj is not None:
        params["objective"] = "none"
    # continued training
    predictor = None
    if init_model is not None:
        if isinstance(init_model, str):
            predictor = Booster(model_file=init_model)
        elif isinstance(init_model, Booster):
            predictor = init_model
    init_iteration = predictor.current_iteration if predictor is not None else 0

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature
    if predictor is not None:
        train_set.set_predictor(predictor)

    booster = Booster(params=params, train_set=train_set)
    if predictor is not None:
        booster._gbdt._merge_from(predictor._gbdt)

    is_valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        if valid_names is None:
            valid_names = ["valid_%d" % i for i in range(len(valid_sets))]
        for i, vset in enumerate(valid_sets):
            if vset is train_set:
                is_valid_contain_train = True
                train_data_name = valid_names[i]
                continue
            if vset.reference is None:
                vset.reference = train_set
            booster.add_valid(vset, valid_names[i])

    # callbacks
    cbs = set(callbacks or [])
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(
            callback_mod.early_stopping(
                early_stopping_rounds, bool(params.get("first_metric_only", False)),
                verbose=bool(verbose_eval),
            )
        )
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    cbs_before = {c for c in cbs if getattr(c, "before_iteration", False)}
    cbs_after = cbs - cbs_before
    cbs_before = sorted(cbs_before, key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted(cbs_after, key=lambda c: getattr(c, "order", 0))

    # crash-safe checkpoint/resume (resil/checkpoint.py). Restore happens
    # AFTER valid sets attach (their score carries come from the checkpoint,
    # not a tree replay) and after callbacks exist (the early-stopping bests
    # restore into the live stoppers).
    start_iteration = init_iteration
    ckpt_writer = None
    if resume_from or checkpoint_path:
        from .resil import checkpoint as ckpt_mod

        if resume_from:
            ckpt = ckpt_mod.restore(booster, resume_from, cbs_after)
            init_iteration = ckpt.begin_iteration
            start_iteration = ckpt.iteration
            # num_boost_round is a train() ARGUMENT, so restore()'s
            # config-digest warning cannot catch a mismatched end bound —
            # check it against the manifest's end_iteration here
            ckpt_end = int(ckpt.manifest["end_iteration"])
            live_end = init_iteration + num_boost_round
            if live_end < start_iteration:
                raise LightGBMError(
                    "resume_from: num_boost_round=%d ends the run at "
                    "iteration %d, BEFORE the checkpoint's position %d — "
                    "nothing would train and the returned model would carry "
                    "more iterations than requested; pass the original run's "
                    "num_boost_round (%d)"
                    % (num_boost_round, live_end, start_iteration,
                       ckpt_end - init_iteration)
                )
            if live_end != ckpt_end:
                log.warning(
                    "resume: num_boost_round=%d ends the run at iteration %d "
                    "but the checkpointed run ended at %d; the resumed run "
                    "will NOT be bit-identical to the original"
                    % (num_boost_round, live_end, ckpt_end)
                )
        if checkpoint_path:
            # refuse unsupported configs (dart) NOW, not at the first cadence
            # boundary checkpoint_rounds iterations in
            ckpt_mod.check_checkpointable(booster._gbdt)
            ckpt_writer = ckpt_mod.CheckpointWriter(
                checkpoint_path, checkpoint_rounds, cbs_after,
                keep=max(checkpoint_keep, 1),
            )

    # Device-resident chunked boosting (GBDT.train_chunk): up to
    # device_chunk_size iterations fuse into one jitted dispatch; callbacks,
    # eval and early stopping then observe chunk BOUNDARIES only
    # (docs/DeviceResidentBoosting.md). Custom objectives and
    # before-iteration callbacks (reset_parameter mutates per-iteration
    # config) force the per-iteration loop; early stopping clamps the chunk
    # so a stop can never overshoot its detection window.
    chunk = 1
    if fobj is None and not cbs_before:
        chunk = booster._gbdt.device_chunk()
        if chunk > 1 and early_stopping_rounds is not None and early_stopping_rounds > 0:
            chunk = min(chunk, early_stopping_rounds)
        # an early_stopping() instance handed in via callbacks= carries its
        # window as an attribute — clamp to it too, or the stop check would
        # run at chunk granularity instead of the requested one
        for cb in cbs_after:
            sr = getattr(cb, "stopping_rounds", 0)
            if chunk > 1 and isinstance(sr, int) and sr > 0:
                chunk = min(chunk, sr)

    # training flight recorder (obs/flight.py): run manifest now — the
    # checkpoint restore above already positioned a resumed run, so the
    # manifest's provenance fields are final. start() returning None (bad
    # path, nested run) silently leaves recording off.
    flight_rec = None
    if flight_path:
        parent_fp = None
        if predictor is not None:
            # lineage edge for the manifest: the warm-start parent's
            # fingerprint — the FILE's bytes when init_model was a path
            # (matching the serve registry's file_sha), else the live
            # booster's bare model-text fingerprint
            from .models.model_text import model_fingerprint

            try:
                if isinstance(init_model, str):
                    from .utils.vfile import vopen

                    with vopen(init_model) as fh:
                        parent_fp = model_fingerprint(fh.read())
                else:
                    parent_fp = model_fingerprint(predictor.model_to_string())
            except Exception as e:  # lineage must never fail the run
                log.debug("flight: parent fingerprint failed: %r" % (e,))
        flight_rec = flight_mod.start(
            flight_path,
            flight_mod.build_manifest(
                booster, num_boost_round, init_iteration,
                resume_from=resume_from, checkpoint_path=checkpoint_path,
                parent_fingerprint=parent_fp,
            ),
        )

    # preemption-aware training (resil/preempt.py): SIGTERM latches a flag
    # the boost loop honors at the next chunk boundary — emergency
    # checkpoint, then TrainingPreempted (exit code 75 at the process entry
    # points). Mirrors serve/__main__.py's drain contract for the trainer.
    preempt_watcher = None
    if preempt_exit:
        if ckpt_writer is None:
            log.warning(
                "preempt: preempt_exit armed without checkpoint_path — a "
                "SIGTERM will exit with the preemption code but WITHOUT an "
                "emergency checkpoint to resume from"
            )
        preempt_watcher = preempt_mod.PreemptionWatcher()
        preempt_watcher.install()

    # live fleet telemetry (obs/podwatch.py): per-rank boundary recorder
    # (LIGHTGBM_TPU_TELEMETRY=<dir>) + opt-in scrape endpoint
    # (LIGHTGBM_TPU_TELEMETRY_PORT). Both unset costs one env read per
    # gate here and nothing in the loop; the trained model is bitwise
    # independent of telemetry either way (host-side sampling only).
    telemetry_rec = podwatch_mod.maybe_start(preempt_watcher=preempt_watcher)

    # fleet orchestration (lightgbm_tpu/flex/): a capacity plan arms a
    # boundary-driven watcher that latches the SAME chunk-boundary latch
    # preemption uses, with reason "drain" (exit RESHARD_EXIT_CODE so the
    # flexctl controller relaunches at the new capacity). Threadless: its
    # whole runtime cost is one check_boundary call per chunk boundary.
    # flex_plan unset costs exactly the one env read above — no import, no
    # latch, no objects (the inertness contract).
    latch = preempt_watcher
    flex_watcher = None
    if flex_plan:
        from .flex import watch as flexwatch_mod
        from .obs import dist as dist_mod
        from .resil import checkpoint as ckpt_mod

        if ckpt_writer is None:
            log.warning(
                "flex: flex_plan armed without checkpoint_path — a drain "
                "will exit with the reshard code but WITHOUT a checkpoint "
                "for the relaunch to resume from"
            )
        rank, procs = dist_mod.process_info()
        hb_base = None
        if procs > 1:
            # dead-rank evidence: the telemetry heartbeats refresh every
            # boundary when podwatch is armed; the checkpoint-side ones
            # only at checkpoint cadence (still usable, just coarser)
            hb_base = (podwatch_mod.heartbeat_base(telemetry_rec.out_dir)
                       if telemetry_rec is not None else checkpoint_path)
        if latch is None:
            latch = preempt_mod.BoundaryLatch()
        flex_watcher = flexwatch_mod.maybe_watch(
            flex_plan, latch,
            checkpoint_path=checkpoint_path or flex_plan,
            live_world=ckpt_mod.mesh_world_of(booster._gbdt),
            procs=procs, rank=rank, hb_base=hb_base,
            dead_after_s=flex_dead_after_s,
        )

    evaluation_result_list: List = []
    try:
        with timer_mod.maybe_profile():
            try:
                evaluation_result_list = _boost_loop(
                    booster, params, fobj, feval, valid_sets,
                    is_valid_contain_train, train_data_name, init_iteration,
                    num_boost_round, cbs_before, cbs_after, chunk,
                    start_iteration=start_iteration, ckpt_writer=ckpt_writer,
                    preempt_watcher=latch, flex_watcher=flex_watcher,
                )
            except Exception as e:
                # compose with the collective watchdog instead of racing
                # it: when flex is armed, a named collective deadline is a
                # capacity event (a peer is gone) — drain so the
                # controller reshards onto the survivors
                detail = (flex_watcher.drain_reason_for(e)
                          if flex_watcher is not None else None)
                if detail is None:
                    raise
                flex_watcher.note_failure_drain(detail)
                log.warning(
                    "flex: %s — draining so the orchestrator reshards "
                    "onto the survivors (exiting with the reshard code, "
                    "%d); the last periodic checkpoint is the recovery "
                    "point" % (detail, preempt_mod.RESHARD_EXIT_CODE)
                )
                raise preempt_mod.TrainingDrained(
                    "training drained after %s" % detail,
                    checkpoint_path=getattr(ckpt_writer, "path", None),
                    detail=detail,
                ) from e
        return _finish_train(
            booster, evaluation_result_list, flight_rec, model_stats
        )
    finally:
        if preempt_watcher is not None:
            preempt_watcher.uninstall()
        # a crashed/interrupted run (anywhere — the loop, the deferred stop
        # readback, the profiler, the harvest) still closes its flight log:
        # the records up to the failure are exactly the evidence wanted,
        # and a leaked _ACTIVE recorder would silently disable recording
        # for every later train() in the process
        if flight_rec is not None and flight_mod.active() is flight_rec:
            flight_mod.note_event("aborted")
            flight_mod.stop()
        # same leak rule for the telemetry recorder; the scrape listener
        # (if armed) deliberately stays up across train() calls
        if (telemetry_rec is not None
                and podwatch_mod.active() is telemetry_rec):
            podwatch_mod.stop()


def _finish_train(booster, evaluation_result_list, flight_rec, model_stats):
    """Post-loop bookkeeping (split from train() so its flight-recorder
    finally can distinguish a clean finish from an abort)."""
    # resolve the deferred no-split check before handing the booster back:
    # a stop inside the FINAL chunk (or final iteration) would otherwise
    # leave rolled-back-to-be trees visible to num_trees/current_iteration
    # until something materializes the model
    booster._gbdt._consume_pending_stop()
    booster._gbdt.timers.report()
    # same numbers, machine-readable: phase totals land in the metrics
    # registry so /metrics, bench JSON and bringup reports all agree
    booster._gbdt.timers.publish()

    # env-gated segment profiler (LIGHTGBM_TPU_PROF_SEGMENTS=N): after
    # training, run N profiling iterations of fenced sub-step tree growth —
    # breakdown lands in run_report()/gauges/trace spans; the trainer's
    # state is NOT advanced (obs/prof.py). Unsupported configs log and skip.
    from .obs import prof as prof_mod

    if prof_mod.segments_enabled():
        reason = prof_mod.unsupported_reason(booster._gbdt)
        if reason is not None:
            log.warning("segment profiler skipped: %s" % reason)
        else:
            try:
                rec = prof_mod.profile_growth(
                    booster, iters=prof_mod.segments_iters()
                )
                log.info(
                    "growth segments (s/tree): %s | sum/fused=%.3f bitwise=%s"
                    % (rec["segments_per_tree_s"], rec["segment_sum_ratio"],
                       rec["bitwise_identical"])
                )
            except Exception as e:  # profiling must never fail training
                log.warning("segment profiler failed: %r" % e)

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for (dname, ename, v, _) in evaluation_result_list or []:
        booster.best_score[dname][ename] = v
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration

    # model/data observability tier (docs/Observability.md): both read only
    # host state — the trained model is bitwise-unaffected and nothing new
    # compiles. modelstats also engages whenever a flight log was recorded
    # (one opt-in should yield the whole model-observability picture).
    if flight_rec is not None:
        flight_mod.finish_training(booster)
    from .obs import modelstats as modelstats_mod

    if model_stats or flight_rec is not None or modelstats_mod.env_enabled():
        modelstats_mod.publish(booster)
    return booster


def _boost_loop(
    booster, params, fobj, feval, valid_sets, is_valid_contain_train,
    train_data_name, init_iteration, num_boost_round, cbs_before, cbs_after,
    chunk: int = 1, start_iteration: Optional[int] = None, ckpt_writer=None,
    preempt_watcher=None, flex_watcher=None,
):
    """The boosting iteration loop; returns the last evaluation result list.

    ``chunk > 1`` steps by device-resident chunks (Booster.update_chunk):
    eval and after-iteration callbacks run once per chunk boundary with
    ``iteration`` = the last completed iteration; ``chunk=1`` is the classic
    per-iteration loop, byte-identical to the pre-chunking behavior.

    ``start_iteration`` positions a RESUMED loop past the checkpointed
    iterations while ``init_iteration`` keeps the original run's begin (so
    callback windows and the end bound replay identically); ``ckpt_writer``
    (resil/checkpoint.py) saves the full training state at its cadence
    boundaries."""
    evaluation_result_list: List = []
    needs_eval = valid_sets is not None or bool(
        params.get("is_provide_training_metric")
    )
    i = init_iteration if start_iteration is None else start_iteration
    end = init_iteration + num_boost_round
    if booster._gbdt._stopped:
        # a checkpoint taken AT a no-split stop boundary restores
        # stopped=True: nothing is left to train, and one more loop pass
        # would re-run eval + callbacks the uninterrupted run never had
        return evaluation_result_list
    iter_counter = obs_registry.REGISTRY.counter("train_iterations")
    import time as _time

    flight_on = flight_mod.active() is not None
    telemetry_on = podwatch_mod.active() is not None
    t_boundary = _time.perf_counter()
    while i < end:
        # named fault site: the crash tests SIGKILL here mid-run and prove
        # resume_from replays to a byte-identical model (resil/faults.py)
        faults.maybe_fire("train.iteration")
        for cb in cbs_before:
            cb(
                callback_mod.CallbackEnv(
                    model=booster,
                    params=params,
                    iteration=i,
                    begin_iteration=init_iteration,
                    end_iteration=end,
                    evaluation_result_list=None,
                )
            )
        # the transfer sanitizer's guarded scopes live at the JITTED
        # dispatch seams this loop drives (gbdt.train_chunk, ops.grow_tree,
        # gbdt.finish_tree, serve's bucketed dispatch) rather than around
        # the whole boundary: the sequential path's eager gradient/bagging
        # math legitimately materializes python/numpy scalar constants,
        # which jax uploads through the same implicit path the guard
        # polices (obs/sanitize.py)
        if chunk > 1 and end - i >= chunk:
            with trace_mod.span("train.chunk", cat="train", iteration=i,
                                chunk=chunk):
                done, finished = booster.update_chunk(
                    chunk, sync_stop=needs_eval
                )
            if done == 0:
                break
        else:
            # the tail shorter than a chunk runs per-iteration: a tail-sized
            # scan would trace + XLA-compile a whole second boosting program
            # to save at most chunk-1 host round-trips
            with trace_mod.span("train.iteration", cat="train", iteration=i):
                finished = booster.update(fobj=fobj)
            done = 1
        i += done
        iter_counter.inc(done)
        if sanitize_mod.NAN:
            # boundary tripwire: a non-finite score carry fails HERE, named,
            # instead of surfacing iterations later as a metric collapse
            sanitize_mod.check_scores(booster._gbdt, i - 1)

        evaluation_result_list = []
        if needs_eval:
            if is_valid_contain_train:
                evaluation_result_list.extend(
                    [(train_data_name, n, v, b) for (_, n, v, b) in booster.eval_train(feval)]
                )
            evaluation_result_list.extend(booster.eval_valid(feval))
            hist = booster._gbdt._eval_history
            for (dname, mname, val, _) in evaluation_result_list:
                hist.setdefault(dname, {}).setdefault(mname, []).append(val)
        if flight_on or telemetry_on:
            # one record per boundary: the boundary's wall time (host clock
            # only — the dispatch is async either way, so this is
            # dispatch+eval time, not a fence), shared by the flight
            # recorder and the telemetry ring so both attribute the SAME
            # seconds to the same boundary
            now = _time.perf_counter()
            dt_boundary = now - t_boundary
            t_boundary = now
            if flight_on:
                flight_mod.note_boundary(
                    i - 1, done, dt_boundary, evaluation_result_list
                )
            if telemetry_on:
                podwatch_mod.note_boundary(
                    i - 1, done, dt_boundary, gbdt=booster._gbdt
                )
        try:
            for cb in cbs_after:
                cb(
                    callback_mod.CallbackEnv(
                        model=booster,
                        params=params,
                        iteration=i - 1,
                        begin_iteration=init_iteration,
                        end_iteration=end,
                        evaluation_result_list=evaluation_result_list,
                        chunk=done,
                    )
                )
        except callback_mod.EarlyStopException as es:
            booster.best_iteration = es.best_iteration + 1
            evaluation_result_list = es.best_score
            if flight_on:
                flight_mod.note_event(
                    "early_stop", iteration=i - 1,
                    best_iteration=es.best_iteration + 1,
                )
            break
        wrote_boundary = False
        if ckpt_writer is not None and ckpt_writer.due(i, done):
            # after the boundary's eval + callbacks, so the early-stopping
            # bests captured are exactly the ones a resumed run needs next
            try:
                ckpt_writer.write(booster, init_iteration, end)
                wrote_boundary = True
                if flight_on:
                    flight_mod.note_event("checkpoint", iteration=i)
            except LightGBMError:
                raise  # structural refusal (e.g. dart): a config error, loud
            except Exception as e:
                # a failed write (ENOSPC, NFS blip) must not kill the run it
                # exists to protect: the last good checkpoint is intact on
                # disk (atomic publish), so warn and keep training
                obs_registry.REGISTRY.counter("resil_checkpoint_errors").inc()
                log.warning(
                    "checkpoint: write failed (%s: %s); continuing — the "
                    "last good checkpoint is intact"
                    % (type(e).__name__, str(e)[:200])
                )
        if flex_watcher is not None:
            # the flex capacity watcher runs at the same boundary the
            # latch is honored at, so a plan change seen NOW drains NOW
            # (single-process; a pod takes one more boundary to reach
            # marker consensus — flex/watch.py documents the protocol)
            flex_watcher.check_boundary(i)
        if (preempt_watcher is not None and preempt_watcher.requested()
                and i < end and not finished):
            # a latched SIGTERM (reason "preempt") or flex drain (reason
            # "drain") is honored HERE, at a chunk boundary — the one
            # place the full training state is checkpointable — but NOT
            # when this boundary just finished the run (i == end, or the
            # deferred no-split stop resolved): the trained model is
            # complete in memory, and exiting 75/76 would throw it away
            # just to retrain it on resume. Fault site train.preempt lets
            # the crash tests SIGKILL between the signal and the emergency
            # write (the last periodic checkpoint must carry the resume).
            reason = getattr(preempt_watcher, "reason", "preempt")
            no_barrier = getattr(preempt_watcher, "no_barrier", False)
            faults.maybe_fire("train.preempt")
            ck_path = None
            if ckpt_writer is not None:
                from .obs import dist as dist_mod

                multiproc = dist_mod.process_info()[1] > 1
                if wrote_boundary:
                    # this boundary's periodic checkpoint IS the state an
                    # emergency save would capture — don't publish it twice
                    ck_path = ckpt_writer.path
                elif multiproc and reason == "preempt":
                    # multi-process world: the emergency save would run the
                    # coordinated digest barrier, but SIGTERM latch timing
                    # is per-rank — a peer whose signal landed one boundary
                    # later is inside its next collective, and waiting for
                    # it would burn the whole kill grace window. The
                    # periodic BARRIER checkpoints are the pod-coherent
                    # recovery points; exit on the last one. (A planned
                    # DRAIN is different: the marker protocol latches every
                    # rank at the same boundary, so its coordinated save
                    # below CAN barrier.)
                    log.warning(
                        "preempt: multi-process world — skipping the "
                        "emergency checkpoint (per-rank signal timing "
                        "cannot run the coordinated save barrier); the "
                        "last periodic checkpoint is the recovery point"
                    )
                elif multiproc and no_barrier:
                    # dead-rank drain: the digest barrier can never reach
                    # consensus with a participant gone — survivors exit
                    # on the last periodic checkpoint
                    log.warning(
                        "flex: drain without barrier (%s) — skipping the "
                        "coordinated emergency checkpoint; the last "
                        "periodic checkpoint is the recovery point"
                        % (getattr(preempt_watcher, "detail", "") or reason)
                    )
                else:
                    try:
                        ck_path = ckpt_writer.write(
                            booster, init_iteration, end, emergency=True
                        )
                    except Exception as e:
                        # the grace window is running out either way: exit
                        # preempted on the last good periodic checkpoint
                        log.warning(
                            "preempt: emergency checkpoint failed (%s: %s); "
                            "exiting on the last periodic checkpoint"
                            % (type(e).__name__, str(e)[:200])
                        )
            if reason == "drain":
                detail = getattr(preempt_watcher, "detail", "") or "drain"
                if flight_on:
                    flight_mod.note_event(
                        "drained", iteration=i - 1, checkpoint=ck_path
                    )
                log.warning(
                    "flex: drain (%s) honored at iteration %d; checkpoint "
                    "%s; exiting with the reshard code (%d)"
                    % (detail, i, ck_path or "<none>",
                       preempt_mod.RESHARD_EXIT_CODE)
                )
                raise preempt_mod.TrainingDrained(
                    "training drained for reshard (%s) at iteration %d"
                    % (detail, i),
                    checkpoint_path=ck_path, iteration=i, detail=detail,
                )
            if flight_on:
                flight_mod.note_event(
                    "preempted", iteration=i - 1, checkpoint=ck_path
                )
            log.warning(
                "preempt: signal %d honored at iteration %d; emergency "
                "checkpoint %s; exiting with the preemption code (%d)"
                % (preempt_watcher.signum, i, ck_path or "<none>",
                   preempt_mod.PREEMPT_EXIT_CODE)
            )
            raise preempt_mod.TrainingPreempted(
                "training preempted by signal %d at iteration %d"
                % (preempt_watcher.signum, i),
                checkpoint_path=ck_path, iteration=i,
                signum=preempt_watcher.signum,
            )
        if finished:
            # the deferred no-split stop (models/gbdt.py) resolved at this
            # boundary: the splitless iteration was rolled back already
            if flight_on:
                flight_mod.note_event("no_split_stop", iteration=i - 1)
            break
    return evaluation_result_list


class CVBooster:
    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold, params, seed, stratified, shuffle, config):
    full_data.construct(config)
    num_data = full_data.num_data()
    binned = full_data._binned
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            group = binned.metadata.query_boundaries
            group_info = None
            if group is not None:
                qid = np.zeros(num_data, np.int64)
                for q in range(len(group) - 1):
                    qid[group[q] : group[q + 1]] = q
                group_info = qid
            folds = folds.split(X=np.zeros(num_data), y=binned.metadata.label, groups=group_info)
    else:
        rng = np.random.RandomState(seed)
        if binned.metadata.query_boundaries is not None:
            # group-aware folds: split whole queries
            nq = binned.metadata.num_queries
            qperm = rng.permutation(nq) if shuffle else np.arange(nq)
            fold_qs = np.array_split(qperm, nfold)
            qb = binned.metadata.query_boundaries
            folds = []
            for fq in fold_qs:
                test_idx = np.concatenate(
                    [np.arange(qb[q], qb[q + 1]) for q in sorted(fq)]
                ) if len(fq) else np.array([], np.int64)
                train_idx = np.setdiff1d(np.arange(num_data), test_idx)
                folds.append((train_idx, test_idx))
        elif stratified:
            label = binned.metadata.label.astype(np.int64)
            folds = []
            fold_assign = np.zeros(num_data, np.int64)
            for cls in np.unique(label):
                idx = np.nonzero(label == cls)[0]
                if shuffle:
                    idx = idx[rng.permutation(len(idx))]
                fold_assign[idx] = np.arange(len(idx)) % nfold
            for k in range(nfold):
                test_idx = np.nonzero(fold_assign == k)[0]
                train_idx = np.nonzero(fold_assign != k)[0]
                folds.append((train_idx, test_idx))
        else:
            perm = rng.permutation(num_data) if shuffle else np.arange(num_data)
            chunks = np.array_split(perm, nfold)
            folds = [
                (np.setdiff1d(np.arange(num_data), c), np.sort(c)) for c in chunks
            ]
    return folds


def cv(
    params: Dict,
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics=None,
    fobj=None,
    feval=None,
    init_model=None,
    feature_name: str = "auto",
    categorical_feature: str = "auto",
    early_stopping_rounds: Optional[int] = None,
    fpreproc=None,
    verbose_eval=None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks=None,
    eval_train_metric: bool = False,
) -> Dict[str, List[float]]:
    params = Config.canonicalize(dict(params) if params else {})
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and early_stopping_rounds is None:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    if metrics is not None:
        params["metric"] = metrics
    if params.get("objective") in ("binary",) or str(params.get("objective", "")).startswith("multiclass"):
        pass
    else:
        stratified = False
    config = Config.from_params(params)

    folds = _make_n_folds(train_set, folds, nfold, params, seed, stratified, shuffle, config)

    results = collections.defaultdict(list)
    cvboosters = []
    fold_data = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        te = train_set.subset(np.sort(test_idx))
        booster = Booster(params=params, train_set=tr)
        booster.add_valid(te, "valid")
        cvboosters.append(booster)
        fold_data.append((tr, te))

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds, verbose=False))
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.add(callback_mod.print_evaluation(verbose_eval, show_stdv))
    cbs = sorted(cbs, key=lambda c: getattr(c, "order", 0))

    best_iteration = -1
    for i in range(num_boost_round):
        agg: Dict[str, List[float]] = collections.defaultdict(list)
        for booster in cvboosters:
            booster.update(fobj=fobj)
            for (dname, ename, v, b) in booster.eval_valid(feval):
                agg[("%s %s" % (dname, ename), b)].append(v)
        res_list = []
        for (key, bigger), vals in agg.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[key.split(" ", 1)[1] + "-mean"].append(mean)
            results[key.split(" ", 1)[1] + "-stdv"].append(std)
            res_list.append(("cv_agg", key.split(" ", 1)[1], mean, bigger, std))
        try:
            for cb in cbs:
                cb(
                    callback_mod.CallbackEnv(
                        model=None,
                        params=params,
                        iteration=i,
                        begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=res_list,
                    )
                )
        except callback_mod.EarlyStopException as es:
            best_iteration = es.best_iteration + 1
            for key in list(results.keys()):
                results[key] = results[key][:best_iteration]
            break
    return dict(results)
