"""train() / cv() drivers.

Mirrors /root/reference/python-package/lightgbm/engine.py:19 (train) and :343 (cv):
callback orchestration, early stopping, init_model continuation, evals_result
recording, stratified/group k-fold cross validation.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .obs import registry as obs_registry
from .obs import trace as trace_mod
from .utils import timer as timer_mod
from .config import Config
from .utils import log
from .utils.log import LightGBMError


def train(
    params: Dict,
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    feature_name: str = "auto",
    categorical_feature: str = "auto",
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[Dict] = None,
    verbose_eval: Union[bool, int] = True,
    learning_rates=None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
) -> Booster:
    params = dict(params) if params else {}
    params = Config.canonicalize(params)
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and early_stopping_rounds is None:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    if fobj is not None:
        params["objective"] = "none"
    # continued training
    predictor = None
    if init_model is not None:
        if isinstance(init_model, str):
            predictor = Booster(model_file=init_model)
        elif isinstance(init_model, Booster):
            predictor = init_model
    init_iteration = predictor.current_iteration if predictor is not None else 0

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature
    if predictor is not None:
        train_set.set_predictor(predictor)

    booster = Booster(params=params, train_set=train_set)
    if predictor is not None:
        booster._gbdt._merge_from(predictor._gbdt)

    is_valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        if valid_names is None:
            valid_names = ["valid_%d" % i for i in range(len(valid_sets))]
        for i, vset in enumerate(valid_sets):
            if vset is train_set:
                is_valid_contain_train = True
                train_data_name = valid_names[i]
                continue
            if vset.reference is None:
                vset.reference = train_set
            booster.add_valid(vset, valid_names[i])

    # callbacks
    cbs = set(callbacks or [])
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(
            callback_mod.early_stopping(
                early_stopping_rounds, bool(params.get("first_metric_only", False)),
                verbose=bool(verbose_eval),
            )
        )
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    cbs_before = {c for c in cbs if getattr(c, "before_iteration", False)}
    cbs_after = cbs - cbs_before
    cbs_before = sorted(cbs_before, key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted(cbs_after, key=lambda c: getattr(c, "order", 0))

    # Device-resident chunked boosting (GBDT.train_chunk): up to
    # device_chunk_size iterations fuse into one jitted dispatch; callbacks,
    # eval and early stopping then observe chunk BOUNDARIES only
    # (docs/DeviceResidentBoosting.md). Custom objectives and
    # before-iteration callbacks (reset_parameter mutates per-iteration
    # config) force the per-iteration loop; early stopping clamps the chunk
    # so a stop can never overshoot its detection window.
    chunk = 1
    if fobj is None and not cbs_before:
        chunk = booster._gbdt.device_chunk()
        if chunk > 1 and early_stopping_rounds is not None and early_stopping_rounds > 0:
            chunk = min(chunk, early_stopping_rounds)
        # an early_stopping() instance handed in via callbacks= carries its
        # window as an attribute — clamp to it too, or the stop check would
        # run at chunk granularity instead of the requested one
        for cb in cbs_after:
            sr = getattr(cb, "stopping_rounds", 0)
            if chunk > 1 and isinstance(sr, int) and sr > 0:
                chunk = min(chunk, sr)

    evaluation_result_list: List = []
    with timer_mod.maybe_profile():
        evaluation_result_list = _boost_loop(
            booster, params, fobj, feval, valid_sets, is_valid_contain_train,
            train_data_name, init_iteration, num_boost_round,
            cbs_before, cbs_after, chunk,
        )
    # resolve the deferred no-split check before handing the booster back:
    # a stop inside the FINAL chunk (or final iteration) would otherwise
    # leave rolled-back-to-be trees visible to num_trees/current_iteration
    # until something materializes the model
    booster._gbdt._consume_pending_stop()
    booster._gbdt.timers.report()
    # same numbers, machine-readable: phase totals land in the metrics
    # registry so /metrics, bench JSON and bringup reports all agree
    booster._gbdt.timers.publish()

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for (dname, ename, v, _) in evaluation_result_list or []:
        booster.best_score[dname][ename] = v
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration
    return booster


def _boost_loop(
    booster, params, fobj, feval, valid_sets, is_valid_contain_train,
    train_data_name, init_iteration, num_boost_round, cbs_before, cbs_after,
    chunk: int = 1,
):
    """The boosting iteration loop; returns the last evaluation result list.

    ``chunk > 1`` steps by device-resident chunks (Booster.update_chunk):
    eval and after-iteration callbacks run once per chunk boundary with
    ``iteration`` = the last completed iteration; ``chunk=1`` is the classic
    per-iteration loop, byte-identical to the pre-chunking behavior."""
    evaluation_result_list: List = []
    needs_eval = valid_sets is not None or bool(
        params.get("is_provide_training_metric")
    )
    i = init_iteration
    end = init_iteration + num_boost_round
    iter_counter = obs_registry.REGISTRY.counter("train_iterations")
    while i < end:
        for cb in cbs_before:
            cb(
                callback_mod.CallbackEnv(
                    model=booster,
                    params=params,
                    iteration=i,
                    begin_iteration=init_iteration,
                    end_iteration=end,
                    evaluation_result_list=None,
                )
            )
        if chunk > 1 and end - i >= chunk:
            with trace_mod.span("train.chunk", cat="train", iteration=i,
                                chunk=chunk):
                done, finished = booster.update_chunk(
                    chunk, sync_stop=needs_eval
                )
            if done == 0:
                break
        else:
            # the tail shorter than a chunk runs per-iteration: a tail-sized
            # scan would trace + XLA-compile a whole second boosting program
            # to save at most chunk-1 host round-trips
            with trace_mod.span("train.iteration", cat="train", iteration=i):
                finished = booster.update(fobj=fobj)
            done = 1
        i += done
        iter_counter.inc(done)

        evaluation_result_list = []
        if needs_eval:
            if is_valid_contain_train:
                evaluation_result_list.extend(
                    [(train_data_name, n, v, b) for (_, n, v, b) in booster.eval_train(feval)]
                )
            evaluation_result_list.extend(booster.eval_valid(feval))
            hist = booster._gbdt._eval_history
            for (dname, mname, val, _) in evaluation_result_list:
                hist.setdefault(dname, {}).setdefault(mname, []).append(val)
        try:
            for cb in cbs_after:
                cb(
                    callback_mod.CallbackEnv(
                        model=booster,
                        params=params,
                        iteration=i - 1,
                        begin_iteration=init_iteration,
                        end_iteration=end,
                        evaluation_result_list=evaluation_result_list,
                        chunk=done,
                    )
                )
        except callback_mod.EarlyStopException as es:
            booster.best_iteration = es.best_iteration + 1
            evaluation_result_list = es.best_score
            break
        if finished:
            break
    return evaluation_result_list


class CVBooster:
    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold, params, seed, stratified, shuffle, config):
    full_data.construct(config)
    num_data = full_data.num_data()
    binned = full_data._binned
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            group = binned.metadata.query_boundaries
            group_info = None
            if group is not None:
                qid = np.zeros(num_data, np.int64)
                for q in range(len(group) - 1):
                    qid[group[q] : group[q + 1]] = q
                group_info = qid
            folds = folds.split(X=np.zeros(num_data), y=binned.metadata.label, groups=group_info)
    else:
        rng = np.random.RandomState(seed)
        if binned.metadata.query_boundaries is not None:
            # group-aware folds: split whole queries
            nq = binned.metadata.num_queries
            qperm = rng.permutation(nq) if shuffle else np.arange(nq)
            fold_qs = np.array_split(qperm, nfold)
            qb = binned.metadata.query_boundaries
            folds = []
            for fq in fold_qs:
                test_idx = np.concatenate(
                    [np.arange(qb[q], qb[q + 1]) for q in sorted(fq)]
                ) if len(fq) else np.array([], np.int64)
                train_idx = np.setdiff1d(np.arange(num_data), test_idx)
                folds.append((train_idx, test_idx))
        elif stratified:
            label = binned.metadata.label.astype(np.int64)
            folds = []
            fold_assign = np.zeros(num_data, np.int64)
            for cls in np.unique(label):
                idx = np.nonzero(label == cls)[0]
                if shuffle:
                    idx = idx[rng.permutation(len(idx))]
                fold_assign[idx] = np.arange(len(idx)) % nfold
            for k in range(nfold):
                test_idx = np.nonzero(fold_assign == k)[0]
                train_idx = np.nonzero(fold_assign != k)[0]
                folds.append((train_idx, test_idx))
        else:
            perm = rng.permutation(num_data) if shuffle else np.arange(num_data)
            chunks = np.array_split(perm, nfold)
            folds = [
                (np.setdiff1d(np.arange(num_data), c), np.sort(c)) for c in chunks
            ]
    return folds


def cv(
    params: Dict,
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics=None,
    fobj=None,
    feval=None,
    init_model=None,
    feature_name: str = "auto",
    categorical_feature: str = "auto",
    early_stopping_rounds: Optional[int] = None,
    fpreproc=None,
    verbose_eval=None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks=None,
    eval_train_metric: bool = False,
) -> Dict[str, List[float]]:
    params = Config.canonicalize(dict(params) if params else {})
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and early_stopping_rounds is None:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    if metrics is not None:
        params["metric"] = metrics
    if params.get("objective") in ("binary",) or str(params.get("objective", "")).startswith("multiclass"):
        pass
    else:
        stratified = False
    config = Config.from_params(params)

    folds = _make_n_folds(train_set, folds, nfold, params, seed, stratified, shuffle, config)

    results = collections.defaultdict(list)
    cvboosters = []
    fold_data = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        te = train_set.subset(np.sort(test_idx))
        booster = Booster(params=params, train_set=tr)
        booster.add_valid(te, "valid")
        cvboosters.append(booster)
        fold_data.append((tr, te))

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds, verbose=False))
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.add(callback_mod.print_evaluation(verbose_eval, show_stdv))
    cbs = sorted(cbs, key=lambda c: getattr(c, "order", 0))

    best_iteration = -1
    for i in range(num_boost_round):
        agg: Dict[str, List[float]] = collections.defaultdict(list)
        for booster in cvboosters:
            booster.update(fobj=fobj)
            for (dname, ename, v, b) in booster.eval_valid(feval):
                agg[("%s %s" % (dname, ename), b)].append(v)
        res_list = []
        for (key, bigger), vals in agg.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[key.split(" ", 1)[1] + "-mean"].append(mean)
            results[key.split(" ", 1)[1] + "-stdv"].append(std)
            res_list.append(("cv_agg", key.split(" ", 1)[1], mean, bigger, std))
        try:
            for cb in cbs:
                cb(
                    callback_mod.CallbackEnv(
                        model=None,
                        params=params,
                        iteration=i,
                        begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=res_list,
                    )
                )
        except callback_mod.EarlyStopException as es:
            best_iteration = es.best_iteration + 1
            for key in list(results.keys()):
                results[key] = results[key][:best_iteration]
            break
    return dict(results)
