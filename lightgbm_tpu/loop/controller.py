"""The closed-loop continuous-training controller.

Wires the repo's islands into one production loop (ROADMAP item 5): the
serve stack's drift signal triggers a retrain that WARM-STARTS from the live
published model (``engine.train(init_model=...)`` — bit-exact continuation,
tests/test_warmstart.py), the candidate is gated against the serving model
on a holdout, published through resil/atomic, hot-swapped into every serve
replica through the registry's existing swap path (each load rebuilds the
drift monitor against the new model's lattice + sidecar — the drift-sidecar
refresh), then watched through a settle window with an automatic rollback to
the previous published version on regression.

Preemption safety: every step entry is journaled atomically
(loop/state.py), every step is IDEMPOTENT given its journaled inputs, and
every arrow carries a resil/faults.py site (``loop.observe`` /
``loop.retrain`` / ``loop.validate`` / ``loop.publish`` / ``loop.swap``), so
the kill-anywhere suite SIGKILLs a real controller at each one and proves
the restarted loop converges: the live model file is always either the old
or the fully-validated new version (atomic publish), and the rollback
pointer is durable before the live file is ever touched.

Library use::

    cfg = LoopConfig(model_path=..., workdir=..., params={...},
                     num_boost_round=30, data_provider=my_provider,
                     replicas=[HttpReplica("http://127.0.0.1:8080")],
                     drift_source=HttpDriftSource("http://127.0.0.1:8080"))
    LoopController(cfg).run_cycle(force=True)

``python -m lightgbm_tpu.loop`` wraps this for file-fed operation
(docs/ContinuousTraining.md).
"""
from __future__ import annotations

import json
import os
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.model_text import model_fingerprint
from ..obs import flight as flight_mod
from ..obs import registry as obs_registry
from ..obs import trace as trace_mod
from ..resil import backoff, faults
from ..resil.atomic import atomic_write_text
from ..utils import log
from ..utils.log import LightGBMError
from .state import LoopJournal

#: suffix of the lineage sidecar published next to every live model file —
#: parent fingerprint + flight-manifest digest, fingerprint-checked by the
#: serve registry like the drift sidecar (serve/server.py)
LINEAGE_SUFFIX = ".lineage.json"
LINEAGE_VERSION = 1
#: retained previous-version copy (the rollback target) next to the live file
PREV_SUFFIX = ".prev"

FAULT_OBSERVE = "loop.observe"
FAULT_RETRAIN = "loop.retrain"
FAULT_VALIDATE = "loop.validate"
FAULT_PUBLISH = "loop.publish"
FAULT_SWAP = "loop.swap"


def lineage_path(model_path: str) -> str:
    return model_path + LINEAGE_SUFFIX


def load_lineage(model_path: str, file_sha: str) -> Optional[Dict]:
    """Read + fingerprint-check the lineage sidecar next to ``model_path``;
    None when absent or written for different bytes (a stale sidecar must
    not attribute one model's lineage to another)."""
    try:
        with open(lineage_path(model_path), encoding="utf-8") as fh:
            body = json.load(fh)
    except OSError:
        return None
    except ValueError:
        log.warning("loop: lineage sidecar for %r is not valid JSON; ignored"
                    % model_path)
        return None
    if body.get("fingerprint") != file_sha:
        log.warning(
            "loop: lineage sidecar for %r was written for different model "
            "bytes (fingerprint mismatch); ignored" % model_path
        )
        return None
    return body


# ---------------------------------------------------------------------------
# drift sources
# ---------------------------------------------------------------------------

class HttpDriftSource:
    """Polls a serve replica's ``/drift`` endpoint (serve/drift.py). The
    trigger is any feature in alert state on any model."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def poll(self) -> Tuple[bool, Dict]:
        with urllib.request.urlopen(
            self.base_url + "/drift", timeout=self.timeout_s
        ) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        alerts: List[Dict] = []
        for model, snap in (body.get("models") or {}).items():
            for feat in snap.get("alerts") or []:
                alerts.append({"model": model, "feature": feat})
        return bool(alerts), {"source": self.base_url + "/drift",
                              "alerts": alerts}


class AppDriftSource:
    """In-process twin of :class:`HttpDriftSource` over a live ServeApp
    (tests, single-process deployments)."""

    def __init__(self, app):
        self.app = app

    def poll(self) -> Tuple[bool, Dict]:
        body = self.app.drift_snapshot()
        alerts: List[Dict] = []
        for model, snap in (body.get("models") or {}).items():
            for feat in snap.get("alerts") or []:
                alerts.append({"model": model, "feature": feat})
        return bool(alerts), {"source": "in-process", "alerts": alerts}


# ---------------------------------------------------------------------------
# swap targets (replicas)
# ---------------------------------------------------------------------------

class HttpReplica:
    """One serve process reached over HTTP: hot-swap via the existing
    ``POST /models`` path, verify via ``GET /models``."""

    def __init__(self, base_url: str, timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def __repr__(self) -> str:
        return "HttpReplica(%s)" % self.base_url

    def swap(self, name: str, path: str) -> Dict:
        req = urllib.request.Request(
            self.base_url + "/models",
            data=json.dumps({"name": name, "path": path}).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))["loaded"]

    def served_fingerprint(self, name: str) -> Optional[str]:
        with urllib.request.urlopen(
            self.base_url + "/models", timeout=self.timeout_s
        ) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        for info in body.get("models", []):
            if info.get("name") == name:
                return str(info.get("file_sha"))
        return None


class AppReplica:
    """In-process twin of :class:`HttpReplica` over a ModelRegistry (or a
    ServeApp, whose registry is used)."""

    def __init__(self, app_or_registry):
        self.registry = getattr(app_or_registry, "registry", app_or_registry)

    def __repr__(self) -> str:
        return "AppReplica(%s)" % type(self.registry).__name__

    def swap(self, name: str, path: str) -> Dict:
        return self.registry.load(name, path).info()

    def served_fingerprint(self, name: str) -> Optional[str]:
        for info in self.registry.list():
            if info.get("name") == name:
                return str(info.get("file_sha"))
        return None


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class LoopConfig:
    """Everything one controller needs. ``data_provider(cycle)`` returns
    ``(X, y, X_holdout, y_holdout)`` — it MUST be deterministic per cycle
    (same cycle number -> same arrays), because a controller killed mid-
    retrain re-runs the cycle's training from its checkpoint or from
    scratch and both must see the data the first attempt saw."""

    def __init__(
        self,
        model_path: str,
        workdir: str,
        params: Dict,
        num_boost_round: int,
        data_provider: Callable[[int], Tuple],
        replicas: Sequence = (),
        drift_source=None,
        model_name: Optional[str] = None,
        validation_margin: float = 0.0,
        rollback_margin: float = 0.0,
        settle_fn: Optional[Callable[["LoopController", Dict], bool]] = None,
        poll_interval_s: float = 5.0,
        observe_budget_s: float = 300.0,
        jitter_seed: Optional[int] = None,
        checkpoint_rounds: int = 0,
        warm_start: bool = True,
        keep_cycles: int = 3,
    ):
        self.model_path = str(model_path)
        self.workdir = str(workdir)
        self.params = dict(params)
        self.num_boost_round = int(num_boost_round)
        self.data_provider = data_provider
        self.replicas = list(replicas)
        self.drift_source = drift_source
        self.model_name = model_name or (
            os.path.splitext(os.path.basename(model_path))[0] or "model"
        )
        self.validation_margin = float(validation_margin)
        self.rollback_margin = float(rollback_margin)
        self.settle_fn = settle_fn
        self.poll_interval_s = float(poll_interval_s)
        self.observe_budget_s = float(observe_budget_s)
        self.jitter_seed = jitter_seed
        self.checkpoint_rounds = int(checkpoint_rounds)
        self.warm_start = bool(warm_start)
        self.keep_cycles = int(keep_cycles)
        self.journal_path = os.path.join(workdir, "loop_journal.json")


# ---------------------------------------------------------------------------
# validation metrics (host-side numpy; bigger_is_better flagged)
# ---------------------------------------------------------------------------

def _auc(y: np.ndarray, score: np.ndarray) -> float:
    """Rank AUC (ties averaged) — the binary gate metric. O(N log N):
    tied ranks are averaged per run of equal sorted scores, not by
    scanning a mask per unique value (continuous GBDT scores make that
    effectively quadratic on a real holdout)."""
    y = np.asarray(y, np.float64).reshape(-1)
    s = np.asarray(score, np.float64).reshape(-1)
    n = len(s)
    order = np.argsort(s, kind="mergesort")
    ss = s[order]
    starts = np.flatnonzero(np.r_[True, ss[1:] != ss[:-1]])
    ends = np.r_[starts[1:], n]
    # mean of ranks (starts+1 .. ends), repeated over each tie run
    ranks = np.empty(n, np.float64)
    ranks[order] = np.repeat((starts + 1 + ends) / 2.0, ends - starts)
    pos = y > 0
    np_, nn = int(pos.sum()), int((~pos).sum())
    if np_ == 0 or nn == 0:
        return 1.0
    return float((ranks[pos].sum() - np_ * (np_ + 1) / 2.0) / (np_ * nn))


def _logloss(y: np.ndarray, prob: np.ndarray) -> float:
    y = np.asarray(y, np.int64).reshape(-1)
    p = np.asarray(prob, np.float64)
    eps = 1e-15
    if p.ndim == 1:  # binary
        p = np.clip(p, eps, 1 - eps)
        return float(-np.mean(np.where(y > 0, np.log(p), np.log(1 - p))))
    p = np.clip(p[np.arange(len(y)), y], eps, 1.0)
    return float(-np.mean(np.log(p)))


def _l2(y: np.ndarray, pred: np.ndarray) -> float:
    d = np.asarray(y, np.float64).reshape(-1) - np.asarray(
        pred, np.float64
    ).reshape(-1)
    return float(np.mean(d * d))


def gate_metric(objective: str):
    """(name, fn(y, prediction) -> value, bigger_is_better) for the
    validation gate, by objective family."""
    obj = str(objective or "").split(" ")[0]
    if obj == "binary":
        return "auc", _auc, True
    if obj.startswith("multiclass") or obj in ("softmax", "multiclassova"):
        return "multi_logloss", _logloss, False
    return "l2", _l2, False


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class LoopController:
    """Drives one journaled loop over one live model file. Single-threaded
    (the loop is a control plane, not a data plane); every device-touching
    phase is the existing train/serve machinery."""

    def __init__(self, cfg: LoopConfig):
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        self.journal = LoopJournal.load(cfg.journal_path)

    # -- small helpers -----------------------------------------------------

    def _read(self, path: str) -> str:
        with open(path, encoding="utf-8") as fh:
            return fh.read()

    def _file_sha(self, path: str) -> Optional[str]:
        try:
            return model_fingerprint(self._read(path))
        except OSError:
            return None

    def _cycle_file(self, stem: str, cycle: Optional[int] = None) -> str:
        c = self.journal.cycle if cycle is None else cycle
        return os.path.join(self.cfg.workdir, "%s_c%05d" % (stem, c))

    def _copy_published_set(self, src: str, dst: str) -> None:
        """Copy a model file AND its sidecars (drift + lineage) atomically,
        skipping sidecars the source does not have."""
        atomic_write_text(dst, self._read(src))
        for suffix in (".drift.json", LINEAGE_SUFFIX):
            try:
                body = self._read(src + suffix)
            except OSError:
                continue
            atomic_write_text(dst + suffix, body)

    def _gc_workdir(self) -> None:
        """Drop per-cycle artifacts older than ``keep_cycles`` cycles (the
        journal itself and the live/prev files are never touched)."""
        floor = self.journal.cycle - self.cfg.keep_cycles
        if floor <= 0:
            return
        import re

        pat = re.compile(r"_c(\d{5})(\.|$)")
        for name in os.listdir(self.cfg.workdir):
            m = pat.search(name)
            if m and int(m.group(1)) < floor:
                try:
                    os.unlink(os.path.join(self.cfg.workdir, name))
                except OSError:
                    pass

    # -- bootstrap ---------------------------------------------------------

    def ensure_bootstrap(self) -> bool:
        """Train + publish the INITIAL model when no live file exists yet
        (cycle 0's data, no parent). Returns True when it published. Not a
        journaled cycle — a kill mid-bootstrap simply re-runs it; the
        atomic publish keeps the file never-torn either way."""
        if os.path.exists(self.cfg.model_path):
            return False
        log.info("loop: no live model at %r; bootstrapping"
                 % self.cfg.model_path)
        bst, digest, flight_path = self._train(cycle=0, parent=None)
        self._publish_files(
            bst.model_to_string(), booster=bst, parent_fp=None,
            manifest_digest=digest, flight_path=flight_path, cycle=0,
        )
        return True

    # -- the state steps ---------------------------------------------------

    def run_cycle(self, force: bool = False,
                  max_wait_s: Optional[float] = None) -> Optional[str]:
        """Drive the loop from wherever the journal says it is to the next
        terminal arrow. Returns the cycle outcome ("promoted" / "rejected" /
        "rolled_back"), or None when observe saw no trigger within its
        budget. ``force=True`` skips the drift wait (operator-initiated
        retrain; also what the smoke's kill children use so restarts are
        deterministic)."""
        j = self.journal
        if j.state == "observe":
            if not self._observe(force, max_wait_s):
                return None
        # re-entry: each step advances the journal to the next state; a
        # freshly restarted controller falls into the right arm
        while True:
            state = j.state
            if state == "retrain":
                self._retrain()
            elif state == "validate":
                if not self._validate():
                    self._finish("rejected")
                    return "rejected"
            elif state == "publish":
                self._publish()
            elif state == "swap":
                self._swap()
            elif state == "settle":
                if self._settle():
                    self._finish("promoted")
                    return "promoted"
            elif state == "rollback":
                self._rollback()
                self._finish("rolled_back")
                return "rolled_back"
            else:  # observe is only re-entered via _finish, which returns
                raise LightGBMError(
                    "loop: unexpected state %r inside run_cycle" % state
                )

    def run_forever(self, max_cycles: Optional[int] = None) -> int:
        """Observe/retrain until ``max_cycles`` outcomes (None = forever).
        Returns the number of completed cycles."""
        done = 0
        while max_cycles is None or done < max_cycles:
            out = self.run_cycle()
            if out is not None:
                done += 1
        return done

    def _finish(self, outcome: str) -> None:
        self.journal.finish_cycle(outcome)
        obs_registry.REGISTRY.counter(
            "loop_cycles",
            "continuous-training cycles by terminal outcome",
        ).inc(outcome=outcome)
        log.info("loop: cycle %d finished: %s"
                 % (self.journal.cycle, outcome))
        self._gc_workdir()

    def _observe(self, force: bool, max_wait_s: Optional[float]) -> bool:
        """Watch the drift signal until it triggers (or the budget runs
        out). The poll cadence rides backoff.delays with seeded jitter so a
        fleet of controllers never thunders in phase, and the total wait is
        budget-bounded."""
        faults.maybe_fire(FAULT_OBSERVE)
        with trace_mod.span("loop.observe", cat="loop"):
            if force or self.cfg.drift_source is None:
                trig = {"forced": True} if force else {"unconditional": True}
                self.journal.transition("retrain", trigger=trig)
                return True
            budget = (self.cfg.observe_budget_s
                      if max_wait_s is None else float(max_wait_s))
            # first poll immediately, then jittered fixed-cadence waits
            # until the budget is spent
            sleeps = backoff.delays(
                attempts=10_000_000,
                base_s=self.cfg.poll_interval_s,
                factor=1.0,
                max_s=self.cfg.poll_interval_s * 2,
                jitter=0.1,
                seed=self.cfg.jitter_seed,
                max_elapsed_s=budget,
            )
            while True:
                try:
                    triggered, info = self.cfg.drift_source.poll()
                except Exception as e:
                    # a replica restarting or one dropped connection must
                    # not kill the long-running controller: treat the poll
                    # as quiet and keep the (budget-bounded) cadence
                    log.warn_once(
                        "loop-observe-poll",
                        "loop: drift poll failed (%s: %s); retrying on the "
                        "observe cadence" % (type(e).__name__, str(e)[:200]),
                    )
                    triggered, info = False, {}
                if triggered:
                    log.info("loop: drift trigger: %s"
                             % json.dumps(info)[:400])
                    self.journal.transition("retrain", trigger=info)
                    return True
                d = next(sleeps, None)
                if d is None:
                    return False
                time.sleep(d)

    def _train(self, cycle: int, parent: Optional[str]):
        """One (re)training run: warm-started from ``parent`` when given,
        checkpointed so a killed retrain resumes instead of restarting,
        flight-recorded so the published model carries its manifest digest.
        Returns (booster, manifest_digest, flight_path)."""
        from .. import Dataset  # deferred: keep module import light
        from .. import engine

        X, y, _, _ = self.cfg.data_provider(cycle)
        ckpt = self._cycle_file("retrain", cycle) + ".ckpt"
        flight_path = self._cycle_file("flight", cycle) + ".jsonl"
        rounds = self.cfg.num_boost_round
        ck_rounds = self.cfg.checkpoint_rounds or max(1, rounds // 4)
        kwargs = dict(
            verbose_eval=False,
            checkpoint_path=ckpt,
            checkpoint_rounds=ck_rounds,
        )
        params = dict(self.cfg.params)
        params["flight_record"] = flight_path
        if os.path.exists(ckpt):
            # a killed retrain left its checkpoint: resume it (the
            # checkpoint carries the warm-start trees and the exact score
            # carries). A checkpoint that does not match this cycle's data
            # or config is refused loudly by restore — fall back to fresh.
            # A SIGTERMed retrain (TrainingPreempted, exit code 75 at the
            # CLI) re-enters HERE on restart too: its emergency checkpoint
            # is just another resumable archive — and TrainingPreempted is
            # deliberately NOT a LightGBMError, so the fallback below can
            # never swallow a preemption and retrain from scratch.
            try:
                bst = engine.train(
                    params, Dataset(X, label=y), rounds,
                    resume_from=ckpt, **kwargs,
                )
                return bst, self._flight_digest(flight_path), flight_path
            except LightGBMError as e:
                log.warning(
                    "loop: retrain checkpoint %r unusable (%s); retraining "
                    "from scratch" % (ckpt, str(e)[:200])
                )
                try:
                    os.unlink(ckpt)
                except OSError:
                    pass
        init = (
            self.cfg.model_path
            if parent is not None and self.cfg.warm_start
            else None
        )
        bst = engine.train(
            params, Dataset(X, label=y), rounds, init_model=init, **kwargs,
        )
        return bst, self._flight_digest(flight_path), flight_path

    def _flight_digest(self, flight_path: str) -> str:
        try:
            manifest = flight_mod.load(flight_path)["manifest"]
            return flight_mod.manifest_digest(manifest) if manifest else ""
        except OSError:
            return ""

    def _retrain(self) -> None:
        faults.maybe_fire(FAULT_RETRAIN)
        j = self.journal
        with trace_mod.span("loop.retrain", cat="loop", cycle=j.cycle):
            parent_fp = self._file_sha(self.cfg.model_path)
            bst, digest, flight_path = self._train(j.cycle, parent_fp)
            candidate = self._cycle_file("candidate") + ".txt"
            bst.save_model(candidate)
            # drift reference for the candidate NOW, while its training set
            # is live — published next to the live file at the publish step
            # (the drift-sidecar refresh every hot swap then picks up)
            try:
                bst.save_drift_reference(candidate)
            except Exception as e:  # sidecar is best-effort observability
                log.warning("loop: drift sidecar failed: %r" % (e,))
            j.transition(
                "validate",
                candidate_path=candidate,
                candidate_fingerprint=self._file_sha(candidate),
                candidate_manifest_digest=digest,
                candidate_flight=flight_path,
                parent_fingerprint=parent_fp,
            )

    def _predict_on(self, model_text_path: str, X: np.ndarray) -> np.ndarray:
        from ..basic import Booster

        return Booster(model_file=model_text_path).predict(X)

    def _validate(self) -> bool:
        """Gate the candidate against the SERVING model on the holdout.
        Returns False (-> rejected) when the candidate regresses past the
        margin. Idempotent: recomputes from the journaled candidate; a
        missing/foreign candidate file re-enters retrain instead."""
        faults.maybe_fire(FAULT_VALIDATE)
        j = self.journal
        cand = j.get("candidate_path")
        if not cand or self._file_sha(cand) != j.get("candidate_fingerprint"):
            # killed between training and journaling, or artifacts swept:
            # the candidate cannot be trusted — rebuild it
            log.warning("loop: candidate missing/mismatched; re-entering "
                        "retrain (cycle %d)" % j.cycle)
            j.transition("retrain")
            self._retrain()
            return self._validate()
        with trace_mod.span("loop.validate", cat="loop", cycle=j.cycle):
            _, _, Xh, yh = self.cfg.data_provider(j.cycle)
            name, fn, bigger = gate_metric(self.cfg.params.get("objective"))
            cand_m = fn(yh, self._predict_on(cand, Xh))
            serv_m = (
                fn(yh, self._predict_on(self.cfg.model_path, Xh))
                if os.path.exists(self.cfg.model_path)
                else (-np.inf if bigger else np.inf)
            )
            margin = self.cfg.validation_margin
            passed = (
                cand_m >= serv_m - margin if bigger
                else cand_m <= serv_m + margin
            )
            verdict = dict(
                metric=name, bigger_is_better=bigger, margin=margin,
                candidate=float(cand_m), serving=float(serv_m),
                passed=bool(passed),
            )
            log.info("loop: validate cycle %d: %s" % (j.cycle, verdict))
            if not passed:
                j.update(validation=verdict)
                return False
            # the rollback pointer rides the SAME atomic write that makes
            # publish reachable: after this instant the previous version's
            # identity can never be lost, no matter where a kill lands
            j.transition(
                "publish",
                validation=verdict,
                previous_path=(
                    self.cfg.model_path + PREV_SUFFIX
                    if os.path.exists(self.cfg.model_path) else None
                ),
                previous_fingerprint=self._file_sha(self.cfg.model_path),
            )
            return True

    def _lineage_body(self, file_sha: str, parent_fp: Optional[str],
                      manifest_digest: str, flight_path: Optional[str],
                      cycle: int) -> str:
        return json.dumps({
            "version": LINEAGE_VERSION,
            "fingerprint": file_sha,
            "parent_fingerprint": parent_fp,
            "manifest_digest": manifest_digest,
            "flight_path": flight_path,
            "cycle": cycle,
            "published_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }, indent=1)

    def _publish_files(self, text: str, booster=None,
                       parent_fp: Optional[str] = None,
                       manifest_digest: str = "",
                       flight_path: Optional[str] = None,
                       cycle: int = 0,
                       drift_sidecar_src: Optional[str] = None) -> str:
        """Write the live model file (atomic, fault site INSIDE the rename
        window) + its drift and lineage sidecars. Returns the file sha."""
        live = self.cfg.model_path
        atomic_write_text(live, text, fault_site=FAULT_PUBLISH)
        sha = model_fingerprint(text)
        if drift_sidecar_src is not None:
            try:
                atomic_write_text(
                    live + ".drift.json", self._read(drift_sidecar_src)
                )
            except OSError:
                pass  # candidate had no sidecar (e.g. EFB-bundled train set)
        elif booster is not None:
            try:
                booster.save_drift_reference(live)
            except Exception as e:
                log.warning("loop: drift sidecar failed: %r" % (e,))
        atomic_write_text(
            lineage_path(live),
            self._lineage_body(sha, parent_fp, manifest_digest,
                               flight_path, cycle),
        )
        return sha

    def _publish(self) -> None:
        """Retain the previous version, then atomically replace the live
        file with the journaled candidate. Every sub-step is idempotent:
        a restart mid-publish re-runs only what is not already true."""
        faults.maybe_fire(FAULT_PUBLISH)
        j = self.journal
        cand = j.get("candidate_path")
        cand_sha = j.get("candidate_fingerprint")
        if not (j.get("validation") or {}).get("passed"):
            raise LightGBMError(
                "loop: publish state without a passed validation verdict "
                "(cycle %d) — journal corrupted by hand?" % j.cycle
            )
        if not cand or self._file_sha(cand) != cand_sha:
            raise LightGBMError(
                "loop: journaled candidate %r is missing or altered at "
                "publish (cycle %d) — refusing to publish unvalidated "
                "bytes; remove the journal to restart the cycle"
                % (cand, j.cycle)
            )
        with trace_mod.span("loop.publish", cat="loop", cycle=j.cycle):
            live_sha = self._file_sha(self.cfg.model_path)
            prev = j.get("previous_path")
            if prev and live_sha is not None and live_sha != cand_sha:
                # live still holds the previous version: retain it (model +
                # sidecars) for the rollback. If live already == candidate
                # (killed after the rename), the retained copy from the
                # first attempt is intact — do NOT clobber it.
                if self._file_sha(prev) != j.get("previous_fingerprint"):
                    self._copy_published_set(self.cfg.model_path, prev)
            # idempotent re-entry: when live already holds the candidate
            # (killed after the rename), this rewrites only the sidecars
            self._publish_files(
                self._read(cand),
                parent_fp=j.get("parent_fingerprint"),
                manifest_digest=j.get("candidate_manifest_digest") or "",
                flight_path=j.get("candidate_flight"),
                cycle=j.cycle,
                drift_sidecar_src=cand + ".drift.json",
            )
            j.transition("swap", published_fingerprint=cand_sha)

    def _swap_all(self, expected_sha: str) -> None:
        """Hot-swap every replica to the live file and verify each one
        serves exactly those bytes. Per-replica fault site."""
        for replica in self.cfg.replicas:
            faults.maybe_fire(FAULT_SWAP)
            info = replica.swap(self.cfg.model_name, self.cfg.model_path)
            got = str(info.get("file_sha"))
            if got != expected_sha:
                raise LightGBMError(
                    "loop: replica %r serves %s after swap, expected %s"
                    % (replica, got[:12], expected_sha[:12])
                )
            log.info("loop: swapped %r -> v%s on %r"
                     % (self.cfg.model_name, info.get("version"), replica))

    def _swap(self) -> None:
        j = self.journal
        with trace_mod.span("loop.swap", cat="loop", cycle=j.cycle):
            self._swap_all(str(j.get("published_fingerprint")))
            j.transition("settle")

    def _settle(self) -> bool:
        """Post-swap watch. Default check: the published model must not
        regress past ``rollback_margin`` against the journaled serving
        metric on the holdout. ``settle_fn`` (called with this controller
        and the journaled validation verdict) replaces the decision —
        production deployments point it at live traffic metrics; the tests
        use it to force the rollback path deterministically."""
        j = self.journal
        with trace_mod.span("loop.settle", cat="loop", cycle=j.cycle):
            verdict = j.get("validation") or {}
            if self.cfg.settle_fn is not None:
                ok = bool(self.cfg.settle_fn(self, verdict))
            else:
                _, _, Xh, yh = self.cfg.data_provider(j.cycle)
                name, fn, bigger = gate_metric(
                    self.cfg.params.get("objective")
                )
                live_m = fn(yh, self._predict_on(self.cfg.model_path, Xh))
                base = verdict.get("serving")
                if base is None or not np.isfinite(base):
                    ok = True
                elif bigger:
                    ok = live_m >= base - self.cfg.rollback_margin
                else:
                    ok = live_m <= base + self.cfg.rollback_margin
                log.info("loop: settle cycle %d: %s=%s vs serving %s -> %s"
                         % (j.cycle, name, live_m, base,
                            "ok" if ok else "REGRESSION"))
            if ok:
                return True
            if not j.get("previous_fingerprint"):
                log.warning(
                    "loop: settle regression but no previous version to "
                    "roll back to (first publish); keeping the candidate"
                )
                return True
            j.transition("rollback")
            return False

    def _rollback(self) -> None:
        """Republish the retained previous version and re-swap every
        replica to it. Idempotent; the republish rides the same atomic
        writer (and fires the loop.publish site inside its rename window),
        the re-swaps fire loop.swap — so kills DURING a rollback are part
        of the kill-anywhere proof."""
        j = self.journal
        prev = j.get("previous_path")
        prev_sha = j.get("previous_fingerprint")
        if not prev or self._file_sha(prev) != prev_sha:
            raise LightGBMError(
                "loop: rollback target %r missing or altered (expected %s) "
                "— the retained previous version must be restored by the "
                "operator" % (prev, str(prev_sha)[:12])
            )
        with trace_mod.span("loop.rollback", cat="loop", cycle=j.cycle):
            if self._file_sha(self.cfg.model_path) != prev_sha:
                atomic_write_text(
                    self.cfg.model_path, self._read(prev),
                    fault_site=FAULT_PUBLISH,
                )
            # restore the previous version's sidecars next to the live file
            for suffix in (".drift.json", LINEAGE_SUFFIX):
                try:
                    atomic_write_text(
                        self.cfg.model_path + suffix,
                        self._read(prev + suffix),
                    )
                except OSError:
                    # the previous version had none: drop the stale one so
                    # a replica never pairs old bytes with new sidecars
                    try:
                        os.unlink(self.cfg.model_path + suffix)
                    except OSError:
                        pass
            self._swap_all(str(prev_sha))
            log.warning(
                "loop: cycle %d rolled back to %s"
                % (j.cycle, str(prev_sha)[:12])
            )
