"""``python -m lightgbm_tpu.loop`` — run the continuous-training controller.

    python -m lightgbm_tpu.loop --model live.txt --workdir loopdir \\
        --data train.tsv --holdout holdout.tsv \\
        --params params.json --rounds 30 \\
        --replica http://127.0.0.1:8080 --drift-url http://127.0.0.1:8080

``--data`` / ``--holdout`` are whitespace-separated numeric text files with
the label in column 0, RE-READ at every cycle — the operator (or a feed
job) replaces them as fresh data arrives. The controller journals every
state transition to ``<workdir>/loop_journal.json``; re-running the same
command after ANY crash resumes the loop at the journaled step
(docs/ContinuousTraining.md). ``--once --force`` runs exactly one
operator-initiated cycle without waiting for a drift trigger.
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..resil.preempt import PREEMPT_EXIT_CODE, TrainingPreempted
from ..utils import log
from .controller import (
    HttpDriftSource,
    HttpReplica,
    LoopConfig,
    LoopController,
)


class FileDataProvider:
    """Label-in-column-0 text files, re-read per cycle. Deterministic for a
    GIVEN file content — the operator contract is that the files only
    change BETWEEN cycles (the journal's retrain checkpoint makes a
    mid-cycle swap a loud config-digest warning, not silent drift)."""

    def __init__(self, data_path: str, holdout_path: str):
        self.data_path = data_path
        self.holdout_path = holdout_path

    def __call__(self, cycle: int):
        tr = np.loadtxt(self.data_path, dtype=np.float64, ndmin=2)
        ho = np.loadtxt(self.holdout_path, dtype=np.float64, ndmin=2)
        return tr[:, 1:], tr[:, 0], ho[:, 1:], ho[:, 0]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.loop",
        description="drift-triggered retrain -> validate -> publish -> "
                    "hot-swap controller (preemption-safe)",
    )
    p.add_argument("--model", required=True,
                   help="the LIVE published model file (created on first "
                        "run when missing)")
    p.add_argument("--workdir", required=True,
                   help="journal + per-cycle artifacts directory")
    p.add_argument("--data", required=True,
                   help="training data file (label in column 0), re-read "
                        "per cycle")
    p.add_argument("--holdout", required=True,
                   help="validation-gate holdout file (label in column 0)")
    p.add_argument("--params", required=True,
                   help="JSON file (or inline JSON object) of training "
                        "params")
    p.add_argument("--rounds", type=int, default=50,
                   help="boosting iterations per retrain")
    p.add_argument("--replica", action="append", default=[],
                   metavar="URL", help="serve replica base URL (repeat)")
    p.add_argument("--drift-url", default=None,
                   help="serve base URL whose /drift endpoint triggers "
                        "retrains; omitted = every cycle is unconditional")
    p.add_argument("--margin", type=float, default=0.0,
                   help="validation gate margin (candidate may be at most "
                        "this much worse than serving)")
    p.add_argument("--rollback-margin", type=float, default=0.0,
                   help="settle regression margin before rollback")
    p.add_argument("--poll-s", type=float, default=30.0,
                   help="drift poll cadence (seeded-jitterable)")
    p.add_argument("--observe-budget-s", type=float, default=3600.0,
                   help="max wait per observe pass before returning idle")
    p.add_argument("--jitter-seed", type=int, default=None,
                   help="seed for the poll jitter (reproducible schedules)")
    p.add_argument("--once", action="store_true",
                   help="run one cycle (or one observe pass) and exit")
    p.add_argument("--max-cycles", type=int, default=None,
                   help="exit after this many completed cycles")
    p.add_argument("--force", action="store_true",
                   help="skip the drift wait (operator-initiated retrain)")
    p.add_argument("--no-warm-start", action="store_true",
                   help="retrain from scratch instead of init_model "
                        "continuation")
    return p


def _load_params(spec: str) -> dict:
    s = spec.strip()
    if s.startswith("{"):
        return json.loads(s)
    with open(spec, encoding="utf-8") as fh:
        return json.load(fh)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = LoopConfig(
        model_path=args.model,
        workdir=args.workdir,
        params=_load_params(args.params),
        num_boost_round=args.rounds,
        data_provider=FileDataProvider(args.data, args.holdout),
        replicas=[HttpReplica(u) for u in args.replica],
        drift_source=(
            HttpDriftSource(args.drift_url) if args.drift_url else None
        ),
        validation_margin=args.margin,
        rollback_margin=args.rollback_margin,
        poll_interval_s=args.poll_s,
        observe_budget_s=args.observe_budget_s,
        jitter_seed=args.jitter_seed,
        warm_start=not args.no_warm_start,
    )
    ctl = LoopController(cfg)
    try:
        if ctl.ensure_bootstrap() and cfg.replicas:
            ctl._swap_all(ctl._file_sha(cfg.model_path))
        if args.once:
            out = ctl.run_cycle(force=args.force)
            log.info("loop: cycle outcome: %s" % out)
            return 0
        ctl.run_forever(max_cycles=args.max_cycles)
    except TrainingPreempted as e:
        # a SIGTERMed retrain published its emergency checkpoint; exit with
        # the preemption code so the supervisor restarts this command —
        # the journal re-enters the cycle and _train resumes from the
        # cycle's checkpoint instead of retraining from scratch
        # (docs/FaultTolerance.md §Elastic training)
        log.warning(
            "loop: retrain preempted (%s); checkpoint %s — re-run this "
            "command to resume; exiting %d"
            % (e, e.checkpoint_path or "<none>", e.exit_code)
        )
        return e.exit_code
    return 0


if __name__ == "__main__":
    sys.exit(main())
