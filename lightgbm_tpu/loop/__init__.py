"""Closed-loop continuous training (docs/ContinuousTraining.md).

One controller closes the production loop the rest of the package provides
the pieces for: the serve drift monitor detects distribution shift, boosting
warm-starts bit-exactly from the live published model, a holdout gate
compares candidate vs serving, resil/atomic publishes, the serve registry
hot-swaps every replica (drift sidecar refreshed per load), and a settle
watch rolls back to the previous published version on regression — with a
journaled state machine (loop/state.py) that survives SIGKILL at any point.

    python -m lightgbm_tpu.loop --model live.txt --workdir loopdir \\
        --data train.tsv --holdout holdout.tsv --params params.json \\
        --rounds 30 --replica http://127.0.0.1:8080 \\
        --drift-url http://127.0.0.1:8080
"""
from .controller import (  # noqa: F401
    AppDriftSource,
    AppReplica,
    HttpDriftSource,
    HttpReplica,
    LINEAGE_SUFFIX,
    LoopConfig,
    LoopController,
    gate_metric,
    lineage_path,
    load_lineage,
)
from .state import (  # noqa: F401
    LoopJournal,
    LoopStateError,
    OUTCOMES,
    STATES,
)
