"""Journaled state machines: the shared base and the continuous-training
loop's concrete machine.

One cycle of the closed loop walks

    OBSERVE -> RETRAIN -> VALIDATE -> PUBLISH -> SWAP -> SETTLE
                                 \\-> (rejected)            \\-> ROLLBACK

and every transition is ONE atomic journal write (resil/atomic.py: temp +
fsync + rename), so a controller SIGKILLed at any instant re-enters at the
step the journal last recorded — it never re-publishes a half-validated
candidate (PUBLISH is only reachable through a journaled ``validation`` with
``passed=true``) and never loses the rollback pointer (``previous_*`` is
recorded IN the same atomic write that enters PUBLISH, before the live file
is touched). The journal is a single JSON object, not an event log: the
controller's whole persistent state is the one file, and the atomic writer
guarantees a reader sees either the old record or the new one, never a torn
mix (docs/ContinuousTraining.md documents the format field by field).

:class:`StateJournal` is the machinery with the loop specifics factored
out — states, edges, fresh record and error class are class attributes —
so the fleet orchestrator's journal (``lightgbm_tpu/flex/controller.py``)
rides the same tested atomic-write/load/transition code instead of
reimplementing it.

Loop states:

  ``observe``   watching the drift signal; the only state a cycle starts or
                ends in. ``last_outcome`` carries the previous cycle's
                terminal result.
  ``retrain``   warm-started training of the candidate is (re)running.
  ``validate``  the candidate file exists and is being gated against the
                serving model on the holdout.
  ``publish``   the candidate passed the gate; the live model file is being
                replaced through resil/atomic. ``previous_*`` (the rollback
                pointer) is already durable.
  ``swap``      every replica is being hot-swapped to the published file.
  ``settle``    the post-swap watch; a regression here enters rollback.
  ``rollback``  the previous version is being republished and re-swapped.

Cycle outcomes: ``promoted`` / ``rejected`` / ``rolled_back`` (the
``loop_cycles_total{outcome=}`` counter labels).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from ..resil.atomic import atomic_write_text
from ..utils.log import LightGBMError

JOURNAL_VERSION = 1

STATES = (
    "observe", "retrain", "validate", "publish", "swap", "settle", "rollback",
)
OUTCOMES = ("promoted", "rejected", "rolled_back")

#: legal transitions (from -> allowed next states). ``observe`` is reachable
#: from every terminal arrow via finish_cycle.
_EDGES = {
    "observe": ("retrain",),
    "retrain": ("validate",),
    # validate -> retrain: a restarted controller whose journaled candidate
    # file is missing/altered rebuilds it instead of gating stale bytes
    "validate": ("publish", "observe", "retrain"),
    "publish": ("swap",),
    "swap": ("settle",),
    "settle": ("rollback", "observe"),
    "rollback": ("observe",),
}


class JournalError(LightGBMError):
    """An illegal transition or a structurally unusable journal — a
    controller bug or operator error, never a crash artifact (crash
    artifacts are impossible by the atomic-write construction)."""


class LoopStateError(JournalError):
    """The loop journal's flavor of :class:`JournalError` (kept as a
    distinct class: PR 11 callers and tests catch it by name)."""


def _fresh_record() -> Dict[str, Any]:
    return {
        "version": JOURNAL_VERSION,
        "seq": 0,
        "cycle": 0,
        "state": "observe",
        "updated_at": "",
        # per-cycle fields (reset when a new cycle leaves observe)
        "trigger": None,
        "candidate_path": None,
        "candidate_fingerprint": None,
        "candidate_manifest_digest": None,
        "candidate_flight": None,
        "parent_fingerprint": None,
        "validation": None,
        # rollback pointer: durable BEFORE the live file is touched
        "previous_path": None,
        "previous_fingerprint": None,
        "published_fingerprint": None,
        # history
        "last_outcome": None,
        "outcomes": {k: 0 for k in OUTCOMES},
    }


#: the per-cycle fields a new cycle clears on its observe -> retrain edge
_CYCLE_FIELDS = (
    "trigger", "candidate_path", "candidate_fingerprint",
    "candidate_manifest_digest", "candidate_flight", "parent_fingerprint",
    "validation", "published_fingerprint",
)


class StateJournal:
    """A single-JSON-object durable state machine; every mutation is an
    atomic file replace. Not thread-safe by design — one controller owns
    one journal (two controllers on one journal is an operator error the
    seq counter makes visible, not a supported deployment).

    Subclasses declare ``WHAT`` (the name used in error messages),
    ``VERSION``, ``STATES``, ``EDGES``, ``ERROR`` (the exception class to
    raise) and ``fresh_record`` (which must include ``version``, ``seq``,
    ``state`` and ``updated_at``); :meth:`_on_transition` hooks
    machine-specific edge bookkeeping.
    """

    WHAT = "state"
    VERSION = 1
    STATES: tuple = ()
    EDGES: Dict[str, tuple] = {}
    ERROR = JournalError

    def __init__(self, path: str, record: Optional[Dict[str, Any]] = None):
        self.path = path
        self.rec = record if record is not None else self.fresh_record()

    @classmethod
    def fresh_record(cls) -> Dict[str, Any]:
        return {
            "version": cls.VERSION,
            "seq": 0,
            "state": cls.STATES[0],
            "updated_at": "",
        }

    # -- IO ----------------------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "StateJournal":
        """Read the journal back, or start fresh when none exists. A file
        that exists but does not parse is NOT silently reset: the atomic
        writer cannot produce one, so it means operator damage — refusing
        loudly beats re-entering the machine at the wrong step."""
        try:
            with open(path, encoding="utf-8") as fh:
                body = json.load(fh)
        except OSError:
            return cls(path)
        except ValueError as e:
            raise cls.ERROR(
                "%s journal %r is not valid JSON (%s); the atomic writer "
                "cannot have produced this — refusing to guess the %s "
                "state. Repair or remove the file explicitly."
                % (cls.WHAT, path, e, cls.WHAT)
            )
        if not isinstance(body, dict) or body.get("version") != cls.VERSION:
            raise cls.ERROR(
                "%s journal %r has version %r (supported: %d)"
                % (cls.WHAT, path, body.get("version") if isinstance(body, dict)
                   else None, cls.VERSION)
            )
        if body.get("state") not in cls.STATES:
            raise cls.ERROR(
                "%s journal %r records unknown state %r"
                % (cls.WHAT, path, body.get("state"))
            )
        rec = cls.fresh_record()
        rec.update(body)
        return cls(path, rec)

    def _write(self) -> None:
        self.rec["seq"] = int(self.rec["seq"]) + 1
        self.rec["updated_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        d = os.path.dirname(os.path.abspath(self.path))
        if d:
            os.makedirs(d, exist_ok=True)
        atomic_write_text(self.path, json.dumps(self.rec, indent=1))

    # -- accessors ---------------------------------------------------------

    @property
    def state(self) -> str:
        return str(self.rec["state"])

    def get(self, key: str, default: Any = None) -> Any:
        return self.rec.get(key, default)

    # -- transitions -------------------------------------------------------

    def _illegal(self, cur: str, state: str) -> str:
        return "illegal %s transition %s -> %s" % (self.WHAT, cur, state)

    def _on_transition(self, cur: str, state: str) -> None:
        """Machine-specific bookkeeping for a legal edge, applied to
        ``self.rec`` BEFORE the state/fields fold (same atomic write)."""

    def transition(self, state: str, **fields: Any) -> None:
        """Move to ``state``, folding ``fields`` into the record, in ONE
        atomic write. Illegal edges raise (a controller bug must not
        journal itself into an unreachable position). Re-entering the
        CURRENT state is always legal — that is exactly what a restarted
        controller does."""
        if state not in self.STATES:
            raise self.ERROR("unknown %s state %r" % (self.WHAT, state))
        cur = self.state
        if state != cur and state not in self.EDGES[cur]:
            raise self.ERROR(self._illegal(cur, state))
        self._on_transition(cur, state)
        self.rec["state"] = state
        self.rec.update(fields)
        self._write()

    def update(self, **fields: Any) -> None:
        """Fold fields into the record without changing state (one atomic
        write) — e.g. the retrain step journaling its candidate before the
        validate edge."""
        self.rec.update(fields)
        self._write()


class LoopJournal(StateJournal):
    """The one durable record of where the loop is (see module doc)."""

    WHAT = "loop"
    VERSION = JOURNAL_VERSION
    STATES = STATES
    EDGES = _EDGES
    ERROR = LoopStateError

    @classmethod
    def fresh_record(cls) -> Dict[str, Any]:
        return _fresh_record()

    @property
    def cycle(self) -> int:
        return int(self.rec["cycle"])

    def _illegal(self, cur: str, state: str) -> str:
        return "illegal loop transition %s -> %s (cycle %d)" % (
            cur, state, self.cycle)

    def _on_transition(self, cur: str, state: str) -> None:
        if cur == "observe" and state == "retrain":
            # a new cycle begins: bump the counter and clear the previous
            # cycle's candidate bookkeeping (previous_* survives — it keeps
            # naming the last published-and-kept version until the next
            # publish overwrites it)
            self.rec["cycle"] = self.cycle + 1
            for k in _CYCLE_FIELDS:
                self.rec[k] = None

    def finish_cycle(self, outcome: str) -> None:
        """Terminal arrow of a cycle: record the outcome, return to
        observe. Reachable from validate (rejected), settle (promoted) and
        rollback (rolled_back)."""
        if outcome not in OUTCOMES:
            raise LoopStateError("unknown cycle outcome %r" % (outcome,))
        cur = self.state
        if cur == "observe":
            raise LoopStateError("finish_cycle from observe (no cycle open)")
        if "observe" not in _EDGES[cur] and cur != "observe":
            # promote/reject/rollback all end on states with an observe
            # edge; anything else is a controller bug
            raise LoopStateError(
                "cycle cannot finish from state %r" % (cur,)
            )
        self.rec["state"] = "observe"
        self.rec["last_outcome"] = outcome
        outcomes = dict(self.rec.get("outcomes") or {})
        outcomes[outcome] = int(outcomes.get(outcome, 0)) + 1
        self.rec["outcomes"] = outcomes
        self._write()
