"""Resilience smoke: REAL kill/resume + drain, end to end — the
``check.sh --resil`` gate.

Two acts, both against real processes (no mocks):

  1. crash/resume — a training subprocess is SIGKILLed mid-run by an
     injected fault (``LIGHTGBM_TPU_FAULTS=train.iteration:5:kill``) while
     checkpointing every 2 rounds; this driver resumes from the surviving
     checkpoint and asserts the final model string is BYTE-identical to an
     uninterrupted run.
  2. serve drain — ``python -m lightgbm_tpu.serve`` is booted, requests are
     held in flight by an induced batcher stall, SIGTERM lands mid-flight;
     every accepted request must complete, the process must exit 0, and the
     final drain report must say so.

Run: JAX_PLATFORMS=cpu python helpers/resil_smoke.py
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

_TRAIN_CHILD = """
import os, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu import engine

rng = np.random.RandomState(5)
X = rng.randn(250, 5)
y = (X[:, 0] + 0.3 * rng.randn(250) > 0).astype(float)
params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "feature_fraction": 0.7}
bst = engine.train(params, lgb.Dataset(X, label=y), 8,
                   checkpoint_path=sys.argv[1], checkpoint_rounds=2)
print("TRAIN-CHILD-DONE")
""" % REPO


def _train_local(resume_from=None):
    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine

    rng = np.random.RandomState(5)
    X = rng.randn(250, 5)
    y = (X[:, 0] + 0.3 * rng.randn(250) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "feature_fraction": 0.7}
    return engine.train(params, lgb.Dataset(X, label=y), 8,
                        resume_from=resume_from)


def crash_resume_act(td: str) -> dict:
    ck = os.path.join(td, "crash.ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LIGHTGBM_TPU_FAULTS="train.iteration:5:kill")
    r = subprocess.run(
        [sys.executable, "-c", _TRAIN_CHILD, ck],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    if r.returncode != -9 or "TRAIN-CHILD-DONE" in r.stdout:
        return {"ok": False, "error": "child was not SIGKILLed (rc=%s)"
                % r.returncode, "stderr_tail": r.stderr[-500:]}
    if not os.path.exists(ck):
        return {"ok": False, "error": "no checkpoint survived the crash"}
    os.environ.pop("LIGHTGBM_TPU_FAULTS", None)
    resumed = _train_local(resume_from=ck).model_to_string()
    reference = _train_local().model_to_string()
    return {
        "ok": resumed == reference,
        "killed_rc": r.returncode,
        "byte_identical": resumed == reference,
    }


def _read_line(proc, timeout_s=180.0):
    box = {}
    t = threading.Thread(
        target=lambda: box.setdefault("line", proc.stdout.readline()),
        daemon=True,
    )
    t.start()
    t.join(timeout_s)
    return box.get("line")


def drain_act(td: str) -> dict:
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(400, 5)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), 3,
    )
    model_path = os.path.join(td, "m.txt")
    bst.save_model(model_path)
    Xt = rng.randn(6, 5)
    expected = bst.predict(Xt)

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LIGHTGBM_TPU_FAULTS="serve.batcher:1:hang:1.5")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu.serve", model_path,
         "--port", "0", "--max-delay-ms", "1", "--drain-timeout-s", "20"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    try:
        line = _read_line(proc)
        if not line:
            return {"ok": False, "error": "server never printed startup"}
        port = json.loads(line)["port"]
        base = "http://127.0.0.1:%d" % port
        oks = []

        def post():
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"rows": Xt.tolist()}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            oks.append(bool(np.array_equal(expected,
                                           np.asarray(body["predictions"]))))

        threads = [threading.Thread(target=post) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # in flight (first batch stalled by the fault)
        proc.send_signal(signal.SIGTERM)
        for t in threads:
            t.join(timeout=30)
        rc = proc.wait(timeout=30)
        final = [json.loads(l) for l in proc.stdout.read().splitlines()
                 if l.startswith("{")]
        report = final[-1] if final else {}
        return {
            "ok": rc == 0 and oks == [True] * 3 and report.get("drained") is True,
            "exit_code": rc,
            "in_flight_completed": sum(oks),
            "drained": report.get("drained"),
        }
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=15)


def main() -> int:
    with tempfile.TemporaryDirectory() as td:
        crash = crash_resume_act(td)
        drain = drain_act(td) if crash["ok"] else {"ok": False,
                                                   "error": "skipped"}
    ok = crash["ok"] and drain["ok"]
    print(json.dumps({
        "resil_smoke": "PASS" if ok else "FAIL",
        "crash_resume": crash,
        "serve_drain": drain,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
