"""Ad-hoc differential fuzz: spec-mode grower vs sequential across random
configs (the r4 close-out's fuzz-sweep pattern, pointed at the r5 grower).

Each trial draws a random config (leaves, depth, bagging, feature fraction,
regularization, monotone, categorical, missing density, EFB, weights,
objective, learner) and trains twice — LIGHTGBM_TPU_GROW=seq vs spec — and
compares model strings byte for byte, in one of two tiers (ADVICE r5 #3):

- "byte" tier (even trials): forces LIGHTGBM_TPU_SPEC_HIST=flat plus the
  xla histogram impl — the configuration test_spec_grow's exact-equality
  contract covers. ANY model-string mismatch is a FAIL; there is no
  tie-flip tolerance, so a prefix-validation bug that produces a
  plausible-looking tree cannot be absorbed as benign.
- "lanes" tier (odd trials): forces the lanes batched histogram, whose
  vmapped common-max regrouping makes spec trees only empirically equal to
  seq. A mismatch here falls back to the prediction-allclose check and
  counts as "tie-flip" when predictions agree.

Run: JAX_PLATFORMS=cpu python helpers/fuzz_spec_grow.py [n_trials]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def one_trial(i: int, tier: str = "byte"):
    import jax

    import lightgbm_tpu as lgb
    import lightgbm_tpu.ops.grow as grow_mod
    import lightgbm_tpu.ops.histogram as hist_mod

    rng = np.random.RandomState(1000 + i)
    n = int(rng.choice([700, 1500, 3000]))
    f = int(rng.choice([5, 8, 12]))
    X = rng.randn(n, f)
    cat_cols = []
    if rng.rand() < 0.4:
        c = rng.randint(0, f)
        X[:, c] = rng.randint(0, rng.randint(3, 20), n)
        cat_cols = [c]
    if rng.rand() < 0.5:
        X[rng.rand(n, f) < rng.uniform(0.01, 0.2)] = np.nan
    obj = rng.choice(["binary", "regression", "multiclass"])
    if obj == "multiclass":
        y = rng.randint(0, 3, n).astype(float)
    elif obj == "binary":
        y = (np.nan_to_num(X[:, 0] + 0.5 * X[:, 1]) + 0.2 * rng.randn(n) > 0).astype(float)
    else:
        y = np.nan_to_num(X[:, 0]) + 0.1 * rng.randn(n)
    params = {
        "objective": obj, "verbosity": -1,
        "num_leaves": int(rng.choice([4, 15, 31, 63])),
        "min_data_in_leaf": int(rng.choice([1, 5, 20])),
        "learning_rate": float(rng.choice([0.05, 0.1, 0.3])),
        "seed": int(rng.randint(0, 1000)),
    }
    if obj == "multiclass":
        params["num_class"] = 3
    if rng.rand() < 0.3:
        params["max_depth"] = int(rng.randint(3, 8))
    if rng.rand() < 0.3:
        params.update(bagging_fraction=0.7, bagging_freq=1)
    if rng.rand() < 0.3:
        params["feature_fraction"] = 0.7
    if rng.rand() < 0.3:
        params.update(lambda_l1=0.2, lambda_l2=1.0)
    if rng.rand() < 0.2:
        params["min_gain_to_split"] = 0.01
    if rng.rand() < 0.2 and obj == "regression":
        mono = [0] * f
        mono[0] = 1
        params["monotone_constraints"] = mono
    learner = rng.choice(["serial", "serial", "data"])
    if learner != "serial":
        params["tree_learner"] = learner
    dskw = {}
    if rng.rand() < 0.3:
        dskw["weight"] = rng.rand(n) + 0.5
    if cat_cols:
        dskw["categorical_feature"] = cat_cols
    rounds = int(rng.choice([2, 4]))

    hist_prev = hist_mod._ENV_IMPL
    models = {}
    try:
        if tier == "byte":
            # byte-exact tier: flat batched hist + xla impl — the combo whose
            # equality IS structural (test_spec_grow's contract)
            grow_mod._ENV_SPEC_HIST = "flat"
            hist_mod._ENV_IMPL = "xla"
        else:
            grow_mod._ENV_SPEC_HIST = "lanes"
        for mode in ("seq", "spec"):
            grow_mod._ENV_GROW = mode
            jax.clear_caches()
            bst = lgb.train(params, lgb.Dataset(X.copy(), label=y, **dict(dskw)), rounds)
            models[mode] = bst
    finally:
        grow_mod._ENV_GROW = ""
        grow_mod._ENV_SPEC_HIST = ""
        hist_mod._ENV_IMPL = hist_prev
    s = models["seq"].model_to_string()
    a = models["spec"].model_to_string()
    if s == a:
        return "exact"
    if tier == "byte":
        # no tolerance in this tier: flat+xla spec must match seq bit for bit
        print("FAIL(byte) trial %d params=%s dskw_keys=%s" % (i, params, list(dskw)))
        return "FAIL"
    # lanes tier: predict on the RAW matrix (NaNs included) so missing-
    # default-direction divergence cannot hide behind the tie-flip label
    p1 = models["seq"].predict(X)
    p2 = models["spec"].predict(X)
    if np.allclose(p1, p2, rtol=5e-3, atol=5e-4):
        return "tie-flip"
    print("FAIL trial %d params=%s dskw_keys=%s" % (i, params, list(dskw)))
    return "FAIL"


def main():
    n_trials = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    counts = {}
    for i in range(n_trials):
        tier = "byte" if i % 2 == 0 else "lanes"
        r = one_trial(i, tier)
        key = "%s:%s" % (tier, r)
        counts[key] = counts.get(key, 0) + 1
        print("trial %d [%s]: %s  (totals %s)" % (i, tier, r, counts), flush=True)
    print("DONE", counts)
    sys.exit(1 if any(k.endswith(":FAIL") for k in counts) else 0)


if __name__ == "__main__":
    main()
