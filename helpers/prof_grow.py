"""Per-split latency profiling harness at the bench shape.

Usage: JAX_PLATFORMS=cpu python helpers/prof_grow.py [rows] [leaves] [iters]
Prints compile time, steady-state iters/s, and (with LIGHTGBM_TPU_PROFILE
set) writes a jax profiler trace.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_higgs_like(n, f, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logits = X @ w + 0.5 * np.sin(X[:, 0] * 2.0) + 0.25 * X[:, 1] * X[:, 2]
    y = (logits + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return X, y


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 255
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 10

    import jax
    import lightgbm_tpu as lgb

    X, y = make_higgs_like(rows, 28)
    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "max_bin": 255,
        "learning_rate": 0.1,
        "verbosity": -1,
    }
    t0 = time.time()
    ds = lgb.Dataset(X, label=y)
    booster = lgb.Booster(params=params, train_set=ds)
    print("bin: %.1fs" % (time.time() - t0), flush=True)

    t0 = time.time()
    booster.update()
    jax.block_until_ready(booster._gbdt.scores)
    print("first iter (compile): %.1fs" % (time.time() - t0), flush=True)
    t0 = time.time()
    booster.update()
    jax.block_until_ready(booster._gbdt.scores)
    print("second iter: %.2fs" % (time.time() - t0), flush=True)

    trace_dir = os.environ.get("LIGHTGBM_TPU_PROFILE")
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                booster.update()
            jax.block_until_ready(booster._gbdt.scores)
        print("trace written to", trace_dir, flush=True)

    t0 = time.time()
    for _ in range(iters):
        booster.update()
    jax.block_until_ready(booster._gbdt.scores)
    dt = time.time() - t0
    print(
        "steady: %d iters in %.2fs -> %.3f iters/s (%.1f ms/iter, %.0f us/split)"
        % (iters, dt, iters / dt, 1000 * dt / iters, 1e6 * dt / iters / max(leaves - 1, 1)),
        flush=True,
    )


if __name__ == "__main__":
    main()
