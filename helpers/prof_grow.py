"""Per-split latency profiling harness at the bench shape.

Usage: JAX_PLATFORMS=cpu python helpers/prof_grow.py [rows] [leaves] [iters]
Prints compile time, steady-state iters/s and, when the corresponding env
vars are set, richer attribution:

  * LIGHTGBM_TPU_PROFILE=<dir>   — jax profiler trace (TensorBoard/Perfetto)
  * LIGHTGBM_TPU_TRACE=<path>    — obs Chrome-trace spans (this harness
    wraps each stage in a span, so the timeline carries bin/compile/steady
    sections next to the training-phase spans)
  * LIGHTGBM_TPU_PROF_SEGMENTS=1 — the segment profiler breakdown
    (obs/prof.py): per-segment seconds inside tree growth + the
    fused-vs-segmented bitwise identity verdict

Clock: time.perf_counter throughout (the rule JX009 enforces inside
ops//models/ — wall-clock NTP steps corrupt intervals); the dataset comes
from helpers/bench_data.make_higgs_like, the SAME generator bench.py uses,
so numbers here are comparable with bench output.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from helpers.bench_data import make_higgs_like


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 255
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 10

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import trace as trace_mod

    X, y = make_higgs_like(rows, 28)
    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "max_bin": 255,
        "learning_rate": 0.1,
        "verbosity": -1,
    }
    t0 = time.perf_counter()
    with trace_mod.span("prof_grow.bin", cat="prof_grow"):
        ds = lgb.Dataset(X, label=y)
        booster = lgb.Booster(params=params, train_set=ds)
    print("bin: %.1fs" % (time.perf_counter() - t0), flush=True)

    t0 = time.perf_counter()
    with trace_mod.span("prof_grow.compile", cat="prof_grow"):
        booster.update()
        jax.block_until_ready(booster._gbdt.scores)
    print("first iter (compile): %.1fs" % (time.perf_counter() - t0), flush=True)
    t0 = time.perf_counter()
    booster.update()
    jax.block_until_ready(booster._gbdt.scores)
    print("second iter: %.2fs" % (time.perf_counter() - t0), flush=True)

    trace_dir = os.environ.get("LIGHTGBM_TPU_PROFILE")
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                booster.update()
            jax.block_until_ready(booster._gbdt.scores)
        print("trace written to", trace_dir, flush=True)

    t0 = time.perf_counter()
    with trace_mod.span("prof_grow.steady", cat="prof_grow", iters=iters):
        for _ in range(iters):
            booster.update()
        jax.block_until_ready(booster._gbdt.scores)
    dt = time.perf_counter() - t0
    print(
        "steady: %d iters in %.2fs -> %.3f iters/s (%.1f ms/iter, %.0f us/split)"
        % (iters, dt, iters / dt, 1000 * dt / iters, 1e6 * dt / iters / max(leaves - 1, 1)),
        flush=True,
    )

    from lightgbm_tpu.obs import prof as prof_mod

    if prof_mod.segments_enabled():
        reason = prof_mod.unsupported_reason(booster._gbdt)
        if reason is not None:
            print("segment profiler skipped: %s" % reason, flush=True)
        else:
            rec = prof_mod.profile_growth(
                booster, iters=prof_mod.segments_iters()
            )
            print(
                "growth segments (s/tree): %s" % rec["segments_per_tree_s"],
                flush=True,
            )
            print(
                "segment sum %.3fs vs fused %.3fs (ratio %.3f), bitwise=%s"
                % (rec["segment_sum_s_per_tree"],
                   rec["fused_growth_s_per_tree"],
                   rec["segment_sum_ratio"], rec["bitwise_identical"]),
                flush=True,
            )


if __name__ == "__main__":
    main()
