"""Background TPU relay probe loop.

The axon TPU tunnel (see BENCH_NOTES.md) has died mid-round twice.  This
loop probes the backend every PERIOD seconds in a killed-process-group
subprocess (a timeout-killed TPU client can wedge the tunnel, so the probe
child must die with its whole group) and appends one JSON line per attempt
to .tpu_probe.log.  It exits 0 the first time a probe completes a real
matmul on the chip, so a supervisor waiting on this process learns the
instant the TPU is usable.
"""
import json
import os
import signal
import subprocess
import sys
import time

PERIOD = int(os.environ.get("TPU_PROBE_PERIOD", "600"))
TIMEOUT = int(os.environ.get("TPU_PROBE_TIMEOUT", "120"))
LOG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".tpu_probe.log")

PROBE = """
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
y = (x @ x).block_until_ready()
print("PROBE_OK", d[0].platform, len(d))
"""


def relay_listening():
    """True if any tunnel port (8082-8117) has a listener — near-free check
    so the dead-relay steady state doesn't burn 2 CPU-minutes of jax init
    per cycle on the single-core host (it skews perf measurements)."""
    try:
        out = subprocess.run(
            ["ss", "-tln"], capture_output=True, text=True, timeout=10
        ).stdout
    except Exception:
        return True  # can't tell; fall through to the real probe
    for line in out.splitlines():
        for tok in line.split():
            if ":" in tok:
                port = tok.rsplit(":", 1)[-1]
                if port.isdigit() and 8082 <= int(port) <= 8117:
                    return True
    return False


def probe_once():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"
    p = subprocess.Popen(
        [sys.executable, "-c", PROBE],
        env=env,
        start_new_session=True,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = p.communicate(timeout=TIMEOUT)
        ok = p.returncode == 0 and "PROBE_OK" in out
        return ok, ("ok" if ok else f"rc={p.returncode}"), out[-500:]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.wait()
        return False, "timeout", ""


def main():
    while True:
        if not relay_listening():
            ok, status, tail = False, "relay-dead (no 808x listener)", ""
        else:
            ok, status, tail = probe_once()
        with open(LOG, "a") as f:
            f.write(json.dumps({
                "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "status": status,
                "tail": tail.strip(),
            }) + "\n")
        if ok:
            return 0
        time.sleep(PERIOD)


if __name__ == "__main__":
    sys.exit(main())
