"""Shared synthetic-workload generators for bench/profiling harnesses.

One definition of the Higgs-shaped dataset (was duplicated between bench.py
and helpers/prof_grow.py, with silently different feature distributions —
their numbers were not comparable). bench.py re-exports
:func:`make_higgs_like`, so existing ``from bench import make_higgs_like``
call sites (helpers/tpu_bringup.py stages) keep working.

Stdlib + numpy only: importable from the bench orchestrator process, which
must never touch jax.
"""
from __future__ import annotations

import numpy as np


def make_higgs_like(n: int, f: int, seed: int = 7):
    """[n, f] float32 features + binary labels, HIGGS-shaped: 21 unit-
    gaussian "low-level" kinematic features and f-21 derived positive
    "high-level" features (products of low-level pairs plus noise), labels
    from a sparse linear logit. Matches the reference's headline Higgs
    experiment shape (binning/shape-equivalent, synthetic values)."""
    rng = np.random.RandomState(seed)
    X = np.empty((n, f), np.float32)
    low = min(21, f)
    X[:, :low] = rng.randn(n, low).astype(np.float32)
    for j in range(low, f):
        a, b = rng.randint(0, low, 2)
        X[:, j] = np.abs(X[:, a] * X[:, b] + rng.randn(n).astype(np.float32) * 0.5)
    w = rng.randn(f) * (rng.rand(f) > 0.3)
    logits = X @ w * 0.3 + rng.randn(n) * 2.0
    y = (logits > 0).astype(np.float32)
    return X, y
