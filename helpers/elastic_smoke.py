"""check.sh --elastic: the elastic preemption-tolerance chain, ONE invocation.

Drives a real data-parallel training through every kill the scheduler can
throw at it, on forced-8-CPU-device workers (the ISSUE-15 shapes), and
gates on the exactness taxonomy docs/FaultTolerance.md §Elastic training
documents:

  1. **uninterrupted reference** — 12 rounds, data learner, chunked,
     bagging, 8 devices.
  2. **SIGKILL mid-run** — a fault-injected ``train.iteration:9:kill``
     murders the checkpointing run between boundaries (rc=-9; the archive
     from boundary 6 survives).
  3. **resume + SIGTERM preemption** — the resumed run (same mesh) is
     SIGTERMed mid-train with ``preempt_exit`` armed: it must publish an
     EMERGENCY boundary checkpoint and exit with the documented preemption
     code 75 (EX_TEMPFAIL), not 0 and not a crash code.
  4. **auto-resume** — resuming the emergency checkpoint to completion
     yields a final model BYTE-equal to the uninterrupted reference, with
     exactly 12 trees (one completed run, no double-trained boundary).
  4b. **SIGKILL at `train.preempt`** — a kill BETWEEN the latched SIGTERM
     and the emergency write: the pre-preemption archive must carry a
     byte-identical resume (the kill-anywhere matrix at the new sites).
  5. **8 -> 2 reshard** — the same mid-run checkpoint resumed on TWO
     forced devices: must complete with the loud reshard warning, split
     structure identical to the reference, prefix trees byte-exact, and
     suffix leaf values within ulp tolerance (the psum grouping changed —
     byte-identity across a world-size change is NOT claimed, measured
     impossible; the reference's own distributed training has the same
     num_machines dependence).
  6. **serial <-> data@1 reshard** — a serial checkpoint resumed as the
     data learner on one device IS byte-identical (world size unchanged).

HARD FAILURES: any byte mismatch in legs 4/6, a wrong exit code in leg 3,
a missing emergency checkpoint, structural divergence in leg 5, or a
missing reshard warning.
"""
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROUNDS = 12
CKPT_ROUNDS = 3

WORKER = r'''
import os, sys, time
sys.path.insert(0, %(repo)r)
from lightgbm_tpu.utils.platform import force_cpu_devices
jax = force_cpu_devices(int(os.environ["ELASTIC_NDEV"]))
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu import engine, callback
from lightgbm_tpu.resil.preempt import PREEMPT_EXIT_CODE, TrainingPreempted

mode = sys.argv[1]
ckpt = sys.argv[2]
out = sys.argv[3] if len(sys.argv) > 3 else ""

rng = np.random.RandomState(7)
N, F = 1003, 6
X = rng.randn(N, F)
y = (X[:, 0] + 0.3 * rng.randn(N) > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "bagging_freq": 2, "bagging_fraction": 0.8,
          "feature_fraction": 0.8}
if mode == "reshard":
    # warnings visible: the parent asserts the loud reshard warning fired
    # (verbosity is footer-only — the tree comparisons are body-structural)
    params["verbosity"] = 0
if os.environ.get("ELASTIC_LEARNER", "data") == "data":
    params.update(tree_learner="data", device_chunk_size=3)

kw = {}
if mode in ("ckpt", "resume", "resume_preempt", "reshard"):
    kw["checkpoint_path"] = ckpt
    kw["checkpoint_rounds"] = %(ckpt_rounds)d
if mode in ("resume", "resume_preempt", "reshard"):
    kw["resume_from"] = ckpt
cbs = None
if mode == "resume_preempt":
    kw["preempt_exit"] = True
    def pacer(env):
        # give the parent a window to land its SIGTERM between boundaries
        print("BOUNDARY %%d" %% env.iteration, flush=True)
        time.sleep(0.3)
    pacer.order = 90
    cbs = [pacer]

try:
    bst = engine.train(params, lgb.Dataset(X, label=y), %(rounds)d,
                       verbose_eval=False, callbacks=cbs, **kw)
except TrainingPreempted as e:
    print("PREEMPTED iter=%%d ckpt=%%s" %% (e.iteration, e.checkpoint_path),
          flush=True)
    sys.exit(PREEMPT_EXIT_CODE)

if out:
    with open(out, "w") as fh:
        fh.write(bst.model_to_string())
print("TREES %%d" %% len(bst._gbdt.trees()), flush=True)
print("CHILD-DONE", flush=True)
''' % {"repo": REPO, "rounds": ROUNDS, "ckpt_rounds": CKPT_ROUNDS}


def _env(ndev, learner="data", faults=""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % ndev
    env["ELASTIC_NDEV"] = str(ndev)
    env["ELASTIC_LEARNER"] = learner
    if faults:
        env["LIGHTGBM_TPU_FAULTS"] = faults
    else:
        env.pop("LIGHTGBM_TPU_FAULTS", None)
    return env


def _run(args, env, timeout=600, expect_rc=0, tag=""):
    r = subprocess.run([sys.executable, "-c", WORKER] + list(args),
                       env=env, cwd=REPO, capture_output=True, text=True,
                       timeout=timeout)
    if expect_rc is not None and r.returncode != expect_rc:
        print("elastic_smoke FAILED [%s]: rc=%s (expected %s)"
              % (tag, r.returncode, expect_rc))
        print(r.stdout[-1500:])
        print(r.stderr[-1500:])
        sys.exit(1)
    return r


def _sigterm_at_first_boundary(proc, timeout_s=300.0):
    """Read the child's stdout until the first BOUNDARY marker, then
    SIGTERM it. A watchdog timer SIGKILLs a child that wedges before its
    first boundary — `for line in proc.stdout` blocks inside readline, so
    an in-loop clock check could never fire (a direct check.sh run has no
    bringup stage timeout above it)."""
    import threading

    killer = threading.Timer(timeout_s, proc.kill)
    killer.daemon = True
    killer.start()
    try:
        for line in proc.stdout:
            if line.startswith("BOUNDARY"):
                proc.send_signal(signal.SIGTERM)
                return True
        return False  # EOF without a boundary (wedged child was killed)
    finally:
        killer.cancel()


def _model_body(path):
    with open(path, encoding="utf-8") as fh:
        return fh.read().split("parameters:")[0]


def _trees(path):
    """(split_feature tuple, threshold tuple, leaf_value tuple) per tree,
    parsed from the model text — enough for structural + value checks."""
    import re

    with open(path, encoding="utf-8") as fh:
        text = fh.read().split("parameters:")[0]
    out = []
    for block in text.split("\nTree=")[1:]:
        f = {}
        for line in block.splitlines():
            m = re.match(r"(split_feature|threshold|leaf_value)=(.*)", line)
            if m:
                f[m.group(1)] = m.group(2).split()
        out.append((tuple(f.get("split_feature", [])),
                    tuple(f.get("threshold", [])),
                    tuple(float(v) for v in f.get("leaf_value", []))))
    return out


def main() -> int:
    import tempfile

    work = tempfile.mkdtemp(prefix="elastic_smoke_")
    ckpt = os.path.join(work, "run.ckpt")
    ref_out = os.path.join(work, "ref.txt")
    final_out = os.path.join(work, "final.txt")
    reshard_out = os.path.join(work, "reshard2.txt")
    t0 = time.time()

    # 1. uninterrupted reference @ 8 devices
    _run(["ref", "", ref_out], _env(8), tag="ref")
    print("elastic_smoke: reference trained (8 devices)")

    # 2. SIGKILL mid-run. The chunked loop makes ~6 train.iteration passes
    # for 12 rounds (first iteration sequential, then chunks of 3, then the
    # tail); occurrence 4 lands after the iteration-7 checkpoint with 5
    # iterations still to train
    r = _run(["ckpt", ckpt], _env(8, faults="train.iteration:4:kill"),
             expect_rc=-9, tag="sigkill")
    assert "CHILD-DONE" not in r.stdout, "kill did not land"
    assert os.path.exists(ckpt), "no checkpoint survived the SIGKILL"
    print("elastic_smoke: SIGKILLed mid-run; checkpoint survived")

    # 3. resume (same mesh) + SIGTERM preemption -> emergency ckpt + exit 75
    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER, "resume_preempt", ckpt],
        env=_env(8), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    # wait for the first post-resume boundary so the SIGTERM lands mid-run
    if not _sigterm_at_first_boundary(proc):
        proc.wait(timeout=30)
        print("elastic_smoke FAILED: resumed run never reached a boundary")
        return 1
    tail = proc.stdout.read()
    err = proc.stderr.read()
    proc.wait(timeout=300)
    if proc.returncode != 75:
        print("elastic_smoke FAILED: preempted run exited %s, expected 75"
              % proc.returncode)
        print(tail[-800:], err[-800:])
        return 1
    assert "PREEMPTED" in tail, tail[-400:]
    assert "CHILD-DONE" not in tail, "preempted run claimed completion"
    print("elastic_smoke: SIGTERM honored -> emergency checkpoint + exit 75")
    # snapshot the EMERGENCY checkpoint for the reshard leg: the auto-resume
    # below keeps checkpointing to the same path and would leave only the
    # final (nothing-left-to-train) boundary behind
    mid_ckpt = os.path.join(work, "mid.ckpt")
    with open(ckpt, "rb") as src, open(mid_ckpt, "wb") as dst:
        dst.write(src.read())

    # 4. auto-resume to completion: byte-equal to the uninterrupted run
    r = _run(["resume", ckpt, final_out], _env(8), tag="auto-resume")
    assert "TREES %d" % ROUNDS in r.stdout, (
        "expected exactly %d trees (one completed run): %s"
        % (ROUNDS, r.stdout[-200:]))
    if _model_body(final_out) != _model_body(ref_out):
        print("elastic_smoke FAILED: kill->resume->preempt->resume model "
              "differs from the uninterrupted run")
        return 1
    print("elastic_smoke: auto-resume BYTE-identical to uninterrupted "
          "(%d trees, no double-trained boundary)" % ROUNDS)

    # 4b. kill-anywhere at the new fault sites: SIGKILL BETWEEN the latched
    # SIGTERM and the emergency write (train.preempt) — the pre-preemption
    # checkpoint must carry a byte-identical resume
    kp_ckpt = os.path.join(work, "killpreempt.ckpt")
    with open(mid_ckpt, "rb") as src, open(kp_ckpt, "wb") as dst:
        dst.write(src.read())
    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER, "resume_preempt", kp_ckpt],
        env=_env(8, faults="train.preempt:1:kill"), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    if not _sigterm_at_first_boundary(proc):
        proc.wait(timeout=30)
        print("elastic_smoke FAILED: train.preempt leg never reached a "
              "boundary")
        return 1
    proc.stdout.read()
    proc.stderr.read()
    proc.wait(timeout=300)
    if proc.returncode != -9:
        print("elastic_smoke FAILED: train.preempt kill exited %s, "
              "expected -9" % proc.returncode)
        return 1
    kp_out = os.path.join(work, "killpreempt.txt")
    r = _run(["resume", kp_ckpt, kp_out], _env(8), tag="killpreempt-resume")
    if _model_body(kp_out) != _model_body(ref_out):
        print("elastic_smoke FAILED: resume after a train.preempt kill "
              "differs from the uninterrupted run")
        return 1
    print("elastic_smoke: SIGKILL at train.preempt -> periodic checkpoint "
          "carried a BYTE-identical resume")

    # 5. the same checkpoint resharded onto 2 devices. The emergency ckpt
    # from leg 3 was taken at an 8-device boundary — exactly the artifact a
    # shrunken preemption slice must be able to consume.
    r = _run(["reshard", mid_ckpt, reshard_out], _env(2), tag="reshard-8to2")
    assert "resharding data@8" in r.stderr and "ulp" in r.stderr, (
        "reshard warning missing from stderr: %s" % r.stderr[-600:])
    ref_trees, re_trees = _trees(ref_out), _trees(reshard_out)
    assert len(re_trees) == ROUNDS, len(re_trees)
    drifted = 0
    for i, (a, b) in enumerate(zip(ref_trees, re_trees)):
        assert a[0] == b[0], "split features diverge at tree %d" % i
        assert a[1] == b[1], "thresholds diverge at tree %d" % i
        if a[2] != b[2]:
            drifted += 1
            for va, vb in zip(a[2], b[2]):
                assert abs(va - vb) <= 2e-4 * max(abs(va), 1e-6) + 2e-6, (
                    "leaf drift beyond ulp tolerance at tree %d" % i)
    print("elastic_smoke: 8->2 reshard completed — split structure "
          "identical, %d/%d trees with ulp-level leaf drift (warned)"
          % (drifted, ROUNDS))

    # 6. serial <-> data@1: world size unchanged -> byte-identical
    ser_ckpt = os.path.join(work, "serial.ckpt")
    ser_ref = os.path.join(work, "serial_ref.txt")
    ser_out = os.path.join(work, "serial_as_data.txt")
    _run(["ref", "", ser_ref], _env(1, learner="serial"), tag="serial-ref")
    _run(["ckpt", ser_ckpt],
         _env(1, learner="serial", faults="train.iteration:9:kill"),
         expect_rc=-9, tag="serial-kill")
    _run(["resume", ser_ckpt, ser_out], _env(1, learner="data"),
         tag="serial-to-data1")
    if _model_body(ser_out) != _model_body(ser_ref):
        print("elastic_smoke FAILED: serial -> data@1 resume not "
              "byte-identical")
        return 1
    print("elastic_smoke: serial -> data@1 resume BYTE-identical")

    print("elastic_smoke OK: SIGKILL + SIGTERM(75) + auto-resume "
          "byte-identity, 8->2 reshard structural identity, serial<->data@1 "
          "byte-identity")
    print(json.dumps({
        "ok": True, "rounds": ROUNDS, "devices": 8,
        "preempt_exit_code": 75, "byte_identical_after_preempt": True,
        "byte_identical_after_preempt_kill": True,
        "reshard_structural_match": True,
        "reshard_drifted_trees": drifted,
        "serial_data1_byte_identical": True,
        "wall_s": round(time.time() - t0, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
