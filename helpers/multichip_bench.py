"""Device-count scaling bench for the data-parallel sharded-chunk path.

Sweeps ``tree_learner=data + device_chunk_size`` over a list of device
counts and records a devices-vs-iters/s scaling curve — the ISSUE-8 proof
artifact for pod-scale data-parallel training (ROADMAP item 1: the paper's
Higgs-1M-on-v5e-8 target is a scaling claim, so the scaling curve is the
headline evidence). Two modes:

  * ``--sweep 1,4,8``: the driver mode helpers/tpu_bringup.py's
    ``bench_multichip`` stage runs. Each device count needs its own
    process (the jax device world is fixed at backend init), so the sweep
    re-execs this file once per count and emits ONE summary JSON line
    (``RESULT {...}``) whose record carries a ``metric`` key — the shape
    obs/report.load_bench_records adopts, so MULTICHIP_r*.json charts next
    to the BENCH_r* series in the HTML run report.
  * ``--devices D``: one measurement. On a CPU host the device world is
    forced to D virtual devices (XLA_FLAGS, before backend init); on real
    chips the mesh is capped with ``num_machines=D`` instead.

Stays importable without jax until a single-measurement run starts.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def measure(devices: int, rows: int, iters: int, chunk: int, leaves: int) -> dict:
    sys.path.insert(0, REPO)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or not os.environ.get(
        "JAX_PLATFORMS"
    ):
        from lightgbm_tpu.utils.platform import force_cpu_devices

        jax = force_cpu_devices(devices)
    else:
        import jax
    import numpy as np

    import lightgbm_tpu as lgb
    from helpers.bench_data import make_higgs_like
    from lightgbm_tpu.models.model_text import model_fingerprint

    n_dev = min(devices, len(jax.devices()))
    X, y = make_higgs_like(rows, 28)
    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": 255,
        "learning_rate": 0.1, "verbosity": -1,
        "tree_learner": "data" if n_dev > 1 else "serial",
        "num_machines": n_dev, "device_chunk_size": chunk,
    }
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)

    def run(count: int) -> None:
        i = 0
        while i < count:
            if chunk > 1:
                done, _ = bst.update_chunk(min(chunk, count - i))
                i += max(done, 1)
            else:
                bst.update()
                i += 1

    # warmup compiles both programs the timed loop uses: the sequential
    # first iteration and the full chunk-sized scan
    t0 = time.time()
    run(chunk + 1)
    _ = float(np.ravel(np.asarray(bst._gbdt.scores))[0])
    compile_s = time.time() - t0
    t0 = time.time()
    run(iters)
    _ = float(np.ravel(np.asarray(bst._gbdt.scores))[0])
    dt = time.time() - t0
    rec = {
        "devices": n_dev,
        "iters_per_sec": round(iters / dt, 4),
        "first_dispatch_s": round(compile_s, 2),
        "model_hash": model_fingerprint(bst.model_to_string()),
        "platform": jax.default_backend(),
        "fallback_reason": bst._gbdt.device_chunk_fallback_reason(),
    }
    if n_dev > 1:
        # compute-vs-collective attribution (obs/dist.py): the segmented
        # sharded profile says WHY scaling bends — comms_fraction,
        # per-segment seconds, per-device rows/waits; its bitwise check
        # re-proves the fused program was measured, not a lookalike.
        # Never fatal to the bench measurement itself.
        try:
            from lightgbm_tpu.obs import dist as dist_mod

            prof = dist_mod.profile_sharded_growth(bst, iters=1)
            rec["comms_fraction"] = prof["comms_fraction"]
            rec["dist_segments"] = prof["segments_per_tree_s"]
            rec["dist_collective"] = prof["collective_segments"]
            rec["collective_bytes_per_split"] = prof[
                "collective_bytes_per_split"
            ]
            rec["per_device"] = prof["per_device"]
            rec["dist_bitwise"] = prof["bitwise_identical"]
        except Exception as e:
            rec["dist_prof_error"] = repr(e)[:200]
    return rec


def sweep(counts, rows, iters, chunk, leaves) -> dict:
    points = []
    for d in counts:
        env = dict(os.environ)
        if env.get("LIGHTGBM_TPU_TRACE"):
            # per-worker trace files: the sweep's children inherit one env
            # path and would clobber each other at exit; the driver merges
            # them back with `python -m lightgbm_tpu.obs.trace merge`
            env["LIGHTGBM_TPU_TRACE"] = "%s.dev%d" % (
                env["LIGHTGBM_TPU_TRACE"], d,
            )
        # a fresh process per device count: the jax device world is fixed
        # at backend init, so the sweep cannot reconfigure in-process
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--devices", str(d),
             "--rows", str(rows), "--iters", str(iters), "--chunk",
             str(chunk), "--leaves", str(leaves)],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        rec = None
        for line in (out.stdout or "").splitlines():
            if line.startswith("RESULT "):
                rec = json.loads(line[len("RESULT "):])
        if rec is None:
            rec = {"devices": d, "error": (out.stderr or "")[-400:],
                   "rc": out.returncode}
        points.append(rec)
        print("multichip: devices=%s -> %s" % (d, rec), file=sys.stderr,
              flush=True)
    good = [p for p in points if p.get("iters_per_sec")]
    base = next((p for p in good if p["devices"] == 1), None)
    summary = {
        "metric": "higgs_multichip_iters_per_sec",
        "unit": "iters/s",
        "value": good[-1]["iters_per_sec"] if good else 0.0,
        "rows": rows, "iters": iters, "chunk": chunk, "leaves": leaves,
        "scaling": points,
        "platform": good[-1].get("platform") if good else "unknown",
        "ok": bool(good),
    }
    if base and len(good) > 1:
        summary["speedup_vs_1dev"] = round(
            good[-1]["iters_per_sec"] / base["iters_per_sec"], 3
        )
        # scaling efficiency vs the sweep's OWN n=1 point: measured
        # iters/s over the ideal linear D x base — the MULTICHIP series'
        # regression signal (helpers/bench_diff.py WARNs on drops)
        eff = [
            [p["devices"],
             round(p["iters_per_sec"]
                   / (p["devices"] * base["iters_per_sec"]), 4)]
            for p in sorted(good, key=lambda p: p["devices"])
        ]
        summary["efficiency_by_devices"] = eff
        summary["scaling_efficiency"] = eff[-1][1]
    # adopt the attribution block of the widest profiled point so the
    # MULTICHIP record itself says why scaling bends (obs/dist.py)
    profiled = [p for p in good if p.get("comms_fraction") is not None]
    if profiled:
        top = profiled[-1]
        for key in ("comms_fraction", "dist_segments", "dist_collective",
                    "collective_bytes_per_split", "per_device",
                    "dist_bitwise"):
            if key in top:
                summary[key] = top[key]
    return summary


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--sweep", type=str, default="")
    ap.add_argument("--rows", type=int, default=0)
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=0)
    ap.add_argument("--leaves", type=int, default=0)
    args = ap.parse_args()
    on_chip = os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu")
    rows = args.rows or (1_000_000 if on_chip else 20_000)
    iters = args.iters or (16 if on_chip else 8)
    chunk = args.chunk or (16 if on_chip else 4)
    leaves = args.leaves or (255 if on_chip else 31)
    if args.sweep:
        counts = [int(x) for x in args.sweep.split(",") if x]
        summary = sweep(counts, rows, iters, chunk, leaves)
        print(json.dumps(summary), flush=True)
        return 0 if summary.get("ok") else 1
    rec = measure(max(args.devices, 1), rows, iters, chunk, leaves)
    print("RESULT " + json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
