"""graftsan concurrency stress smoke: the real serve stack under full
sanitizer instrumentation (check.sh --san, bringup `san` stage).

With ``LIGHTGBM_TPU_SAN=transfer,nan,locks`` armed BEFORE import (so every
serve/obs lock is an order-recording _SanLock and the bucketed dispatch runs
under the no-implicit-upload guard), this drives everything the PRs 3-9
serve/obs stack does concurrently:

  * N predictor threads hammering ServeApp.predict with mixed row counts
    and kinds (exact + fused), half on drift-shifted traffic;
  * a hot-swap thread alternating two model versions through
    ModelRegistry.load (watchdog disarm/arm window included);
  * a scrape thread pulling prometheus_metrics() + drift_snapshot();
  * a final graceful drain with requests still in flight.

PASS requires: zero sanitizer trips (no implicit transfer, no lock-order
inversion) and zero prediction errors on the real stack — while a seeded
self-check proves each tripwire actually fires (a deliberate inversion and
a deliberate implicit upload must both raise). The sanitizer being CLEAN on
instrumented code is only evidence if the instruments are live.

Run: JAX_PLATFORMS=cpu python helpers/san_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["LIGHTGBM_TPU_SAN"] = "transfer,nan,locks"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import sanitize
    from lightgbm_tpu.serve.server import ServeApp

    assert sanitize.MODES == frozenset(
        ("transfer", "nan", "locks")
    ), sanitize.MODES

    rng = np.random.RandomState(0)
    F = 6
    X = rng.randn(800, F)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)

    # two model versions (trained UNDER the transfer/nan tripwires — the
    # training dispatch seams are part of the smoke)
    boosters = [
        lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "device_chunk_size": 4, "num_iterations": rounds},
            lgb.Dataset(X, label=y),
        )
        for rounds in (6, 10)
    ]

    failures: list = []
    stop = threading.Event()

    with tempfile.TemporaryDirectory() as td:
        paths = []
        for i, bst in enumerate(boosters):
            p = os.path.join(td, "m%d.txt" % i)
            bst.save_model(p)
            paths.append(p)

        app = ServeApp(
            batch=True, max_delay_ms=1.0, warmup_rows=64, drift=True,
            drift_min_count=64,
        )
        app.registry.load("m", paths[0])
        app.arm_retrace_watchdog()

        # every serve-stack lock must actually be instrumented, or a clean
        # run proves nothing
        for obj, attr in (
            (app.registry, "_lock"), (app.registry, "_load_lock"),
            (app, "_state_lock"), (app.batcher, "_submit_lock"),
        ):
            lk = getattr(obj, attr)
            assert type(lk).__name__ == "_SanLock", (attr, type(lk))

        shifted = X[:64] + np.array([3.0] + [0.0] * (F - 1))

        def predictor(tid: int) -> None:
            r = np.random.RandomState(tid)
            try:
                for i in range(60):
                    n = int(r.choice([1, 7, 16, 33, 64]))
                    rows = X[r.randint(0, len(X), n)]
                    if tid % 2 == 0 and i % 3 == 0:
                        rows = shifted[:n] if n <= 64 else rows
                    out, _served = app.predict(
                        rows, fused=bool(tid % 3 == 0)
                    )
                    if out.shape[0] != n or not np.isfinite(out).all():
                        raise AssertionError(
                            "bad prediction shape/values: %r" % (out.shape,)
                        )
            except Exception as e:  # noqa: BLE001 - collected for the verdict
                failures.append(("predict[%d]" % tid, repr(e)))

        def swapper() -> None:
            try:
                for i in range(6):
                    if stop.is_set():
                        return
                    app.registry.load("m", paths[(i + 1) % 2])
                    time.sleep(0.05)
            except Exception as e:  # noqa: BLE001
                failures.append(("hot-swap", repr(e)))

        def scraper() -> None:
            # counters materialize lazily on first inc, so the early scrapes
            # legitimately lack serve_requests — the final-text assertion
            # below the joins is the real check
            try:
                while not stop.is_set():
                    app.prometheus_metrics()
                    app.drift_snapshot()
                    app.registry.list()
                    time.sleep(0.01)
            except Exception as e:  # noqa: BLE001
                failures.append(("scrape", repr(e)))

        threads = [
            threading.Thread(
                target=predictor, args=(t,), name="predict-%d" % t,
                daemon=True,
            )
            for t in range(6)
        ] + [
            threading.Thread(target=swapper, name="hot-swap", daemon=True),
            threading.Thread(target=scraper, name="scrape", daemon=True),
        ]
        for t in threads:
            t.start()
        # ONE shared deadline for all workers (well under the bringup
        # stage's 1800s timeout), and a hung thread is a NAMED failure —
        # a deadlock is exactly the bug class this smoke exists to catch,
        # not something to mask behind a successful drain
        deadline = time.monotonic() + 240
        for t in threads[:7]:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                failures.append((t.name, "thread hung past the join deadline"))
        stop.set()
        threads[7].join(timeout=30)
        if threads[7].is_alive():
            failures.append((threads[7].name, "scrape thread hung"))

        text = app.prometheus_metrics()
        if "lgbtpu_requests_total" not in text:
            failures.append(
                ("scrape", "final scrape lacks lgbtpu_requests_total")
            )

        drained = app.drain(timeout_s=30.0)
        if app.batcher is not None:
            app.batcher.close()
        if not drained:
            failures.append(("drain", "in-flight requests outlived drain"))

        edges = sanitize.lock_edges()
        if not edges:
            failures.append(
                ("locks", "no acquisition-order edges recorded — "
                          "instrumentation never engaged")
            )

    # ---- seeded tripwires: a clean run only counts if the teeth bite ----
    seeded = {}
    try:
        import jax

        with sanitize.transfer_scope("seeded"):
            jax.jit(lambda a: a * 2)(np.ones(4, np.float32))
        seeded["transfer"] = "MISSED"
    except sanitize.SanitizerError:
        seeded["transfer"] = "caught"
    a = sanitize.make_lock("seed.A")
    b = sanitize.make_lock("seed.B")
    with a:
        with b:
            pass
    try:
        with b:
            with a:
                pass
        seeded["inversion"] = "MISSED"
    except sanitize.SanitizerError:
        seeded["inversion"] = "caught"

    ok = not failures and all(v == "caught" for v in seeded.values())
    # ONE compact line: the bringup driver's result parser reads the last
    # JSON line of stdout (helpers/tpu_bringup.py _parse_result)
    print(json.dumps({
        "ok": ok,
        "san_smoke": "PASS" if ok else "FAIL",
        "failures": failures,
        "seeded": seeded,
        "lock_edges": len(edges),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
