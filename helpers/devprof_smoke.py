"""Device-timeline smoke: capture -> parse -> verdict in ONE invocation.

Wired as ``helpers/check.sh --devprof`` and as the ``devprof`` bringup
stage (helpers/tpu_bringup.py runs this file by path, driver stays
jax-free). What it proves, end to end, on whatever backend is present:

 1. a scoped ``devprof.capture()`` window around real (already-compiled)
    boosting iterations emits a parseable XLA profile;
 2. the stdlib parser reconstructs a NON-EMPTY timeline with lanes
    (``/device:`` lanes on TPU; the documented host-executor proxy on
    CPU) and attributes device self-time to named TraceAnnotation
    segments — a majority of it, since the capture runs with the obs
    tracer live;
 3. the bound-ness verdict comes back with its evidence numbers;
 4. ``devprof_*`` gauges land in the one MetricsRegistry, the
    ``device_timeline`` section lands in run_report(), and obs/report.py
    renders the section into HTML.

Exit 0 and a final compact JSON line on success (the bringup stage
records it into TPU_BRINGUP.json); exit 1 with the reason otherwise.
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("DEVPROF_SMOKE_ROWS", 6000))
ITERS = int(os.environ.get("DEVPROF_SMOKE_ITERS", 4))


def fail(msg):
    print("devprof_smoke: FAIL: %s" % msg, file=sys.stderr)
    print(json.dumps({"ok": False, "error": msg[:300]}), flush=True)
    sys.exit(1)


def main():
    import numpy as np

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import REGISTRY
    from lightgbm_tpu.obs import devprof
    from lightgbm_tpu.obs import report as report_mod

    rng = np.random.RandomState(11)
    X = rng.rand(ROWS, 10).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.rand(ROWS) > 0.65).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "learning_rate": 0.1, "verbosity": -1}
    booster = lgb.Booster(params=params,
                          train_set=lgb.Dataset(X, label=y))
    for _ in range(2):  # compile outside the window
        booster.update()
    jax.block_until_ready(booster._gbdt.scores)

    with tempfile.TemporaryDirectory(prefix="lgbtpu_devprof_smoke_") as td:
        cap_dir = os.path.join(td, "profile")
        with devprof.capture(cap_dir) as target:
            for _ in range(ITERS):
                booster.update()
            jax.block_until_ready(booster._gbdt.scores)
        files = devprof.find_trace_files(target)
        if not files:
            fail("capture emitted no trace files under %s" % target)
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = None
        rec = devprof.analyze_dir(target, device_kind=kind,
                                  platform=jax.default_backend(),
                                  iters=ITERS)

    # -- a real, non-empty timeline ---------------------------------------
    if not rec.get("events"):
        fail("parsed timeline is empty")
    if rec.get("lanes_source") not in ("device", "host_executor"):
        fail("no usable lanes (lanes_source=%r)" % rec.get("lanes_source"))
    segs = rec.get("segments") or {}
    named = {k: v for k, v in segs.items() if k != "unattributed"}
    if not named:
        fail("attribution produced no named segments (segments=%r)"
             % sorted(segs))
    verdict = (rec.get("verdict") or {})
    if verdict.get("bound") not in ("host-bound", "device-bound",
                                    "transfer-bound"):
        fail("no bound-ness verdict (%r)" % verdict)
    if not verdict.get("evidence"):
        fail("verdict carries no evidence block")
    if not rec.get("top_ops"):
        fail("no top-op attribution rows")

    # -- publication: gauges + run-report section + HTML page -------------
    devprof.publish(rec)
    rr = REGISTRY.run_report()
    if "devprof_device_busy_fraction" not in (rr.get("gauges") or {}):
        fail("devprof gauges missing from the registry")
    if "device_timeline" not in rr:
        fail("device_timeline section missing from run_report()")
    html = report_mod.render(metrics=rr, title="devprof smoke")
    if "Device timeline" not in html:
        fail("report.py did not render the Device timeline section")

    out = {
        "ok": True,
        "verdict": verdict.get("bound"),
        "device_busy_fraction": rec.get("device_busy_fraction"),
        "transfer_seconds": (rec.get("transfers") or {}).get(
            "total_seconds"),
        "attributed_fraction": rec.get("attributed_fraction"),
        "lanes_source": rec.get("lanes_source"),
        "events": rec.get("events"),
        "top_segment": next(iter(named), None),
        "report_bytes": len(html),
    }
    print("devprof_smoke: PASS — verdict=%s busy=%.3f attributed=%.0f%%"
          % (out["verdict"], out["device_busy_fraction"] or 0.0,
             100 * (out["attributed_fraction"] or 0.0)), file=sys.stderr)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
