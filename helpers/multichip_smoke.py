"""check.sh --multichip: the composed sharded-chunk path on 8 forced CPU
devices, gated on model-string equality.

Runs ONE worker subprocess pinned to 8 virtual CPU devices
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) that trains the
same data three ways:

  * the data-parallel learner with the per-iteration (serial) loop
    (``device_chunk_size=1`` — one shard_map dispatch per tree);
  * the data-parallel learner with the composed sharded-chunk path
    (``device_chunk_size=5`` — a whole chunk of iterations is ONE
    shard_map dispatch with psum over the mesh);
  * the serial single-device learner (``tree_learner=serial``) as the
    structural cross-check.

HARD FAILURES: any serial-loop-vs-sharded-chunk model-string mismatch
(the PR 2 bit-identity obligation extended to meshes), a fallback away
from the chunked path, more than one train_chunk compile, or a
serial-learner structural divergence (split features/thresholds must
match; leaf values may differ in late ulps — the psum regroups the f32
histogram sums, docs/DataParallel.md §Exactness).
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import json, sys
    sys.path.insert(0, "@REPO@")
    from lightgbm_tpu.utils.platform import force_cpu_devices
    jax = force_cpu_devices(8)
    assert len(jax.devices()) == 8, jax.devices()
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import retrace as retrace_mod

    rng = np.random.RandomState(7)
    N, F, ROUNDS, CHUNK = 4096, 8, 11, 5
    X = rng.randn(N, F)
    w = rng.randn(F) * (rng.rand(F) > 0.3)
    y = (X @ w + 0.5 * rng.randn(N) > 0).astype(float)

    def train(learner, chunk):
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "tree_learner": learner, "device_chunk_size": chunk,
             "bagging_freq": 2, "bagging_fraction": 0.8}
        return lgb.train(p, lgb.Dataset(X, label=y), ROUNDS)

    serial_loop = train("data", 1)
    before = retrace_mod.counts().get("gbdt.train_chunk", 0)
    sharded = train("data", CHUNK)
    compiles = retrace_mod.counts().get("gbdt.train_chunk", 0) - before
    assert sharded._gbdt.device_chunk_fallback_reason() is None, (
        "sharded chunk path fell back: %s"
        % sharded._gbdt.device_chunk_fallback_reason())
    # iteration 0 is sequential, then 2 full chunks of 5 -> ONE compile
    assert compiles == 1, "expected 1 train_chunk compile, saw %d" % compiles
    m_loop = serial_loop.model_to_string().split("parameters:")[0]
    m_shard = sharded.model_to_string().split("parameters:")[0]
    assert m_loop == m_shard, (
        "serial-loop vs sharded-chunk MODEL STRING MISMATCH")
    s_loop = np.asarray(serial_loop._gbdt.scores)[:, :N]
    s_shard = np.asarray(sharded._gbdt.scores)[:, :N]
    assert np.array_equal(s_loop, s_shard), "score carries differ"

    single = train("serial", 1)
    t_single, t_shard = single._gbdt.trees(), sharded._gbdt.trees()
    assert len(t_single) == len(t_shard)
    for i, (a, b) in enumerate(zip(t_single, t_shard)):
        assert np.array_equal(a.split_feature, b.split_feature), (
            "serial-vs-sharded split features diverge at tree %d" % i)
        assert np.array_equal(a.threshold_bin, b.threshold_bin), (
            "serial-vs-sharded thresholds diverge at tree %d" % i)
        np.testing.assert_allclose(a.leaf_value, b.leaf_value,
                                   rtol=2e-4, atol=2e-6)
    print("RESULT " + json.dumps({
        "ok": True, "devices": 8, "rounds": ROUNDS, "chunk": CHUNK,
        "train_chunk_compiles": compiles,
        "model_match": True, "serial_struct_match": True,
    }), flush=True)
    """
).replace("@REPO@", REPO)


def main() -> int:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-c", WORKER], env=env, capture_output=True,
        text=True, cwd=REPO, timeout=1500,
    )
    sys.stderr.write(out.stderr[-2000:] if out.stderr else "")
    rec = None
    for line in (out.stdout or "").splitlines():
        if line.startswith("RESULT "):
            rec = json.loads(line[len("RESULT "):])
    if out.returncode != 0 or not rec or not rec.get("ok"):
        print("multichip_smoke FAILED (rc=%d)" % out.returncode)
        if out.stdout:
            print(out.stdout[-1000:])
        return 1
    print(
        "multichip_smoke OK: %d devices, %d rounds, chunk=%d, "
        "%d train_chunk compile(s), serial-loop==sharded-chunk model "
        "strings, serial-learner structure matched"
        % (rec["devices"], rec["rounds"], rec["chunk"],
           rec["train_chunk_compiles"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
