"""Full Higgs-shape benchmark: 10.5M x 28, 500 iterations, 255 leaves.

The reference's headline experiment trains the real 10.5M-row Higgs set in
238.5 s / 500 iters on 16 Xeon E5-2670 threads with test AUC 0.8452
(/root/reference/docs/Experiments.rst:103-128). This runs the SAME shape —
10M train rows + 500k held-out (the reference's split) — on whatever
backend is live (TPU via the relay, else the native CPU learner), so the
1M bench stops being a proxy (VERDICT r4 item 7).

The features are synthetic Higgs-like (bench.make_higgs_like): timing is
shape-faithful; the absolute AUC is not comparable to the real dataset's
0.8452, so the quality sanity is "test AUC well above chance and close to
train" rather than the reference value. Single-core caveat: this box has
ONE core vs the reference's 16 threads — the per-core comparison is the
honest one (238.5 s x ~16 = ~3800 core-seconds).

Emits one JSON line; appends nothing (BENCH_NOTES.md records the result).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TRAIN = int(os.environ.get("HIGGS_N_TRAIN", 10_000_000))
N_TEST = int(os.environ.get("HIGGS_N_TEST", 500_000))
ITERS = int(os.environ.get("HIGGS_ITERS", 500))


def main() -> None:
    from bench import make_higgs_like

    t0 = time.time()
    X, y = make_higgs_like(N_TRAIN + N_TEST, 28)
    Xtr, ytr = X[:N_TRAIN], y[:N_TRAIN]
    Xte, yte = X[N_TRAIN:], y[N_TRAIN:]
    synth_s = time.time() - t0
    print("higgs: synthesized %.1fM rows in %.0fs" % ((N_TRAIN + N_TEST) / 1e6, synth_s),
          file=sys.stderr, flush=True)

    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.metric import AUCMetric

    platform = jax.default_backend()
    params = {
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 255,
        "learning_rate": 0.1,
        "metric": "auc",
        "verbosity": -1,
    }
    if platform == "cpu":
        params["device_type"] = "cpu"  # native host learner

    t0 = time.time()
    ds = lgb.Dataset(Xtr, label=ytr)
    bst = lgb.Booster(params=params, train_set=ds)
    bin_s = time.time() - t0
    print("higgs: binned in %.0fs" % bin_s, file=sys.stderr, flush=True)

    t0 = time.time()
    last_log = t0
    for i in range(ITERS):
        bst.update()
        now = time.time()
        if now - last_log > 120:
            print("higgs: iter %d/%d (%.2f it/s)" % (
                i + 1, ITERS, (i + 1) / (now - t0)), file=sys.stderr, flush=True)
            last_log = now
    # close the async pipeline (block_until_ready can lie on the tunnel)
    float(np.asarray(jax.numpy.ravel(bst._gbdt.scores)[0]))
    train_s = time.time() - t0

    score = bst._gbdt._train_score_np()
    m = AUCMetric(bst.config)
    m.init(ds._binned.metadata, ds.num_data())
    train_auc = float(m.eval(score, bst._gbdt.objective)[0][1])
    t0 = time.time()
    pred = bst.predict(Xte)
    pred_s = time.time() - t0
    order = np.argsort(pred)
    ranks = np.empty(len(pred))
    ranks[order] = np.arange(len(pred))
    pos = yte > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    test_auc = float(
        (ranks[pos].sum() - n_pos * (n_pos - 1) / 2) / (n_pos * n_neg)
    )

    print(json.dumps({
        "metric": "higgs_full_train_seconds",
        "value": round(train_s, 1),
        "unit": "s (binary, %.1fM x 28, 255 leaves, %d iters)" % (N_TRAIN / 1e6, ITERS),
        "iters_per_sec": round(ITERS / train_s, 4),
        "platform": platform,
        "train_auc": round(train_auc, 5),
        "test_auc": round(test_auc, 5),
        "test_predict_s": round(pred_s, 1),
        "bin_s": round(bin_s, 1),
        "reference": "238.5 s / 500 iters on 16 threads, test AUC 0.8452 (Experiments.rst:103-128)",
    }), flush=True)


if __name__ == "__main__":
    main()
