#!/usr/bin/env bash
# Pre-merge gate: static analysis first (cheap, seconds), then the test
# suite. Mirrors what tier-1 enforces — tests/test_graftlint.py re-runs the
# graftlint baseline check inside pytest — but fails faster when the lint
# gate is the problem.
#
# Usage:
#   helpers/check.sh            # graftlint + ruff/mypy (if installed) + tier-1
#   helpers/check.sh --quick    # same lint gate, then the quick pytest tier
#   helpers/check.sh --lint     # lint gate only, no pytest
#   helpers/check.sh --serve    # lint gate, then the serving smoke: boot
#                               # `python -m lightgbm_tpu.serve`, hit
#                               # /healthz + one /predict, shut down
#   helpers/check.sh --obs      # lint gate, then the observability smoke:
#                               # traced mini-train + serve, validate the
#                               # Chrome-trace JSON + Prometheus /metrics
#   helpers/check.sh --resil    # lint gate, then the resilience smoke:
#                               # SIGKILL a checkpointing training run at an
#                               # injected fault site, resume bit-identically;
#                               # SIGTERM-drain the real server mid-flight
#   helpers/check.sh --drift    # lint gate, then the model/data-observability
#                               # smoke: flight-recorded train (JSONL schema),
#                               # drift-monitored serve (shifted traffic must
#                               # alert, in-dist must not), HTML run report
#   helpers/check.sh --prof     # lint gate, then the performance-attribution
#                               # smoke: segment-profiled mini-train —
#                               # breakdown structure + fused-vs-segmented
#                               # bitwise identity + cost-analysis cross-check
#   helpers/check.sh --multichip
#                               # lint gate, then the multichip smoke: the
#                               # composed data-parallel sharded-chunk path
#                               # on 8 forced CPU devices — serial-loop vs
#                               # sharded-chunk model strings must match
#                               # bit for bit, one train_chunk compile,
#                               # serial-learner structural cross-check
#   helpers/check.sh --dist-obs # lint gate, then the distributed-obs smoke:
#                               # segmented sharded chunk bitwise-identical
#                               # to the fused one (model strings + score
#                               # carries) on 8 forced CPU devices, merged
#                               # pod registry exposition (counters == the
#                               # per-process sums), merged Perfetto trace
#                               # with disjoint pids, MULTICHIP record with
#                               # comms_fraction + scaling_efficiency, and
#                               # the HTML Multichip report page — from ONE
#                               # invocation (docs/Observability.md)
#   helpers/check.sh --san      # lint gate (JX011-JX013 engaged), then the
#                               # runtime sanitizer: unit tests (seeded
#                               # transfer/NaN/lock-inversion violations all
#                               # caught; off-path provably free) + the
#                               # concurrency stress smoke (concurrent
#                               # predict + hot-swap + drain + drift +
#                               # /metrics scrape under
#                               # LIGHTGBM_TPU_SAN=transfer,nan,locks)
#   helpers/check.sh --loop     # lint gate, then the continuous-training
#                               # smoke: real serve stack — drift-shifted
#                               # traffic raises a PSI alert, the loop
#                               # controller observes it over HTTP,
#                               # retrains warm-started from the live
#                               # model, gates on AUC, publishes through
#                               # resil/atomic and hot-swaps the replica
#                               # (new version answers /predict with
#                               # lineage, drift sidecar refreshed), plus
#                               # one seeded mid-publish SIGKILL recovered
#                               # from the journal — under the full
#                               # runtime sanitizer
#   helpers/check.sh --tune     # lint gate, then the histogram-autotuner
#                               # smoke: sweep a tiny bucket-shape set on
#                               # CPU, persist + reload the tune cache,
#                               # gate the measured win (tuned route no
#                               # slower than the static default at every
#                               # swept shape, strictly faster at >= 1),
#                               # and prove the routing machinery is
#                               # bit-transparent (default-pinned table ==
#                               # untuned bytes; same-table reruns and
#                               # chunk=1-vs-4 byte-identical)
#   helpers/check.sh --devprof  # lint gate, then the device-timeline
#                               # smoke: capture a scoped jax.profiler
#                               # window around real boosting iterations,
#                               # parse the emitted Chrome trace with the
#                               # stdlib devprof parser, assert a
#                               # non-empty attributed timeline + a
#                               # host/device/transfer-bound verdict +
#                               # the device_timeline report section —
#                               # ONE invocation (obs/devprof.py)
#   helpers/check.sh --elastic  # lint gate, then the elastic preemption-
#                               # tolerance smoke: ONE invocation at forced-
#                               # 8-CPU-device shapes — SIGKILL mid-run ->
#                               # same-mesh resume, SIGTERM -> emergency
#                               # checkpoint + exit 75 -> auto-resume
#                               # byte-equal to the uninterrupted run,
#                               # 8->2 resharded resume (loud warning +
#                               # structural identity), serial<->data@1
#                               # byte-identity (docs/FaultTolerance.md
#                               # §Elastic training)
#   helpers/check.sh --podwatch # lint gate, then the fleet-telemetry
#                               # smoke: ONE invocation — a real 2-process
#                               # CPU training run with the telemetry ring
#                               # + scrape endpoint armed and rank 1 seeded
#                               # slow, scraped live mid-run (/metrics +
#                               # /health + /timeline), then aggregated
#                               # (python -m lightgbm_tpu.obs.podwatch)
#                               # with the seeded straggler named in the
#                               # verdict + telemetry-off byte-identity
#                               # (docs/Observability.md §Fleet telemetry)
#   helpers/check.sh --flex     # lint gate, then the flexctl chaos
#                               # smoke: ONE invocation — a scripted
#                               # capacity storm on forced-multi-CPU
#                               # children (shrink 8->2 at a boundary,
#                               # grow back, SIGKILL one launch mid-
#                               # chunk) supervised end-to-end, gated
#                               # on flex_reshards labels matching the
#                               # script and the exactness taxonomy
#                               # (docs/FaultTolerance.md §Fleet
#                               # orchestrator)
#   helpers/check.sh --ir       # lint gate, then the graftir program
#                               # audit smoke: ONE invocation — seeded
#                               # violations per IR rule all caught, then
#                               # the real tree's registered jit entry
#                               # points traced abstractly over the quick
#                               # shape lattice and checked against the
#                               # IR001-IR006 baseline + the checked-in
#                               # program-fingerprint contract
#                               # (docs/StaticAnalysis.md §Program-level
#                               # audit)
#   helpers/check.sh --bench-diff [CUR BASE]
#                               # the bench regression gate: golden-fixture
#                               # self-test (synthetic regression must FAIL,
#                               # improvement must PASS) + informational
#                               # BENCH_r* series diff; with CUR and BASE
#                               # paths it hard-gates that pair instead.
#                               # Part of the pre-merge flow for any PR that
#                               # claims (or risks) a perf change
#                               # (docs/Observability.md).
#
# ruff/mypy are optional: the container may not ship them (no network
# installs); when absent they are skipped with a notice — graftlint and
# pytest are the hard gate either way.
set -u -o pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"
case "$MODE" in
    full|--quick|--lint|--serve|--obs|--resil|--prof|--drift|--multichip|--dist-obs|--san|--loop|--tune|--devprof|--elastic|--podwatch|--flex|--ir|--bench-diff) ;;
    *)
        echo "check.sh: unknown mode '$MODE' (expected --quick, --lint, --serve, --obs, --resil, --prof, --drift, --multichip, --dist-obs, --san, --loop, --tune, --devprof, --elastic, --podwatch, --flex, --ir or --bench-diff)" >&2
        exit 2
        ;;
esac
fail=0

echo "== graftlint (lightgbm_tpu/ + helpers/ + bench.py against baseline) =="
python -m tools.graftlint lightgbm_tpu/ helpers/ bench.py || fail=1

echo "== graftlint (tools/, no baseline) =="
python -m tools.graftlint --no-baseline tools/ || fail=1

if python -m ruff --version >/dev/null 2>&1; then
    echo "== ruff =="
    python -m ruff check lightgbm_tpu/ tools/ helpers/ tests/ || fail=1
else
    echo "== ruff not installed; skipping (config in pyproject.toml) =="
fi

if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy (strict zone: lightgbm_tpu/utils, tools) =="
    python -m mypy || fail=1
else
    echo "== mypy not installed; skipping (config in pyproject.toml) =="
fi

if [ "$fail" -ne 0 ]; then
    echo "check.sh: lint gate FAILED (fix or baseline with justification)"
    exit 1
fi

if [ "$MODE" = "--lint" ]; then
    echo "check.sh: lint gate clean"
    exit 0
fi

if [ "$MODE" = "--serve" ]; then
    echo "== serve smoke (boot server, /healthz + /predict, shut down) =="
    exec env JAX_PLATFORMS=cpu python helpers/serve_smoke.py
fi

if [ "$MODE" = "--obs" ]; then
    echo "== obs smoke (traced mini-train + serve, validate trace + /metrics) =="
    exec env JAX_PLATFORMS=cpu python helpers/obs_smoke.py
fi

if [ "$MODE" = "--resil" ]; then
    echo "== resil smoke (SIGKILL/resume bit-identity + SIGTERM serve drain) =="
    exec env JAX_PLATFORMS=cpu python helpers/resil_smoke.py
fi

if [ "$MODE" = "--prof" ]; then
    echo "== prof smoke (segment breakdown + bitwise identity + cost analysis) =="
    exec env JAX_PLATFORMS=cpu python helpers/obs_smoke.py --prof
fi

if [ "$MODE" = "--drift" ]; then
    echo "== drift smoke (flight JSONL + PSI separation + HTML report) =="
    exec env JAX_PLATFORMS=cpu python helpers/obs_smoke.py --drift
fi

if [ "$MODE" = "--multichip" ]; then
    echo "== multichip smoke (8 forced CPU devices, sharded-chunk bit-identity) =="
    exec python helpers/multichip_smoke.py
fi

if [ "$MODE" = "--dist-obs" ]; then
    echo "== dist-obs smoke (segmented sharded chunk + merged registry/trace/report) =="
    exec env JAX_PLATFORMS=cpu python helpers/dist_obs_smoke.py
fi

if [ "$MODE" = "--san" ]; then
    echo "== sanitizer unit tests (seeded violations caught, off-path free) =="
    env JAX_PLATFORMS=cpu python -m pytest tests/test_sanitize.py -q \
        -p no:cacheprovider || exit 1
    echo "== graftsan concurrency stress smoke (predict+swap+drain+drift+scrape) =="
    exec env JAX_PLATFORMS=cpu python helpers/san_smoke.py
fi

if [ "$MODE" = "--loop" ]; then
    echo "== loop smoke (drift -> retrain -> validate -> publish -> swap + SIGKILL recovery) =="
    exec env JAX_PLATFORMS=cpu python helpers/loop_smoke.py
fi

if [ "$MODE" = "--tune" ]; then
    echo "== tune smoke (sweep + cache round-trip + perf gate + bit-transparency) =="
    exec env JAX_PLATFORMS=cpu python helpers/tune_smoke.py
fi

if [ "$MODE" = "--devprof" ]; then
    echo "== devprof smoke (capture -> parse -> verdict + report section) =="
    exec env JAX_PLATFORMS=cpu python helpers/devprof_smoke.py
fi

if [ "$MODE" = "--elastic" ]; then
    echo "== elastic smoke (SIGKILL/SIGTERM -> resume byte-identity + 8->2 reshard) =="
    exec python helpers/elastic_smoke.py
fi

if [ "$MODE" = "--podwatch" ]; then
    echo "== podwatch smoke (2-proc train + live scrape + straggler verdict) =="
    exec python helpers/podwatch_smoke.py
fi

if [ "$MODE" = "--flex" ]; then
    echo "== flex smoke (capacity storm: shrink/grow drains + mid-chunk SIGKILL under flexctl) =="
    exec python helpers/flex_smoke.py
fi

if [ "$MODE" = "--ir" ]; then
    echo "== irscan smoke (seeded IR violations caught + real-tree scan vs baseline/contract) =="
    exec python helpers/irscan_smoke.py
fi

if [ "$MODE" = "--bench-diff" ]; then
    if [ $# -ge 3 ]; then
        echo "== bench-diff gate ($2 vs $3) =="
        exec python helpers/bench_diff.py "$2" "$3"
    fi
    echo "== bench-diff self-test (golden fixtures) =="
    python helpers/bench_diff.py --self-test || exit 1
    echo "== bench-diff series (informational) =="
    python helpers/bench_diff.py --series 'BENCH_r*.json' || true
    exit 0
fi

if [ "$MODE" = "--quick" ]; then
    MARK='quick and not slow'
else
    MARK='not slow'
fi

echo "== pytest (-m \"$MARK\") =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "$MARK" \
    --continue-on-collection-errors -p no:cacheprovider
