"""check.sh --flex: the flexctl chaos smoke, ONE invocation.

Drives the elastic fleet orchestrator (lightgbm_tpu/flex) through a
scripted capacity storm on forced-multi-CPU-device children and gates on
the exactness taxonomy docs/FaultTolerance.md documents:

  leg A — **capacity chaos, in-process controller**. A scripted plan
     shrinks the world 8 -> 2 after iteration 4 and grows it back 2 -> 8
     after iteration 7; launch #3 additionally gets a fault-injected
     SIGKILL mid-chunk (``train.iteration:2:kill``). Expected run:
     child 1 (world 8) drains at the shrink boundary and exits 76,
     child 2 (world 2) drains at the grow boundary and exits 76,
     child 3 (world 8) is murdered mid-chunk (rc -9, a plain crash),
     child 4 (world 8) resumes and finishes. Gates: exactly 2 reshards
     with the scripted {from,to,reason} labels on ``flex_reshards``,
     exactly 1 crash restart, the loud ulp-drift warning EXACTLY once
     per world change, final model structurally identical to the
     uninterrupted reference with the pre-drain tree prefix byte-exact
     and every leaf within ulp tolerance (the world changed twice —
     byte-identity is NOT claimed, measured impossible).
  leg B — **same storm class, no world change, real CLI**. The
     ``python -m lightgbm_tpu.flex`` entry point supervises a run whose
     plan never changes and whose first child is SIGKILLed mid-run:
     one crash restart, zero reshards, and — because the row world
     size never changed — a final model BYTE-identical to the
     uninterrupted reference.

HARD FAILURES: wrong reshard count/labels, wrong restart count, a missing
or duplicated ulp warning, structural divergence or prefix/byte mismatch,
or a controller that does not finish with rc 0.

The last stdout line is a JSON result for helpers/tpu_bringup.py.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 12
CKPT_ROUNDS = 3
CHILD_TIMEOUT_S = 420.0

BASE_PARAMS = {
    "task": "train",
    "objective": "binary",
    "num_leaves": "15",
    "verbosity": "-1",
    "bagging_freq": "2",
    "bagging_fraction": "0.8",
    "feature_fraction": "0.8",
    "tree_learner": "data",
    "device_chunk_size": "3",
    "num_iterations": str(ROUNDS),
}


def _fail(msg, *tails):
    print("flex_smoke FAILED: %s" % msg, flush=True)
    for t in tails:
        if t:
            print(t[-1500:], flush=True)
    print(json.dumps({"ok": False, "error": msg}), flush=True)
    return 1


def _write_data(path):
    import numpy as np

    rng = np.random.RandomState(7)
    n, f = 1003, 6
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.3 * rng.randn(n) > 0).astype(float)
    np.savetxt(path, np.column_stack([y, X]), fmt="%.10g", delimiter="\t")


def _cli_env(world):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % world
    env.pop("LIGHTGBM_TPU_FAULTS", None)
    return env


def _train_ref(data, out):
    kv = dict(BASE_PARAMS, data=data, output_model=out)
    argv = [sys.executable, "-m", "lightgbm_tpu"]
    argv += ["%s=%s" % (k, v) for k, v in kv.items()]
    r = subprocess.run(argv, env=_cli_env(8), cwd=REPO, capture_output=True,
                       text=True, timeout=CHILD_TIMEOUT_S)
    if r.returncode != 0:
        print(r.stdout[-1500:])
        print(r.stderr[-1500:])
        raise RuntimeError("reference training failed rc=%d" % r.returncode)


def _model_body(path):
    with open(path, encoding="utf-8") as fh:
        return fh.read().split("parameters:")[0]


def _trees(path):
    """(split_feature tuple, threshold tuple, leaf_value tuple) per tree —
    structural + value comparisons without trusting float formatting."""
    import re

    out = []
    for block in _model_body(path).split("\nTree=")[1:]:
        f = {}
        for line in block.splitlines():
            m = re.match(r"(split_feature|threshold|leaf_value)=(.*)", line)
            if m:
                f[m.group(1)] = m.group(2).split()
        out.append((tuple(f.get("split_feature", [])),
                    tuple(f.get("threshold", [])),
                    tuple(float(v) for v in f.get("leaf_value", []))))
    return out


def _tree_blocks(path):
    return _model_body(path).split("\nTree=")[1:]


def _ulp_close(a, b):
    return abs(a - b) <= 2e-4 * max(abs(a), abs(b), 1e-6) + 2e-6


class _TimedChild:
    """Popen wrapper whose wait() cannot wedge the smoke: a child that
    outlives the per-launch budget is SIGKILLed and reported as a crash."""

    def __init__(self, proc):
        self.proc = proc

    def wait(self):
        try:
            return self.proc.wait(timeout=CHILD_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            return self.proc.wait()


def _leg_a(work, data):
    """Scripted shrink/grow storm + a mid-chunk SIGKILL, controller
    in-process so the kill can be injected into EXACTLY one launch."""
    from lightgbm_tpu.flex import CapacityPlan, FlexController, marker_path
    from lightgbm_tpu.flex.__main__ import child_env
    from lightgbm_tpu.obs.registry import REGISTRY
    from lightgbm_tpu.utils import log as tlog

    ckpt = os.path.join(work, "a.ckpt")
    out = os.path.join(work, "a_model.txt")
    plan_path = os.path.join(work, "a_plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"world": 8, "steps": [
            {"after_iteration": 4, "world": 2, "reason": "shrink"},
            {"after_iteration": 7, "world": 8, "reason": "grow"},
        ]}, fh)

    kill_attempt = 3
    iters = []  # (attempt, world) receipts, for the storm-shape report

    def launch(world, attempt):
        kv = dict(BASE_PARAMS, data=data, output_model=out,
                  flex_plan=plan_path, checkpoint_path=ckpt,
                  checkpoint_rounds=str(CKPT_ROUNDS))
        if os.path.exists(ckpt):
            kv["resume_from"] = ckpt
        env = child_env(dict(os.environ), world, True)
        env.pop("LIGHTGBM_TPU_FAULTS", None)
        if attempt == kill_attempt:
            env["LIGHTGBM_TPU_FAULTS"] = "train.iteration:2:kill"
        argv = [sys.executable, "-m", "lightgbm_tpu"]
        argv += ["%s=%s" % (k, v) for k, v in kv.items()]
        iters.append((attempt, world))
        lp = os.path.join(work, "a_launch%d.log" % attempt)
        fh = open(lp, "w")
        return _TimedChild(subprocess.Popen(
            argv, env=env, cwd=REPO, stdout=fh, stderr=fh))

    ulp_warnings = []
    tlog.register_callback(
        lambda line: ulp_warnings.append(line) if "ulp level" in line
        else sys.stderr.write(line))
    try:
        ctl = FlexController(
            launch, CapacityPlan(plan_path),
            os.path.join(work, "a.flex.journal.json"),
            marker=marker_path(ckpt), initial_world=8,
            min_healthy_s=1.0, backoff_base_s=0.2, backoff_max_s=2.0,
            seed=7,
        )
        rc = ctl.run(max_launches=8)
    finally:
        tlog.register_callback(None)
    s = ctl.summary()
    if rc != 0:
        return None, "leg A controller rc=%d (summary %s)" % (rc, s)

    if int(s["reshards"]) != 2:
        return None, "leg A expected 2 reshards, got %s" % s["reshards"]
    want_log = [{"from": 8, "to": 2, "reason": "shrink", "exact": False},
                {"from": 2, "to": 8, "reason": "grow", "exact": False}]
    if list(s["reshard_log"]) != want_log:
        return None, "leg A reshard_log %s != %s" % (s["reshard_log"],
                                                     want_log)
    c = REGISTRY.counter("flex_reshards")
    for fw, tw, why in ((8, 2, "shrink"), (2, 8, "grow")):
        got = c.value(**{"from": str(fw), "to": str(tw), "reason": why})
        if got != 1:
            return None, ("leg A flex_reshards{from=%d,to=%d,reason=%s} "
                          "= %s, expected 1" % (fw, tw, why, got))
    if int(s["restarts"]) != 1:
        return None, "leg A expected 1 crash restart, got %s" % s["restarts"]
    if len(ulp_warnings) != 2:
        return None, ("leg A expected the ulp-drift warning exactly once "
                      "per world change (2), saw %d" % len(ulp_warnings))
    worlds = [w for _, w in iters]
    if worlds != [8, 2, 8, 8]:
        return None, "leg A launch worlds %s != [8, 2, 8, 8]" % worlds
    return {"out": out, "launches": s["launches"], "worlds": worlds}, None


def _leg_b(work, data):
    """The real ``python -m lightgbm_tpu.flex`` CLI, constant-world plan,
    first child SIGKILLed mid-run: crash restart + byte-identity."""
    ckpt = os.path.join(work, "b.ckpt")
    out = os.path.join(work, "b_model.txt")
    plan_path = os.path.join(work, "b_plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"world": 8}, fh)

    # occurrence 4 of train.iteration lands after a periodic checkpoint
    # exists (the elastic_smoke-measured shape at 12 rounds / chunk 3);
    # the RESUMED child replays fewer than 4 passes, so the inherited
    # fault spec can never re-fire and the relaunch completes
    env = dict(os.environ)
    env["LIGHTGBM_TPU_FAULTS"] = "train.iteration:4:kill"
    argv = [sys.executable, "-m", "lightgbm_tpu.flex",
            "flex_plan=%s" % plan_path, "checkpoint_path=%s" % ckpt,
            "flex_force_cpu=true", "flex_max_launches=4", "flex_seed=3",
            "data=%s" % data, "output_model=%s" % out,
            "checkpoint_rounds=%d" % CKPT_ROUNDS]
    argv += ["%s=%s" % (k, v) for k, v in BASE_PARAMS.items()]
    r = subprocess.run(argv, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=4 * CHILD_TIMEOUT_S)
    summary = None
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("{"):
            try:
                summary = json.loads(line)
                break
            except ValueError:
                continue
    if r.returncode != 0 or not summary or not summary.get("ok"):
        return None, ("leg B flexctl rc=%d summary=%s\n%s\n%s"
                      % (r.returncode, summary, r.stdout[-1000:],
                         r.stderr[-1000:]))
    if int(summary.get("reshards") or 0) != 0:
        return None, "leg B expected 0 reshards, got %s" % summary
    if int(summary.get("restarts") or 0) != 1:
        return None, "leg B expected 1 restart, got %s" % summary
    return {"out": out, "summary": summary}, None


def main() -> int:
    import tempfile

    work = tempfile.mkdtemp(prefix="flex_smoke_")
    data = os.path.join(work, "train.tsv")
    ref_out = os.path.join(work, "ref_model.txt")
    _write_data(data)
    t0 = time.time()

    _train_ref(data, ref_out)
    t_ref = time.time() - t0
    print("flex_smoke: reference trained (8 devices, %.1fs — %.2f it/s)"
          % (t_ref, ROUNDS / t_ref), flush=True)

    t1 = time.time()
    a, err = _leg_a(work, data)
    if err:
        return _fail(err)
    t_a = time.time() - t1
    print("flex_smoke: leg A storm complete — worlds %s, 2 reshards "
          "(8->2 shrink, 2->8 grow), 1 crash restart, ulp warning once "
          "per change (%.1fs)" % (a["worlds"], t_a), flush=True)

    ref_trees, a_trees = _trees(ref_out), _trees(a["out"])
    if len(a_trees) != ROUNDS or len(ref_trees) != ROUNDS:
        return _fail("leg A tree count %d vs reference %d (want %d)"
                     % (len(a_trees), len(ref_trees), ROUNDS))
    for i, (rt, at) in enumerate(zip(ref_trees, a_trees)):
        if rt[0] != at[0] or rt[1] != at[1]:
            return _fail("leg A tree %d structure diverged from the "
                         "uninterrupted reference" % i)
        for rv, av in zip(rt[2], at[2]):
            if not _ulp_close(rv, av):
                return _fail("leg A tree %d leaf drift beyond ulp "
                             "tolerance: %r vs %r" % (i, rv, av))
    prefix = 0
    for rb, ab in zip(_tree_blocks(ref_out), _tree_blocks(a["out"])):
        if rb != ab:
            break
        prefix += 1
    if prefix < 4:
        return _fail("leg A pre-drain prefix only %d trees byte-exact "
                     "(the shrink latched after iteration 4, so >= 4 "
                     "trees predate any world change)" % prefix)
    print("flex_smoke: leg A exactness — structure identical, %d-tree "
          "prefix byte-exact, all leaves ulp-close" % prefix, flush=True)

    t2 = time.time()
    b, err = _leg_b(work, data)
    if err:
        return _fail(err)
    print("flex_smoke: leg B flexctl CLI survived the SIGKILL — 1 restart,"
          " 0 reshards (%.1fs)" % (time.time() - t2), flush=True)
    if _model_body(b["out"]) != _model_body(ref_out):
        return _fail("leg B model differs from the uninterrupted reference"
                     " — same-world resume must be byte-identical")
    print("flex_smoke: leg B byte-identity holds (world never changed)",
          flush=True)

    elapsed = time.time() - t0
    print("flex_smoke: PASS (%.1fs)" % elapsed, flush=True)
    print(json.dumps({"ok": True, "elapsed_s": round(elapsed, 1),
                      "legA": {"worlds": a["worlds"],
                               "launches": a["launches"],
                               "prefix_trees": prefix},
                      "legB": b["summary"]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
