"""One-command TPU bring-up: staged first-contact validation + bench.

The TPU relay has been dead for rounds 2-3; when it returns, chip time is
scarce and the first contact must be choreographed, not improvised. This
script runs, in order, each stage in its own killed-process-group subprocess
(a timeout-killed TPU client can wedge the tunnel — see BENCH_NOTES.md):

  1. matmul      — device claim + one bf16 matmul (tunnel sanity)
  2. pallas      — histogram_pallas(interpret=False) vs the numpy oracle at
                   bench shapes, bf16 and f32 operands. The on-silicon
                   analogue of the reference GPU path's in-code cross-check
                   (/root/reference/src/treelearner/gpu_tree_learner.cpp:996-1019).
  3. smoke / smoke_seq — 100k-row binary training (pow2 lattice to cap
                   compile cost) under the spec and sequential growers;
                   train-AUC sanity vs the known CPU value (~0.74)
  4. bench_early — full bench.py RIGHT AFTER the grower race (the relay
                   has died mid-bringup in 3 of 4 rounds; the headline 1M
                   number lands in BENCH_TPU.json before the measurement
                   tail, already auto-adopting the better grower)
  5. smoke_* variants + pack4 — the routing/precision bake-off
  6. bench       — final full bench.py with the complete bake-off;
                   overwrites BENCH_TPU.json on success only.

Every stage appends a JSON line to .tpu_bringup.log and the final summary
lands in TPU_BRINGUP.json. Run directly, or let the probe chain fire it:

    python helpers/tpu_probe_loop.py && python helpers/tpu_bringup.py

Uses the persistent JAX compilation cache (.jax_cache) so a second contact
skips the multi-minute compiles.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, ".tpu_bringup.log")


def _load_resil(modname: str):
    """A lightgbm_tpu.resil module by FILE path: importing it through the
    package would execute lightgbm_tpu/__init__ and pull jax into this
    driver process, which stays jax-free on the no-trace path by design.
    Only the deliberately jax-free resil modules (backoff, preempt) load
    this way."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lgbtpu_resil_%s" % modname,
        os.path.join(REPO, "lightgbm_tpu", "resil", "%s.py" % modname),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_backoff():
    return _load_resil("backoff")


_PREEMPT_RC = None


def _preempt_exit_code() -> int:
    """resil/preempt.py's PREEMPT_EXIT_CODE — the documented 'SIGTERMed
    child published an emergency checkpoint; re-run to resume' exit code
    run_with_retry treats as resumable. Cached: _run_child consults it on
    every nonzero-rc child."""
    global _PREEMPT_RC
    if _PREEMPT_RC is None:
        _PREEMPT_RC = int(_load_resil("preempt").PREEMPT_EXIT_CODE)
    return _PREEMPT_RC


# transient tunnel/TPU-client wedges (the relay dying and coming back, a
# stuck client that the process-group kill cleared) deserve another shot
# before a stage is recorded failed: retries beyond the first attempt, and
# the exponential backoff before each one (resil/backoff.py — the same
# schedule helper the serve dispatch retry uses)
STAGE_RETRIES = int(os.environ.get("LIGHTGBM_TPU_BRINGUP_RETRIES", "1"))
STAGE_BACKOFF_S = float(os.environ.get("LIGHTGBM_TPU_BRINGUP_BACKOFF_S", "20"))
_REHEARSAL = os.environ.get("LIGHTGBM_TPU_BRINGUP_CPU") == "1"
# a CPU rehearsal must never write the production summary: bench.py's
# bake-off adoption reads TPU_BRINGUP.json, and CPU-measured smoke rates
# would steer a later REAL chip window to the wrong config
SUMMARY = os.path.join(
    REPO, "TPU_BRINGUP_REHEARSAL.json" if _REHEARSAL else "TPU_BRINGUP.json"
)

STAGE_TIMEOUTS = {
    "matmul": 180,
    "pallas": 900,     # first Mosaic lowering can be slow
    "pack4": 900,      # nibble-packing measurement (VERDICT r3 item 8)
    "smoke": 1800,     # bucket-lattice switch compile at 100k rows
    "smoke_seq": 1800,  # sequential grower (spec-batch win measurement)
    "tune": 1800,   # histogram autotune sweep: every supported impl raced
                    # at the grower's bucket-shape distribution, persisted
                    # as TUNE_HIST.json for bench/training auto-adoption
                    # (obs/tune.py, ISSUE 13)
    "irscan": 1800,  # graftir program audit: seeded IR001-IR006 violations
                     # caught + the real tree's jit entry points traced
                     # abstractly and checked against the baseline +
                     # fingerprint contract — the traced programs audited
                     # BEFORE bench spends chip time on them (obs/irscan.py,
                     # ISSUE 16)
    "bench_early": 3600,  # headline secured before the long tail of stages
    "smoke_pallas": 1800,  # same smoke, pallas histogram impl (routing race)
    "smoke_xla_radix": 1800,  # same smoke, plain-XLA radix factorization
    "smoke_bf16": 1800,  # same smoke, bf16 MXU operands (AUC delta record)
    "smoke_psplit": 1800,  # opt-in Pallas split-scan kernel (first lowering)
    "bench_chunk": 3600,   # device-resident boosting sweep at the 1M shape
    "bench_multichip": 3600,  # devices∈{1,4,8} sharded-chunk scaling (ISSUE 8)
    "bench_predict": 1800,  # packed-inference serving bench (ISSUE 3)
    "prof": 1800,   # segment-profiled mini-train (obs/prof.py, ISSUE 6)
    "devprof": 1800,  # device-timeline audit: capture -> parse -> verdict
                      # (obs/devprof.py, ISSUE 14) — on silicon this is the
                      # first artifact that says host/device/transfer-bound
                      # from real /device: lanes
    "san": 1800,    # graftsan stress smoke under full instrumentation
                    # (obs/sanitize.py, ISSUE 11)
    "loop": 1800,   # continuous-training loop smoke: drift -> retrain ->
                    # validate -> publish -> swap + mid-publish SIGKILL
                    # recovery on the real serve stack (loop/, ISSUE 12)
    "elastic": 1800,  # elastic preemption-tolerance smoke: SIGKILL ->
                      # same-mesh resume byte-identity, SIGTERM -> exit-75
                      # emergency checkpoint -> auto-resume, 8->2 reshard
                      # structural identity (resil/, ISSUE 15)
    "flex": 1800,   # flexctl chaos smoke: scripted capacity storm
                    # (shrink 8->2 at a boundary, grow back, SIGKILL one
                    # launch mid-chunk) supervised end-to-end — reshard
                    # counters must match the script and the final model
                    # must match the uninterrupted reference per the
                    # exactness taxonomy (flex/, ISSUE 20)
    "podwatch": 1800,  # fleet-telemetry smoke: real 2-process training
                       # scraped live mid-run (/metrics /health /timeline),
                       # shards aggregated, seeded straggler rank named in
                       # the verdict + telemetry-off byte-identity
                       # (obs/podwatch.py, ISSUE 19)
    "bench": 3600,
}

_COMMON = """
import os, sys, time, json
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "axon")
import jax
if os.environ.get("LIGHTGBM_TPU_BRINGUP_CPU") == "1":
    # dress-rehearsal mode: XLA compute stages run on the CPU backend (the
    # env var alone is not enough — this machine's sitecustomize re-pins
    # the axon platform via jax.config.update at interpreter start); the
    # Mosaic kernel stages (pallas/pack4/smoke_pallas) cannot lower on CPU
    # and rehearse only their fail-and-continue path
    jax.config.update("jax_platforms", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
jax.config.update("jax_compilation_cache_dir", %r)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
import jax.numpy as jnp


def timeloop(fn, scales, reps=8):
    # single trailing VALUE fetch closes the pipeline: on this tunneled
    # backend block_until_ready can return before the enqueued work executes
    # (measured r4), and each fetch carries ~66ms of wire latency — amortize
    # it over the reps instead of paying it per call
    acc = fn(0)
    jax.block_until_ready(acc)
    _ = float(jnp.ravel(acc)[0])
    t0 = time.time()
    for i in range(reps):
        acc = fn(i %% len(scales))
    _ = float(jnp.ravel(acc)[0])
    return round((time.time() - t0) * 1000 / reps, 2)
""" % os.path.join(REPO, ".jax_cache")

MATMUL = _COMMON + """
d = jax.devices()
t0 = time.time()
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = (x @ x).block_until_ready()
print(json.dumps({"ok": True, "platform": d[0].platform, "n_devices": len(d),
                  "matmul_s": round(time.time() - t0, 2),
                  "checksum": float(jnp.sum(y, dtype=jnp.float32))}))
"""

PALLAS = _COMMON + """
sys.path.insert(0, %r)
from lightgbm_tpu.ops.hist_pallas import histogram_pallas, histogram_pallas_v1

rng = np.random.RandomState(0)
F, N, B, K = 28, 1 << 18, 255, 3
bins_np = rng.randint(0, B, size=(F, N)).astype(np.uint8)
vals_np = rng.randn(N, K).astype(np.float32)
bins = jax.device_put(jnp.asarray(bins_np))
vals = jax.device_put(jnp.asarray(vals_np))

def oracle(bins, vals):
    out = np.zeros((F, B, K), np.float64)
    for f in range(F):
        for k in range(K):
            out[f, :, k] = np.bincount(bins[f], weights=vals[:, k], minlength=B)[:B]
    return out

ref = oracle(bins_np, vals_np)
scales = [jnp.float32(1.0 + 0.01 * i) for i in range(8)]
res = {}
for dt in ("float32", "bfloat16"):
    t0 = time.time()
    h = np.asarray(histogram_pallas(bins, vals, B, dtype_name=dt,
                                    interpret=False))
    dtime = time.time() - t0
    err = np.abs(h.astype(np.float64) - ref)
    rel = err / np.maximum(np.abs(ref), 1.0)
    res[dt] = {"max_abs": float(err.max()), "max_rel": float(rel.max()),
               "first_call_s": round(dtime, 2)}
    res[dt]["per_call_ms"] = timeloop(
        lambda i, dt=dt: histogram_pallas(bins, vals * scales[i], B,
                                          dtype_name=dt, interpret=False),
        scales)
res["v1_per_call_ms"] = timeloop(
    lambda i: histogram_pallas_v1(bins, vals * scales[i], B,
                                  dtype_name="float32", interpret=False),
    scales)
from lightgbm_tpu.ops.histogram import leaf_histogram
res["xla_per_call_ms"] = timeloop(
    lambda i: leaf_histogram(bins, vals * scales[i], B, impl="xla"), scales)
res["xla_radix_per_call_ms"] = timeloop(
    lambda i: leaf_histogram(bins, vals * scales[i], B, impl="xla_radix"),
    scales)
# f32 accumulates in chunk order: 1e-4 rel absorbs summation-order ULP at
# 2^18 rows (measured 1.8e-5 on first contact); bf16 rounds operands to
# ~2^-8 — record it, gate loosely, judge by the smoke AUC
ok = res["float32"]["max_rel"] < 1e-4 and res["bfloat16"]["max_rel"] < 0.5
print(json.dumps({"ok": bool(ok), **res}))
""" % REPO

PACK4 = _COMMON + """
sys.path.insert(0, %r)
from lightgbm_tpu.ops.hist_pallas import (
    histogram_pallas, histogram_pallas_packed4, pack4,
)

# the 4-bit-bin measurement (VERDICT r3 item 8): max_bin=15-class shape,
# nibble-packed vs u8 bins — dense_nbits_bin.hpp:42's question on TPU
rng = np.random.RandomState(1)
F, N, B, K = 28, 1 << 20, 16, 3
bins = jax.device_put(jnp.asarray(
    rng.randint(0, B, size=(F, N)).astype(np.uint8)))
vals = jax.device_put(jnp.asarray(rng.randn(N, K).astype(np.float32)))
bp, vp = pack4(bins, vals)
bp, vp = jax.device_put(bp), jax.device_put(vp)
scales = [jnp.float32(1.0 + 0.01 * i) for i in range(8)]

u8_ms = timeloop(lambda i: histogram_pallas(bins, vals * scales[i], B,
                                            dtype_name="float32"), scales)
p4_ms = timeloop(lambda i: histogram_pallas_packed4(bp, vp * scales[i], B,
                                                    dtype_name="float32"),
                 scales)
h1 = np.asarray(histogram_pallas(bins, vals, B, dtype_name="float32"))
h2 = np.asarray(histogram_pallas_packed4(bp, vp, B, dtype_name="float32"))
agree = float(np.abs(h1 - h2).max())
win = (u8_ms - p4_ms) / u8_ms * 100.0
out = {"ok": agree < 1e-2, "u8_ms": u8_ms, "packed4_ms": p4_ms,
       "win_pct": round(win, 1), "max_abs_diff": agree,
       "verdict": "keep" if win > 10 else "not-worth-it"}
with open(os.path.join(%r, "PACK4_MEASURE.json"), "w") as f:
    json.dump(out, f); f.write(chr(10))
print(json.dumps(out))
""" % (REPO, REPO)

SMOKE = _COMMON + """
sys.path.insert(0, %r)
os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"   # cap first-contact compile cost
os.environ["LIGHTGBM_TPU_TIMETAG"] = "1"  # async phase accumulators -> obs_report
import lightgbm_tpu as lgb
from lightgbm_tpu.metric import AUCMetric

sys.path.insert(0, %r)
from bench import make_higgs_like
X, y = make_higgs_like(100_000, 28)
params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
          "learning_rate": 0.1, "metric": "auc", "verbosity": -1}
ds = lgb.Dataset(X, label=y)
bst = lgb.Booster(params=params, train_set=ds)
t0 = time.time()
bst.update()
jax.block_until_ready(bst._gbdt.scores)
compile_s = time.time() - t0
t0 = time.time()
for _ in range(10):
    bst.update()
# value fetch, not just block: the async loop (deferred stop check) means
# block_until_ready alone can return before the enqueued work executes
float(np.asarray(jnp.ravel(bst._gbdt.scores)[0]))
bench_s = time.time() - t0
score = bst._gbdt._train_score_np()
m = AUCMetric(bst.config); m.init(ds._binned.metadata, ds.num_data())
auc = float(m.eval(score, bst._gbdt.objective)[0][1])
# model_hash feeds the spec-vs-seq on-chip exactness check (ADVICE r5 #1):
# smoke and smoke_seq train the same data/seed under the two growers, so
# their model strings must match bit for bit — _check_spec_seq_match below
# compares the hashes once both stages have run
from lightgbm_tpu.models.model_text import model_fingerprint
# the same structured run-report block bench.py emits (obs/registry.py):
# phase seconds, jit trace counts, device-memory gauges — per stage
from lightgbm_tpu.obs import REGISTRY as _obs_registry
from lightgbm_tpu.obs import memwatch as _memwatch
bst._gbdt.timers.publish()
_memwatch.snapshot("post_smoke")
print(json.dumps({"ok": auc > 0.70, "first_iter_s": round(compile_s, 1),
                  "iters_per_sec": round(10 / bench_s, 3),
                  "train_auc_11_iters": round(auc, 5),
                  "model_hash": model_fingerprint(bst.model_to_string()),
                  "obs_report": _obs_registry.run_report(),
                  "platform": jax.default_backend()}))
""" % (REPO, REPO)


# sequential grower vs the (r5 default-on-TPU) speculative top-k batch
# grower: the 'smoke' stage runs spec, this one forces seq — their
# iters_per_sec ratio is the measured spec-batch win
SMOKE_SEQ = SMOKE.replace(
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"',
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"\n'
    'os.environ["LIGHTGBM_TPU_GROW"] = "seq"',
)
assert 'LIGHTGBM_TPU_GROW' in SMOKE_SEQ

# same 100k training smoke with the pallas radix histogram impl instead of
# the (r5 default) XLA one-hot: on-silicon r4 measurements had XLA at
# 16.8ms vs pallas v1's 34.8ms for a full-N pass; the feature-batched v2
# kernel is the unmeasured contender this stage races at the real workload
# (iters_per_sec side by side with the 'smoke' stage)
SMOKE_PALLAS = SMOKE.replace(
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"',
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"\n'
    'os.environ["LIGHTGBM_TPU_HIST_IMPL"] = "pallas"',
)

SMOKE_XLA_RADIX = SMOKE.replace(
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"',
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"\n'
    'os.environ["LIGHTGBM_TPU_HIST_IMPL"] = "xla_radix"',
)
assert "xla_radix" in SMOKE_XLA_RADIX
# .replace on an exact anchor: fail loudly if the anchor drifts, or this
# stage would silently re-measure the default impl under the variant label
assert "LIGHTGBM_TPU_HIST_IMPL" in SMOKE_PALLAS

# bf16 MXU operands (the reference GPU path's single-precision trade,
# GPU-Performance.rst:131-145): same smoke, records the AUC delta vs the
# f32 'smoke' stage — the judged bf16-vs-f32 number (VERDICT r3 item 1)
SMOKE_BF16 = SMOKE.replace(
    '"learning_rate": 0.1,',
    '"learning_rate": 0.1, "tpu_hist_dtype": "bfloat16",',
)
assert "bfloat16" in SMOKE_BF16

# single-launch Pallas split-scan kernel (ops/split_pallas.py, opt-in):
# first Mosaic lowering AND its per-split fixed-cost effect, measured at
# the same 100k workload
SMOKE_PSPLIT = SMOKE.replace(
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"',
    'os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"\n'
    'os.environ["LIGHTGBM_TPU_SPLIT_IMPL"] = "pallas"',
)
assert "SPLIT_IMPL" in SMOKE_PSPLIT


# Device-resident boosting sweep (ISSUE 2 tentpole): train the 1M Higgs
# shape with device_chunk_size in {1, 4, 16} — chunk>1 fuses that many
# boosting iterations into ONE jitted lax.scan dispatch (GBDT.train_chunk),
# removing the per-iteration host round-trip the r4 breakdown measured.
# Records per-iteration host-wall (dispatch) vs pipeline-closed total time
# so the dispatch gap is a first-class number; bench.py auto-adopts the
# winning chunk via the "winner_chunk" field, like the r5 grower bake-off.
BENCH_CHUNK = _COMMON + """
sys.path.insert(0, %r)
os.environ.setdefault("LIGHTGBM_TPU_LATTICE", "pow2")
import lightgbm_tpu as lgb

from bench import make_higgs_like

on_chip = jax.default_backend() in ("tpu", "axon")
# headline 1M Higgs shape on silicon; the CPU dress rehearsal shrinks to
# fit the stage timeout (its rates rehearse the mechanism only — platform
# tagging keeps them out of bench adoption, like every other stage)
N, LEAVES, ITERS = (1_000_000, 255, 16) if on_chip else (20_000, 31, 8)
X, y = make_higgs_like(N, 28)
ds = lgb.Dataset(X, label=y)
sweep = {}
best, best_rate = 1, -1.0
for c in (1, 4, 16):
    params = {"objective": "binary", "num_leaves": LEAVES, "max_bin": 255,
              "learning_rate": 0.1, "verbosity": -1, "device_chunk_size": c}
    bst = lgb.Booster(params=params, train_set=ds)

    def run(count):
        i = 0
        while i < count:
            if c > 1:
                done, _ = bst.update_chunk(min(c, count - i))
                i += max(done, 1)
            else:
                bst.update()
                i += 1

    # warmup compiles BOTH programs the measured loop will use: the
    # sequential first iteration, then one full c-sized chunk
    run(c + 1)
    _ = float(jnp.ravel(bst._gbdt.scores)[0])
    meas = max(ITERS // max(c, 1), 1) * max(c, 1)  # whole chunks only
    t0 = time.time()
    run(meas)
    host_wall_s = time.time() - t0   # time the HOST spent issuing the work
    _ = float(jnp.ravel(bst._gbdt.scores)[0])  # close the async pipeline
    total_s = time.time() - t0
    sweep[str(c)] = {
        "iters_per_sec": round(meas / total_s, 3),
        "host_wall_per_iter_s": round(host_wall_s / meas, 5),
        "total_per_iter_s": round(total_s / meas, 5),
        "device_gap_per_iter_s": round((total_s - host_wall_s) / meas, 5),
    }
    if meas / total_s > best_rate:
        best, best_rate = c, meas / total_s
print(json.dumps({"ok": len(sweep) == 3, "winner_chunk": best,
                  "sweep": sweep, "rows": N, "num_leaves": LEAVES,
                  "platform": jax.default_backend()}))
""" % REPO
assert "device_chunk_size" in BENCH_CHUNK


# Packed-inference serving bench (ISSUE 3 tentpole): train a model at the
# bench feature shape, compile it to a PackedEnsemble (serve/packed.py), and
# measure the two serving numbers that matter — fused-path throughput
# (rows/s at a big batch, single dispatch each) and bucket-cached dispatch
# latency (p50/p99 over mixed 200-1024-row batches AFTER warmup, when the
# shape-bucket cache guarantees zero retraces). bench.py records the same
# pair into the headline BENCH json; this stage is the on-chip capture.
BENCH_PREDICT = _COMMON + """
sys.path.insert(0, %r)
os.environ.setdefault("LIGHTGBM_TPU_LATTICE", "pow2")
import lightgbm_tpu as lgb
from lightgbm_tpu.serve.cache import BucketedDispatcher

from bench import make_higgs_like

on_chip = jax.default_backend() in ("tpu", "axon")
N, ITERS, LEAVES = (1_000_000, 16, 255) if on_chip else (20_000, 6, 31)
X, y = make_higgs_like(N, 28)
params = {"objective": "binary", "num_leaves": LEAVES, "max_bin": 255,
          "learning_rate": 0.1, "verbosity": -1}
bst = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
for _ in range(ITERS):
    bst.update()
pk = bst.to_packed()

# throughput: fused (bin+traverse+sum on device) at a big resident batch
BIG = min(N, 1 << 17)
xd = jax.device_put(jnp.asarray(X[:BIG].astype(np.float32)))
out = pk.fused_scores(xd)
_ = float(jnp.ravel(out)[0])  # compile + close the pipeline
reps = 8
t0 = time.time()
for _ in range(reps):
    out = pk.fused_scores(xd)
_ = float(jnp.ravel(out)[0])
rows_per_sec = BIG * reps / (time.time() - t0)

# latency: mixed-size batches through the pow2 bucket cache; warm the three
# buckets first so the measured loop is the steady state (zero retraces)
disp = BucketedDispatcher(
    lambda x: np.asarray(pk.fused_scores(jnp.asarray(x))), min_rows=256)
for b in (256, 512, 1024):
    disp(X[:b].astype(np.float32))
warm_traces = disp.retraces
lat = []
lrng = np.random.RandomState(0)
for _ in range(60):
    n = int(lrng.randint(200, 1025))
    t1 = time.time()
    disp(X[:n].astype(np.float32))
    lat.append(time.time() - t1)
lat.sort()
print(json.dumps({
    "ok": rows_per_sec > 0 and disp.retraces == warm_traces,
    "rows_per_sec": round(rows_per_sec, 1),
    "throughput_batch_rows": BIG,
    "predict_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
    "predict_p99_ms": round(lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3, 3),
    "retraces_after_warmup": disp.retraces - warm_traces,
    "num_trees": pk.num_trees,
    "platform": jax.default_backend()}))
""" % REPO
assert "fused_scores" in BENCH_PREDICT


# Kernel-level performance attribution (ISSUE 6): run tree growth as
# separately-dispatched fenced sub-steps (obs/prof.py) at a training smoke
# shape, record the growth_segments_s breakdown + the measured cost-analysis
# book, and prove the segmented model bitwise-identical to the fused
# grower's ON SILICON — the instrument that makes the Pallas-kernel work
# (ROADMAP item 2) measurable before and after.
PROF = _COMMON + """
sys.path.insert(0, %r)
os.environ["LIGHTGBM_TPU_LATTICE"] = "pow2"   # cap first-contact compile cost
os.environ["LIGHTGBM_TPU_COSTS"] = "1"
import lightgbm_tpu as lgb
from lightgbm_tpu.obs import costs as costs_mod
from lightgbm_tpu.obs import prof as prof_mod

from bench import make_higgs_like

on_chip = jax.default_backend() in ("tpu", "axon")
N, LEAVES = (100_000, 255) if on_chip else (20_000, 63)
X, y = make_higgs_like(N, 28)
params = {"objective": "binary", "num_leaves": LEAVES, "max_bin": 255,
          "learning_rate": 0.1, "verbosity": -1}
bst = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
bst.update()  # one real iteration so gradients are post-root state
reason = prof_mod.unsupported_reason(bst._gbdt)
if reason is not None:
    print(json.dumps({"ok": False, "error": "unsupported: " + reason,
                      "platform": jax.default_backend()}))
    sys.exit(0)
rec = prof_mod.profile_growth(bst, iters=2)
segs = rec["segments_per_tree_s"]
structure_ok = all(
    k in segs for k in
    ("partition", "hist_build", "hist_subtract", "split_scan", "leaf_update"))
print(json.dumps({
    "ok": bool(rec["bitwise_identical"]) and structure_ok,
    "platform": jax.default_backend(),
    "rows": rec["rows"], "num_leaves": rec["num_leaves"],
    "grow_mode": rec["grow_mode"],
    "growth_segments_s": segs,
    "segment_sum_ratio": rec["segment_sum_ratio"],
    "fused_growth_s_per_tree": rec["fused_growth_s_per_tree"],
    "bitwise_identical": rec["bitwise_identical"],
    "cost_analysis": costs_mod.COSTS.report()}))
""" % REPO
assert "profile_growth" in PROF and "bitwise_identical" in PROF


def _render_report(summary: dict) -> str:
    """Render the round's HTML run report (obs/report.py by file path):
    the BENCH_r*.json series plus this round's bench record; returns the
    output path (recorded in the summary)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lgbtpu_obs_report",
        os.path.join(REPO, "lightgbm_tpu", "obs", "report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bench_records = mod.load_bench_records(
        os.path.join(REPO, "BENCH_r*.json")
    ) + mod.load_bench_records(
        # multichip scaling records chart in their own section
        os.path.join(REPO, "MULTICHIP_r*.json")
    )
    bench = (summary.get("stages") or {}).get("bench") or {}
    # the bench stage result IS the parsed bench record (run_bench); its
    # obs_report block is what render() unwraps for the metrics sections
    metrics = bench if "metric" in bench else None
    html = mod.render(
        metrics=metrics, bench_records=bench_records,
        title="TPU bringup report (%s)" % summary.get("t", ""),
    )
    out = SUMMARY.replace(".json", "_report.html")
    with open(out, "w", encoding="utf-8") as fh:
        fh.write(html)
    return out


def _load_bench_diff():
    """helpers/bench_diff.py by FILE path (stdlib-only module), keeping this
    driver jax-free — same pattern as _load_backoff."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lgbtpu_bench_diff", os.path.join(REPO, "helpers", "bench_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_diff_verdict(prev: dict, result: dict) -> dict:
    """Regression verdict of this round's bench vs the previous on-chip
    record (helpers/bench_diff.py thresholds). Recorded in the summary —
    every bringup round carries its own regression verdict; never fatal to
    the bringup itself."""
    if not prev or "metric" not in result:
        return {"status": "SKIP", "note": "no prior record or no result"}
    try:
        bd = _load_bench_diff()
        current = {k: v for k, v in result.items()
                   if k not in ("ok", "wall_s", "attempts")}
        rows, failed = bd.compare(current, prev)
        return {
            "status": "FAIL" if failed else "PASS",
            "baseline_t": prev.get("t") or prev.get("recorded_at"),
            "rows": rows,
        }
    except Exception as e:
        return {"status": "ERROR", "note": "%s: %s" % (type(e).__name__, e)}


def _check_spec_seq_match(summary: dict) -> None:
    """ADVICE r5 #1: the smoke/smoke_seq pair trains the same data and seed
    under the spec and sequential growers — their model strings must agree
    bit for bit. On TPU the flat batched histogram's f32 regrouping COULD
    silently diverge (the exactness claim is only CPU-verified); comparing
    the two stages' model hashes turns that into a loud bringup failure
    instead of a silently-wrong exactness guarantee."""
    stages = summary.get("stages", {})
    ha = stages.get("smoke", {}).get("model_hash")
    hb = stages.get("smoke_seq", {}).get("model_hash")
    if not ha or not hb:
        return  # a stage failed before hashing; its own ok=False tells why
    summary["spec_seq_model_match"] = ha == hb
    if ha != hb:
        stages["smoke_seq"]["ok"] = False
        stages["smoke_seq"]["error"] = (
            "spec-vs-seq model divergence: grower model hashes differ on "
            "this backend (f32 histogram regrouping? see ADVICE.md #1)"
        )


def log_line(stage: str, payload: dict) -> None:
    with open(LOG, "a") as f:
        f.write(json.dumps({"t": time.strftime("%Y-%m-%dT%H:%M:%S"),
                            "stage": stage, **payload}) + "\n")


def _parse_result(out: str):
    """Last parseable JSON line of stdout, or None. Scans from the end so a
    stray brace-initial log line (e.g. a printed dict repr) can't shadow the
    real result; invalid candidates are skipped, not fatal — during the
    scarce TPU window this script must never die on a parse error."""
    for l in reversed(out.splitlines()):
        if l.startswith("{"):
            try:
                return json.loads(l)
            except ValueError:
                continue
    return None


def _trace_path() -> str:
    return os.environ.get("LIGHTGBM_TPU_TRACE", "")


def _stage_span(stage: str):
    """Driver-side obs span per bringup stage (no-op without
    LIGHTGBM_TPU_TRACE; the import stays conditional so the driver process
    never pulls jax in on the no-trace path)."""
    if not _trace_path():
        import contextlib

        return contextlib.nullcontext()
    from lightgbm_tpu.obs import trace as trace_mod

    return trace_mod.span("bringup.%s" % stage, cat="bringup")


def _run_child(stage: str, argv, env=None) -> dict:
    t0 = time.time()
    if _trace_path():
        # one trace file per PROCESS: a child inheriting the driver's path
        # would clobber it at exit — each stage writes <path>.stage_<name>
        env = dict(os.environ if env is None else env)
        env["LIGHTGBM_TPU_TRACE"] = "%s.stage_%s" % (_trace_path(), stage)
    proc = subprocess.Popen(
        argv, cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = proc.communicate(timeout=STAGE_TIMEOUTS[stage])
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        # drain what the wedged stage managed to print before the kill — the
        # only clue to where first contact stalled (TimeoutExpired itself
        # carries output=None for Popen.communicate)
        try:
            out, err = proc.communicate(timeout=5)
        except Exception:
            out, err = "", ""
        result = {"ok": False, "error": "timeout after %ds" % STAGE_TIMEOUTS[stage],
                  "stdout_tail": out.strip()[-800:], "stderr_tail": err.strip()[-800:]}
        result["wall_s"] = round(time.time() - t0, 1)
        log_line(stage, result)
        return result
    result = _parse_result(out)
    if proc.returncode != 0 or result is None:
        result = {"ok": False, "error": "rc=%s" % proc.returncode,
                  "stderr_tail": err.strip()[-800:]}
        if proc.returncode == _preempt_exit_code():
            # the child was SIGTERMed mid-train and published an emergency
            # checkpoint before exiting (resil/preempt.py): re-running the
            # stage RESUMES it — run_with_retry treats this as transient
            result["preempted"] = True
            result["error"] = "preempted (rc=%s)" % proc.returncode
    result["wall_s"] = round(time.time() - t0, 1)
    log_line(stage, result)
    return result


def run_stage(stage: str, src: str) -> dict:
    return _run_child(stage, [sys.executable, "-c", src])


def _is_transient(result: dict) -> bool:
    """Only two shapes are worth retrying: a timeout-KILLED child (hung
    tunnel / wedged TPU client, the failure this retry exists for), and a
    PREEMPTED child (exit code 75: it published an emergency checkpoint on
    SIGTERM, so the re-run resumes the stage instead of restarting it —
    docs/FaultTolerance.md §Elastic training). A child that ran to
    completion and failed (other nonzero rc, in-child assertion) is
    deterministic — re-running it just doubles time-to-red on real TPU
    time without new information."""
    if result.get("preempted"):
        return True
    return str(result.get("error", "")).startswith("timeout")


def run_with_retry(stage: str, fn) -> dict:
    """Run a stage up to 1 + STAGE_RETRIES times, sleeping the exponential
    backoff schedule between attempts; only transient failures (timeout
    kills) retry. Every attempt is logged; the returned result carries
    ``attempts`` so the summary records how many shots a flaky tunnel
    needed."""
    attempts = 1 + max(STAGE_RETRIES, 0)
    schedule = _load_backoff().delays(
        attempts, base_s=STAGE_BACKOFF_S, factor=2.0, max_s=600.0
    )
    result = {"ok": False, "error": "stage never ran"}
    for attempt in range(1, attempts + 1):
        result = fn()
        result["attempts"] = attempt
        if result.get("ok") or not _is_transient(result):
            return result
        if attempt < attempts:
            delay = next(schedule)
            log_line(stage, {"retry_after_attempt": attempt,
                             "backoff_s": delay})
            print(
                "bringup: stage %s %s (attempt %d/%d); %s in %.0fs"
                % (stage, "preempted" if result.get("preempted") else "failed",
                   attempt, attempts,
                   "resuming from its emergency checkpoint"
                   if result.get("preempted") else "retrying", delay),
                flush=True,
            )
            time.sleep(delay)
    return result


def run_san(stage: str = "san") -> dict:
    """graftsan concurrency stress smoke (helpers/san_smoke.py, ISSUE 11) —
    executed by FILE path in a child process with the full sanitizer armed
    (the child sets LIGHTGBM_TPU_SAN itself), so the driver stays jax-free
    and the instrumented locks/guards live only in the child. On silicon
    this is the proof the serve stack's lock discipline and explicit-upload
    contract hold on the real backend, not just the CPU CI box."""
    return _run_child(
        stage, [sys.executable, os.path.join(REPO, "helpers", "san_smoke.py")]
    )


def run_loop(stage: str = "loop") -> dict:
    """Continuous-training closed-loop smoke (helpers/loop_smoke.py,
    ISSUE 12) — executed by FILE path in a child process (the child arms
    its own sanitizer env), so the driver stays jax-free. On silicon this
    proves the drift -> warm-start retrain -> gate -> atomic publish ->
    hot-swap cycle, and its mid-publish SIGKILL recovery, hold on the real
    backend, not just the CPU CI box."""
    return _run_child(
        stage, [sys.executable, os.path.join(REPO, "helpers", "loop_smoke.py")]
    )


def run_elastic(stage: str = "elastic") -> dict:
    """Elastic preemption-tolerance smoke (helpers/elastic_smoke.py,
    ISSUE 15) — executed by FILE path in a child process, driver stays
    jax-free. The child drives the full chain at forced-8-CPU-device
    shapes: SIGKILL mid-run -> same-mesh resume byte-identical, SIGTERM ->
    emergency checkpoint + exit 75 -> auto-resume byte-identical, plus the
    8->2 reshard (structural identity, exact carries, loud warning). On
    silicon this is the evidence a preempted pod run costs a boundary, not
    the run."""
    return _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "elastic_smoke.py")],
    )


def run_flex(stage: str = "flex") -> dict:
    """Flexctl chaos smoke (helpers/flex_smoke.py, ISSUE 20) — executed by
    FILE path in a child process, driver stays jax-free. The child scripts
    a capacity storm over forced-multi-CPU trainer children: a planned
    shrink drains at a chunk boundary and exits with the reshard code, the
    grow-back drains again, a SIGKILLed launch restarts at the same world,
    and the final model matches the uninterrupted reference per the
    exactness taxonomy (docs/FaultTolerance.md §Fleet orchestrator). On
    silicon this is the evidence a capacity change costs one boundary
    drain, not the run."""
    return _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "flex_smoke.py")],
    )


def run_podwatch(stage: str = "podwatch") -> dict:
    """Fleet-telemetry smoke (helpers/podwatch_smoke.py, ISSUE 19) —
    executed by FILE path in a child process, driver stays jax-free. The
    child runs a real 2-process training world with the telemetry ring +
    scrape endpoint armed and rank 1 seeded slow, scrapes /metrics +
    /health + /timeline live mid-run, aggregates the shards and requires
    the straggler verdict to name the seeded rank — plus the telemetry-off
    byte-identity of the trained model. On silicon this is the proof a pod
    can be watched (and a sick rank named) while the chips are busy."""
    return _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "podwatch_smoke.py")],
    )


def run_devprof(stage: str = "devprof") -> dict:
    """Device-timeline audit smoke (helpers/devprof_smoke.py, ISSUE 14) —
    executed by FILE path in a child process, driver stays jax-free. The
    child captures a scoped jax.profiler window around real boosting
    iterations, parses the emitted Chrome trace with the stdlib devprof
    parser, and emits the host/device/transfer-bound verdict — so the
    next unattended chip window ships the DIAGNOSIS (why TPU <> CPU)
    alongside the bench numbers, recorded into TPU_BRINGUP.json."""
    return _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "devprof_smoke.py")],
    )


def run_irscan(stage: str = "irscan") -> dict:
    """graftir program-audit smoke (helpers/irscan_smoke.py, ISSUE 16) —
    executed by FILE path in a child process, driver stays jax-free. The
    child proves each seeded IR001-IR006 violation is caught, then traces
    every registered jit entry point abstractly (no program executes) and
    checks the real tree against the findings baseline and the checked-in
    program-fingerprint contract — so a hot-path program that drifted
    (dropped donation, stripped FMA pin, f64 leak, baked constant, rogue
    collective axis) fails HERE, before bench_early spends chip time
    compiling and running it. On a TPU env the contract check self-reports
    as skipped (fingerprints are pinned per environment) while the rules
    and seeded checks still gate."""
    return _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "irscan_smoke.py")],
    )


def run_tune(stage: str = "tune") -> dict:
    """Histogram autotune sweep (obs/tune.py, ISSUE 13) — a child process
    (`python -m lightgbm_tpu.obs.tune`, driver stays jax-free) races every
    supported histogram impl (the full IMPLS vocabulary — xla family,
    scatter, and the Pallas kernels incl. the ISSUE 17 wide-bin
    pallas_onehot / pallas_bitplane — gated by impl_supported + the chip's
    CHIP_PEAKS vmem_bytes; new impls enter with zero wiring here) at the
    bucket-shape distribution the grower emits for the
    1M bench geometry, and atomically persists TUNE_HIST.json. Running it
    BEFORE bench_early means the very next bench worker — and every
    training that adopts LIGHTGBM_TPU_HIST_TUNE — routes each shape class
    to its measured winner unattended (docs/HistogramRouting.md)."""
    env = dict(os.environ)
    out = os.path.join(REPO, "TUNE_HIST.json")
    if _REHEARSAL:
        # a CPU rehearsal must never publish the production tune cache:
        # bench.py auto-adopts TUNE_HIST.json, and although a CPU-backend
        # table self-filters on chip (resolve_route), it WOULD route the
        # relay-down CPU-fallback benches — same isolation rule as the
        # rehearsal summary file
        env["JAX_PLATFORMS"] = "cpu"
        out = os.path.join(REPO, "TUNE_HIST_REHEARSAL.json")
    result = _run_child(
        stage,
        [sys.executable, "-m", "lightgbm_tpu.obs.tune",
         "--out", out,
         # trained histogram widths are num_bin <= max_bin (binning.py), so
         # the route keys the grower actually emits at the bench geometry
         # are 255 (max_bin=255), 63, and 15 (packed4 territory, B<=16) —
         # NOT the round powers of two, which would never match a call
         "--rows", "1048576", "--bins", "15,63,255", "--features", "28",
         "--dtypes", "float32,bfloat16", "--repeats", "3"],
        env=env,
    )
    result.setdefault("ok", bool(result.get("digest")))
    return result


def run_bench(stage: str = "bench") -> dict:
    env = dict(os.environ)
    env.pop("BENCH_FORCE_PLATFORMS", None)
    if _REHEARSAL:
        # pin the bench to CPU outright: probing the axon backend would
        # burn ~20 min against a dead relay — or run the scarce REAL chip
        # from inside a rehearsal if the relay happens to be up
        env["BENCH_FORCE_PLATFORMS"] = "cpu"
        env.setdefault("BENCH_PROBE_TIMEOUT_S", "60")
    env["BENCH_TIMEOUT_S"] = str(STAGE_TIMEOUTS[stage] - 120)
    result = _run_child(stage, [sys.executable, os.path.join(REPO, "bench.py")], env=env)
    result.setdefault("ok", result.get("value", 0) > 0)
    if "metric" in result and result.get("platform") in ("tpu", "axon"):
        # platform-guarded: a half-up tunnel can make bench fall back to
        # CPU, and a CPU record must never overwrite the on-chip evidence
        # (BENCH_TPU.json is the durable proof a chip run ever happened)
        with open(os.path.join(REPO, "BENCH_TPU.json"), "w") as f:
            json.dump({"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                       **{k: v for k, v in result.items() if k not in ("ok", "wall_s")}}, f)
            f.write("\n")
    return result


def run_multichip(stage: str = "bench_multichip") -> dict:
    """Device-count scaling sweep (helpers/multichip_bench.py --sweep):
    tree_learner=data + device_chunk_size over devices∈{1,4,8} — the
    ISSUE-8 scaling-curve evidence. On success the summary record (it
    carries a "metric" key, the load_bench_records adoption shape) is also
    written as the next MULTICHIP_r*.json so the HTML run report charts
    the scaling series next to BENCH_r*."""
    env = dict(os.environ)
    if _REHEARSAL:
        env["JAX_PLATFORMS"] = "cpu"
    result = _run_child(
        stage,
        [sys.executable, os.path.join(REPO, "helpers", "multichip_bench.py"),
         "--sweep", "1,4,8"],
        env=env,
    )
    result.setdefault("ok", bool(result.get("scaling")))
    # fold the sweep workers' per-device-count traces (multichip_bench
    # appends .dev<D> to the stage's trace path) into ONE Perfetto timeline
    # with disjoint pids — obs/trace.py's merge, imported by file path so
    # the driver stays jax-free
    base_trace = os.environ.get("LIGHTGBM_TPU_TRACE")
    if base_trace:
        import glob as glob_mod
        import importlib.util

        child_traces = sorted(
            glob_mod.glob("%s.stage_%s.dev*" % (base_trace, stage))
        )
        if child_traces:
            try:
                spec = importlib.util.spec_from_file_location(
                    "lgbtpu_obs_trace",
                    os.path.join(REPO, "lightgbm_tpu", "obs", "trace.py"),
                )
                tmod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(tmod)
                merged = "%s.stage_%s.merged.json" % (base_trace, stage)
                stats = tmod.merge_traces(merged, child_traces)
                result["merged_trace"] = merged
                result["merged_trace_pids"] = stats["pids"]
            except Exception as e:
                result["merged_trace_error"] = repr(e)[:200]
    if result.get("ok") and "metric" in result:
        import glob
        import re

        # next index past the HIGHEST existing round (a count would renumber
        # into a gap and overwrite evidence after any cleanup)
        taken = [
            int(m.group(1))
            for p in glob.glob(os.path.join(REPO, "MULTICHIP_r*.json"))
            if (m := re.search(r"MULTICHIP_r(\d+)\.json$", p))
        ]
        path = os.path.join(
            REPO, "MULTICHIP_r%02d.json" % (max(taken, default=0) + 1)
        )
        with open(path, "w") as f:
            json.dump(
                {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 **{k: v for k, v in result.items()
                    if k not in ("ok", "wall_s", "attempts")}}, f)
            f.write("\n")
        result["record_path"] = os.path.basename(path)
    return result


def _dump(summary) -> None:
    """Persist after EVERY stage: the relay dies unpredictably, and a
    partial summary still feeds bench.py's bake-off auto-adoption."""
    with open(SUMMARY, "w") as f:
        json.dump(summary, f, indent=1)


def main() -> int:
    # ordered by decision value per minute of chip time: the spec-vs-seq
    # grower race and the histogram routing race feed bench auto-adoption;
    # pack4 is a shelved-accelerator measurement and goes last
    summary = {"t": time.strftime("%Y-%m-%dT%H:%M:%S"), "stages": {},
               "verdict": "in progress"}
    # the previous on-chip record, captured BEFORE run_bench can overwrite
    # it — this round's regression verdict diffs against it (bench_diff)
    try:
        with open(os.path.join(REPO, "BENCH_TPU.json")) as f:
            prev_bench = json.load(f)
    except Exception:
        prev_bench = None
    for stage, src in (("matmul", MATMUL), ("pallas", PALLAS),
                       ("smoke", SMOKE),
                       ("smoke_seq", SMOKE_SEQ),
                       # histogram autotune BEFORE the headline: the sweep
                       # persists TUNE_HIST.json, so bench_early (and every
                       # later training) already routes each bucket shape
                       # to its measured winner (obs/tune.py, ISSUE 13)
                       ("tune", "TUNE"),
                       # program-level audit BEFORE any bench: the traced
                       # entry points (incl. the tune-routed histogram
                       # impls) are linted at the jaxpr/StableHLO level —
                       # a drifted program fails in seconds here instead
                       # of poisoning an hour of bench wall-clock
                       # (obs/irscan.py, ISSUE 16)
                       ("irscan", "IRSCAN"),
                       # headline FIRST after routing is measured: the
                       # relay has died mid-bringup in three of four
                       # rounds; with smoke+smoke_seq in the summary the
                       # bench already auto-adopts the better grower, so
                       # the 1M number is secured before the measurement
                       # tail (the final bench re-runs with the full
                       # bake-off and overwrites)
                       ("bench_early", None),
                       ("smoke_pallas", SMOKE_PALLAS),
                       ("smoke_bf16", SMOKE_BF16),
                       ("smoke_xla_radix", SMOKE_XLA_RADIX),
                       ("smoke_psplit", SMOKE_PSPLIT),
                       # chunked-boosting sweep before pack4: it feeds the
                       # final bench's device_chunk_size auto-adoption
                       ("bench_chunk", BENCH_CHUNK),
                       # data-parallel sharded-chunk scaling curve
                       # (ISSUE 8): its own worker sweep, not a _COMMON src
                       ("bench_multichip", "MULTICHIP"),
                       # serving throughput/latency capture (ISSUE 3)
                       ("bench_predict", BENCH_PREDICT),
                       # kernel-level attribution: segment breakdown +
                       # bitwise proof + cost analysis, on silicon (ISSUE 6)
                       ("prof", PROF),
                       # device-timeline audit: profiled capture -> parsed
                       # lanes -> host/device/transfer-bound verdict with
                       # evidence, from the REAL chip (ISSUE 14)
                       ("devprof", "DEVPROF"),
                       # runtime sanitizer stress smoke: concurrent
                       # predict + hot-swap + drain + drift + scrape under
                       # LIGHTGBM_TPU_SAN=transfer,nan,locks (ISSUE 11)
                       ("san", "SAN"),
                       # closed-loop continuous training: drift-triggered
                       # warm-start retrain -> gate -> publish -> swap with
                       # SIGKILL recovery on the real stack (ISSUE 12)
                       ("loop", "LOOP"),
                       # elastic preemption tolerance: SIGKILL/SIGTERM ->
                       # resume byte-identity + reshard chain (ISSUE 15)
                       ("elastic", "ELASTIC"),
                       # fleet telemetry: live mid-run scrape + aggregated
                       # straggler verdict on a real 2-process world
                       # (ISSUE 19)
                       ("podwatch", "PODWATCH"),
                       # elastic fleet orchestration: scripted capacity
                       # storm (shrink/grow drains + mid-chunk SIGKILL)
                       # supervised by flexctl (ISSUE 20)
                       ("flex", "FLEX"),
                       ("pack4", PACK4)):
        print("bringup: stage %s ..." % stage, flush=True)
        with _stage_span(stage):
            if src == "MULTICHIP":
                runner = lambda s=stage: run_multichip(s)  # noqa: E731
            elif src == "TUNE":
                runner = lambda s=stage: run_tune(s)  # noqa: E731
            elif src == "SAN":
                runner = lambda s=stage: run_san(s)  # noqa: E731
            elif src == "IRSCAN":
                runner = lambda s=stage: run_irscan(s)  # noqa: E731
            elif src == "DEVPROF":
                runner = lambda s=stage: run_devprof(s)  # noqa: E731
            elif src == "LOOP":
                runner = lambda s=stage: run_loop(s)  # noqa: E731
            elif src == "ELASTIC":
                runner = lambda s=stage: run_elastic(s)  # noqa: E731
            elif src == "PODWATCH":
                runner = lambda s=stage: run_podwatch(s)  # noqa: E731
            elif src == "FLEX":
                runner = lambda s=stage: run_flex(s)  # noqa: E731
            elif src is None:
                runner = lambda s=stage: run_bench(s)  # noqa: E731
            else:
                runner = lambda s=stage, c=src: run_stage(s, c)  # noqa: E731
            result = run_with_retry(stage, runner)
        summary["stages"][stage] = result
        if stage == "smoke_seq":
            _check_spec_seq_match(summary)
        _dump(summary)
        print("bringup: %s -> %s" % (stage, json.dumps(result)), flush=True)
        if not result.get("ok"):
            # matmul failing = relay gone again; pallas failing = still worth
            # running the smokes + bench (auto-adoption just won't pick the
            # kernel, and bench.py retries with LIGHTGBM_TPU_HIST_IMPL=xla
            # on TPU worker failure by itself)
            if stage == "matmul":
                summary["verdict"] = "relay dead at stage %s" % stage
                _dump(summary)
                return 1
    print("bringup: stage bench ...", flush=True)
    with _stage_span("bench"):
        summary["stages"]["bench"] = run_with_retry("bench", run_bench)
    ok = summary["stages"]["bench"].get("ok", False)
    # regression verdict vs the previous on-chip record: every bringup
    # round records where the perf trajectory moved (helpers/bench_diff.py)
    summary["bench_diff"] = _bench_diff_verdict(
        prev_bench, summary["stages"]["bench"]
    )
    print("bringup: bench_diff -> %s" % summary["bench_diff"].get("status"),
          flush=True)
    summary["verdict"] = "ok" if ok else "bench failed"
    # self-contained HTML run report next to the summary (obs/report.py,
    # loaded by FILE path — stdlib-only, the driver stays jax-free): the
    # BENCH_r* series + this round's obs_report render into the one
    # artifact a bringup round attaches for humans
    try:
        summary["report_html"] = _render_report(summary)
    except Exception as e:  # the report must never fail the round
        print("bringup: report render failed: %r" % (e,), flush=True)
    _dump(summary)
    if _trace_path():
        from lightgbm_tpu.obs import trace as trace_mod

        trace_mod.stop()  # write the driver's stage-span timeline
    print("bringup: done -> %s" % json.dumps(summary["stages"]["bench"]), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
