"""Observability smoke: traced mini-train + serve, then validate the outputs.

What `helpers/check.sh --obs` runs. In-process, on CPU:

  1. trains a tiny booster with ``LIGHTGBM_TPU_TRACE`` pointed at a temp
     file, runs one packed-serving predict through a ServeApp, and stops
     the tracer;
  2. validates the emitted Chrome-trace JSON structurally — pid/tid/ph/ts
     on every event, >= 3 distinct training-phase spans, >= 1 serve request
     span, and phase spans time-nested inside an iteration span;
  3. validates the Prometheus exposition: parses every sample line, and
     requires latency quantiles, qps, the jit-trace gauges and the
     device-memory gauge to be present;
  4. checks memwatch shape math against the actual donated hist buffer.

Exit 0 on success with an OK line; any failure raises (nonzero exit).
"""
from __future__ import annotations

import json
import os
import re
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE\+\-\.]+$"
)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    trace_path = os.path.join(tempfile.mkdtemp(prefix="lgbtpu_obs_"), "trace.json")
    os.environ["LIGHTGBM_TPU_TRACE"] = trace_path

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import memwatch, trace
    from lightgbm_tpu.serve.server import ServeApp

    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=3,
    )

    model_path = os.path.join(os.path.dirname(trace_path), "m.txt")
    bst.save_model(model_path)
    app = ServeApp(max_delay_ms=1.0, min_bucket_rows=8)
    app.registry.load("m", model_path)
    out, _ = app.predict(rng.randn(5, 4))
    assert out.shape[0] == 5

    # --- trace structure ---------------------------------------------------
    path = trace.stop()
    assert path == trace_path, (path, trace_path)
    doc = json.load(open(path))
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert events, "no complete events in the trace"
    for e in events:
        for field in ("pid", "tid", "ph", "ts", "dur", "name"):
            assert field in e, (field, e)
    names = {e["name"] for e in events}
    phases = names & {
        "boosting(grad)", "bagging", "tree growth", "renew+score update",
    }
    assert len(phases) >= 3, "phase spans missing: %s" % sorted(names)
    assert "train.iteration" in names
    assert "serve.request" in names, sorted(names)
    # nesting: some phase span lies inside an iteration span on one thread
    iters = [e for e in events if e["name"] == "train.iteration"]
    nested = any(
        it["ts"] <= e["ts"] and e["ts"] + e["dur"] <= it["ts"] + it["dur"]
        and e["tid"] == it["tid"]
        for it in iters
        for e in events
        if e["name"] in phases
    )
    assert nested, "no phase span nests inside an iteration span"

    # --- prometheus exposition --------------------------------------------
    text = app.prometheus_metrics()
    app.close()
    for line in text.strip().splitlines():
        if line.startswith("#") or not line:
            continue
        assert _PROM_LINE.match(line), "bad exposition line: %r" % line
    for needle in (
        'lgbtpu_request_latency_seconds{quantile="0.5"}',
        "lgbtpu_qps",
        "lgbtpu_jit_traces_total",
        "lgbtpu_device_peak_bytes",
        "lgbtpu_requests_total",
    ):
        assert needle in text, "missing %r in /metrics exposition" % needle

    # --- memwatch shape math ----------------------------------------------
    attr = memwatch.attribute_training(bst._gbdt)
    hist = bst._gbdt._hist_buf
    assert hist is not None and attr["hist_carry"]["bytes"] == hist.nbytes

    print("obs smoke OK: %d trace events, phases=%s" % (
        len(events), sorted(phases),
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
