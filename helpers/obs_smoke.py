"""Observability smoke: traced mini-train + serve, then validate the outputs.

What `helpers/check.sh --obs` runs. In-process, on CPU:

  1. trains a tiny booster with ``LIGHTGBM_TPU_TRACE`` pointed at a temp
     file, runs one packed-serving predict through a ServeApp, and stops
     the tracer;
  2. validates the emitted Chrome-trace JSON structurally — pid/tid/ph/ts
     on every event, >= 3 distinct training-phase spans, >= 1 serve request
     span, and phase spans time-nested inside an iteration span;
  3. validates the Prometheus exposition: parses every sample line, and
     requires latency quantiles, qps, the jit-trace gauges and the
     device-memory gauge to be present;
  4. checks memwatch shape math against the actual donated hist buffer.

``--prof`` (what `helpers/check.sh --prof` runs) instead validates the
performance-attribution tier: a segment-profiled mini-train whose breakdown
must carry every core segment, whose segmented model must be BITWISE
identical to the fused grower's, whose run_report must carry the
``growth_segments_s`` + ``cost_analysis`` sections, and whose cost-analysis
byte counts must agree with memwatch's shape math for the same tensors.

``--drift`` (what `helpers/check.sh --drift` runs) validates the MODEL/data
observability tier (docs/Observability.md §Model & data observability):
a flight-recorded train whose JSONL schema must parse (manifest + one
record per boundary + one per tree), a drift-monitored serve where
covariate-shifted traffic must drive PSI above threshold (alert counter
fires) while in-distribution traffic stays below, and an HTML run report
that must render non-empty with learning-curve/importance SVG charts.

Exit 0 on success with an OK line; any failure raises (nonzero exit).
"""
from __future__ import annotations

import json
import os
import re
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE\+\-\.]+$"
)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    trace_path = os.path.join(tempfile.mkdtemp(prefix="lgbtpu_obs_"), "trace.json")
    os.environ["LIGHTGBM_TPU_TRACE"] = trace_path

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import memwatch, trace
    from lightgbm_tpu.serve.server import ServeApp

    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=3,
    )

    model_path = os.path.join(os.path.dirname(trace_path), "m.txt")
    bst.save_model(model_path)
    app = ServeApp(max_delay_ms=1.0, min_bucket_rows=8)
    app.registry.load("m", model_path)
    out, _ = app.predict(rng.randn(5, 4))
    assert out.shape[0] == 5

    # --- trace structure ---------------------------------------------------
    path = trace.stop()
    assert path == trace_path, (path, trace_path)
    doc = json.load(open(path))
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert events, "no complete events in the trace"
    for e in events:
        for field in ("pid", "tid", "ph", "ts", "dur", "name"):
            assert field in e, (field, e)
    names = {e["name"] for e in events}
    phases = names & {
        "boosting(grad)", "bagging", "tree growth", "renew+score update",
    }
    assert len(phases) >= 3, "phase spans missing: %s" % sorted(names)
    assert "train.iteration" in names
    assert "serve.request" in names, sorted(names)
    # nesting: some phase span lies inside an iteration span on one thread
    iters = [e for e in events if e["name"] == "train.iteration"]
    nested = any(
        it["ts"] <= e["ts"] and e["ts"] + e["dur"] <= it["ts"] + it["dur"]
        and e["tid"] == it["tid"]
        for it in iters
        for e in events
        if e["name"] in phases
    )
    assert nested, "no phase span nests inside an iteration span"

    # --- prometheus exposition --------------------------------------------
    text = app.prometheus_metrics()
    app.close()
    for line in text.strip().splitlines():
        if line.startswith("#") or not line:
            continue
        assert _PROM_LINE.match(line), "bad exposition line: %r" % line
    for needle in (
        'lgbtpu_request_latency_seconds{quantile="0.5"}',
        "lgbtpu_qps",
        "lgbtpu_jit_traces_total",
        "lgbtpu_device_peak_bytes",
        "lgbtpu_requests_total",
    ):
        assert needle in text, "missing %r in /metrics exposition" % needle

    # --- memwatch shape math ----------------------------------------------
    attr = memwatch.attribute_training(bst._gbdt)
    hist = bst._gbdt._hist_buf
    assert hist is not None and attr["hist_carry"]["bytes"] == hist.nbytes

    print("obs smoke OK: %d trace events, phases=%s" % (
        len(events), sorted(phases),
    ))
    return 0


def prof_main() -> int:
    """Segment-profiler smoke (check.sh --prof): breakdown structure,
    fused-vs-segmented bitwise identity, report sections, cost-analysis
    bytes vs memwatch shape math."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["LIGHTGBM_TPU_COSTS"] = "1"

    import jax.numpy as jnp
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import REGISTRY, memwatch
    from lightgbm_tpu.obs import costs as costs_mod
    from lightgbm_tpu.obs import prof as prof_mod
    from lightgbm_tpu.ops.histogram import leaf_histogram

    rng = np.random.RandomState(0)
    N, F = 5000, 6
    X = rng.randn(N, F).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + rng.randn(N) * 0.3 > 0).astype(
        np.float32
    )
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=2,
    )
    reason = prof_mod.unsupported_reason(bst._gbdt)
    assert reason is None, "profiler unexpectedly unsupported: %s" % reason
    rec = prof_mod.profile_growth(bst, iters=2)

    # --- breakdown structure + the bitwise-identity proof ------------------
    segs = rec["segments_per_tree_s"]
    missing = [s for s in prof_mod.CORE_SEGMENTS if s not in segs]
    assert not missing, "segments missing from breakdown: %s" % missing
    assert all(v >= 0 for v in segs.values()), segs
    assert rec["bitwise_identical"] is True, (
        "segmented model diverged from the fused grower's"
    )
    assert rec["segment_sum_s_per_tree"] > 0
    assert rec["splits_per_tree"] > 0

    # --- report sections ---------------------------------------------------
    report = REGISTRY.run_report()
    assert "growth_segments_s" in report, sorted(report)
    assert set(prof_mod.CORE_SEGMENTS) <= set(report["growth_segments_s"])
    assert "cost_analysis" in report, sorted(report)
    grow_cost = report["cost_analysis"].get("ops.grow_tree")
    assert grow_cost and grow_cost.get("flops", 0) > 0, grow_cost
    prom = REGISTRY.prometheus_text()
    assert "lgbtpu_growth_segment_seconds_total" in prom
    assert "lgbtpu_xla_cost_flops" in prom
    assert 'lgbtpu_jit_traces{name="ops.grow_tree"}' in prom

    # --- cost-analysis bytes vs memwatch shape math ------------------------
    bins = jnp.zeros((F, 512), jnp.uint8)
    vals = jnp.zeros((512, 3), jnp.float32)
    hrec = costs_mod.COSTS.harvest(
        "smoke.leaf_histogram", leaf_histogram, (bins, vals, 16)
    )
    assert hrec is not None
    assert hrec["argument_bytes"] == bins.nbytes + vals.nbytes, hrec
    assert hrec["output_bytes"] == memwatch.hist_carry_bytes(1, F, 16), hrec

    print(
        "prof smoke OK: bitwise identical over %d trees, segments=%s, "
        "sum/fused ratio=%.3f" % (
            rec["trees"], sorted(segs), rec["segment_sum_ratio"],
        )
    )
    return 0


def drift_main() -> int:
    """Model/data observability smoke (check.sh --drift): flight JSONL
    schema, drift PSI separation (shifted vs in-distribution traffic),
    non-empty HTML run report."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    work = tempfile.mkdtemp(prefix="lgbtpu_drift_")
    flight_path = os.path.join(work, "run.jsonl")
    os.environ["LIGHTGBM_TPU_FLIGHT"] = flight_path

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import REGISTRY, flight, report
    from lightgbm_tpu.serve.server import ServeApp

    rng = np.random.RandomState(7)
    n, f, rounds = 3000, 6, 8
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=rounds,
        valid_sets=[lgb.Dataset(X[:500], label=y[:500])],
        verbose_eval=False,
    )
    os.environ.pop("LIGHTGBM_TPU_FLIGHT")

    # --- flight JSONL schema ----------------------------------------------
    rec = flight.load(flight_path)
    man = rec["manifest"]
    for key in ("config_digest", "num_data", "num_features", "label_digest",
                "num_boost_round", "backend"):
        assert man.get(key) not in (None, ""), (key, man)
    assert man["num_data"] == n and man["num_boost_round"] == rounds
    assert len(rec["iterations"]) == rounds, len(rec["iterations"])
    for it in rec["iterations"]:
        assert "iteration" in it and "evals" in it and it["evals"], it
    assert len(rec["trees"]) == bst.num_trees(), (
        len(rec["trees"]), bst.num_trees(),
    )
    for t in rec["trees"]:
        for key in ("num_leaves", "max_depth", "total_gain", "max_gain"):
            assert key in t, (key, t)
    assert rec["end"] and rec["end"]["num_trees"] == bst.num_trees()

    # --- drift separation: shifted traffic alerts, in-dist does not -------
    model_path = os.path.join(work, "m.txt")
    os.environ["LIGHTGBM_TPU_DRIFT_SIDECAR"] = "1"
    bst.save_model(model_path)
    os.environ.pop("LIGHTGBM_TPU_DRIFT_SIDECAR")
    assert os.path.exists(model_path + ".drift.json"), "sidecar missing"

    app = ServeApp(max_delay_ms=1.0, min_bucket_rows=8, drift=True)
    try:
        app.registry.load("m", model_path)
        X_in = np.random.RandomState(8).randn(1500, f)
        app.predict(X_in)
        snap = app.drift_snapshot()["models"]["m"]
        assert snap["source"] == "sidecar", snap["source"]
        in_psis = [
            v["psi"] for v in snap["features"].values()
            if v.get("psi") is not None
        ]
        assert in_psis and max(in_psis) < snap["threshold"], (
            "in-distribution traffic drifted: %s" % in_psis
        )
        assert not snap["alerts"], snap["alerts"]

        X_shift = np.random.RandomState(9).randn(1500, f) + np.r_[
            3.0, 3.0, np.zeros(f - 2)
        ]
        app.predict(X_shift)
        snap = app.drift_snapshot()["models"]["m"]
        alert_psis = [
            v["psi"] for v in snap["features"].values() if v.get("alert")
        ]
        assert alert_psis and max(alert_psis) > snap["threshold"], snap
        assert snap["alerts"], "alert list empty after shifted traffic"
        alerts = app.metrics.registry.counter("serve_drift_alerts").values()
        assert sum(alerts.values()) >= 1, alerts
        prom = app.prometheus_metrics()
        assert "lgbtpu_serve_drift_psi" in prom
        assert "lgbtpu_serve_drift_alerts_total" in prom
        drift_snapshot = app.drift_snapshot()
    finally:
        app.close()

    # --- HTML run report ---------------------------------------------------
    html = report.render(
        flight=rec, metrics={"obs_report": REGISTRY.run_report()},
        drift=drift_snapshot,
    )
    out = os.path.join(work, "report.html")
    with open(out, "w", encoding="utf-8") as fh:
        fh.write(html)
    assert len(html) > 2000, len(html)
    for needle in ("<svg", "Learning curves", "Run manifest",
                   "Serve drift", "ALERT"):
        assert needle in html, "report missing %r" % needle

    print(
        "drift smoke OK: flight %d iters / %d trees, in-dist psi<thr, "
        "shifted alerts=%s, report %d bytes (%s)"
        % (len(rec["iterations"]), len(rec["trees"]),
           snap["alerts"], len(html), out)
    )
    return 0


if __name__ == "__main__":
    if "--prof" in sys.argv[1:]:
        sys.exit(prof_main())
    if "--drift" in sys.argv[1:]:
        sys.exit(drift_main())
    sys.exit(main())
