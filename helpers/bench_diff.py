"""bench_diff: the bench-regression gate (compare bench JSON vs a baseline).

The BENCH_r01→r05 trajectory was eyeballed by hand; this makes it a gate.
Given two bench.py output records (the one-line JSON the driver captures),
apply per-metric thresholds and emit a markdown verdict table:

  * ``higgs1m_boost_iters_per_sec`` drop > 5%          -> FAIL
  * ``train_auc`` drop > 0.002 absolute                -> FAIL
  * ``predict.rows_per_sec`` drop > 10%                -> FAIL
  * ``predict.retraces_after_warmup`` > 0 (current)    -> FAIL
  * ``jit_retraces_after_warmup`` gauge > 0 (current)  -> FAIL
  * ``error`` field present in current                 -> FAIL
  * ``predict.p99_ms`` rise > 25%                      -> WARN
  * ``growth_segments_s`` share shift > 10 points      -> WARN
  * ``roofline_source`` measured -> analytic           -> WARN
  * ``hist_routing`` changed (env/default impl or
    tune-table digest; obs/tune.py)                    -> WARN
    (a routing flip changes which kernels were measured — the throughput
    rows then reflect routing, never gated as a code regression)
  * serve drift alert counted / PSI gauge > 0.2        -> WARN
    (serve/drift.py: drifted input invalidates comparisons but is a data
    condition, not a code regression)
  * ``device_busy_fraction`` drop > 0.15 /
    ``transfer_seconds`` > 2x (obs/devprof.py)          -> WARN
    (the bound-ness of the run moved — a pointer into the record's
    device_timeline section, never gated as a code regression)
  * ``podwatch`` verdicts present (straggler/stall/dead)
    or iteration spread grew (obs/podwatch.py)          -> WARN
    (fleet-telemetry signals name sick RANKS, not code — a straggling
    host invalidates the throughput comparison but must never FAIL it)

Throughput comparisons apply only between records from the SAME platform —
a CPU-fallback capture vs an on-chip record is apples-to-oranges and every
such row reads SKIP (the ``roofline_source`` stamp exists for the same
reason).

Usage (also wired as ``helpers/check.sh --bench-diff``):

    python helpers/bench_diff.py CURRENT.json BASELINE.json   # hard gate
    python helpers/bench_diff.py --series 'BENCH_r*.json'     # informational
    python helpers/bench_diff.py --self-test                  # golden fixtures

``--self-test`` runs the golden fixtures under tests/golden/bench_diff/:
the synthetic ~10% regression must FAIL and the improvement must PASS —
the gate gating itself. helpers/tpu_bringup.py imports :func:`compare` to
stamp every bringup round with a regression verdict vs the previous
BENCH_TPU.json.

Stdlib only (no jax, no numpy): runs in driver processes that must never
touch a backend.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(REPO, "tests", "golden", "bench_diff")

THRESHOLDS = {
    "iters_drop_pct": 5.0,
    "auc_drop_abs": 0.002,
    "predict_rows_drop_pct": 10.0,
    "predict_p99_rise_pct": 25.0,
    "segment_share_shift_pts": 10.0,
    "scaling_eff_drop": 0.10,
    "busy_fraction_drop": 0.15,
    "podwatch_spread_growth": 2.0,  # iteration-spread growth factor
}

PASS, WARN, FAIL, SKIP = "PASS", "WARN", "FAIL", "SKIP"


def load_bench_json(path: str) -> Dict:
    """A bench record from any of the shapes it is captured in: bench.py's
    raw one-line JSON, the driver's BENCH_r*.json wrapper (record under
    ``"parsed"``), or a log with stderr lines above the record."""
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if "metric" in doc:
            return doc
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    raise ValueError("no bench record in %s" % path)


def _row(metric, baseline, current, threshold, status, note="") -> Dict:
    return {
        "metric": metric, "baseline": baseline, "current": current,
        "threshold": threshold, "status": status, "note": note,
    }


def _pct(cur: float, base: float) -> float:
    return (cur - base) / base * 100.0 if base else 0.0


def compare(
    current: Dict, baseline: Dict, thresholds: Optional[Dict] = None
) -> Tuple[List[Dict], bool]:
    """(verdict rows, failed). ``failed`` is True iff any row is FAIL."""
    th = dict(THRESHOLDS, **(thresholds or {}))
    rows: List[Dict] = []
    same_platform = current.get("platform") == baseline.get("platform")
    plat_note = (
        ""
        if same_platform
        else "platform %s vs %s — not comparable"
        % (current.get("platform"), baseline.get("platform"))
    )

    if current.get("error"):
        rows.append(_row("error", None, str(current["error"])[:120],
                         "absent", FAIL, "current capture errored"))

    # headline throughput
    base_v, cur_v = baseline.get("value"), current.get("value")
    if base_v and cur_v is not None:
        if not same_platform:
            rows.append(_row("value(iters/s)", base_v, cur_v, "-", SKIP,
                             plat_note))
        else:
            d = _pct(cur_v, base_v)
            status = FAIL if d < -th["iters_drop_pct"] else PASS
            rows.append(_row(
                "value(iters/s)", base_v, cur_v,
                ">-%.1f%%" % th["iters_drop_pct"], status,
                "%+.1f%%" % d,
            ))

    # model quality
    base_a, cur_a = baseline.get("train_auc"), current.get("train_auc")
    if base_a is not None and cur_a is not None:
        drop = base_a - cur_a
        status = FAIL if drop > th["auc_drop_abs"] else PASS
        rows.append(_row("train_auc", base_a, cur_a,
                         "drop<=%.3g" % th["auc_drop_abs"], status,
                         "%+.4f" % (cur_a - base_a)))

    # serving numbers
    bp = baseline.get("predict") or {}
    cp = current.get("predict") or {}
    if bp.get("rows_per_sec") and cp.get("rows_per_sec") is not None:
        if not same_platform:
            rows.append(_row("predict.rows_per_sec", bp["rows_per_sec"],
                             cp["rows_per_sec"], "-", SKIP, plat_note))
        else:
            d = _pct(cp["rows_per_sec"], bp["rows_per_sec"])
            status = FAIL if d < -th["predict_rows_drop_pct"] else PASS
            rows.append(_row(
                "predict.rows_per_sec", bp["rows_per_sec"],
                cp["rows_per_sec"],
                ">-%.1f%%" % th["predict_rows_drop_pct"], status,
                "%+.1f%%" % d,
            ))
    if bp.get("p99_ms") and cp.get("p99_ms") is not None and same_platform:
        d = _pct(cp["p99_ms"], bp["p99_ms"])
        status = WARN if d > th["predict_p99_rise_pct"] else PASS
        rows.append(_row("predict.p99_ms", bp["p99_ms"], cp["p99_ms"],
                         "<+%.1f%%" % th["predict_p99_rise_pct"], status,
                         "%+.1f%%" % d))

    # retraces: absolute gates on the CURRENT capture (baseline-independent)
    cr = cp.get("retraces_after_warmup")
    if cr is not None:
        rows.append(_row("predict.retraces_after_warmup",
                         bp.get("retraces_after_warmup"), cr, "== 0",
                         FAIL if cr > 0 else PASS,
                         "bucket cache must hold after warmup"))
    gauges = (current.get("obs_report") or {}).get("gauges") or {}
    jr = gauges.get("jit_retraces_after_warmup")
    if jr is not None:
        rows.append(_row("jit_retraces_after_warmup", None, jr, "== 0",
                         FAIL if jr > 0 else PASS, "retrace watchdog"))

    # roofline provenance: a measured->analytic flip means the next
    # comparison would be apples-to-oranges — surface it
    brs, crs = baseline.get("roofline_source"), current.get("roofline_source")
    if brs or crs:
        status = WARN if (brs == "measured" and crs != "measured") else PASS
        rows.append(_row("roofline_source", brs, crs, "no measured->analytic",
                         status, ""))

    # histogram routing provenance (obs/tune.py, ISSUE 13): records measured
    # under different kernel routing (env impl, backend default, or a
    # different tune-table digest) are comparing different kernels — the
    # throughput rows then reflect a routing change, not a code regression,
    # so this WARNs and never FAILs (docs/HistogramRouting.md)
    bhr, chr_ = baseline.get("hist_routing"), current.get("hist_routing")
    if bhr is not None or chr_ is not None:
        def _fmt_routing(h):
            if not h:
                return None
            impl = h.get("env_impl") or h.get("impl_default")
            dig = h.get("tune_digest")
            return "%s%s" % (impl, " tune=%s" % dig if dig else "")

        if bhr is None or chr_ is None:
            # one record predates the routing stamp: nothing to verify —
            # informational, never noise on every first new-format diff
            rows.append(_row(
                "hist_routing", _fmt_routing(bhr), _fmt_routing(chr_),
                "unchanged", SKIP,
                "routing provenance absent in one record",
            ))
        else:
            same = _fmt_routing(bhr) == _fmt_routing(chr_)
            rows.append(_row(
                "hist_routing", _fmt_routing(bhr), _fmt_routing(chr_),
                "unchanged", PASS if same else WARN,
                "" if same else "histogram kernel routing changed — "
                "throughput deltas reflect routing, not a code regression",
            ))

    # serve feature drift (serve/drift.py): any PSI alert in the current
    # capture, or a tracked PSI gauge above 0.2, is a WARN — drifted input
    # makes every other row's comparison suspect (the model was measured
    # against traffic it wasn't trained on), but it is a data condition,
    # not a code regression, so it never FAILs the gate
    obs = current.get("obs_report") or {}
    drift_alerts = sum(
        v for k, v in (obs.get("counters") or {}).items()
        if k.startswith("serve_drift_alerts")
    )
    drift_psis = {
        k: float(v) for k, v in (obs.get("gauges") or {}).items()
        if k.startswith("serve_drift_psi")
    }
    if drift_alerts or drift_psis:
        worst_k = max(drift_psis, key=drift_psis.get) if drift_psis else None
        worst_v = drift_psis.get(worst_k, 0.0) if worst_k else 0.0
        status = WARN if (drift_alerts > 0 or worst_v > 0.2) else PASS
        rows.append(_row(
            "serve_drift", None,
            "%d alert(s)" % int(drift_alerts), "0 alerts, psi<=0.2", status,
            "max psi %.3f (%s)" % (worst_v, worst_k) if worst_k else "",
        ))

    # multichip scaling efficiency (helpers/multichip_bench.py): a drop
    # between MULTICHIP rounds means the pod curve bent — same-platform
    # only, and a WARN rather than a FAIL (device counts, chip generations
    # and comms fabric vary between capture environments; the
    # comms_fraction attribution in the record says why)
    bse = baseline.get("scaling_efficiency")
    cse = current.get("scaling_efficiency")
    if bse is not None and cse is not None:
        if not same_platform:
            rows.append(_row("scaling_efficiency", bse, cse, "-", SKIP,
                             plat_note))
        else:
            d = float(cse) - float(bse)
            status = WARN if d < -th["scaling_eff_drop"] else PASS
            rows.append(_row(
                "scaling_efficiency", bse, cse,
                ">-%.2f" % th["scaling_eff_drop"], status,
                "%+.3f (never a hard FAIL; see comms_fraction)" % d,
            ))

    # device-timeline audit (obs/devprof.py, ISSUE 14): a busy-fraction
    # drop (or a transfer-time blow-up) between same-platform records
    # means the bound-ness of the run moved — a diagnosis pointer into the
    # device_timeline section, NOT a throughput gate, so it WARNs and
    # never FAILs
    bdb = baseline.get("device_busy_fraction")
    cdb = current.get("device_busy_fraction")
    if bdb is not None or cdb is not None:
        if bdb is None or cdb is None:
            rows.append(_row(
                "device_busy_fraction", bdb, cdb, "-", SKIP,
                "devprof stamp absent in one record",
            ))
        elif not same_platform:
            rows.append(_row("device_busy_fraction", bdb, cdb, "-", SKIP,
                             plat_note))
        else:
            d = float(cdb) - float(bdb)
            status = WARN if d < -th["busy_fraction_drop"] else PASS
            rows.append(_row(
                "device_busy_fraction", bdb, cdb,
                ">-%.2f" % th["busy_fraction_drop"], status,
                "%+.3f (never a hard FAIL; see device_timeline)" % d,
            ))
        bts = baseline.get("transfer_seconds")
        cts = current.get("transfer_seconds")
        # max(2x, 0.01s floor): a 0.0s baseline (clean device-resident run)
        # must still WARN when transfers appear, not fall through a falsy
        # guard — that 0 -> seconds jump is the exact regression this row
        # exists to surface
        if (same_platform and bts is not None and cts is not None
                and float(cts) > max(2.0 * float(bts), 0.01)):
            rows.append(_row(
                "transfer_seconds", bts, cts, "<=2x", WARN,
                "H2D/D2H time doubled — check the devprof transfer table",
            ))

    # growth-segment share drift (profiler breakdown, obs/prof.py)
    bs = baseline.get("growth_segments_s") or {}
    cs = current.get("growth_segments_s") or {}
    if bs and cs:
        bt, ct = sum(bs.values()), sum(cs.values())
        worst, worst_shift = None, 0.0
        for seg in sorted(set(bs) | set(cs)):
            b_share = bs.get(seg, 0.0) / bt * 100.0 if bt else 0.0
            c_share = cs.get(seg, 0.0) / ct * 100.0 if ct else 0.0
            if abs(c_share - b_share) > abs(worst_shift):
                worst, worst_shift = seg, c_share - b_share
        status = (
            WARN if abs(worst_shift) > th["segment_share_shift_pts"] else PASS
        )
        rows.append(_row(
            "growth_segments share", None, worst,
            "shift<=%g pts" % th["segment_share_shift_pts"], status,
            "max shift %+.1f pts (%s)" % (worst_shift, worst),
        ))

    # fleet-telemetry drift (obs/podwatch.py): sick-rank verdicts and an
    # iteration spread that grew name HOST conditions — they invalidate a
    # throughput comparison but are never a code regression, so WARN only
    cpw = current.get("podwatch") or {}
    if cpw:
        bpw = baseline.get("podwatch") or {}
        bad = [v for v in (cpw.get("verdicts") or [])
               if v.get("verdict") in ("straggler", "stall", "dead")]
        if bad:
            first = bad[0]
            rows.append(_row(
                "podwatch.verdicts",
                len([v for v in (bpw.get("verdicts") or [])
                     if v.get("verdict") in ("straggler", "stall", "dead")]),
                len(bad), "0", WARN,
                "%s rank %s — %s" % (first.get("verdict"),
                                     first.get("rank"),
                                     str(first.get("why", ""))[:120]),
            ))
        bsp = bpw.get("iteration_spread")
        csp = cpw.get("iteration_spread")
        if bsp is not None and csp is not None:
            grew = (float(csp)
                    > max(float(bsp) * th["podwatch_spread_growth"], 1.0))
            rows.append(_row(
                "podwatch.iteration_spread", bsp, csp,
                "<=%gx" % th["podwatch_spread_growth"],
                WARN if grew else PASS,
                "pod ranks drifting apart — see the record's podwatch "
                "block" if grew else "",
            ))

    failed = any(r["status"] == FAIL for r in rows)
    return rows, failed


def to_markdown(rows: List[Dict], failed: bool, title: str = "") -> str:
    lines = []
    if title:
        lines.append("### bench-diff: %s" % title)
    lines.append("| metric | baseline | current | threshold | status | note |")
    lines.append("|---|---|---|---|---|---|")
    for r in rows:
        lines.append("| %s | %s | %s | %s | %s | %s |" % (
            r["metric"],
            "-" if r["baseline"] is None else r["baseline"],
            "-" if r["current"] is None else r["current"],
            r["threshold"], r["status"], r["note"],
        ))
    lines.append("")
    lines.append("**verdict: %s**" % ("FAIL" if failed else "PASS"))
    return "\n".join(lines)


def self_test() -> int:
    """The gate gating itself: the golden ~10% regression fixture must
    FAIL, the improvement fixture must PASS. Returns 0 on success."""
    base = load_bench_json(os.path.join(GOLDEN_DIR, "baseline.json"))
    reg = load_bench_json(os.path.join(GOLDEN_DIR, "regression.json"))
    imp = load_bench_json(os.path.join(GOLDEN_DIR, "improvement.json"))
    rows_r, failed_r = compare(reg, base)
    rows_i, failed_i = compare(imp, base)
    ok = True
    if not failed_r:
        print("bench_diff self-test: regression fixture did NOT fail!")
        print(to_markdown(rows_r, failed_r, "regression fixture"))
        ok = False
    fail_metrics = {r["metric"] for r in rows_r if r["status"] == FAIL}
    if "value(iters/s)" not in fail_metrics:
        print("bench_diff self-test: regression fixture missed the "
              "throughput drop (failed: %s)" % sorted(fail_metrics))
        ok = False
    if failed_i:
        print("bench_diff self-test: improvement fixture FAILED wrongly:")
        print(to_markdown(rows_i, failed_i, "improvement fixture"))
        ok = False
    if ok:
        print("bench_diff self-test OK: regression fixture FAILS "
              "(%s), improvement fixture PASSES" % sorted(fail_metrics))
    return 0 if ok else 1


def series(pattern: str) -> int:
    """Informational pairwise comparison of a BENCH_r*.json series:
    consecutive same-platform records only; never exits nonzero (historic
    records are evidence, not a gate)."""
    paths = sorted(glob.glob(pattern))
    if len(paths) < 2:
        print("bench_diff: series %r has %d record(s); nothing to compare"
              % (pattern, len(paths)))
        return 0
    records = []
    for p in paths:
        try:
            records.append((p, load_bench_json(p)))
        except (OSError, ValueError) as e:
            print("bench_diff: skipping %s (%s)" % (p, e))
    for (pa, a), (pb, b) in zip(records, records[1:]):
        title = "%s -> %s" % (os.path.basename(pa), os.path.basename(pb))
        if a.get("platform") != b.get("platform"):
            print("### bench-diff: %s\nplatform %s -> %s: skipped "
                  "(not comparable)\n" % (title, a.get("platform"),
                                          b.get("platform")))
            continue
        rows, failed = compare(b, a)
        print(to_markdown(rows, failed, title + " (informational)"))
        print()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("current", nargs="?", help="current bench JSON")
    ap.add_argument("baseline", nargs="?", help="baseline bench JSON")
    ap.add_argument("--baseline", dest="baseline_opt", help="baseline path")
    ap.add_argument("--series", help="glob of a BENCH_r*.json series "
                                     "(informational pairwise diffs)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the golden-fixture self test")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict rows as JSON instead of markdown")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()
    if args.series:
        return series(args.series)
    if not args.current:
        ap.error("need CURRENT (and BASELINE), --series, or --self-test")
    baseline_path = args.baseline or args.baseline_opt
    if not baseline_path:
        ap.error("need a BASELINE to diff against")
    current = load_bench_json(args.current)
    baseline = load_bench_json(baseline_path)
    rows, failed = compare(current, baseline)
    if args.json:
        print(json.dumps({"rows": rows, "failed": failed}, indent=1))
    else:
        print(to_markdown(rows, failed, "%s vs %s"
                          % (os.path.basename(args.current),
                             os.path.basename(baseline_path))))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
