#!/usr/bin/env python
"""Fleet-telemetry smoke: live scrape + straggler verdict on a REAL pod.

ONE invocation proves the whole podwatch chain (docs/Observability.md
§Fleet telemetry, obs/podwatch.py) end to end:

  1. a real 2-process CPU training world (jax.distributed, one rank per
     process) runs with the telemetry ring + heartbeats armed
     (LIGHTGBM_TPU_TELEMETRY) and the scrape endpoint up on rank 0
     (LIGHTGBM_TPU_TELEMETRY_PORT); rank 1 carries a seeded per-boundary
     sleep — the straggler the aggregator must later name;
  2. the parent scrapes rank 0 LIVE, mid-run: /health must answer with a
     mid-run iteration, /metrics must expose the lgbtpu_* families, and
     /timeline must already hold boundary samples;
  3. after the pod drains, ``python -m lightgbm_tpu.obs.podwatch <dir>
     --json`` folds both ranks' shards + heartbeats and the straggler
     verdict must name rank 1 with its diverging segment and the factor/
     threshold evidence;
  4. telemetry-off byte-identity: the same single-process training run
     with and without LIGHTGBM_TPU_TELEMETRY must produce byte-identical
     model text (the recorder samples host state only).

The parent stays jax-free (subprocesses do all jax work) so the driver can
run on any box, matching the tpu_bringup stage contract.
"""
import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: seeded per-boundary sleep (seconds) — rank 1 is the straggler
LAG_RANK0 = 0.05
LAG_RANK1 = 0.35

WORKER = textwrap.dedent(
    """
    import os, sys, json, time, hashlib
    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, world = int(sys.argv[1]), int(sys.argv[2])
    coord_port, http_port, outdir, lag = (
        sys.argv[3], sys.argv[4], sys.argv[5], float(sys.argv[6])
    )
    os.environ["LIGHTGBM_TPU_TELEMETRY"] = outdir
    os.environ["LIGHTGBM_TPU_TIMETAG"] = "1"
    if rank == 0:
        os.environ["LIGHTGBM_TPU_TELEMETRY_PORT"] = http_port
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address="127.0.0.1:" + coord_port,
                               num_processes=world, process_id=rank)
    sys.path.insert(0, "@REPO@")
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    X = rng.randn(1200, 10)
    y = (X[:, 0] + 0.5 * X[:, 3] + 0.2 * rng.randn(1200) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)

    def laggard(env):  # seeded per-boundary sleep (after-iteration)
        time.sleep(lag)
    laggard.order = 100

    booster = lgb.train(
        {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "min_data_in_leaf": 5, "device_chunk_size": 4},
        ds, num_boost_round=80, callbacks=[laggard], verbose_eval=False,
    )
    sha = hashlib.sha256(booster.model_to_string().encode()).hexdigest()
    print("RESULT " + json.dumps({"rank": rank, "model_sha": sha,
                                  "iters": booster.current_iteration}),
          flush=True)
    # barrier exit: rank 0 hosts the coordinator, and leaving early would
    # tear it down under the still-training straggler
    jax.distributed.shutdown()
    """
).replace("@REPO@", REPO)

IDENTITY_WORKER = textwrap.dedent(
    """
    import os, sys, hashlib
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, "@REPO@")
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(11)
    X = rng.randn(600, 8)
    y = (X[:, 1] - X[:, 2] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train(
        {"objective": "binary", "num_leaves": 15, "device_chunk_size": 4},
        ds, num_boost_round=24, verbose_eval=False,
    )
    print("SHA " + hashlib.sha256(
        booster.model_to_string().encode()).hexdigest(), flush=True)
    """
).replace("@REPO@", REPO)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def _fail(msg):
    print("podwatch_smoke: FAIL — %s" % msg, flush=True)
    return 1


def _scrape_live(http_port, procs, deadline_s=300.0):
    """Poll /health until rank 0 is mid-run, then scrape all three
    endpoints. Returns (ok, detail)."""
    base = "http://127.0.0.1:%d" % http_port
    t0 = time.monotonic()
    health = None
    while time.monotonic() - t0 < deadline_s:
        if any(p.poll() is not None and p.returncode != 0 for p in procs):
            return False, "a worker died before the live scrape"
        if procs[0].poll() is not None:
            return False, "rank 0 finished before a mid-run scrape landed"
        try:
            code, body = _get(base + "/health", timeout=2.0)
        except OSError:
            time.sleep(0.05)
            continue
        if code != 200:
            time.sleep(0.05)
            continue
        health = json.loads(body)
        if health.get("telemetry_armed") and health.get("iteration", 0) > 0:
            break
        time.sleep(0.02)
    else:
        return False, "no mid-run /health answer within %.0fs" % deadline_s
    if health["iteration"] >= 80:
        return False, "scrape landed post-run (iteration %d)" % health["iteration"]
    if health.get("rank") != 0 or health.get("world") != 2:
        return False, "unexpected /health identity: %r" % (health,)

    code, prom = _get(base + "/metrics", timeout=5.0)
    if code != 200 or "lgbtpu_train_iterations_total" not in prom:
        return False, "/metrics missing lgbtpu_train_iterations_total"
    if "# TYPE lgbtpu_train_iterations_total counter" not in prom:
        return False, "/metrics missing the TYPE line"

    code, tl = _get(base + "/timeline", timeout=5.0)
    timeline = json.loads(tl)
    if code != 200 or not timeline.get("telemetry_armed"):
        return False, "/timeline not armed"
    samples = timeline.get("samples") or []
    if not samples or timeline.get("rank") != 0:
        return False, "/timeline empty mid-run"
    s = samples[-1]
    for key in ("iteration", "chunk", "dt_s", "it_per_s", "counters"):
        if key not in s:
            return False, "/timeline sample missing %r" % key
    print("podwatch_smoke: live scrape OK at iteration %d "
          "(%d timeline samples)" % (health["iteration"], len(samples)),
          flush=True)
    return True, ""


def _run_pod(tmp, attempt):
    """One coordinated 2-process run; None on a coordinator port race."""
    outdir = os.path.join(tmp, "telemetry%d" % attempt)
    os.makedirs(outdir, exist_ok=True)
    worker = os.path.join(tmp, "worker.py")
    with open(worker, "w") as fh:
        fh.write(WORKER)
    coord_port, http_port = _free_port(), _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # no virtual devices: one real proc per rank
    errs = [open(os.path.join(tmp, "err_a%d_r%d.log" % (attempt, r)), "w+")
            for r in range(2)]
    procs = []
    try:
        for r, lag in ((0, LAG_RANK0), (1, LAG_RANK1)):
            procs.append(subprocess.Popen(
                [sys.executable, worker, str(r), "2", str(coord_port),
                 str(http_port), outdir, str(lag)],
                env=env, stdout=subprocess.PIPE, stderr=errs[r], text=True,
            ))
        ok, detail = _scrape_live(http_port, procs)
        results = []
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            errs[r].seek(0)
            err_text = errs[r].read()
            if p.returncode != 0:
                low = err_text.lower()
                if "address already in use" in low or "failed to bind" in low:
                    return None  # port race: retry on fresh ports
                raise AssertionError(
                    "rank %d rc=%d\n%s" % (r, p.returncode, err_text[-2000:])
                )
            line = next(l for l in out.splitlines() if l.startswith("RESULT "))
            results.append(json.loads(line[len("RESULT "):]))
        if not ok:
            raise AssertionError("live scrape failed: %s" % detail)
        return outdir, results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for fh in errs:
            fh.close()


def _aggregate(outdir):
    """python -m lightgbm_tpu.obs.podwatch <dir> --json in a fresh process
    (the operator's invocation, not an in-process shortcut)."""
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.obs.podwatch", outdir, "--json"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120,
    )
    if proc.returncode != 0:
        raise AssertionError("aggregator rc=%d\n%s"
                             % (proc.returncode, proc.stderr[-2000:]))
    return json.loads(proc.stdout)


def _identity_sha(tmp, tag, telemetry_dir):
    script = os.path.join(tmp, "identity.py")
    with open(script, "w") as fh:
        fh.write(IDENTITY_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("LIGHTGBM_TPU_TELEMETRY", None)
    env.pop("LIGHTGBM_TPU_TELEMETRY_PORT", None)
    if telemetry_dir:
        env["LIGHTGBM_TPU_TELEMETRY"] = telemetry_dir
    proc = subprocess.run([sys.executable, script], env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise AssertionError("identity run (%s) rc=%d\n%s"
                             % (tag, proc.returncode, proc.stderr[-2000:]))
    line = next(l for l in proc.stdout.splitlines() if l.startswith("SHA "))
    return line.split()[1]


def main():
    tmp = tempfile.mkdtemp(prefix="podwatch_smoke_")
    print("podwatch_smoke: workdir %s" % tmp, flush=True)

    # -- 1+2: the 2-process world, scraped live ----------------------------
    pod = None
    for attempt in range(2):
        pod = _run_pod(tmp, attempt)
        if pod is not None:
            break
    if pod is None:
        return _fail("coordinator port bind failed twice")
    outdir, results = pod
    print("podwatch_smoke: pod drained: %s" % json.dumps(results), flush=True)
    if any(r["iters"] != 80 for r in results):
        return _fail("a rank did not finish all 80 iterations: %r" % results)

    # -- 3: aggregate + the seeded straggler named -------------------------
    summary = _aggregate(outdir)
    print("podwatch_smoke: verdicts: %s"
          % json.dumps(summary["verdicts"]), flush=True)
    if summary.get("world") != 2 or len(summary.get("ranks", {})) != 2:
        return _fail("aggregator did not see both ranks: %r"
                     % summary.get("ranks"))
    stragglers = [v for v in summary["verdicts"]
                  if v["verdict"] == "straggler"]
    if not stragglers:
        return _fail("no straggler verdict for the seeded slow rank")
    v = stragglers[0]
    if v["rank"] != 1:
        return _fail("straggler verdict blamed rank %r, seeded rank 1"
                     % v["rank"])
    ev = v.get("evidence") or {}
    if not ev.get("segment"):
        return _fail("straggler verdict carries no diverging segment")
    if float(ev.get("factor", 0)) < float(ev.get("threshold", 1.5)):
        return _fail("straggler factor %r below its own threshold %r"
                     % (ev.get("factor"), ev.get("threshold")))
    # the seeded sleep lives in a callback — time no TIMETAG phase claims —
    # so the honest attribution is the synthetic host bucket
    if v["rank"] == 1 and ev["segment"] != "host_other":
        print("podwatch_smoke: note — diverging segment %r (expected "
              "host_other for a callback sleep)" % ev["segment"], flush=True)
    print("podwatch_smoke: straggler rank 1 named (%.2fx, segment %s)"
          % (float(ev["factor"]), ev["segment"]), flush=True)

    # -- 4: telemetry-off byte-identity ------------------------------------
    sha_on = _identity_sha(tmp, "armed", os.path.join(tmp, "id_telemetry"))
    sha_off = _identity_sha(tmp, "off", None)
    if sha_on != sha_off:
        return _fail("model bytes differ with telemetry armed: %s vs %s"
                     % (sha_on, sha_off))
    print("podwatch_smoke: telemetry-off byte-identity holds (%s)"
          % sha_off[:12], flush=True)

    print("podwatch_smoke: PASS", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
