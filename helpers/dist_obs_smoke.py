"""check.sh --dist-obs: the distributed-observability stack, one invocation.

Composes every ISSUE-10 surface and asserts the acceptance bundle:

  * an 8-forced-CPU-device worker trains the SAME data twice — fused
    sharded chunks vs `obs/dist.segmented_train_chunk` (every sub-step a
    fenced shard_map dispatch) — and HARD-FAILS on any model-string or
    score-carry mismatch; with the dist-obs features off the retrace
    watchdog must count exactly ONE train_chunk compile (no new traces);
    `profile_sharded_growth` must report bitwise identity vs the fused
    grower plus a well-formed comms_fraction/per-device breakdown, and the
    N=1003-over-8 shard-skew gauges must show the known 7x126+121 split;
  * a second (2-device) worker plays the other pod rank for the FILE-BASED
    merge path: both ranks' registry snapshots merge into one Prometheus
    exposition whose counters equal the per-process sums, and both ranks'
    Chrome traces merge into one Perfetto timeline with disjoint pids;
  * a tiny multichip_bench --sweep 1,2 produces a MULTICHIP-shaped record
    carrying comms_fraction + scaling_efficiency + per-device segment
    seconds;
  * obs/report.py renders an HTML report whose Multichip section charts
    the scaling efficiency, the comms/compute split and the per-device
    table.

Everything lands under a temp dir; the repo's MULTICHIP_r*.json evidence
series is never touched.
"""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import json, sys
    sys.path.insert(0, "@REPO@")
    rank = int(sys.argv[1])
    devices = int(sys.argv[2])
    snap_path = sys.argv[3]
    from lightgbm_tpu.utils.platform import force_cpu_devices
    jax = force_cpu_devices(devices)
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import dist, registry, retrace as retrace_mod

    N, F, ROUNDS, CHUNK = (1003, 6, 9, 4) if rank == 0 else (512, 4, 5, 2)
    rng = np.random.RandomState(7 + rank)
    X = rng.randn(N, F)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "tree_learner": "data", "num_machines": devices,
              "device_chunk_size": CHUNK,
              "bagging_freq": 2, "bagging_fraction": 0.8}

    before = retrace_mod.counts().get("gbdt.train_chunk", 0)
    fused = lgb.train(params, lgb.Dataset(X, label=y), ROUNDS)
    compiles = retrace_mod.counts().get("gbdt.train_chunk", 0) - before
    # dist-obs features are OFF here: the skew gauges are host math and the
    # wait fences are env-gated, so the watchdog must see exactly the one
    # chunk compile the pre-ISSUE-10 path had
    assert compiles == 1, "expected 1 train_chunk compile, saw %d" % compiles

    out = {"rank": rank, "devices": devices, "compiles": compiles}
    if rank == 0:
        seg = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
        seg.update()
        done = 1
        while done < ROUNDS:
            d, stopped = dist.segmented_train_chunk(
                seg._gbdt, min(CHUNK, ROUNDS - done))
            done += d
            if stopped:
                break
        m_f = fused.model_to_string().split("parameters:")[0]
        m_s = seg.model_to_string().split("parameters:")[0]
        assert m_f == m_s, (
            "fused-chunk vs SEGMENTED-chunk MODEL STRING MISMATCH")
        assert np.array_equal(fused._gbdt.scores_canonical_np(),
                              seg._gbdt.scores_canonical_np()), (
            "fused vs segmented score carries differ")
        prof = dist.profile_sharded_growth(fused, iters=1)
        assert prof["bitwise_identical"], "segmented grower not bitwise"
        assert 0.0 < prof["comms_fraction"] < 1.0, prof["comms_fraction"]
        assert set(prof["collective_segments"]) <= set(
            prof["segments_per_tree_s"])
        rows = sorted(e["rows"] for e in prof["per_device"])
        assert rows == [121] + [126] * 7, rows
        shard_g = registry.REGISTRY.gauge("train_shard_rows").values()
        assert sum(shard_g.values()) == N, shard_g
        out.update(model_match=True, comms_fraction=prof["comms_fraction"],
                   dist_segments=prof["segments_per_tree_s"],
                   per_device=prof["per_device"])
    # every rank publishes something distinguishable and snapshots itself
    registry.REGISTRY.counter("dist_smoke_total").inc(10 * (rank + 1))
    registry.REGISTRY.gauge("dist_smoke_rank").set(float(rank))
    snap = dist.snapshot()
    snap["process"] = rank
    with open(snap_path, "w") as fh:
        json.dump(snap, fh)
    out["counters"] = registry.REGISTRY.counters()
    print("RESULT " + json.dumps(out), flush=True)
    """
).replace("@REPO@", REPO)


def _run_worker(rank: int, devices: int, snap_path: str, trace_path: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=%d" % devices
    ).strip()
    env["LIGHTGBM_TPU_TRACE"] = trace_path
    out = subprocess.run(
        [sys.executable, "-c", WORKER, str(rank), str(devices), snap_path],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=1500,
    )
    sys.stderr.write(out.stderr[-2000:] if out.stderr else "")
    rec = None
    for line in (out.stdout or "").splitlines():
        if line.startswith("RESULT "):
            rec = json.loads(line[len("RESULT "):])
    if out.returncode != 0 or rec is None:
        print("dist_obs_smoke: rank %d worker FAILED (rc=%d)"
              % (rank, out.returncode))
        if out.stdout:
            print(out.stdout[-1500:])
        return None
    return rec


def main() -> int:
    sys.path.insert(0, REPO)
    from lightgbm_tpu.obs import dist, report, trace

    tmp = tempfile.mkdtemp(prefix="dist_obs_smoke_")
    snaps = [os.path.join(tmp, "reg.rank%d.json" % r) for r in range(2)]
    traces = [os.path.join(tmp, "trace.rank%d.json" % r) for r in range(2)]

    r0 = _run_worker(0, 8, snaps[0], traces[0])
    r1 = _run_worker(1, 2, snaps[1], traces[1])
    if r0 is None or r1 is None:
        return 1
    if not r0.get("model_match"):
        print("dist_obs_smoke: segmented/fused identity not proven")
        return 1

    # ---- pod-wide registry merge (file-based rank fallback) -------------
    merged = dist.merge_snapshots(
        dist.merge_snapshot_files(os.path.join(tmp, "reg.rank*.json"))
    )
    expo = merged.prometheus_text()
    expo_path = os.path.join(tmp, "merged_metrics.prom")
    with open(expo_path, "w") as fh:
        fh.write(expo)
    want = sum(r["counters"].get("dist_smoke_total", 0) for r in (r0, r1))
    got = merged.counter("dist_smoke_total").value()
    if int(got) != int(want) or int(want) != 30:
        print("dist_obs_smoke: merged counter %s != per-process sum %s"
              % (got, want))
        return 1
    iters_want = sum(r["counters"].get("train_iterations", 0)
                     for r in (r0, r1))
    if int(merged.counter("train_iterations").value()) != int(iters_want):
        print("dist_obs_smoke: merged train_iterations mismatch")
        return 1
    if ("lgbtpu_dist_smoke_rank" not in expo
            or 'process="0"' not in expo or 'process="1"' not in expo):
        print("dist_obs_smoke: gauge lost its process provenance label")
        return 1

    # ---- pod-wide trace merge ------------------------------------------
    merged_trace = os.path.join(tmp, "trace_merged.json")
    stats = trace.merge_traces(merged_trace, traces)
    if stats["files"] != 2 or stats["pids"] < 2 or stats["events"] <= 0:
        print("dist_obs_smoke: trace merge malformed: %s" % stats)
        return 1

    # ---- MULTICHIP record with the new attribution fields ---------------
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("LIGHTGBM_TPU_TRACE", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "helpers", "multichip_bench.py"),
         "--sweep", "1,2", "--rows", "3000", "--iters", "4", "--chunk", "2",
         "--leaves", "15"],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=1500,
    )
    summary = None
    for line in (out.stdout or "").splitlines():
        if line.strip().startswith("{"):
            try:
                summary = json.loads(line)
            except ValueError:
                continue
    if not summary or not summary.get("ok"):
        print("dist_obs_smoke: multichip sweep failed (rc=%d)\n%s"
              % (out.returncode, (out.stderr or "")[-1000:]))
        return 1
    for key in ("comms_fraction", "scaling_efficiency", "dist_segments",
                "per_device"):
        if summary.get(key) is None:
            print("dist_obs_smoke: MULTICHIP record missing %r" % key)
            return 1
    mc_path = os.path.join(tmp, "MULTICHIP_smoke.json")
    with open(mc_path, "w") as fh:
        json.dump(summary, fh)

    # ---- HTML report with the Multichip page ----------------------------
    html = report.render(
        metrics={"gauges": {}, "counters": {}},
        bench_records=[("MULTICHIP_smoke.json", summary)],
        title="dist-obs smoke report",
    )
    html_path = os.path.join(tmp, "report.html")
    with open(html_path, "w") as fh:
        fh.write(html)
    for marker in ("Multichip scaling", "scaling efficiency",
                   "collective vs compute", "per-device shard table"):
        if marker not in html:
            print("dist_obs_smoke: report missing %r section" % marker)
            return 1

    print(
        "dist_obs_smoke OK: segmented==fused sharded chunk (model strings + "
        "score carries), 1 train_chunk compile, comms_fraction=%.3f, "
        "shard rows 7x126+121; merged exposition (%s), merged trace "
        "(%d events / %d pids -> %s), MULTICHIP record (eff=%.2f) and "
        "Multichip report page (%s) all emitted"
        % (r0["comms_fraction"], expo_path, stats["events"], stats["pids"],
           merged_trace, summary["scaling_efficiency"], html_path)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
