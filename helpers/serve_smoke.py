"""Serving smoke: boot the real server process, hit it, verify, shut down.

The check.sh --serve gate. Trains a tiny model, saves it, launches
``python -m lightgbm_tpu.serve`` as a SUBPROCESS (the same entry point an
operator uses, port 0 = ephemeral), reads the startup JSON line for the
port, then over real HTTP: /healthz must report ready, and one /predict
must return bit-identical values to Booster.predict. Exits nonzero on any
mismatch; always tears the server down.

Run: JAX_PLATFORMS=cpu python helpers/serve_smoke.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _read_startup_line(proc, timeout_s: float = 180.0):
    """First stdout line, read on a thread so a wedged boot can't hang us."""
    box = {}

    def read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout_s)
    return box.get("line")


def main() -> int:
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(600, 5)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), 4,
    )
    Xt = rng.randn(8, 5)
    expected = bst.predict(Xt)

    with tempfile.TemporaryDirectory() as td:
        model_path = os.path.join(td, "smoke_model.txt")
        bst.save_model(model_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.serve", model_path,
             "--port", "0", "--max-delay-ms", "1"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        try:
            line = _read_startup_line(proc)
            if not line:
                print("serve_smoke: server never printed its startup line")
                return 1
            startup = json.loads(line)
            port = startup["port"]
            base = "http://127.0.0.1:%d" % port

            with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
                health = json.loads(r.read())
            assert health["status"] == "ok" and health["ready"], health

            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"rows": Xt.tolist()}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                body = json.loads(r.read())
            got = np.asarray(body["predictions"])
            if not np.array_equal(expected, got):
                print("serve_smoke: /predict mismatch vs Booster.predict")
                print("  max abs diff:", float(np.abs(expected - got).max()))
                return 1
            print(json.dumps({
                "serve_smoke": "PASS", "port": port,
                "backend": startup["backend"], "n": body["n"],
            }))
            return 0
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
