#!/bin/bash
# Build the reference LightGBM CLI out-of-tree for cross-validation tests
# (tests/test_reference_binary_xval.py). The reference CMakeLists pins
# EXECUTABLE_OUTPUT_PATH to its own source dir, so the binary is moved out
# and the source tree restored afterwards (/root/reference must stay
# unmodified).
#
# Usage: helpers/build_reference_cli.sh [REFERENCE_DIR] [OUT_DIR]
#   then: LGBM_REF_BINARY=$OUT_DIR/lightgbm python -m pytest tests/test_reference_binary_xval.py
set -euo pipefail
REF="${1:-/root/reference}"
OUT="${2:-/tmp/lgbm_ref_build}"
mkdir -p "$OUT"
cd "$OUT"
cmake "$REF" -DCMAKE_BUILD_TYPE=Release >/dev/null
make -j"$(nproc)" lightgbm >/dev/null
# the reference build drops the exe into the source tree; relocate it
if [ -f "$REF/lightgbm" ]; then
  mv "$REF/lightgbm" "$OUT/lightgbm"
fi
echo "reference CLI at $OUT/lightgbm"
