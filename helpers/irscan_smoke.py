"""graftir smoke: real-program scan + seeded-violation self-check in ONE
invocation.

Wired as ``helpers/check.sh --ir`` and as the ``irscan`` bringup stage
(helpers/tpu_bringup.py runs this file by path, driver stays jax-free).
What it proves, end to end, on whatever backend is present:

 1. the registry bootstrap trains the tiny corpus, reaches the chunked
    device path, and traces EVERY registered entry point abstractly over
    the quick shape lattice (no program executes);
 2. the real tree is clean under IR001-IR006 modulo the checked-in
    justified baseline (zero silent suppressions — stale entries fail);
 3. the lowered programs match the checked-in fingerprint contract when
    this environment is the one the contract was pinned on (a foreign
    env skips LOUDLY, it never rubber-stamps);
 4. each of the six IR rules catches its own seeded violation — a scan
    that can no longer see a poisoned program must fail here, not pass
    silently forever.

Exit 0 and a final compact JSON line on success (the bringup stage
records it into TPU_BRINGUP.json); exit 1 with the reason otherwise.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg):
    print("irscan_smoke: FAIL: %s" % msg, file=sys.stderr)
    print(json.dumps({"ok": False, "error": msg[:300]}), flush=True)
    sys.exit(1)


def main():
    # the sharded entry needs a multi-device mesh; on CPU hosts pin the
    # same virtual 8-device platform the module CLI and the tests use —
    # BEFORE jax initializes a backend
    if os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu"):
        from lightgbm_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(8)

    from lightgbm_tpu.obs import irscan

    # -- seeded violations: every rule proves it still bites --------------
    selfcheck = irscan.run_selfcheck()
    missed = sorted(r for r, ok in selfcheck.items() if not ok)
    if missed:
        fail("seeded violation(s) NOT caught: %s" % ", ".join(missed))

    # -- the real tree, quick lattice, baseline + contract ----------------
    result = irscan.run_scan()
    for reason in result.skipped:
        print("irscan_smoke: skipped %s" % reason, file=sys.stderr)
    if not result.audits:
        fail("scan audited zero programs")
    baseline, _ = irscan.load_baseline(irscan.DEFAULT_BASELINE)
    new, stale = irscan.compare_to_baseline(result.findings, baseline)
    if new:
        fail("unsuppressed finding(s): %s"
             % "; ".join(f.format() for f in new[:5]))
    if stale:
        fail("stale baseline entr(ies): %s" % "; ".join(sorted(stale)))
    problems, skip = irscan.check_contract(
        irscan.load_contract(irscan.DEFAULT_CONTRACT),
        result.audits, result.trace_counts,
    )
    if skip is not None:
        print("irscan_smoke: contract %s" % skip, file=sys.stderr)
    if problems:
        fail("fingerprint contract: %s" % "; ".join(problems[:5]))

    out = {
        "ok": True,
        "entries": len(result.trace_counts),
        "programs": len(result.audits),
        "findings_baselined": len(result.findings),
        "rules_selfchecked": sorted(selfcheck),
        "contract": "skipped" if skip is not None else "ok",
        "skipped_entries": result.skipped,
    }
    print("irscan_smoke: PASS — %d entries, %d programs, contract=%s, "
          "%d rule(s) self-checked"
          % (out["entries"], out["programs"], out["contract"],
             len(out["rules_selfchecked"])), file=sys.stderr)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
