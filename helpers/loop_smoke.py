"""Closed-loop continuous-training smoke: the REAL serve stack end to end
(check.sh --loop, bringup `loop` stage).

One invocation proves the whole loop (docs/ContinuousTraining.md), with the
runtime sanitizer armed (``LIGHTGBM_TPU_SAN=transfer,nan,locks``) so a full
cycle — bootstrap train, drift detection, warm-started retrain, holdout
gate, atomic publish, hot swap — is also a sanitizer-clean certification:

  1. the controller BOOTSTRAPS the live model (publish + drift sidecar +
     lineage) and a real ``ThreadingHTTPServer`` serve stack loads it with
     drift monitoring on;
  2. in-distribution traffic through ``POST /predict`` leaves ``/drift``
     quiet; DRIFT-SHIFTED traffic raises a real PSI alert;
  3. the controller's observe pass sees the alert over HTTP, retrains
     warm-started from the live model on the shifted data, the candidate
     passes the AUC gate, publishes through resil/atomic and hot-swaps the
     replica via ``POST /models`` — after which ``/predict`` answers from
     the NEW version carrying lineage (parent fingerprint + flight manifest
     digest) and ``/drift`` runs against the REFRESHED sidecar;
  4. a seeded mid-publish SIGKILL (``loop.publish:3:kill`` — occurrence 1
     is the bootstrap's rename window, 2 the publish-step entry, 3 INSIDE
     the promote's atomic rename window) kills a second controller world;
     the restarted controller converges with the journaled cycle completed
     exactly once.

Run: JAX_PLATFORMS=cpu python helpers/loop_smoke.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("LIGHTGBM_TPU_SAN", "transfer,nan,locks")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

F = 5
SHIFT = 1.6


def _provider(cycle: int):
    """Deterministic per cycle: base distribution for the bootstrap, the
    drift-shifted one for every retrain cycle."""
    rng = np.random.RandomState(100 + cycle)
    n = 600
    shift = 0.0 if cycle == 0 else SHIFT
    X = rng.randn(n, F) + shift
    y = ((X[:, 0] - shift) + 0.3 * rng.randn(n) > 0).astype(float)
    Xh = rng.randn(200, F) + shift
    yh = ((Xh[:, 0] - shift) > 0).astype(float)
    return X, y, Xh, yh


def _post(base, path, body, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def serve_acts(td: str, result: dict) -> bool:
    from lightgbm_tpu.loop import (
        HttpDriftSource, HttpReplica, LoopConfig, LoopController,
    )
    from lightgbm_tpu.serve.server import make_server

    live = os.path.join(td, "live.txt")
    cfg = LoopConfig(
        model_path=live,
        workdir=os.path.join(td, "wd"),
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "device_chunk_size": 4},
        num_boost_round=8,
        data_provider=_provider,
        poll_interval_s=0.2,
        observe_budget_s=30.0,
        jitter_seed=7,
    )
    ctl = LoopController(cfg)
    ctl.ensure_bootstrap()
    assert os.path.exists(live + ".drift.json"), "bootstrap drift sidecar"

    server = make_server(
        port=0, drift=True, drift_min_count=200, warmup_rows=64,
    )
    base = "http://127.0.0.1:%d" % server.server_address[1]
    app = server.app
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        app.registry.load(cfg.model_name, live)
        v1 = _get(base, "/models")["models"][0]
        result["v1"] = {"version": v1["version"], "file_sha": v1["file_sha"]}

        rng = np.random.RandomState(0)
        # act 2a: in-distribution traffic -> /drift stays quiet
        for _ in range(4):
            rows = rng.randn(100, F).tolist()
            _post(base, "/predict", {"rows": rows})
        drift = _get(base, "/drift")
        quiet = not drift["models"][cfg.model_name]["alerts"]
        result["in_dist_quiet"] = quiet
        # act 2b: drift-shifted traffic -> a real PSI alert
        for _ in range(6):
            rows = (rng.randn(100, F) + SHIFT).tolist()
            _post(base, "/predict", {"rows": rows})
        drift = _get(base, "/drift")
        alerts = drift["models"][cfg.model_name]["alerts"]
        result["drift_alerts"] = alerts
        if not (quiet and alerts):
            result["error"] = "drift separation failed"
            return False

        # act 3: the controller's observe pass sees the alert over HTTP
        # and drives the full cycle against the real server
        cfg.drift_source = HttpDriftSource(base)
        cfg.replicas = [HttpReplica(base)]
        outcome = ctl.run_cycle()
        result["cycle_outcome"] = outcome
        if outcome != "promoted":
            result["error"] = "cycle outcome %r" % outcome
            return False
        pred = _post(base, "/predict",
                     {"rows": (rng.randn(3, F) + SHIFT).tolist()})
        v2 = _get(base, "/models")["models"][0]
        result["v2"] = {
            "version": v2["version"], "file_sha": v2["file_sha"],
            "parent_fingerprint": v2["parent_fingerprint"],
            "manifest_digest": v2["manifest_digest"],
        }
        drift2 = _get(base, "/drift")["models"][cfg.model_name]
        result["post_swap_drift_source"] = drift2.get("source")
        ok = (
            v2["version"] == v1["version"] + 1
            and v2["file_sha"] != v1["file_sha"]
            and v2["parent_fingerprint"] == v1["file_sha"]
            and bool(v2["manifest_digest"])
            and pred["parent_fingerprint"] == v1["file_sha"]
            and pred["manifest_digest"] == v2["manifest_digest"]
            and drift2.get("source") == "sidecar"  # refreshed per swap
        )
        if not ok:
            result["error"] = "post-swap verification failed"
        return ok
    finally:
        server.shutdown()
        app.drain(timeout_s=10.0)


_KILL_CHILD = """
import os, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from lightgbm_tpu.loop import AppReplica, LoopConfig, LoopController
from lightgbm_tpu.serve.server import ModelRegistry

wd = sys.argv[1]
live = os.path.join(wd, "live.txt")

def provider(cycle):
    rng = np.random.RandomState(100 + cycle)
    shift = 0.0 if cycle == 0 else 1.6
    X = rng.randn(300, 5) + shift
    y = ((X[:, 0] - shift) + 0.3 * rng.randn(300) > 0).astype(float)
    Xh = rng.randn(120, 5) + shift
    yh = ((Xh[:, 0] - shift) > 0).astype(float)
    return X, y, Xh, yh

ctl = LoopController(LoopConfig(
    model_path=live, workdir=wd,
    params={"objective": "binary", "num_leaves": 8, "verbosity": -1},
    num_boost_round=5, data_provider=provider,
    replicas=[AppReplica(ModelRegistry())],
))
ctl.ensure_bootstrap()
out = ctl.run_cycle(force=True)
print("KILL-CHILD outcome=%%s sha=%%s" %% (out, ctl._file_sha(live)))
""" % REPO


def kill_act(result: dict) -> bool:
    """Seeded mid-publish SIGKILL (inside the atomic rename window), then a
    restart that must converge on the journaled cycle."""
    with tempfile.TemporaryDirectory() as wd:
        # the child bootstraps AND cycles in one process, so loop.publish
        # occurrences are: 1 = bootstrap's rename window, 2 = the publish
        # step's entry fire, 3 = the promote's atomic rename window — the
        # hardest crash point, which is the one this act seeds
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   LIGHTGBM_TPU_FAULTS="loop.publish:3:kill")
        r = subprocess.run(
            [sys.executable, "-c", _KILL_CHILD, wd],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
        )
        if r.returncode != -9 or "KILL-CHILD outcome" in r.stdout:
            result["error"] = ("kill child not SIGKILLed (rc=%s)"
                               % r.returncode)
            result["kill_stderr"] = r.stderr[-500:]
            return False
        env.pop("LIGHTGBM_TPU_FAULTS")
        r = subprocess.run(
            [sys.executable, "-c", _KILL_CHILD, wd],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
        )
        if r.returncode != 0:
            result["error"] = "restart failed"
            result["kill_stderr"] = r.stderr[-800:]
            return False
        out = r.stdout.split("outcome=")[1].split()[0]
        journal = json.load(
            open(os.path.join(wd, "loop_journal.json"))
        )
        result["kill_recovered_outcome"] = out
        ok = (
            out == "promoted"
            and journal["state"] == "observe"
            and journal["cycle"] == 1
            and sum(journal["outcomes"].values()) == 1
        )
        if not ok:
            result["error"] = "kill recovery inconsistent"
        return ok


def main() -> int:
    result: dict = {"san": os.environ.get("LIGHTGBM_TPU_SAN", "")}
    with tempfile.TemporaryDirectory() as td:
        ok = serve_acts(td, result)
    ok = kill_act(result) and ok
    result["ok"] = ok
    result["loop_smoke"] = "PASS" if ok else "FAIL"
    # ONE compact line: the bringup driver's result parser reads the last
    # JSON line of stdout (helpers/tpu_bringup.py _parse_result)
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
