#!/usr/bin/env python
"""Generate docs/Parameters.md from the Config dataclass + alias table.

Counterpart of the reference's helpers/parameter_generator.py, which parses
config.h comment blocks into docs/Parameters.rst and config_auto.cpp and whose
output CI diffs to keep code and docs in lockstep
(/root/reference/.ci/test.sh:27-60). Here the single source of truth is
lightgbm_tpu/config.py itself: the dataclass fields (name, type, default,
section) and PARAM_ALIASES are introspected, so the doc can never drift from
the code without tests/test_param_docs.py noticing.

Usage:  python helpers/gen_param_docs.py [--check]
  --check: exit 1 if docs/Parameters.md is out of date (the CI mode).
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "docs", "Parameters.md")


def _sections():
    """Parse config.py's `# --- section ---` groupings in declaration order."""
    import dataclasses

    from lightgbm_tpu.config import Config

    src = open(os.path.join(REPO, "lightgbm_tpu", "config.py")).read()
    body = src.split("class Config:", 1)[1]
    section = "core"
    field_section = {}
    for line in body.splitlines():
        m = re.match(r"\s*# --- (.+?) ---", line)
        if m:
            section = m.group(1)
            continue
        m = re.match(r"\s{4}(\w+)\s*:", line)
        if m:
            field_section[m.group(1)] = section
        if line.strip().startswith("def "):
            break

    fields = []
    for f in dataclasses.fields(Config):
        default = (
            f.default
            if f.default is not dataclasses.MISSING
            else f.default_factory()
        )
        type_name = {
            "str": "string", "int": "int", "float": "double", "bool": "bool",
        }.get(getattr(f.type, "__name__", str(f.type)), None)
        if type_name is None:
            t = str(f.type)
            type_name = "multi-double" if "float" in t else (
                "multi-int" if "int" in t else "multi-string"
            )
        fields.append(
            (field_section.get(f.name, "core"), f.name, type_name, default)
        )
    return fields


def render() -> str:
    from lightgbm_tpu.config import PARAM_ALIASES

    fields = _sections()
    alias_of = {}
    for alias, canonical in sorted(PARAM_ALIASES.items()):
        alias_of.setdefault(canonical, []).append(alias)

    lines = [
        "# Parameters",
        "",
        "All training/prediction parameters of lightgbm_tpu, generated from",
        "`lightgbm_tpu/config.py` by `helpers/gen_param_docs.py` — do not edit",
        "by hand; regenerate with `python helpers/gen_param_docs.py`.",
        "",
        "Names, defaults, and aliases follow the reference's parameter table",
        "(`docs/Parameters.rst`, generated from `config.h` comments by",
        "`helpers/parameter_generator.py`). Parameters are passed as",
        "`key=value` pairs on the CLI / config file, or as dict entries in",
        "the Python `params` argument; aliases resolve to the canonical name",
        "with conflict detection (`config.py Config.canonicalize`).",
        "",
    ]
    current = None
    for section, name, type_name, default in fields:
        if section != current:
            lines += ["## %s" % section.capitalize(), ""]
            current = section
        if isinstance(default, str):
            default_txt = '"%s"' % default
        elif isinstance(default, bool):
            default_txt = "true" if default else "false"
        elif isinstance(default, list):
            default_txt = "(empty)" if not default else ",".join(map(str, default))
        else:
            default_txt = str(default)
        entry = "- **`%s`** : %s, default = `%s`" % (name, type_name, default_txt)
        aliases = alias_of.get(name)
        if aliases:
            entry += ", aliases: %s" % ", ".join("`%s`" % a for a in aliases)
        lines.append(entry)
    lines.append("")

    lines += [
        "## Alias table",
        "",
        "%d aliases resolve to canonical parameters:" % len(PARAM_ALIASES),
        "",
        "| alias | canonical |",
        "|---|---|",
    ]
    for alias, canonical in sorted(PARAM_ALIASES.items()):
        lines.append("| `%s` | `%s` |" % (alias, canonical))
    lines.append("")
    return "\n".join(lines)


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        on_disk = open(OUT).read() if os.path.exists(OUT) else ""
        if on_disk != text:
            sys.stderr.write(
                "docs/Parameters.md is stale — regenerate with "
                "`python helpers/gen_param_docs.py`\n"
            )
            return 1
        return 0
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as fh:
        fh.write(text)
    print("wrote %s (%d lines)" % (OUT, text.count("\n")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
