"""Histogram-autotuner smoke: the `check.sh --tune` gate (ISSUE 13).

ONE invocation proves the whole tune-cache lifecycle on CPU:

 1. SWEEP — measure every supported impl at the grower's bucket-shape
    distribution for a small training geometry (obs/tune.py), write the
    cache atomically, reload it (digest + schema round-trip).
 2. PERF GATE — the acceptance criterion, from the sweep's own recorded
    medians: the tuned route is no slower than the static default impl at
    EVERY swept shape, and strictly faster (>= 1.1x) at >= 1 — the
    measured CPU win the static route was leaving on the table (the
    scatter default loses up to ~9x at small-bucket shapes on this class
    of box; the r5 on-silicon notes found the same inversion for TPU
    small buckets).
 3. EXACTNESS — (a) retraining under a DEFAULT-PINNED table (every entry
    = the backend default impl) is BIT-IDENTICAL to the untuned run: the
    routing machinery itself adds zero arithmetic change; (b) two
    trainings under the real winners table are byte-identical
    (frozen-per-run determinism); (c) chunk=1 vs device_chunk_size=4
    match under BOTH tables (the device-resident contract holds under
    routing; parameters footers stripped — device_chunk_size echoes
    there).

Run under JAX_PLATFORMS=cpu (check.sh does). Emits a one-line JSON verdict
for the bringup driver.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs import tune  # noqa: E402
from lightgbm_tpu.ops import histogram as hist_mod  # noqa: E402

N_ROWS, N_FEAT, MAX_BIN = 3000, 8, 63
ROUNDS = 8
PARAMS = {
    "objective": "binary", "num_leaves": 15, "max_bin": MAX_BIN,
    "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 5,
}
# strict-win threshold: the observed inversions are 1.3x-9x, so 1.1x keeps
# the gate meaningful while riding above scheduler noise
STRICT_WIN = 1.1
# both bin widths the wide-bin kernel family (ISSUE 17) targets enter the
# sweep so the new contenders are raced at 63 AND 255 on CPU
SWEEP_BINS = [MAX_BIN, 255]
# the routed vocabulary as of PR 12 — the baseline for the "new contenders
# made nothing slower" gate below
PR12_IMPLS = ("xla", "xla_radix", "scatter", "pallas", "pallas_packed4")


def _data():
    rng = np.random.RandomState(7)
    X = rng.randn(N_ROWS, N_FEAT)
    y = (X[:, 0] + 0.5 * rng.randn(N_ROWS) > 0).astype(np.float64)
    return X, y


def _strip_params(model_str: str) -> str:
    """Trees + feature metadata only — the parameters footer echoes
    device_chunk_size and legitimately differs across chunk settings."""
    return model_str.split("parameters:")[0]


def _train(X, y, extra=None):
    p = dict(PARAMS)
    p.update(extra or {})
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=ROUNDS)
    return bst.model_to_string()


def _perf_gate(table):
    """(ok, worst_ratio, best_ratio, details): winner vs the static default
    from the sweep's recorded per-impl medians."""
    default = hist_mod.default_impl()
    worst = float("inf")
    best = 0.0
    details = []
    for e in table["entries"]:
        times = e.get("times_ms") or {}
        if default not in times:
            return False, 0.0, 0.0, ["default %r not swept at %s" %
                                     (default, e)]
        ratio = times[default] / times[e["impl"]]
        worst = min(worst, ratio)
        best = max(best, ratio)
        details.append(
            "B=%d rows=%d: %s %.3fms vs %s %.3fms (%.2fx)"
            % (e["B"], e["rows_bucket"], e["impl"], times[e["impl"]],
               default, times[default], ratio)
        )
    # the winner is the per-shape argmin, so >= 1.0 everywhere holds by
    # construction when the default was raced; the strict-win clause is the
    # real measurement
    ok = worst >= 1.0 and best >= STRICT_WIN
    return ok, worst, best, details


def _pr12_gate(table):
    """(ok, details): the tuned route with the enlarged impl vocabulary
    (ISSUE 17: xla_onehot / pallas_onehot / pallas_bitplane) is no slower
    than the PR-12 winner at EVERY swept shape — enlarging the candidate
    set must never degrade a shape the old vocabulary already served — and
    the new CPU-measurable contender was actually raced everywhere."""
    ok = True
    details = []
    for e in table["entries"]:
        times = e.get("times_ms") or {}
        if "xla_onehot" not in times:
            ok = False
            details.append(
                "B=%d rows=%d: xla_onehot missing from the race"
                % (e["B"], e["rows_bucket"])
            )
            continue
        old = {i: times[i] for i in PR12_IMPLS if i in times}
        if not old:
            ok = False
            details.append(
                "B=%d rows=%d: no PR-12 impl measured" % (e["B"],
                                                          e["rows_bucket"])
            )
            continue
        old_best = min(old, key=old.get)
        ratio = old[old_best] / times[e["impl"]]
        if ratio < 1.0:
            ok = False
        details.append(
            "B=%d rows=%d: routed %s %.3fms vs PR-12 winner %s %.3fms "
            "(%.2fx)" % (e["B"], e["rows_bucket"], e["impl"],
                         times[e["impl"]], old_best, old[old_best], ratio)
        )
    return ok, details


def _eligibility_gate():
    """The capability/candidate layers record the new impls as eligible at
    the wide-bin widths: xla_onehot races on CPU, the Pallas twins are
    supported at B=63/255 on TPU (adoption happens unattended in the next
    bringup window's tune stage)."""
    for b in (63, 255):
        cands = tune.candidate_impls(b, "cpu")
        assert "xla_onehot" in cands, (
            "xla_onehot not a CPU sweep candidate at B=%d: %s" % (b, cands)
        )
        for impl in ("pallas_onehot", "pallas_bitplane"):
            assert hist_mod.impl_supported(impl, b, "tpu"), (
                "%s must be eligible at B=%d on TPU" % (impl, b)
            )
            assert impl in tune.candidate_impls(b, "tpu"), (
                "%s missing from the TPU candidate race at B=%d" % (impl, b)
            )
    assert not hist_mod.impl_supported("pallas_onehot", 257, "tpu"), (
        "pallas_onehot capability must cap at the 256-bin family"
    )


def main() -> int:
    X, y = _data()
    with tempfile.TemporaryDirectory(prefix="tune_smoke_") as td:
        winners_path = os.path.join(td, "TUNE_HIST.json")
        pinned_path = os.path.join(td, "TUNE_PINNED.json")

        # ---- 1. sweep + persist + reload -------------------------------
        shapes = tune.sweep_shapes(N_ROWS, SWEEP_BINS, N_FEAT)
        # two attempts absorb a noisy first measurement pass on a loaded box
        for attempt in range(2):
            table = tune.sweep(shapes, repeats=3)
            perf_ok, worst, best_ratio, details = _perf_gate(table)
            if perf_ok:
                break
        tune.save_table(table, winners_path)
        reloaded = tune.load_table(winners_path)
        assert reloaded["digest"] == table["digest"], "round-trip digest"
        print("tune-smoke: sweep %d shapes -> %d entries, digest %s"
              % (len(shapes), len(table["entries"]), table["digest"]))
        for line in details:
            print("tune-smoke:   " + line)
        assert perf_ok, (
            "perf gate failed: tuned route must be no slower everywhere "
            "(worst ratio %.3f) and >= %.1fx faster somewhere (best %.3f)"
            % (worst, STRICT_WIN, best_ratio)
        )
        print("tune-smoke: PERF GATE ok (worst %.2fx, best %.2fx vs "
              "default %r)" % (worst, best_ratio, hist_mod.default_impl()))

        # ---- 1b. enlarged vocabulary gates (ISSUE 17) ------------------
        pr12_ok, pr12_details = _pr12_gate(table)
        for line in pr12_details:
            print("tune-smoke:   " + line)
        assert pr12_ok, (
            "PR-12 gate failed: the route with the enlarged vocabulary "
            "must be no slower than the PR-12 winner at every swept shape"
        )
        _eligibility_gate()
        print("tune-smoke: NEW-CONTENDER GATE ok (xla_onehot raced "
              "everywhere; pallas_onehot/pallas_bitplane eligible at "
              "B=63/255 on TPU)")

        # ---- 2. routing machinery is bit-transparent -------------------
        default = hist_mod.default_impl()
        pinned = tune.build_table(
            [dict(e, impl=default) for e in table["entries"]]
        )
        tune.save_table(pinned, pinned_path)
        untuned = _train(X, y)
        under_pinned = _train(X, y, {"hist_tune": pinned_path})
        assert under_pinned == untuned, (
            "default-pinned table must train BIT-IDENTICAL to the untuned "
            "run — the routing seam itself leaked an arithmetic change"
        )
        print("tune-smoke: default-pinned table bit-identical to untuned")

        # ---- 3. frozen-per-run determinism + chunk contract ------------
        tuned_a = _train(X, y, {"hist_tune": winners_path})
        tuned_b = _train(X, y, {"hist_tune": winners_path})
        assert tuned_a == tuned_b, "same-table reruns must be byte-identical"
        routed = tuned_a != untuned
        # the perf gate above proved a non-default winner exists, so the
        # winners table MUST change routed arithmetic — a vacuous pass here
        # (key mismatch, broken pick lookup) would leave every exactness
        # check below comparing the untuned run against itself
        assert routed, (
            "winners table with non-default impls never engaged the route "
            "— the smoke's exactness checks would be vacuous"
        )
        print("tune-smoke: winners-table determinism ok (route engaged)")
        chunk_pinned = _train(
            X, y, {"hist_tune": pinned_path, "device_chunk_size": 4}
        )
        assert _strip_params(chunk_pinned) == _strip_params(untuned), (
            "chunk=4 under the pinned table diverged from chunk=1"
        )
        chunk_tuned = _train(
            X, y, {"hist_tune": winners_path, "device_chunk_size": 4}
        )
        assert _strip_params(chunk_tuned) == _strip_params(tuned_a), (
            "chunk=4 under the winners table diverged from chunk=1"
        )
        print("tune-smoke: chunk=1 vs chunk=4 identical under both tables")

        print(json.dumps({
            "ok": True, "entries": len(table["entries"]),
            "digest": table["digest"],
            "perf_worst_ratio": round(worst, 3),
            "perf_best_ratio": round(best_ratio, 3),
            "route_engaged": bool(routed),
            "pr12_gate": bool(pr12_ok),
            "winners": {"%d:%d" % (e["B"], e["rows_bucket"]): e["impl"]
                        for e in table["entries"]},
        }), flush=True)
        print("TUNE-SMOKE PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
